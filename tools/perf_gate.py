#!/usr/bin/env python
"""Performance regression gate over consecutive BENCH_*.json files.

The driver appends one ``BENCH_rNN.json`` per round; each embeds the
bench result under ``parsed`` (plus the raw child ``tail``).  This gate
compares the latest two rounds scenario-by-scenario and exits nonzero
when a comparable scenario regressed beyond the noise bound.

Comparability rules (the whole point — a gate that fires on noise or on
apples-vs-oranges gets deleted within a week):

* Scenarios are matched by ``detail.model`` + ``detail.attention`` +
  ``detail.batch``.  BENCH rounds that ran different model scales (the
  common case when the bench's own degradation ladder picked different
  rungs) simply have no common scenario and the gate passes with a note.
* Degraded lines never gate.  A line is degraded when it carries a
  top-level ``degraded``/``fallback`` flag (bench.py contract) or a
  ``detail.fallback`` string (older rounds): the number was produced on
  a fallback rung, so comparing it against a healthy run is noise.
* When both lines embed the cost attribution block
  (``detail.telemetry.attribution``, docs/observability.md) and the
  analytical flops differ by >1%, the model genuinely changed between
  rounds even though the scenario label matched — skipped, not gated.
* Within a comparable pair, regression means
  ``new.value < old.value * (1 - noise)`` (default noise 0.20: CPU
  fallback hosts are shared and wobble; TPU rounds can pass a tighter
  ``--noise``).
* When both lines of a comparable pair embed a goodput ledger
  (``detail.goodput.goodput_frac``, docs/observability.md "Goodput"),
  the fraction gates under the same noise bound as its own compared
  entry — throughput can hold steady while compile or data-wait creep
  eats the wall clock, and this is the line that catches it.  A ledger
  present on only one side is a ``[skip]`` note, never a gate.

Matrix scenarios (the top-level ``matrix`` dict bench.py emits — one
keyed line per dense/MoE/LoRA x context x loss_impl x matmul_precision
cell) gate key-by-key with their own rules:

* A key present only in the NEW round is informational — new scenarios
  never gate (there is nothing to regress against).
* A key present only in the OLD round warns ("scenario removed"), unless
  the new round's top-level ``skipped`` list names it — then it was
  skipped for budget this round, a note, not a warning.
* A matrix line flagged ``degraded`` (e.g. the quantized loss-parity
  gate failed, bench.py ``parity``) is skipped, never compared.
* Comparable pairs gate on ``tokens_per_sec`` with the same noise bound
  and the same >1% analytical-flops drift skip as the headline lines.

Usage::

    python tools/perf_gate.py BENCH_r04.json BENCH_r05.json
    python tools/perf_gate.py            # auto: latest two BENCH_*.json
    python tools/perf_gate.py --self-test

Exit codes: 0 pass (or nothing comparable), 1 regression, 2 usage/IO.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any

DEFAULT_NOISE = 0.20
_FLOPS_DRIFT = 0.01


def load_results(path: str) -> list[dict[str, Any]]:
    """Bench lines out of one BENCH_*.json: the ``parsed`` wrapper, a raw
    result object, or a list of results."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        return [r for r in doc if isinstance(r, dict)]
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return [doc["parsed"]]
    if isinstance(doc, dict) and "metric" in doc:
        return [doc]
    return []


def scenario_key(result: dict[str, Any]) -> str:
    detail = result.get("detail") or {}
    return "{model}|{attention}|batch={batch}".format(
        model=detail.get("model", "?"),
        attention=detail.get("attention", "?"),
        batch=detail.get("batch", "?"),
    )


def is_degraded(result: dict[str, Any]) -> bool:
    if result.get("degraded") or result.get("fallback"):
        return True
    detail = result.get("detail") or {}
    return bool(detail.get("fallback"))


def _attribution_flops(result: dict[str, Any]) -> float | None:
    attr = ((result.get("detail") or {}).get("telemetry") or {}).get("attribution")
    if isinstance(attr, dict) and "flops" in attr:
        try:
            return float(attr["flops"])
        except (TypeError, ValueError):
            return None
    return None


def _goodput_frac(result: dict[str, Any]) -> float | None:
    ledger = (result.get("detail") or {}).get("goodput")
    if isinstance(ledger, dict) and "goodput_frac" in ledger:
        try:
            return float(ledger["goodput_frac"])
        except (TypeError, ValueError):
            return None
    return None


def _tuned_plan_winner(result: dict[str, Any]) -> str | None:
    block = (result.get("detail") or {}).get("tuned_plan")
    if isinstance(block, dict) and block.get("winner"):
        return str(block["winner"])
    return None


def _offload_tps(result: dict[str, Any]) -> float | None:
    """Tiered tokens/s out of the activation-tier offload scenario block
    (``detail.offload``, bench.py _offload_main), or None when the block
    is absent/malformed — or when the scenario itself is degraded: a
    non-bitwise loss or a tiered config that no longer fits under its own
    cap means the scenario measured something broken, and a broken line
    never gates (same philosophy as the parity-failed matrix lines)."""
    block = (result.get("detail") or {}).get("offload")
    if not isinstance(block, dict):
        return None
    if not block.get("loss_bitwise_identical") or not block.get("tiered_fits"):
        return None
    try:
        return float((block.get("tiered") or {})["tokens_per_sec"])
    except (KeyError, TypeError, ValueError):
        return None


def _offload_degraded(result: dict[str, Any]) -> str | None:
    """Reason string when an offload block is present but unusable."""
    block = (result.get("detail") or {}).get("offload")
    if not isinstance(block, dict):
        return None
    if not block.get("loss_bitwise_identical"):
        return "loss not bitwise identical"
    if not block.get("tiered_fits"):
        return "tiered config does not fit its own cap"
    return None


def compare(
    old: list[dict[str, Any]],
    new: list[dict[str, Any]],
    *,
    noise: float = DEFAULT_NOISE,
) -> dict[str, Any]:
    """Pure comparison core (unit-tested; the CLI is a thin shell).

    Returns {"compared", "regressions", "skipped", "notes"} — ``notes``
    carries informational observations that must NEVER gate, like the
    analytic mesh planner's winning plan (``detail.tuned_plan``,
    autotune/search.py) flipping between rounds: a plan change explains a
    throughput shift, it is not itself a regression."""
    old_by_key = {scenario_key(r): r for r in old if not is_degraded(r)}
    regressions: list[dict[str, Any]] = []
    compared: list[dict[str, Any]] = []
    skipped: list[str] = []
    notes: list[str] = []
    for result in new:
        key = scenario_key(result)
        if is_degraded(result):
            skipped.append(f"{key}: new line degraded ({result.get('fallback') or 'detail.fallback'})")
            continue
        prev = old_by_key.get(key)
        if prev is None:
            skipped.append(f"{key}: no matching non-degraded scenario in old round")
            continue
        if result.get("metric") != prev.get("metric"):
            skipped.append(f"{key}: metric changed {prev.get('metric')} -> {result.get('metric')}")
            continue
        f_old, f_new = _attribution_flops(prev), _attribution_flops(result)
        if f_old and f_new and abs(f_new - f_old) / max(f_old, 1.0) > _FLOPS_DRIFT:
            skipped.append(
                f"{key}: analytical flops drifted {f_old:.3g} -> {f_new:.3g}; "
                "workload changed, not comparable"
            )
            continue
        old_v = float(prev.get("value", 0.0))
        new_v = float(result.get("value", 0.0))
        entry = {
            "scenario": key,
            "metric": result.get("metric"),
            "old": old_v,
            "new": new_v,
            "ratio": new_v / old_v if old_v else float("inf"),
        }
        compared.append(entry)
        if old_v > 0 and new_v < old_v * (1.0 - noise):
            regressions.append(entry)
        g_old, g_new = _goodput_frac(prev), _goodput_frac(result)
        if g_old is not None and g_new is not None:
            g_entry = {
                "scenario": key,
                "metric": "goodput_frac",
                "old": g_old,
                "new": g_new,
                "ratio": g_new / g_old if g_old else float("inf"),
            }
            compared.append(g_entry)
            if g_old > 0 and g_new < g_old * (1.0 - noise):
                regressions.append(g_entry)
        elif (g_old is None) != (g_new is None):
            side = "old" if g_old is None else "new"
            skipped.append(
                f"{key}: goodput ledger missing on the {side} side; "
                "goodput_frac not compared"
            )
        # Offload scenario (detail.offload, bench.py): the TIERED run's
        # tokens/s gates under the same noise bound — the offload ladder
        # must stay competitive round-over-round, not just fit. Degraded
        # blocks (loss not bitwise / tiered no longer fits) skip, and a
        # block on only one side skips — same contract as goodput.
        o_old, o_new = _offload_tps(prev), _offload_tps(result)
        has_o_old = isinstance((prev.get("detail") or {}).get("offload"), dict)
        has_o_new = isinstance((result.get("detail") or {}).get("offload"), dict)
        o_reason = _offload_degraded(result)
        if o_reason is not None:
            skipped.append(
                f"{key}: offload scenario degraded ({o_reason}); never gates"
            )
        if o_old is not None and o_new is not None:
            o_entry = {
                "scenario": key,
                "metric": "offload_tiered_tokens_per_sec",
                "old": o_old,
                "new": o_new,
                "ratio": o_new / o_old if o_old else float("inf"),
            }
            compared.append(o_entry)
            if o_old > 0 and o_new < o_old * (1.0 - noise):
                regressions.append(o_entry)
        elif (has_o_old or has_o_new) and o_reason is None:
            side = "old" if o_old is None else "new"
            skipped.append(
                f"{key}: offload scenario missing or degraded on the {side} "
                "side; not compared"
            )
        # Tuned-plan drift: INFORM, never gate — a re-tune picking a
        # different winning plan between rounds is context for any
        # throughput movement above, not a failure of its own.
        p_old, p_new = _tuned_plan_winner(prev), _tuned_plan_winner(result)
        if p_old and p_new and p_old != p_new:
            notes.append(
                f"{key}: tuned plan changed between rounds: "
                f"{p_old} -> {p_new} (informational, never gates)"
            )
    return {
        "compared": compared,
        "regressions": regressions,
        "skipped": skipped,
        "notes": notes,
    }


def matrix_lines(results: list[dict[str, Any]]) -> dict[str, dict[str, Any]]:
    """Keyed matrix scenario lines across a round's bench lines (the
    last line carrying a ``matrix`` dict wins, matching bench.py's
    last-JSON-wins carry-forward)."""
    lines: dict[str, dict[str, Any]] = {}
    for result in results:
        mat = result.get("matrix")
        if isinstance(mat, dict):
            lines = {k: v for k, v in mat.items() if isinstance(v, dict)}
    return lines


def skipped_scenarios(results: list[dict[str, Any]]) -> set[str]:
    """Scenario names the round reports as skipped-for-budget (bench.py
    top-level ``skipped`` list) — distinguishes "absent because removed"
    from "absent because this round ran out of budget"."""
    names: set[str] = set()
    for result in results:
        for entry in result.get("skipped") or []:
            if isinstance(entry, dict) and "scenario" in entry:
                names.add(str(entry["scenario"]))
    return names


def _matrix_flops(line: dict[str, Any]) -> float | None:
    attr = line.get("attribution")
    if isinstance(attr, dict) and "flops" in attr:
        try:
            return float(attr["flops"])
        except (TypeError, ValueError):
            return None
    return None


def compare_matrix(
    old: list[dict[str, Any]],
    new: list[dict[str, Any]],
    *,
    noise: float = DEFAULT_NOISE,
) -> dict[str, Any]:
    """Key-by-key matrix gate (pure, unit-tested via --self-test).

    Returns {"compared", "regressions", "skipped", "notes"}; only
    ``regressions`` affects the exit code — new keys and removed keys
    land in ``notes`` (informational / warning) by design."""
    old_mat, new_mat = matrix_lines(old), matrix_lines(new)
    new_skipped = skipped_scenarios(new)
    regressions: list[dict[str, Any]] = []
    compared: list[dict[str, Any]] = []
    skipped: list[str] = []
    notes: list[str] = []
    for key, line in new_mat.items():
        if is_degraded(line):
            skipped.append(
                f"matrix:{key}: line degraded ({line.get('fallback') or 'flagged'})"
            )
            continue
        prev = old_mat.get(key)
        if prev is None:
            notes.append(f"matrix:{key}: new scenario (informational, never gates)")
            continue
        if is_degraded(prev):
            skipped.append(f"matrix:{key}: old line degraded; nothing to gate against")
            continue
        f_old, f_new = _matrix_flops(prev), _matrix_flops(line)
        if f_old and f_new and abs(f_new - f_old) / max(f_old, 1.0) > _FLOPS_DRIFT:
            skipped.append(
                f"matrix:{key}: analytical flops drifted {f_old:.3g} -> {f_new:.3g}; "
                "workload changed, not comparable"
            )
            continue
        old_v = float(prev.get("tokens_per_sec", 0.0))
        new_v = float(line.get("tokens_per_sec", 0.0))
        entry = {
            "scenario": f"matrix:{key}",
            "metric": "tokens_per_sec",
            "old": old_v,
            "new": new_v,
            "ratio": new_v / old_v if old_v else float("inf"),
        }
        compared.append(entry)
        if old_v > 0 and new_v < old_v * (1.0 - noise):
            regressions.append(entry)
    for key in old_mat:
        if key in new_mat:
            continue
        if key in new_skipped:
            notes.append(f"matrix:{key}: skipped for budget this round (not removed)")
        else:
            notes.append(
                f"matrix:{key}: WARNING scenario removed (present last round, "
                "absent and not in the new round's skipped list)"
            )
    return {
        "compared": compared,
        "regressions": regressions,
        "skipped": skipped,
        "notes": notes,
    }


def _latest_pair(root: str) -> tuple[str, str] | None:
    def round_no(path: str) -> int:
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        return int(m.group(1)) if m else -1

    files = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")), key=round_no)
    if len(files) < 2:
        return None
    return files[-2], files[-1]


def _self_test() -> int:
    """Synthetic inject: a 50% drop must gate, a 2% wobble must not, and
    degraded / flops-drifted lines must be skipped."""
    base = {
        "metric": "tokens_per_sec_per_chip",
        "value": 1000.0,
        "detail": {
            "model": "gpt L2 d128 T128",
            "attention": "dense",
            "batch": 4,
            "telemetry": {"attribution": {"flops": 1.0e9}},
        },
    }

    def variant(**kw: Any) -> dict[str, Any]:
        out = json.loads(json.dumps(base))
        out.update({k: v for k, v in kw.items() if k != "flops"})
        if "flops" in kw:
            out["detail"]["telemetry"]["attribution"]["flops"] = kw["flops"]
        return out

    verdict = compare([base], [variant(value=500.0)])
    assert verdict["regressions"], "50% drop must gate"
    verdict = compare([base], [variant(value=980.0)])
    assert not verdict["regressions"] and verdict["compared"], "2% wobble must pass"
    verdict = compare([base], [variant(value=500.0, degraded=True, fallback="oom")])
    assert not verdict["regressions"] and verdict["skipped"], "degraded must skip"
    verdict = compare([base], [variant(value=500.0, flops=2.0e9)])
    assert not verdict["regressions"] and verdict["skipped"], "flops drift must skip"

    # --- goodput gate -------------------------------------------------
    def with_goodput(result: dict[str, Any], frac: float) -> dict[str, Any]:
        out = json.loads(json.dumps(result))
        out["detail"]["goodput"] = {"goodput_frac": frac}
        return out

    g_base = with_goodput(base, 0.90)
    # Throughput flat but goodput collapsed (compile/data-wait creep) gates.
    verdict = compare([g_base], [with_goodput(variant(value=1000.0), 0.40)])
    assert any(
        r["metric"] == "goodput_frac" for r in verdict["regressions"]
    ), "goodput collapse must gate"
    # A small goodput wobble under the noise bound passes.
    verdict = compare([g_base], [with_goodput(variant(value=1000.0), 0.85)])
    assert not verdict["regressions"], "goodput wobble must pass"
    assert any(
        c["metric"] == "goodput_frac" for c in verdict["compared"]
    ), "goodput pair must be compared"
    # A ledger on only one side skips, never gates.
    verdict = compare([g_base], [variant(value=1000.0)])
    assert not any(
        r["metric"] == "goodput_frac" for r in verdict["regressions"]
    ), "one-sided ledger must not gate"
    assert any(
        "goodput ledger missing" in s for s in verdict["skipped"]
    ), "one-sided ledger must note a skip"
    verdict = compare([base], [with_goodput(variant(value=1000.0), 0.95)])
    assert any(
        "goodput ledger missing" in s for s in verdict["skipped"]
    ), "ledger new-side-only must note a skip"

    # --- tuned-plan drift notes ---------------------------------------
    def with_plan(result: dict[str, Any], winner: str) -> dict[str, Any]:
        out = json.loads(json.dumps(result))
        out["detail"]["tuned_plan"] = {"winner": winner, "enumerated": 10, "pruned": 8}
        return out

    p_base = with_plan(base, "d8.f1.t1.s1.p1.e1|mb4|remat0|zero0")
    # A plan flip with steady throughput notes, never gates.
    verdict = compare(
        [p_base], [with_plan(variant(value=1000.0), "d1.f8.t1.s1.p1.e1|mb8|remat0|zero0")]
    )
    assert not verdict["regressions"], "plan flip alone must not gate"
    assert any("tuned plan changed" in n for n in verdict["notes"]), "plan flip must note"
    # Same plan both rounds: silent.
    verdict = compare([p_base], [with_plan(variant(value=1000.0), "d8.f1.t1.s1.p1.e1|mb4|remat0|zero0")])
    assert not verdict["notes"], "unchanged plan must not note"
    # A one-sided tuned_plan block (older rounds predate it): silent.
    verdict = compare([base], [with_plan(variant(value=1000.0), "d8.f1.t1.s1.p1.e1|mb4|remat0|zero0")])
    assert not verdict["notes"], "one-sided tuned_plan must not note"
    # A plan flip NEXT TO a genuine regression: both surface, only the
    # regression gates.
    verdict = compare(
        [p_base], [with_plan(variant(value=400.0), "d1.f8.t1.s1.p1.e1|mb8|remat0|zero0")]
    )
    assert verdict["regressions"] and any(
        "tuned plan changed" in n for n in verdict["notes"]
    ), "regression + plan flip must both surface"

    # --- matrix gate (compare_matrix) ---------------------------------
    def mline(tps: float, flops: float = 5.0e8, **kw: Any) -> dict[str, Any]:
        out = {"tokens_per_sec": tps, "attribution": {"flops": flops}}
        out.update(kw)
        return out

    def round_(mat: dict[str, Any], skipped: list[dict] | None = None) -> list[dict]:
        line = json.loads(json.dumps(base))
        line["matrix"] = mat
        line["skipped"] = skipped or []
        return [line]

    old_round = round_({"dense|short|dense_ce|f32": mline(1000.0)})
    # A genuine matrix regression gates.
    verdict = compare_matrix(old_round, round_({"dense|short|dense_ce|f32": mline(400.0)}))
    assert verdict["regressions"], "60% matrix drop must gate"
    # New key NEVER gates, however bad its number looks.
    verdict = compare_matrix(
        old_round,
        round_(
            {
                "dense|short|dense_ce|f32": mline(1000.0),
                "dense|short|dense_ce|int8": mline(1.0),
            }
        ),
    )
    assert not verdict["regressions"], "new matrix key must never gate"
    assert any("new scenario" in n for n in verdict["notes"]), "new key must note"
    # Removed key warns ...
    verdict = compare_matrix(old_round, round_({}))
    assert not verdict["regressions"], "removed key must not gate"
    assert any("WARNING scenario removed" in n for n in verdict["notes"]), "removed key must warn"
    # ... unless the new round's skipped list names it (budget skip).
    verdict = compare_matrix(
        old_round,
        round_({}, skipped=[{"scenario": "dense|short|dense_ce|f32", "reason": "budget"}]),
    )
    assert not any("WARNING" in n for n in verdict["notes"]), "budget skip must not warn"
    assert any("skipped for budget" in n for n in verdict["notes"]), "budget skip must note"
    # A degraded line (failed loss-parity gate) is skipped, never compared.
    verdict = compare_matrix(
        old_round,
        round_(
            {
                "dense|short|dense_ce|f32": mline(
                    400.0,
                    degraded=True,
                    fallback="loss parity vs f32 failed: max rel diff 0.2 > rtol 0.05",
                    parity={"rtol": 0.05, "max_rel_diff": 0.2, "ok": False},
                )
            }
        ),
    )
    assert not verdict["regressions"] and verdict["skipped"], "degraded parity line must skip"

    # --- fused-CE matrix key (PR 18: key-gated from its second round) --
    fused_key = "dense|50k|fused_ce|f32"
    fused_old = round_({fused_key: mline(800.0)})
    # First appearance never gates, however bad its number looks.
    verdict = compare_matrix(
        old_round,
        round_(
            {
                "dense|short|dense_ce|f32": mline(1000.0),
                fused_key: mline(1.0),
            }
        ),
    )
    assert not verdict["regressions"], "first fused line must never gate"
    assert any(
        fused_key in n and "new scenario" in n for n in verdict["notes"]
    ), "first fused line must note"
    # From its second round on, a collapse gates like any other key.
    verdict = compare_matrix(fused_old, round_({fused_key: mline(300.0)}))
    assert verdict["regressions"], "fused key collapse must gate"
    # A wobble inside the noise bound passes but is compared.
    verdict = compare_matrix(fused_old, round_({fused_key: mline(700.0)}))
    assert not verdict["regressions"], "fused key wobble must pass"
    assert any(
        fused_key in c["scenario"] for c in verdict["compared"]
    ), "fused key wobble must be compared"
    # A fused line that failed the dense-CE parity gate is skipped.
    verdict = compare_matrix(
        fused_old,
        round_(
            {
                fused_key: mline(
                    790.0,
                    degraded=True,
                    fallback="loss parity vs dense CE failed: max rel diff 0.01 > rtol 0.0005",
                    parity={"rtol": 5e-4, "max_rel_diff": 0.01, "ok": False},
                )
            }
        ),
    )
    assert not verdict["regressions"] and verdict["skipped"], "degraded fused line must skip"

    # --- offload scenario gate (detail.offload) -----------------------
    def with_offload(
        result: dict[str, Any], tps: float, *, bitwise: bool = True, fits: bool = True
    ) -> dict[str, Any]:
        out = json.loads(json.dumps(result))
        out["detail"]["offload"] = {
            "tiers": "offload:0-0,full:1-1",
            "hbm_cap_bytes": 100,
            "baseline": {"tokens_per_sec": tps * 1.1, "predicted_hbm_bytes": 120},
            "tiered": {"tokens_per_sec": tps, "predicted_hbm_bytes": 80},
            "baseline_fits": False,
            "tiered_fits": fits,
            "loss_bitwise_identical": bitwise,
        }
        return out

    o_base = with_offload(base, 100.0)
    # Throughput flat but the tiered run collapsed: gates.
    verdict = compare([o_base], [with_offload(variant(value=1000.0), 40.0)])
    assert any(
        r["metric"] == "offload_tiered_tokens_per_sec" for r in verdict["regressions"]
    ), "offload throughput collapse must gate"
    # A small wobble under the noise bound passes but is compared.
    verdict = compare([o_base], [with_offload(variant(value=1000.0), 95.0)])
    assert not verdict["regressions"], "offload wobble must pass"
    assert any(
        c["metric"] == "offload_tiered_tokens_per_sec" for c in verdict["compared"]
    ), "offload pair must be compared"
    # Non-bitwise loss marks the block degraded: skip, never gate.
    verdict = compare(
        [o_base], [with_offload(variant(value=1000.0), 40.0, bitwise=False)]
    )
    assert not verdict["regressions"], "degraded offload must not gate"
    assert any(
        "offload scenario degraded" in s for s in verdict["skipped"]
    ), "degraded offload must note a skip"
    # Tiered config no longer fitting its own cap = degraded too.
    verdict = compare(
        [o_base], [with_offload(variant(value=1000.0), 100.0, fits=False)]
    )
    assert not verdict["regressions"] and any(
        "offload scenario degraded" in s for s in verdict["skipped"]
    ), "cap-violating offload must skip"
    # A block on only one side skips, never gates (scenario's first round).
    verdict = compare([base], [with_offload(variant(value=1000.0), 100.0)])
    assert not any(
        r["metric"] == "offload_tiered_tokens_per_sec" for r in verdict["regressions"]
    ), "one-sided offload must not gate"
    assert any(
        "offload scenario missing" in s for s in verdict["skipped"]
    ), "one-sided offload must note a skip"
    # Neither side carrying the block stays silent.
    verdict = compare([base], [variant(value=980.0)])
    assert not any("offload" in s for s in verdict["skipped"]), "no block, no note"

    # --- parallelism matrix keys (fifth |par segment) ------------------
    par_key = "dense|short|dense_ce|f32|ring-zero1"
    par_parity = {"rtol": 2e-3, "max_rel_diff": 0.0, "ok": True}
    old_par = round_({par_key: mline(1000.0, parity=par_parity)})
    verdict = compare_matrix(
        old_par, round_({par_key: mline(400.0, parity=par_parity)})
    )
    assert verdict["regressions"], "par matrix drop must gate"
    # A parity-failed (degraded) par line skips, never compares.
    verdict = compare_matrix(
        old_par,
        round_(
            {
                par_key: mline(
                    980.0,
                    degraded=True,
                    fallback="loss parity vs dense failed: max rel diff 0.0100 > rtol 0.002",
                    parity={"rtol": 2e-3, "max_rel_diff": 0.01, "ok": False},
                )
            }
        ),
    )
    assert not verdict["regressions"] and verdict["skipped"], "parity-failed par line must skip"
    # Budget-skipped par key notes instead of warning, like any matrix key.
    verdict = compare_matrix(
        old_par, round_({}, skipped=[{"scenario": par_key, "reason": "budget"}])
    )
    assert not any("WARNING" in n for n in verdict["notes"]), "par budget skip must not warn"
    print("perf_gate self-test: OK")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("old", nargs="?", help="older BENCH_*.json")
    parser.add_argument("new", nargs="?", help="newer BENCH_*.json")
    parser.add_argument("--noise", type=float, default=DEFAULT_NOISE)
    parser.add_argument("--root", default=".", help="dir for auto-discovery")
    parser.add_argument("--self-test", action="store_true")
    args = parser.parse_args(argv)

    if args.self_test:
        return _self_test()

    if args.old and args.new:
        pair = (args.old, args.new)
    else:
        pair = _latest_pair(args.root)
        if pair is None:
            print("perf_gate: fewer than two BENCH_r*.json rounds; nothing to gate")
            return 0
    try:
        old, new = load_results(pair[0]), load_results(pair[1])
    except (OSError, json.JSONDecodeError) as exc:
        print(f"perf_gate: cannot read bench rounds: {exc}", file=sys.stderr)
        return 2

    verdict = compare(old, new, noise=args.noise)
    matrix_verdict = compare_matrix(old, new, noise=args.noise)
    regressions = verdict["regressions"] + matrix_verdict["regressions"]
    print(f"perf_gate: {pair[0]} -> {pair[1]} (noise bound {args.noise:.0%})")
    for entry in verdict["compared"] + matrix_verdict["compared"]:
        flag = "REGRESSION" if entry in regressions else "ok"
        print(
            f"  [{flag}] {entry['scenario']}: {entry['old']:.1f} -> "
            f"{entry['new']:.1f} ({entry['ratio']:.2%} of old)"
        )
    for note in verdict["skipped"] + matrix_verdict["skipped"]:
        print(f"  [skip] {note}")
    for note in verdict["notes"] + matrix_verdict["notes"]:
        print(f"  [note] {note}")
    if not any(
        (verdict["compared"], verdict["skipped"], verdict["notes"],
         matrix_verdict["compared"], matrix_verdict["skipped"],
         matrix_verdict["notes"])
    ):
        print("  no bench lines found")
    if regressions:
        print(f"perf_gate: FAIL ({len(regressions)} regression(s))")
        return 1
    print("perf_gate: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
