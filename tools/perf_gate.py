#!/usr/bin/env python
"""Performance regression gate over consecutive BENCH_*.json files.

The driver appends one ``BENCH_rNN.json`` per round; each embeds the
bench result under ``parsed`` (plus the raw child ``tail``).  This gate
compares the latest two rounds scenario-by-scenario and exits nonzero
when a comparable scenario regressed beyond the noise bound.

Comparability rules (the whole point — a gate that fires on noise or on
apples-vs-oranges gets deleted within a week):

* Scenarios are matched by ``detail.model`` + ``detail.attention`` +
  ``detail.batch``.  BENCH rounds that ran different model scales (the
  common case when the bench's own degradation ladder picked different
  rungs) simply have no common scenario and the gate passes with a note.
* Degraded lines never gate.  A line is degraded when it carries a
  top-level ``degraded``/``fallback`` flag (bench.py contract) or a
  ``detail.fallback`` string (older rounds): the number was produced on
  a fallback rung, so comparing it against a healthy run is noise.
* When both lines embed the cost attribution block
  (``detail.telemetry.attribution``, docs/observability.md) and the
  analytical flops differ by >1%, the model genuinely changed between
  rounds even though the scenario label matched — skipped, not gated.
* Within a comparable pair, regression means
  ``new.value < old.value * (1 - noise)`` (default noise 0.20: CPU
  fallback hosts are shared and wobble; TPU rounds can pass a tighter
  ``--noise``).

Usage::

    python tools/perf_gate.py BENCH_r04.json BENCH_r05.json
    python tools/perf_gate.py            # auto: latest two BENCH_*.json
    python tools/perf_gate.py --self-test

Exit codes: 0 pass (or nothing comparable), 1 regression, 2 usage/IO.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any

DEFAULT_NOISE = 0.20
_FLOPS_DRIFT = 0.01


def load_results(path: str) -> list[dict[str, Any]]:
    """Bench lines out of one BENCH_*.json: the ``parsed`` wrapper, a raw
    result object, or a list of results."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        return [r for r in doc if isinstance(r, dict)]
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return [doc["parsed"]]
    if isinstance(doc, dict) and "metric" in doc:
        return [doc]
    return []


def scenario_key(result: dict[str, Any]) -> str:
    detail = result.get("detail") or {}
    return "{model}|{attention}|batch={batch}".format(
        model=detail.get("model", "?"),
        attention=detail.get("attention", "?"),
        batch=detail.get("batch", "?"),
    )


def is_degraded(result: dict[str, Any]) -> bool:
    if result.get("degraded") or result.get("fallback"):
        return True
    detail = result.get("detail") or {}
    return bool(detail.get("fallback"))


def _attribution_flops(result: dict[str, Any]) -> float | None:
    attr = ((result.get("detail") or {}).get("telemetry") or {}).get("attribution")
    if isinstance(attr, dict) and "flops" in attr:
        try:
            return float(attr["flops"])
        except (TypeError, ValueError):
            return None
    return None


def compare(
    old: list[dict[str, Any]],
    new: list[dict[str, Any]],
    *,
    noise: float = DEFAULT_NOISE,
) -> dict[str, Any]:
    """Pure comparison core (unit-tested; the CLI is a thin shell)."""
    old_by_key = {scenario_key(r): r for r in old if not is_degraded(r)}
    regressions: list[dict[str, Any]] = []
    compared: list[dict[str, Any]] = []
    skipped: list[str] = []
    for result in new:
        key = scenario_key(result)
        if is_degraded(result):
            skipped.append(f"{key}: new line degraded ({result.get('fallback') or 'detail.fallback'})")
            continue
        prev = old_by_key.get(key)
        if prev is None:
            skipped.append(f"{key}: no matching non-degraded scenario in old round")
            continue
        if result.get("metric") != prev.get("metric"):
            skipped.append(f"{key}: metric changed {prev.get('metric')} -> {result.get('metric')}")
            continue
        f_old, f_new = _attribution_flops(prev), _attribution_flops(result)
        if f_old and f_new and abs(f_new - f_old) / max(f_old, 1.0) > _FLOPS_DRIFT:
            skipped.append(
                f"{key}: analytical flops drifted {f_old:.3g} -> {f_new:.3g}; "
                "workload changed, not comparable"
            )
            continue
        old_v = float(prev.get("value", 0.0))
        new_v = float(result.get("value", 0.0))
        entry = {
            "scenario": key,
            "metric": result.get("metric"),
            "old": old_v,
            "new": new_v,
            "ratio": new_v / old_v if old_v else float("inf"),
        }
        compared.append(entry)
        if old_v > 0 and new_v < old_v * (1.0 - noise):
            regressions.append(entry)
    return {"compared": compared, "regressions": regressions, "skipped": skipped}


def _latest_pair(root: str) -> tuple[str, str] | None:
    def round_no(path: str) -> int:
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        return int(m.group(1)) if m else -1

    files = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")), key=round_no)
    if len(files) < 2:
        return None
    return files[-2], files[-1]


def _self_test() -> int:
    """Synthetic inject: a 50% drop must gate, a 2% wobble must not, and
    degraded / flops-drifted lines must be skipped."""
    base = {
        "metric": "tokens_per_sec_per_chip",
        "value": 1000.0,
        "detail": {
            "model": "gpt L2 d128 T128",
            "attention": "dense",
            "batch": 4,
            "telemetry": {"attribution": {"flops": 1.0e9}},
        },
    }

    def variant(**kw: Any) -> dict[str, Any]:
        out = json.loads(json.dumps(base))
        out.update({k: v for k, v in kw.items() if k != "flops"})
        if "flops" in kw:
            out["detail"]["telemetry"]["attribution"]["flops"] = kw["flops"]
        return out

    verdict = compare([base], [variant(value=500.0)])
    assert verdict["regressions"], "50% drop must gate"
    verdict = compare([base], [variant(value=980.0)])
    assert not verdict["regressions"] and verdict["compared"], "2% wobble must pass"
    verdict = compare([base], [variant(value=500.0, degraded=True, fallback="oom")])
    assert not verdict["regressions"] and verdict["skipped"], "degraded must skip"
    verdict = compare([base], [variant(value=500.0, flops=2.0e9)])
    assert not verdict["regressions"] and verdict["skipped"], "flops drift must skip"
    print("perf_gate self-test: OK")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("old", nargs="?", help="older BENCH_*.json")
    parser.add_argument("new", nargs="?", help="newer BENCH_*.json")
    parser.add_argument("--noise", type=float, default=DEFAULT_NOISE)
    parser.add_argument("--root", default=".", help="dir for auto-discovery")
    parser.add_argument("--self-test", action="store_true")
    args = parser.parse_args(argv)

    if args.self_test:
        return _self_test()

    if args.old and args.new:
        pair = (args.old, args.new)
    else:
        pair = _latest_pair(args.root)
        if pair is None:
            print("perf_gate: fewer than two BENCH_r*.json rounds; nothing to gate")
            return 0
    try:
        old, new = load_results(pair[0]), load_results(pair[1])
    except (OSError, json.JSONDecodeError) as exc:
        print(f"perf_gate: cannot read bench rounds: {exc}", file=sys.stderr)
        return 2

    verdict = compare(old, new, noise=args.noise)
    print(f"perf_gate: {pair[0]} -> {pair[1]} (noise bound {args.noise:.0%})")
    for entry in verdict["compared"]:
        flag = "REGRESSION" if entry in verdict["regressions"] else "ok"
        print(
            f"  [{flag}] {entry['scenario']}: {entry['old']:.1f} -> "
            f"{entry['new']:.1f} ({entry['ratio']:.2%} of old)"
        )
    for note in verdict["skipped"]:
        print(f"  [skip] {note}")
    if not verdict["compared"] and not verdict["skipped"]:
        print("  no bench lines found")
    if verdict["regressions"]:
        print(f"perf_gate: FAIL ({len(verdict['regressions'])} regression(s))")
        return 1
    print("perf_gate: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
