"""TPU liveness probe: backend init + compile + execute + host sync.

The single probe both chip gates use (tools/chip_watch.sh,
tools/run_chip_evidence.sh, tools/run_chip_phase2.sh), so a probe
hardening lands once. Backend init alone is NOT enough — r4 hit a
window where the backend came up but the tunnel's remote_compile
helper was dead (HTTP 500 / blocked sockets) and every armed step then
hung to its watchdog. Compiling and device_get-syncing a tiny jitted
matmul exercises the full path.

Exit 0 iff the chip is usable; nonzero (with a one-line reason on
stderr) otherwise. Callers wrap it in their own `timeout`.
"""

from __future__ import annotations

import sys


def main() -> int:
    try:
        import jax
        import jax.numpy as jnp

        if jax.default_backend() != "tpu":
            print(f"backend is {jax.default_backend()!r}, not tpu", file=sys.stderr)
            return 1
        x = jnp.ones((128, 128))
        got = float(jax.device_get(jax.jit(lambda a: a @ a)(x)[0, 0]))
        if got != 128.0:
            print(f"compile probe computed {got}, expected 128.0", file=sys.stderr)
            return 1
        return 0
    except Exception as exc:  # noqa: BLE001 — probe boundary
        print(f"probe failed: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
