"""Long-context training sweep: tokens/s + peak HBM across T.

VERDICT r2 #7: ring/Ulysses exist but the longest measured context was
4k on one chip. This sweeps single-chip T (16k-32k with remat + flash is
the target) and, with --mesh sequence=N, the SP paths on a virtual mesh.
Each cell runs a few real optimizer steps of a GPT sized to fit and
reports tokens/s, step time, and the device's peak_bytes_in_use.

Usage (repo root):

    python tools/bench_longctx.py                    # single-chip sweep
    python tools/bench_longctx.py --seqs 16384,32768 --batch 1
    JAX_PLATFORMS=cpu python tools/bench_longctx.py --seqs 1024 --cpu-smoke

Emits one JSON line per T.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def _peak_bytes() -> float:
    stats = jax.local_devices()[0].memory_stats() or {}
    return float(stats.get("peak_bytes_in_use", 0.0))


def _cell(seq: int, batch: int, *, attention: str, cpu_smoke: bool,
          steps: int) -> dict:
    from flax.linen import meta as nn_meta

    from llmtrain_tpu.config.schemas import RunConfig
    from llmtrain_tpu.models.gpt import GPTAdapter
    from llmtrain_tpu.training.optimizer import build_optimizer
    from llmtrain_tpu.training.train_step import create_train_state, make_train_step
    from llmtrain_tpu.utils.hw import mfu as compute_mfu

    if cpu_smoke:
        dims = dict(d_model=64, n_layers=2, n_heads=4, d_ff=128, vocab_size=256)
    else:  # GPT-2-small body, long context
        dims = dict(d_model=768, n_layers=12, n_heads=12, d_ff=3072,
                    vocab_size=50257)
    cfg = RunConfig.model_validate(
        {
            "run": {"name": f"lc{seq}", "device": "cpu" if cpu_smoke else "tpu"},
            "model": {
                "name": "gpt",
                "block_size": seq,
                "dropout": 0.0,
                "dtype": "float32" if cpu_smoke else "bfloat16",
                "attention": attention,
                "remat": True,
                "extra": {
                    "tokenizer": "byte",
                    "loss_impl": "chunked_ce",
                    "assume_packed": True,
                },
                **dims,
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "micro_batch_size": batch,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
            },
        }
    )
    adapter = GPTAdapter()
    model = adapter.build_model(cfg)
    tx = build_optimizer(cfg.trainer)
    rng = jax.random.key(0)
    params = nn_meta.unbox(adapter.init_params(model, cfg, rng))
    n_params = sum(int(np.prod(np.shape(x))) for x in jax.tree.leaves(params))
    state = create_train_state(params, tx)
    step_fn = jax.jit(
        make_train_step(adapter, model, tx, grad_accum_steps=1, use_dropout=False)
    )
    tokens = np.random.default_rng(0).integers(
        0, dims["vocab_size"], size=(1, batch, seq), dtype=np.int32
    )
    batch_dict = {
        "input_ids": jnp.asarray(tokens),
        "labels": jnp.asarray(tokens),
        "attention_mask": jnp.ones_like(jnp.asarray(tokens)),
    }
    t0 = time.perf_counter()
    state, metrics = step_fn(state, batch_dict, rng)
    jax.device_get(metrics["loss"])
    compile_s = time.perf_counter() - t0

    # Sync EVERY step via device_get and take the median: r4 on-chip found
    # that block_until_ready on the final loss under-measured T=4k by >2x
    # (mfu 3.78 — beyond the device's peak, i.e. impossible). On the
    # remote-tunnel axon platform block_until_ready can return before
    # execution finishes (same workaround as bench.py); device_get pulls
    # the scalar host-side, which cannot complete early. Pulling one f32
    # per step is a negligible transfer at these shapes.
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        state, metrics = step_fn(state, batch_dict, rng)
        jax.device_get(metrics["loss"])
        times.append(time.perf_counter() - t0)
    step_time = float(np.median(times))
    tokens_per_sec = batch * seq / step_time
    return {
        "seq": seq,
        "batch": batch,
        "attention": attention,
        "backend": jax.default_backend(),
        "step_time_s": round(step_time, 4),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "mfu": round(
            compute_mfu(tokens_per_sec, n_params=n_params,
                        n_layers=dims["n_layers"], seq_len=seq,
                        d_model=dims["d_model"]), 4,
        ),
        "peak_hbm_gb": round(_peak_bytes() / 2**30, 3),
        "compile_s": round(compile_s, 1),
        "loss": float(jax.device_get(metrics["loss"])),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="4096,8192,16384,32768")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--attention", default="flash")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--cpu-smoke", action="store_true")
    args = ap.parse_args()

    for seq in (int(s) for s in args.seqs.split(",")):
        try:
            row = _cell(seq, args.batch, attention=args.attention,
                        cpu_smoke=args.cpu_smoke, steps=args.steps)
        except Exception as exc:  # noqa: BLE001 — report OOM etc. per cell
            row = {"seq": seq, "batch": args.batch, "error": str(exc)[:200]}
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
