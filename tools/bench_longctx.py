"""Long-context training sweep: tokens/s + peak HBM across T.

VERDICT r2 #7: ring/Ulysses exist but the longest measured context was
4k on one chip. This sweeps single-chip T (16k-32k with remat + flash is
the target) and, with --mesh sequence=N, the SP paths on a virtual mesh.
Each cell runs a few real optimizer steps of a GPT sized to fit and
reports tokens/s, step time, and the device's peak_bytes_in_use.

Usage (repo root):

    python tools/bench_longctx.py                    # single-chip sweep
    python tools/bench_longctx.py --seqs 16384,32768 --batch 1
    JAX_PLATFORMS=cpu python tools/bench_longctx.py --seqs 1024 --cpu-smoke

Emits one JSON line per T.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")


def _peak_bytes() -> float:
    from llmtrain_tpu.utils.hw import peak_memory_bytes

    return peak_memory_bytes()


def _mem_keys() -> list[str]:
    from llmtrain_tpu.utils.hw import memory_stats_keys

    return memory_stats_keys()


def _cell(seq: int, batch: int, *, attention: str, cpu_smoke: bool,
          steps: int, window: int = 0) -> dict:
    from _bench_common import build_train_cell, make_batch, measure_cell
    from llmtrain_tpu.config.schemas import RunConfig
    from llmtrain_tpu.utils.hw import mfu as compute_mfu

    if cpu_smoke:
        dims = dict(d_model=64, n_layers=2, n_heads=4, d_ff=128, vocab_size=256)
    else:  # GPT-2-small body, long context
        dims = dict(d_model=768, n_layers=12, n_heads=12, d_ff=3072,
                    vocab_size=50257)
    cfg = RunConfig.model_validate(
        {
            "run": {"name": f"lc{seq}", "device": "cpu" if cpu_smoke else "tpu"},
            "model": {
                "name": "gpt",
                "block_size": seq,
                "dropout": 0.0,
                "dtype": "float32" if cpu_smoke else "bfloat16",
                "attention": attention,
                "remat": True,
                "extra": {
                    "tokenizer": "byte",
                    "loss_impl": "chunked_ce",
                    "assume_packed": True,
                    **({"sliding_window": window} if window else {}),
                },
                **dims,
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "micro_batch_size": batch,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
            },
        }
    )
    # Measurement discipline (device_get-synced median of per-step times)
    # lives in _bench_common.measure_cell: r4 on-chip found that blocking
    # only on the final loss under-measured T=4k by >2x (mfu 3.78 —
    # beyond the device's peak, i.e. impossible) because block_until_ready
    # can return early through the axon tunnel.
    step_fn, state, n_params = build_train_cell(cfg)
    batch_dict = make_batch(batch, seq, dims["vocab_size"])
    m = measure_cell(step_fn, state, batch_dict, steps)
    step_time = m["step_time_s"]
    tokens_per_sec = batch * seq / step_time
    # One memory_stats RPC; the note keys off the ROUNDED value actually
    # recorded, so a row can never read 0.0 without its diagnostic.
    peak_hbm_gb = round(_peak_bytes() / 2**30, 3)
    return {
        "seq": seq,
        "batch": batch,
        "attention": attention,
        "window": window,
        "backend": jax.default_backend(),
        "step_time_s": round(step_time, 4),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "mfu": round(
            compute_mfu(tokens_per_sec, n_params=n_params,
                        n_layers=dims["n_layers"], seq_len=seq,
                        d_model=dims["d_model"]), 4,
        ),
        "peak_hbm_gb": peak_hbm_gb,
        "compile_s": round(m["compile_s"], 1),
        "loss": m["loss"],
        # r4 chip windows recorded peak_hbm_gb 0.0 in every row; when that
        # happens again, record what the device DOES report so the failure
        # is diagnosable from the artifact alone.
        **(
            {}
            if peak_hbm_gb > 0
            else {"hbm_note": f"memory_stats keys: {_mem_keys()}"}
        ),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="4096,8192,16384,32768")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--attention", default="flash")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument(
        "--window", type=int, default=0,
        help="sliding-window size (0 = full causal); the O(T*W) cell",
    )
    ap.add_argument("--cpu-smoke", action="store_true")
    args = ap.parse_args()

    for seq in (int(s) for s in args.seqs.split(",")):
        try:
            row = _cell(seq, args.batch, attention=args.attention,
                        cpu_smoke=args.cpu_smoke, steps=args.steps,
                        window=args.window)
        except Exception as exc:  # noqa: BLE001 — report OOM etc. per cell
            row = {"seq": seq, "batch": args.batch, "error": str(exc)[:200]}
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
