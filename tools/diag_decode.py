"""Decode per-token cost attribution by ablation (VERDICT r3 #4).

The chip decode curve is nearly batch-flat (4.4-5.0 ms/token for MHA at
batch 1/8/32, chip_evidence_r4/decode.json), i.e. dominated by a
batch-independent term. Rather than eyeballing a profiler trace, this
tool attributes the per-token cost by differencing ablations of the REAL
decode path (generation.generate, one-scan KV decode):

* ``layers``: L=12 vs L=2 at fixed vocab — the slope is the
  per-transformer-layer cost (weights traffic + per-op latency);
  extrapolated to 12 layers it is the trunk's share.
* ``vocab``: V=50257 vs V=512 at fixed depth — the delta is the
  lm_head GEMV + (B, V) sampling share.
* ``sampler``: greedy vs top-k=40/top-p=0.9 — the sort/filter share
  (the benched sweep is greedy, so this is the serving-config delta).
* ``bf16 params``: cast float params to the model compute dtype —
  the candidate fix: decode of a bf16-compute model reads f32 weights
  today, paying 2x the weight bandwidth the math needs.

Whatever the four ablations do not explain is scan/dispatch overhead +
cache update traffic (reported as ``unattributed``).

Usage (repo root):

    python tools/diag_decode.py                  # TPU: GPT-2-small shape
    JAX_PLATFORMS=cpu python tools/diag_decode.py --cpu-smoke
    python tools/diag_decode.py --batches 1,32 --kv-heads 0,4

Emits one JSON line per cell plus an attribution summary per batch.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

from llmtrain_tpu.distributed import configure_platform  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    configure_platform("cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def _model(*, n_layers: int, vocab: int, n_kv_heads: int, cpu_smoke: bool):
    from llmtrain_tpu.models.gpt import GPT

    if cpu_smoke:
        kw = dict(block_size=128, d_model=64, n_heads=4, d_ff=128)
    else:
        kw = dict(block_size=1024, d_model=768, n_heads=12, d_ff=3072)
    return GPT(
        vocab_size=vocab,
        n_layers=n_layers,
        dropout=0.0,
        dtype=jnp.float32 if cpu_smoke else jnp.bfloat16,
        n_kv_heads=n_kv_heads,
        **kw,
    )


def _time_generate(
    model,
    params,
    batch: int,
    *,
    prompt_len: int,
    new_tokens: int,
    repeats: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
) -> float:
    from _bench_common import time_generate

    prompt = (
        np.random.default_rng(0)
        .integers(0, model.vocab_size, (batch, prompt_len))
        .astype(np.int32)
    )
    return time_generate(
        model, params, prompt, new_tokens=new_tokens, repeats=repeats,
        temperature=temperature, top_k=top_k, top_p=top_p,
    )


def _cast_params(params, dtype):
    return jax.tree.map(
        lambda a: a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a,
        params,
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="1,8,32")
    ap.add_argument("--kv-heads", default="0")
    ap.add_argument("--prompt-len", type=int, default=16)
    ap.add_argument("--new-tokens", type=int, default=256)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--cpu-smoke", action="store_true")
    args = ap.parse_args()
    if args.cpu_smoke:
        args.new_tokens = min(args.new_tokens, 32)

    full_layers = 2 if args.cpu_smoke else 12
    few_layers = 1 if args.cpu_smoke else 2
    full_vocab = 256 if args.cpu_smoke else 50257
    small_vocab = 64 if args.cpu_smoke else 512

    from flax.linen import meta as nn_meta

    for kvh in (int(x) for x in args.kv_heads.split(",")):
        variants = {
            "base": _model(n_layers=full_layers, vocab=full_vocab,
                           n_kv_heads=kvh, cpu_smoke=args.cpu_smoke),
            "shallow": _model(n_layers=few_layers, vocab=full_vocab,
                              n_kv_heads=kvh, cpu_smoke=args.cpu_smoke),
            "small_vocab": _model(n_layers=full_layers, vocab=small_vocab,
                                  n_kv_heads=kvh, cpu_smoke=args.cpu_smoke),
        }
        param_sets = {}
        for name, m in variants.items():
            p = m.init(
                jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                deterministic=True,
            )["params"]
            param_sets[name] = nn_meta.unbox(p)

        for b in (int(x) for x in args.batches.split(",")):
            kw = dict(prompt_len=args.prompt_len, new_tokens=args.new_tokens,
                      repeats=args.repeats)
            base = _time_generate(variants["base"], param_sets["base"], b, **kw)
            shallow = _time_generate(
                variants["shallow"], param_sets["shallow"], b, **kw
            )
            small_v = _time_generate(
                variants["small_vocab"], param_sets["small_vocab"], b, **kw
            )
            sampled = _time_generate(
                variants["base"], param_sets["base"], b,
                temperature=0.8, top_k=40, top_p=0.9, **kw
            )
            compute_dtype = variants["base"].dtype
            cast = _time_generate(
                variants["base"],
                _cast_params(param_sets["base"], compute_dtype), b, **kw
            )

            per_layer = (base - shallow) / (full_layers - few_layers)
            trunk = per_layer * full_layers
            head_and_sample = base - small_v
            row = {
                "backend": jax.default_backend(),
                "batch": b,
                "n_kv_heads": kvh,
                "n_layers": full_layers,
                "ms_per_token": {
                    "base_greedy": round(base, 3),
                    "topk_topp": round(sampled, 3),
                    "params_cast_to_compute_dtype": round(cast, 3),
                },
                "attribution_ms": {
                    f"trunk_{full_layers}L": round(trunk, 3),
                    "lm_head_plus_sampling": round(head_and_sample, 3),
                    "sampler_delta_topk_topp": round(sampled - base, 3),
                    "unattributed_scan_cache_overhead": round(
                        base - trunk - head_and_sample, 3
                    ),
                },
                "cast_win_pct": round(100 * (1 - cast / base), 1),
            }
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
