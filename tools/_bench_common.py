"""Shared single-cell measurement harness for the bench tools.

One place for the lessons the tunnel taught:

* ``block_until_ready`` can return before execution finishes on the
  remote-tunnel axon platform, so every timed step syncs by pulling the
  loss scalar host-side with ``device_get`` (r4: the old
  block-on-last-loss scheme produced an impossible mfu=3.78 cell).
* Per-step timing, median-of-steps — robust to a straggler dispatch.
* The jit train step donates the state buffers like the real Trainer.

``bench.py`` keeps its own copy of the pattern: it is the driver
contract file and must stay runnable standalone (the driver copies it
out of the repo); tools/ can share.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def build_train_cell(cfg: Any) -> tuple[Any, Any, int]:
    """(jitted step_fn, initial state, param count) for a RunConfig.

    The adapter comes from the registry (cfg.model.name), so the same
    cell harness measures any registered family (gpt, llama, ...)."""
    from flax.linen import meta as nn_meta

    from llmtrain_tpu.models.lora import build_adapter
    from llmtrain_tpu.registry import initialize_registries
    from llmtrain_tpu.training.optimizer import build_optimizer
    from llmtrain_tpu.training.train_step import create_train_state, make_train_step

    initialize_registries()
    # build_adapter: same factory the Trainer uses, so lora configs (and
    # any future adapter wrap) measure through the identical step.
    adapter = build_adapter(cfg)
    model = adapter.build_model(cfg)
    tx = build_optimizer(cfg.trainer)
    wrap_tx = getattr(adapter, "wrap_optimizer", None)
    if wrap_tx is not None:
        tx = wrap_tx(tx)
    params = nn_meta.unbox(adapter.init_params(model, cfg, jax.random.key(0)))
    n_params = sum(int(np.prod(np.shape(x))) for x in jax.tree.leaves(params))
    state = create_train_state(params, tx)
    step_fn = jax.jit(
        make_train_step(adapter, model, tx, grad_accum_steps=1, use_dropout=False),
        donate_argnums=(0,),
    )
    return step_fn, state, n_params


def make_batch(
    batch: int, seq: int, vocab: int, mask: np.ndarray | None = None
) -> dict[str, jnp.ndarray]:
    """A deterministic (1, batch, seq) accum-shaped batch dict."""
    tokens = np.random.default_rng(0).integers(
        0, vocab, size=(1, batch, seq), dtype=np.int32
    )
    arr = jnp.asarray(tokens)
    return {
        "input_ids": arr,
        "labels": arr,
        "attention_mask": jnp.asarray(mask) if mask is not None
        else jnp.ones_like(arr),
    }


def time_generate(
    model,
    params,
    prompt: np.ndarray,
    *,
    new_tokens: int,
    repeats: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
) -> float:
    """ms/token for one-scan KV-cache decode (best of ``repeats``).

    Shared by bench_decode and diag_decode so the decode measurement
    discipline lives in one place (np.asarray pulls the tokens host-side
    — the device_get-grade sync; see module docstring).
    """
    from llmtrain_tpu.generation import generate

    def run():
        return np.asarray(
            generate(
                model, params, prompt, max_new_tokens=new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                use_cache=True,
            )
        )

    run()  # compile
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    return min(times) / new_tokens * 1e3


def measure_cell(step_fn, state, batch_dict, steps: int) -> dict:
    """Compile, then time ``steps`` device_get-synced steps (median)."""
    rng = jax.random.key(0)
    t0 = time.perf_counter()
    state, metrics = step_fn(state, batch_dict, rng)
    jax.device_get(metrics["loss"])
    compile_s = time.perf_counter() - t0

    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        state, metrics = step_fn(state, batch_dict, rng)
        jax.device_get(metrics["loss"])
        times.append(time.perf_counter() - t0)
    return {
        "step_time_s": float(np.median(times)),
        "compile_s": compile_s,
        "loss": float(jax.device_get(metrics["loss"])),
    }
