"""Speculative-decoding throughput cells (speculative.py).

The realized speedup depends on draft agreement, which depends on the
trained pair — these cells measure the MECHANICS at GPT-2-small scale,
with per-cell acceptance stats so the number can be interpreted:

* ``self`` — draft == target: isolates the verify-loop overhead when
  the draft costs as much as the target (speedup < 1 by construction —
  the win requires a cheap draft).
* ``fresh`` — a ~25x-smaller randomly-initialized draft. CAVEAT:
  untrained models echo the previous token, so BOTH random models agree
  near-perfectly and this cell behaves like a cheap-draft best case
  (mean_accepted ≈ gamma), bounding the speedup a well-aligned trained
  pair could reach; realistic mid-range acceptance needs a trained
  target/draft pair (train one with configs/presets + --draft-config).

Usage (repo root):

    python tools/bench_speculative.py                 # TPU cells
    JAX_PLATFORMS=cpu python tools/bench_speculative.py --cpu-smoke

Emits one JSON line per cell (ms/token, speedup, target_forwards,
mean_accepted).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _models(cpu_smoke: bool):
    import jax.numpy as jnp
    from flax.linen import meta as nn_meta

    from llmtrain_tpu.models.gpt import GPT

    if cpu_smoke:
        tgt_kw = dict(block_size=128, d_model=64, n_layers=2, n_heads=4, d_ff=128)
        drf_kw = dict(block_size=128, d_model=32, n_layers=1, n_heads=4, d_ff=64)
        vocab = 256
    else:
        tgt_kw = dict(block_size=1024, d_model=768, n_layers=12, n_heads=12,
                      d_ff=3072)
        drf_kw = dict(block_size=1024, d_model=256, n_layers=2, n_heads=4,
                      d_ff=1024)
        vocab = 50257

    def build(kw, seed):
        m = GPT(vocab_size=vocab, dropout=0.0,
                dtype=jnp.float32 if cpu_smoke else jnp.bfloat16, **kw)
        p = nn_meta.unbox(
            m.init(jax.random.key(seed), jnp.zeros((1, 8), jnp.int32),
                   deterministic=True)["params"]
        )
        return m, p

    return build(tgt_kw, 0), build(drf_kw, 1), vocab


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--new-tokens", type=int, default=128)
    ap.add_argument("--gamma", type=int, default=4)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--cpu-smoke", action="store_true")
    args = ap.parse_args()
    if args.cpu_smoke:
        args.new_tokens = min(args.new_tokens, 24)

    from llmtrain_tpu.generation import generate
    from llmtrain_tpu.speculative import speculative_generate

    (tgt, tgt_p), (drf, drf_p), vocab = _models(args.cpu_smoke)
    prompt = np.random.default_rng(0).integers(
        0, vocab, (1, 16), dtype=np.int32
    )

    def timed(fn):
        fn()  # compile
        times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            np.asarray(fn())  # host sync
            times.append(time.perf_counter() - t0)
        return min(times)

    plain_s = timed(
        lambda: generate(tgt, tgt_p, prompt, max_new_tokens=args.new_tokens,
                         temperature=0.0, use_cache=True)
    )
    cells = {
        "self": (tgt, tgt_p),
        "fresh": (drf, drf_p),
    }
    rows = [{
        "cell": "plain", "backend": jax.default_backend(),
        "new_tokens": args.new_tokens,
        "ms_per_token": round(plain_s / args.new_tokens * 1e3, 3),
    }]
    print(json.dumps(rows[0]), flush=True)
    for name, (d, dp) in cells.items():
        try:
            spec_s = timed(
                lambda: speculative_generate(
                    tgt, tgt_p, d, dp, prompt,
                    max_new_tokens=args.new_tokens, gamma=args.gamma,
                )
            )
            _, stats = speculative_generate(
                tgt, tgt_p, d, dp, prompt, max_new_tokens=args.new_tokens,
                gamma=args.gamma, return_stats=True,
            )
            row = {
                "cell": f"speculative_{name}_draft",
                "backend": jax.default_backend(),
                "gamma": args.gamma,
                "new_tokens": args.new_tokens,
                "ms_per_token": round(spec_s / args.new_tokens * 1e3, 3),
                "speedup_vs_plain": round(plain_s / spec_s, 3),
                **stats,
            }
        except Exception as exc:  # noqa: BLE001 — per-cell isolation
            row = {"cell": f"speculative_{name}_draft", "error": str(exc)[:300]}
        rows.append(row)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
