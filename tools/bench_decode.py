"""Decode-scaling microbench: ms/step and tokens/s across batch sizes.

Diagnoses the KV-cache decode curve (RESULTS.md reported a non-monotone
ms/token at batch 1/8/32 in round 1) and measures the GQA narrow-cache
effect — n_kv_heads shrinks per-step K/V cache traffic by
n_heads/n_kv_heads, which is where small-batch decode spends its HBM
bandwidth.

Usage (repo root):

    python tools/bench_decode.py                       # default sweep
    python tools/bench_decode.py --batches 1,8,32 --kv-heads 0,4,1
    LLMTRAIN_PROFILE_DIR=/tmp/tr python tools/bench_decode.py  # + traces

Emits one JSON line per (batch, n_kv_heads) cell:
    {"batch": 8, "n_kv_heads": 0, "ms_per_step": ..., "tokens_per_sec": ...}
and a final summary line. Works on CPU (tiny model smoke) and TPU (the
real measurement — GPT-2-small shape).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from llmtrain_tpu.distributed import configure_platform

# Honour JAX_PLATFORMS=cpu BEFORE backend init: on hosts whose
# sitecustomize registers an accelerator plugin, the env var alone is
# not enough (and an unreachable accelerator tunnel hangs forever).
if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    configure_platform("cpu")


def _build_model(on_tpu: bool, n_kv_heads: int):
    from llmtrain_tpu.models.gpt import GPT

    if on_tpu:  # GPT-2-small shape, the RESULTS.md decode config
        kw = dict(vocab_size=50257, block_size=1024, d_model=768,
                  n_layers=12, n_heads=12, d_ff=3072)
    else:  # CPU smoke
        kw = dict(vocab_size=256, block_size=128, d_model=64,
                  n_layers=2, n_heads=4, d_ff=128)
    return GPT(
        dropout=0.0,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        n_kv_heads=n_kv_heads,
        **kw,
    )


def _bench_cell(model, params, batch: int, prompt_len: int, new_tokens: int,
                repeats: int) -> dict:
    from _bench_common import time_generate

    rng = np.random.default_rng(0)
    prompt = rng.integers(0, model.vocab_size, (batch, prompt_len)).astype(np.int32)
    ms_per_tok = time_generate(
        model, params, prompt, new_tokens=new_tokens, repeats=repeats
    )
    best = ms_per_tok * new_tokens / 1e3
    return {
        "ms_per_step": round(ms_per_tok, 3),
        "tokens_per_sec": round(batch * new_tokens / best, 1),
        "wall_s": round(best, 3),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="1,8,32")
    ap.add_argument("--kv-heads", default="0",
                    help="comma list; 0 = MHA, 1 = MQA, else GQA width")
    ap.add_argument("--prompt-len", type=int, default=16)
    ap.add_argument("--new-tokens", type=int, default=256)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    on_tpu = jax.default_backend() == "tpu"
    batches = [int(x) for x in args.batches.split(",")]
    kv_widths = [int(x) for x in args.kv_heads.split(",")]
    if not on_tpu:
        args.new_tokens = min(args.new_tokens, 32)

    profile_dir = os.environ.get("LLMTRAIN_PROFILE_DIR")
    rows = []
    for kvh in kv_widths:
        model = _build_model(on_tpu, kvh)
        params = model.init(
            jax.random.key(0),
            jnp.zeros((1, 8), jnp.int32),
            deterministic=True,
        )["params"]
        from flax.linen import meta as nn_meta

        params = nn_meta.unbox(params)
        for b in batches:
            if profile_dir:
                cell_dir = os.path.join(profile_dir, f"kv{kvh}_b{b}")
                with jax.profiler.trace(cell_dir):
                    cell = _bench_cell(
                        model, params, b, args.prompt_len,
                        args.new_tokens, args.repeats,
                    )
                cell["trace"] = cell_dir
            else:
                cell = _bench_cell(
                    model, params, b, args.prompt_len,
                    args.new_tokens, args.repeats,
                )
            row = {"backend": jax.default_backend(), "batch": b,
                   "n_kv_heads": kvh, **cell}
            rows.append(row)
            print(json.dumps(row), flush=True)

    print(json.dumps({"summary": rows}))


if __name__ == "__main__":
    main()
