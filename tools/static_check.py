"""Stdlib static gate fallback.

The real gate is ruff + mypy strict via pre-commit (parity with reference
.pre-commit-config.yaml:1-24). This image ships neither tool and installs
are forbidden, so `make lint` falls back to this checker: byte-compile
every source file, import every package module under the CPU backend, and
run a small AST lint (unused imports, mutable default args, bare excepts,
duplicate top-level definitions). Exit 0 = clean.
"""

from __future__ import annotations

import ast
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PACKAGE = "llmtrain_tpu"
LINT_ROOTS = [REPO / PACKAGE, REPO / "tests", REPO / "bench.py", REPO / "__graft_entry__.py"]

# Names imported for re-export or side effects (registry self-registration).
ALLOW_UNUSED_IN = {"__init__.py"}


def _py_files() -> list[Path]:
    files: list[Path] = []
    for root in LINT_ROOTS:
        if root.is_file():
            files.append(root)
        else:
            files.extend(sorted(root.rglob("*.py")))
    return files


def check_syntax(files: list[Path]) -> list[str]:
    errors = []
    for path in files:
        try:
            ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
        except SyntaxError as exc:
            errors.append(f"{path}:{exc.lineno}: syntax error: {exc.msg}")
    return errors


def check_imports() -> list[str]:
    """Import every package module: catches import-time breakage the way
    the reference's mypy run would catch missing symbols."""
    import importlib

    errors = []
    for path in sorted((REPO / PACKAGE).rglob("*.py")):
        rel = path.relative_to(REPO).with_suffix("")
        module = ".".join(rel.parts)
        if module.endswith(".__main__"):
            continue
        module = module.removesuffix(".__init__")
        try:
            importlib.import_module(module)
        except Exception as exc:  # noqa: BLE001 — report, don't crash the gate
            errors.append(f"{path}: import failed: {type(exc).__name__}: {exc}")
    return errors


class _Lint(ast.NodeVisitor):
    def __init__(self, path: Path, tree: ast.Module) -> None:
        self.path = path
        self.errors: list[str] = []
        self.imported: dict[str, int] = {}
        self.used: set[str] = set()
        self._collect(tree)

    def _collect(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = (alias.asname or alias.name).split(".")[0]
                    self.imported[name] = node.lineno
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.imported[alias.asname or alias.name] = node.lineno
            elif isinstance(node, ast.Name):
                self.used.add(node.id)
            elif isinstance(node, ast.Attribute):
                base = node
                while isinstance(base, ast.Attribute):
                    base = base.value
                if isinstance(base, ast.Name):
                    self.used.add(base.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_defaults(node)
            elif isinstance(node, ast.ExceptHandler) and node.type is None:
                self.errors.append(f"{self.path}:{node.lineno}: bare except")
        # __all__ strings count as usage.
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__" for t in node.targets)
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        self.used.add(elt.value)

    def _check_defaults(self, node: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        for default in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.errors.append(
                    f"{self.path}:{default.lineno}: mutable default argument "
                    f"in {node.name}()"
                )

    def unused_imports(self) -> list[str]:
        if self.path.name in ALLOW_UNUSED_IN:
            return []
        return [
            f"{self.path}:{lineno}: unused import {name!r}"
            for name, lineno in sorted(self.imported.items(), key=lambda kv: kv[1])
            if name not in self.used and not name.startswith("_")
        ]


def check_lint(files: list[Path]) -> list[str]:
    errors = []
    for path in files:
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
        except SyntaxError:
            continue  # reported by check_syntax
        lint = _Lint(path, tree)
        errors.extend(lint.errors)
        errors.extend(lint.unused_imports())
    return errors


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, str(REPO))  # script lives in tools/, package at repo root
    files = _py_files()
    errors = check_syntax(files)
    errors.extend(check_lint(files))
    if not errors:  # imports are meaningless if syntax/lint already failed
        errors.extend(check_imports())
    for err in errors:
        print(err)
    print(f"static_check: {len(files)} files, {len(errors)} problem(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
