"""Model-family throughput cells: gpt vs llama (vs qwen2 vs gemma).

The llama family (models/llama.py) shares the attention kernels and the
train step with gpt but differs where it costs: SwiGLU (3 MLP matmuls,
narrower d_ff for matched params), RMSNorm (no mean/bias), RoPE (two
elementwise rotations per layer vs one embedding add), untied head.
This tool measures whether those trades are throughput-neutral on chip:
one train cell per family at GPT-2-small-class size (d_ff 3072 GELU vs
2048 SwiGLU ≈ matched MLP params/FLOPs), same T/batch/loss path.

Usage (repo root):

    python tools/bench_family.py                 # TPU cells
    JAX_PLATFORMS=cpu python tools/bench_family.py --cpu-smoke

Emits one JSON line per family.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")


def _cell(family: str, *, cpu_smoke: bool, steps: int, batch: int) -> dict:
    from _bench_common import build_train_cell, make_batch, measure_cell
    from llmtrain_tpu.config.schemas import RunConfig
    from llmtrain_tpu.utils.hw import mfu as compute_mfu

    if cpu_smoke:
        dims = dict(d_model=64, n_layers=2, n_heads=4, vocab_size=256)
        seq = 128
        d_ff = 128 if family == "gpt" else 88  # gated MLPs: 3 matmuls
    else:
        dims = dict(d_model=768, n_layers=12, n_heads=12, vocab_size=50257)
        seq = 512
        # Matched MLP params: GELU 2·d·3072 ≈ SwiGLU 3·d·2048.
        d_ff = 3072 if family == "gpt" else 2048
    extra: dict = {"tokenizer": "byte"}
    if family != "gpt":
        # llama-stack families (llama/qwen2/gemma): GQA narrow K/V.
        extra["n_kv_heads"] = dims["n_heads"] // 3 if cpu_smoke else 4
    cfg = RunConfig.model_validate(
        {
            "run": {"name": f"fam-{family}", "device": "cpu" if cpu_smoke else "tpu"},
            "model": {
                "name": family,
                "block_size": seq,
                "d_ff": d_ff,
                "dropout": 0.0,
                "dtype": "float32" if cpu_smoke else "bfloat16",
                "attention": "flash",
                "extra": extra,
                **dims,
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": steps,
                "micro_batch_size": batch,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
                "log_every_steps": 10_000,
                "eval_every_steps": 10_000,
                "save_every_steps": 10_000,
            },
            "mlflow": {"enabled": False},
        }
    )
    step_fn, state, n_params = build_train_cell(cfg)
    batch_dict = make_batch(batch, seq, dims["vocab_size"])
    m = measure_cell(step_fn, state, batch_dict, steps)
    toks = batch * seq / m["step_time_s"]
    return {
        "family": family,
        "backend": jax.default_backend(),
        "params": n_params,
        "batch": batch,
        "seq": seq,
        "step_time_ms": round(m["step_time_s"] * 1e3, 2),
        "tokens_per_sec": round(toks, 1),
        "mfu": round(
            compute_mfu(
                toks,
                n_params=n_params,
                n_layers=dims["n_layers"],
                seq_len=seq,
                d_model=dims["d_model"],
            ),
            4,
        ),
        "compile_s": round(m["compile_s"], 1),
        "loss": m["loss"],
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--families", default="gpt,llama,qwen2,gemma")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=0, help="0 = auto per mode")
    ap.add_argument("--cpu-smoke", action="store_true")
    args = ap.parse_args()
    batch = args.batch or (4 if args.cpu_smoke else 64)
    steps = min(args.steps, 3) if args.cpu_smoke else args.steps
    for family in (f.strip() for f in args.families.split(",")):
        try:
            print(json.dumps(_cell(family, cpu_smoke=args.cpu_smoke,
                                   steps=steps, batch=batch)), flush=True)
        except Exception as exc:  # noqa: BLE001 — per-cell isolation
            print(json.dumps({"family": family, "error": str(exc)[:500]}),
                  flush=True)


if __name__ == "__main__":
    main()
