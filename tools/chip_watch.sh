#!/usr/bin/env bash
# Probe the TPU tunnel in a loop and fire a command at the first live
# probe. The axon tunnel comes and goes (r2-r3: down for whole rounds;
# r4: one ~35-min window) — evidence runs must be armed, not manual.
#
#     bash tools/chip_watch.sh                          # default: phase 2
#     bash tools/chip_watch.sh 'python bench.py'        # any command
#     CHIP_WATCH_PROBES=50 CHIP_WATCH_SLEEP=60 bash tools/chip_watch.sh
#
# Runs in the foreground; nohup it for unattended arming:
#     nohup bash tools/chip_watch.sh > chip_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
CMD="${1:-bash tools/run_chip_phase2.sh chip_evidence_p2}"
PROBES="${CHIP_WATCH_PROBES:-200}"
SLEEP="${CHIP_WATCH_SLEEP:-120}"

for i in $(seq 1 "$PROBES"); do
    # tools/tpu_probe.py (shared with the runbooks): backend init +
    # compile + sync, so a dead remote_compile helper doesn't arm a
    # runbook whose every step hangs (r4).
    if timeout 180 python tools/tpu_probe.py >/dev/null 2>&1; then
        echo "[chip-watch] tunnel live at $(date -u +%H:%M:%S); running: $CMD"
        eval "$CMD"
        rc=$?
        # rc=1 is the runbook's own probe failing — the tunnel flapped
        # between our probe and its re-probe. Keep watching; any other
        # exit means the run actually fired, so stand down.
        if [ "$rc" -ne 1 ]; then
            exit "$rc"
        fi
        echo "[chip-watch] command probe-failed (tunnel flap); resuming watch"
    fi
    echo "[chip-watch] probe $i/$PROBES failed at $(date -u +%H:%M:%S); sleeping ${SLEEP}s"
    sleep "$SLEEP"
done
echo "[chip-watch] gave up after $PROBES probes"
exit 1
