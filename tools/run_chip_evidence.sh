#!/usr/bin/env bash
# The on-chip evidence runbook (RESULTS.md "Pending on-chip measurement"),
# as one command for the first session with a live TPU tunnel:
#
#     bash tools/run_chip_evidence.sh [outdir]
#
# Probes the backend first with a hard timeout (the axon tunnel's failure
# mode is an indefinite backend-init hang, never an exception), then runs
# each step with its own timeout so one hang cannot eat the session.
# Artifacts land in <outdir> (default chip_evidence/): bench JSON, pytest
# logs, decode + long-context sweeps. Steps degrade independently — a
# failed step writes its log and the script continues.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-chip_evidence}"
mkdir -p "$OUT"

log() { echo "[chip-evidence] $*" >&2; }

log "probing TPU backend (240s timeout)..."
if ! timeout 240 python -c "import jax; assert jax.default_backend() == 'tpu'" \
    >"$OUT/probe.log" 2>&1; then
    log "TPU backend unreachable — aborting (see $OUT/probe.log)"
    exit 1
fi
log "TPU live."

log "1/5 bench.py (auto-sweep; watchdogged internally)..."
python bench.py >"$OUT/bench.json" 2>"$OUT/bench.log" || log "bench failed"
tail -1 "$OUT/bench.json" || true

log "2/5 compiled-kernel suite (masks, GQA, bf16 bwd, chunked CE)..."
# LLMTRAIN_TEST_TPU=1 is the conftest escape hatch — without it the suite
# forces the hermetic CPU mesh and every TPU-gated test skips.
timeout 2400 env LLMTRAIN_TEST_TPU=1 python -m pytest tests/test_tpu_compiled.py -v \
    >"$OUT/tpu_compiled.log" 2>&1 || log "compiled suite failed/partial"
tail -2 "$OUT/tpu_compiled.log" || true

log "3/5 decode scaling sweep (batch x kv-heads)..."
timeout 2400 python tools/bench_decode.py --batches 1,8,32 --kv-heads 0,4,1 \
    >"$OUT/decode.json" 2>"$OUT/decode.log" || log "decode sweep failed/partial"

log "4/5 long-context sweep (T=4k..32k)..."
timeout 3600 python tools/bench_longctx.py \
    >"$OUT/longctx.json" 2>"$OUT/longctx.log" || log "longctx sweep failed/partial"

log "5/5 BPE headline train (gpt_pycorpus_bpe_tpu, needs runs/pytok8k.json)..."
if [ ! -f runs/pytok8k.json ]; then
    CORPUS="${CORPUS:-$(python -c 'import sysconfig; print(sysconfig.get_paths()["stdlib"])')}"
    if [ ! -d "$CORPUS" ]; then
        log "ERROR: tokenizer corpus '$CORPUS' not found — set CORPUS=<dir>"
    else
        timeout 1200 python -m llmtrain_tpu train-tokenizer \
            --input "$CORPUS" --vocab-size 8192 \
            --output runs/pytok8k.json >"$OUT/tokenizer.log" 2>&1 \
            || log "tokenizer training failed"
    fi
fi
if [ -f runs/pytok8k.json ]; then
    timeout 5400 python -m llmtrain_tpu train \
        --config configs/presets/gpt_pycorpus_bpe_tpu.yaml \
        --run-id chip-evidence-bpe --json \
        >"$OUT/bpe_headline.json" 2>"$OUT/bpe_headline.log" \
        || log "BPE headline failed/partial"
else
    log "no tokenizer file — skipping BPE headline train"
fi

log "done — artifacts in $OUT/. Fold the numbers into RESULTS.md."
