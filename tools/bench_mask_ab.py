"""Masked-vs-packed A/B + GQA narrow-K/V train-step deltas — on one chip.

VERDICT r3 weak #6: the headline bench deliberately runs the packed fast
path (``assume_packed: True`` drops the mask operand from the Pallas
flash kernels), so the in-kernel padding masks added in round 3
(ops/pallas_attention.py) never get a measured cost, and the native GQA
grouping never gets a measured train-step benefit. This tool measures
both at the bench shape:

* packed vs masked: identical config except ``assume_packed`` — the
  delta is the mask-operand overhead (mask loads + select in-kernel).
* ``--kv-heads`` sweep: full MHA vs GQA vs MQA train step — the delta is
  the narrow-K/V saving (smaller K/V projections + kernel reads).

Usage (repo root, TPU):

    python tools/bench_mask_ab.py                 # bench shape, all cells
    python tools/bench_mask_ab.py --batch 16 --steps 5
    JAX_PLATFORMS=cpu python tools/bench_mask_ab.py --cpu-smoke

Emits one JSON line per cell. Sync via device_get (bench.py's tunnel
workaround — block_until_ready can return early through axon).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _cell(
    *,
    batch: int,
    seq: int,
    steps: int,
    assume_packed: bool,
    n_kv_heads: int,
    cpu_smoke: bool,
) -> dict:
    from _bench_common import build_train_cell, make_batch, measure_cell
    from llmtrain_tpu.config.schemas import RunConfig
    from llmtrain_tpu.utils.hw import mfu as compute_mfu

    if cpu_smoke:
        dims = dict(d_model=64, n_layers=2, n_heads=4, d_ff=128, vocab_size=256)
    else:  # the headline bench shape (bench.py)
        dims = dict(d_model=768, n_layers=12, n_heads=12, d_ff=3072,
                    vocab_size=50257)
    extra: dict = {"tokenizer": "byte", "assume_packed": assume_packed}
    if n_kv_heads:
        extra["n_kv_heads"] = n_kv_heads
    cfg = RunConfig.model_validate(
        {
            "run": {"name": "mask-ab", "device": "cpu" if cpu_smoke else "tpu"},
            "model": {
                "name": "gpt",
                "block_size": seq,
                "dropout": 0.0,
                "dtype": "float32" if cpu_smoke else "bfloat16",
                "attention": "flash",
                "extra": extra,
                **dims,
            },
            "data": {"name": "dummy_text"},
            "trainer": {"micro_batch_size": batch, "grad_accum_steps": 1,
                        "warmup_steps": 0},
        }
    )
    step_fn, state, n_params = build_train_cell(cfg)
    mask = np.ones((1, batch, seq), dtype=np.int32)
    if not assume_packed:
        # Realistic padded batch: tails of varying length are masked out,
        # so the masked cell actually exercises the mask operand's effect
        # (an all-ones mask would measure the load but not the selects'
        # worst case; padding also matches the fine-tuning workload this
        # path exists for).
        pad = np.linspace(0, seq // 4, num=batch, dtype=np.int64)
        for i, p in enumerate(pad):
            if p:
                mask[0, i, seq - int(p):] = 0
    batch_dict = make_batch(batch, seq, dims["vocab_size"], mask=mask)

    m = measure_cell(step_fn, state, batch_dict, steps)
    step_time = m["step_time_s"]
    tokens_per_sec = batch * seq / step_time
    return {
        "cell": ("packed" if assume_packed else "masked")
        + (f"+gqa{n_kv_heads}" if n_kv_heads else ""),
        "backend": jax.default_backend(),
        "batch": batch,
        "seq": seq,
        "n_kv_heads": n_kv_heads or dims["n_heads"],
        "assume_packed": assume_packed,
        "params": n_params,
        "step_time_ms": round(step_time * 1e3, 2),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "mfu": round(
            compute_mfu(tokens_per_sec, n_params=n_params,
                        n_layers=dims["n_layers"], seq_len=seq,
                        d_model=dims["d_model"]), 4,
        ),
        "compile_s": round(m["compile_s"], 1),
        "loss": m["loss"],
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--kv-heads", default="0,4",
                    help="comma list; 0 = full MHA (A/B runs per value)")
    ap.add_argument("--cpu-smoke", action="store_true")
    args = ap.parse_args()
    if args.cpu_smoke:
        args.batch, args.seq = 4, 128

    rows = []
    for kv in (int(s) for s in args.kv_heads.split(",")):
        for packed in (True, False):
            try:
                row = _cell(batch=args.batch, seq=args.seq, steps=args.steps,
                            assume_packed=packed, n_kv_heads=kv,
                            cpu_smoke=args.cpu_smoke)
            except Exception as exc:  # noqa: BLE001 — report OOM etc. per cell
                row = {"cell": f"{'packed' if packed else 'masked'}+kv{kv}",
                       "error": str(exc)[:200]}
            rows.append(row)
            print(json.dumps(row), flush=True)

    ok = [r for r in rows if "error" not in r]
    by = {r["cell"]: r["step_time_ms"] for r in ok}
    summary: dict = {}
    # Mask-operand overhead per kv width (masked vs packed, same kv).
    suffixes = {c[len("packed"):] for c in by if c.startswith("packed")}
    for sfx in sorted(suffixes):
        p, m_ = by.get(f"packed{sfx}"), by.get(f"masked{sfx}")
        if p and m_:
            summary[f"mask_overhead_pct{sfx or '+mha'}"] = round(
                100 * (m_ / p - 1), 2
            )
    # Narrow-K/V train-step delta per kv width (gqa vs MHA, packed path).
    if "packed" in by:
        for cell, t in by.items():
            if cell.startswith("packed+gqa"):
                summary[f"gqa_speedup_pct{cell[len('packed'):]}"] = round(
                    100 * (by["packed"] / t - 1), 2
                )
    if summary:
        print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
