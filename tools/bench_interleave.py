"""Interleaved-schedule overhead measurement: v=1 vs v=2 at fixed S, M.

The interleaved (Megatron-style) pipeline schedule shrinks the bubble
from (S-1)/(M+S-1) to (S-1)/(v·M+S-1) at the cost of v× activation hops
and a per-step parameter re-permutation (parallel/pipeline.py). On a
virtual CPU mesh the stage programs serialize, so wall-clock here
measures ONLY the overhead side — extra hops + re-permutation — with the
bubble savings invisible (they need real parallel hardware). That is the
quantity VERDICT r2 #9 asks about: whether the re-permutation cost could
eat the bubble savings.

Usage (repo root):  python tools/bench_interleave.py [--steps 16]

Emits one JSON line per v with steady-state step time, plus theoretical
bubble fractions for context.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from llmtrain_tpu.config import RunConfig  # noqa: E402
from llmtrain_tpu.registry import initialize_registries  # noqa: E402
from llmtrain_tpu.training.trainer import Trainer  # noqa: E402

S, M, L = 4, 4, 8  # stages, microbatches, layers


class _Recorder:
    """Tracker protocol impl that keeps step-time metrics in memory."""

    def __init__(self) -> None:
        self.step_times: list[tuple[int, float]] = []

    def start_run(self, run_id, run_name=None):
        pass

    def log_params(self, params):
        pass

    def log_metrics(self, metrics, step=None):
        if "train/step_time_sec" in metrics:
            self.step_times.append((step, metrics["train/step_time_sec"]))

    def log_artifact(self, local_path, artifact_path=None):
        pass

    def end_run(self, status="FINISHED"):
        pass


def _cfg(v: int, steps: int) -> RunConfig:
    return RunConfig.model_validate(
        {
            "run": {"name": f"ilv{v}", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt_pipeline",
                "block_size": 64,
                "d_model": 64,
                "n_layers": L,
                "n_heads": 4,
                "d_ff": 256,
                "dropout": 0.0,
                "vocab_size": 256,
                "extra": {
                    "tokenizer": "byte",
                    "pipeline_microbatches": M,
                    "pipeline_virtual_chunks": v,
                },
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": steps,
                "micro_batch_size": 8,
                "grad_accum_steps": 1,
                "warmup_steps": 2,
                "log_every_steps": 4,
                "eval_every_steps": 10_000,
                "save_every_steps": 10_000,
            },
            "distributed": {"enabled": False, "mesh": {"pipeline": S, "data": 2}},
            "mlflow": {"enabled": False},
        }
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=16)
    args = ap.parse_args()

    initialize_registries()
    rows = []
    for v in (1, 2):
        rec = _Recorder()
        Trainer(_cfg(v, args.steps), None, rec).fit()
        # First interval includes compile; steady state = the rest.
        steady = [t for _, t in rec.step_times[1:]] or [rec.step_times[-1][1]]
        row = {
            "virtual_chunks": v,
            "steady_step_time_s": round(min(steady), 4),
            "all_intervals_s": [round(t, 4) for _, t in rec.step_times],
            "theoretical_bubble": round((S - 1) / (v * M + S - 1), 4),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    v1, v2 = rows[0]["steady_step_time_s"], rows[1]["steady_step_time_s"]
    print(
        json.dumps(
            {
                "overhead_v2_vs_v1": round(v2 / v1 - 1.0, 4),
                "note": (
                    "CPU mesh serializes stages: this is the pure overhead of "
                    "interleaving (extra hops + param re-permutation); bubble "
                    "savings (theoretical_bubble column) need real hardware"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
