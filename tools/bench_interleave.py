"""Interleaved-schedule measurement + per-phase attribution: v=1 vs v=2.

The interleaved (Megatron-style) pipeline schedule shrinks the bubble
from (S-1)/(M+S-1) to (S-1)/(v·M+S-1) at the cost of v× activation hops
and a per-step parameter re-permutation (parallel/pipeline.py).

Why v=2 is FASTER even on a serialized CPU mesh (the round-3 "anomaly",
VERDICT r3 weak #4): in this SPMD design the whole schedule is one
``lax.scan`` and EVERY device executes a chunk on EVERY tick — bubble
ticks compute garbage instead of idling. Per device and step that is
ticks × layers_per_chunk = (v·M+S-1) · L/(S·v) layer applications, of
which only M·L/S are useful; the wasted fraction equals the theoretical
bubble fraction exactly. At S=4, M=4, L=8: v=1 runs 7·2 = 14 layer
applications, v=2 runs 11·1 = 11 — interleaving cuts per-device compute
by 21%, which is visible on a serialized mesh (and on real hardware,
where it is the bubble saving realized as fewer wasted FLOPs). The
measured v=2 speedup being smaller than 21% quantifies the overhead side
(re-permutation + extra hops).

``--attribute`` measures the phases directly on a forward pass:
  * skeleton   — stage_fn replaced by identity: scan + ppermute hops +
                 buffer writes + chunk param slicing, no compute
  * perm       — full(v=2) minus full(v=2 with identity permutation):
                 the per-step parameter re-permutation gather
  * compute    — full minus skeleton (minus perm for v=2); its v2/v1
                 ratio should track the predicted 11/14

Usage (repo root):  python tools/bench_interleave.py [--steps 16]
                        [--no-trainer] [--attribute]

Emits one JSON line per v with steady-state Trainer step time, then the
attribution table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from llmtrain_tpu.config import RunConfig  # noqa: E402
from llmtrain_tpu.registry import initialize_registries  # noqa: E402
from llmtrain_tpu.training.trainer import Trainer  # noqa: E402

S, M, L = 4, 4, 8  # stages, microbatches, layers


class _Recorder:
    """Tracker protocol impl that keeps step-time metrics in memory."""

    def __init__(self) -> None:
        self.step_times: list[tuple[int, float]] = []

    def start_run(self, run_id, run_name=None):
        pass

    def log_params(self, params):
        pass

    def log_metrics(self, metrics, step=None):
        if "train/step_time_sec" in metrics:
            self.step_times.append((step, metrics["train/step_time_sec"]))

    def log_artifact(self, local_path, artifact_path=None):
        pass

    def end_run(self, status="FINISHED"):
        pass


def _cfg(v: int, steps: int) -> RunConfig:
    return RunConfig.model_validate(
        {
            "run": {"name": f"ilv{v}", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt_pipeline",
                "block_size": 64,
                "d_model": 64,
                "n_layers": L,
                "n_heads": 4,
                "d_ff": 256,
                "dropout": 0.0,
                "vocab_size": 256,
                "extra": {
                    "tokenizer": "byte",
                    "pipeline_microbatches": M,
                    "pipeline_virtual_chunks": v,
                },
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": steps,
                "micro_batch_size": 8,
                "grad_accum_steps": 1,
                "warmup_steps": 2,
                "log_every_steps": 4,
                "eval_every_steps": 10_000,
                "save_every_steps": 10_000,
            },
            "distributed": {"enabled": False, "mesh": {"pipeline": S, "data": 2}},
            "mlflow": {"enabled": False},
        }
    )


def _median_time(fn, *operands, repeats: int = 30) -> float:
    """Median wall seconds of ``jax.device_get(fn(*operands))``."""
    import time

    for _ in range(3):
        jax.device_get(fn(*operands))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.device_get(fn(*operands))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def _attribution(repeats: int) -> dict:
    """Per-phase forward-pass timing of the gpipe schedule at S, M, L."""
    import numpy as np

    from llmtrain_tpu.models.gpt_pipeline import make_stage_fn
    from llmtrain_tpu.parallel import pipeline as pp

    d_model, n_heads, d_ff, seq, batch = 64, 4, 256, 64, 8
    d_head = d_model // n_heads
    mesh = jax.sharding.Mesh(
        np.array(jax.devices()[: S * 2]).reshape(S, 2), ("pipeline", "data")
    )
    rng = np.random.default_rng(0)

    def leaf(*shape):
        return jnp.asarray(rng.normal(0, 0.02, size=(L, *shape)), jnp.float32)

    params = {
        "ln1_scale": jnp.ones((L, d_model)),
        "ln1_bias": jnp.zeros((L, d_model)),
        "qkv_kernel": leaf(d_model, 3, n_heads, d_head),
        "qkv_bias": jnp.zeros((L, 3, n_heads, d_head)),
        "out_kernel": leaf(n_heads, d_head, d_model),
        "out_bias": jnp.zeros((L, d_model)),
        "ln2_scale": jnp.ones((L, d_model)),
        "ln2_bias": jnp.zeros((L, d_model)),
        "fc_kernel": leaf(d_model, d_ff),
        "fc_bias": jnp.zeros((L, d_ff)),
        "proj_kernel": leaf(d_ff, d_model),
        "proj_bias": jnp.zeros((L, d_model)),
    }
    x = jnp.asarray(rng.normal(size=(batch, seq, d_model)), jnp.float32)
    stage_fn = make_stage_fn(attention="dense", dtype=jnp.float32)

    def identity_stage(p, h, key_mask=None):
        return h

    def run(fn, v):
        return jax.jit(
            lambda p, xx: pp.gpipe_apply(
                fn, p, xx, mesh, n_microbatches=M, virtual_chunks=v,
                remat_stage=False,
            )
        )

    real_perm = pp._interleave_permutation
    identity_perm = lambda n, s, v: np.arange(n, dtype=np.int32)  # noqa: E731

    out: dict = {}
    try:
        full = {v: _median_time(run(stage_fn, v), params, x, repeats=repeats)
                for v in (1, 2)}
        pp._interleave_permutation = identity_perm
        noperm_v2 = _median_time(run(stage_fn, 2), params, x, repeats=repeats)
        skeleton = {v: _median_time(run(identity_stage, v), params, x,
                                    repeats=repeats)
                    for v in (1, 2)}
    finally:
        pp._interleave_permutation = real_perm

    compute = {1: full[1] - skeleton[1], 2: noperm_v2 - skeleton[2]}
    apps = {v: (v * M + S - 1) * L // (S * v) for v in (1, 2)}
    out["phases"] = {
        f"v{v}": {
            "full_s": round(full[v], 5),
            "skeleton_s": round(skeleton[v], 5),
            "compute_s": round(compute[v], 5),
            "ticks": v * M + S - 1,
            "layer_apps_per_device": apps[v],
        }
        for v in (1, 2)
    }
    out["phases"]["v2"]["perm_s"] = round(full[2] - noperm_v2, 5)
    out["predicted_compute_ratio_v2_v1"] = round(apps[2] / apps[1], 4)
    out["measured_compute_ratio_v2_v1"] = (
        round(compute[2] / compute[1], 4) if compute[1] > 0 else None
    )
    out["note"] = (
        "every device executes a chunk on EVERY tick, so bubble ticks are "
        "wasted compute, not idle time; v=2's fewer layer-applications "
        "(11 vs 14 here) explain its speedup even on a serialized mesh"
    )
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--no-trainer", action="store_true",
                    help="skip the Trainer-level step timing")
    ap.add_argument("--attribute", action="store_true",
                    help="per-phase forward timing (skeleton/perm/compute)")
    ap.add_argument("--repeats", type=int, default=30)
    args = ap.parse_args()

    initialize_registries()
    if not args.no_trainer:
        rows = []
        for v in (1, 2):
            rec = _Recorder()
            Trainer(_cfg(v, args.steps), None, rec).fit()
            # First interval includes compile; steady state = the rest.
            steady = [t for _, t in rec.step_times[1:]] or [rec.step_times[-1][1]]
            row = {
                "virtual_chunks": v,
                "steady_step_time_s": round(min(steady), 4),
                "all_intervals_s": [round(t, 4) for _, t in rec.step_times],
                "theoretical_bubble": round((S - 1) / (v * M + S - 1), 4),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)

        v1, v2 = rows[0]["steady_step_time_s"], rows[1]["steady_step_time_s"]
        apps = {v: (v * M + S - 1) * L // (S * v) for v in (1, 2)}
        print(
            json.dumps(
                {
                    "speedup_v2_vs_v1": round(1.0 - v2 / v1, 4),
                    "predicted_from_layer_apps": round(1.0 - apps[2] / apps[1], 4),
                    "note": (
                        "bubble ticks execute garbage compute in this design, "
                        "so interleaving's saving is visible even on a "
                        "serialized mesh; see --attribute for phase split"
                    ),
                }
            ),
            flush=True,
        )

    if args.attribute:
        print(json.dumps({"attribution": _attribution(args.repeats)}), flush=True)


if __name__ == "__main__":
    main()
