"""Summarize a chip-evidence artifact dir into RESULTS-ready markdown.

The phase-2 runbook (tools/run_chip_phase2.sh) drops one JSON/log file
per step into its output dir; whoever folds the numbers into RESULTS.md
has to re-derive what each file means. This prints a markdown block per
artifact found — bench line, longctx table, decode sweep, mask A/B,
family cells, speculative bounds, compiled-suite tail — skipping files
that are absent or hold only error rows (named explicitly, so a silent
gap cannot read as "covered").

Usage (repo root):

    python tools/fold_chip_evidence.py [chip_evidence_p2]
"""

from __future__ import annotations

import json
import sys
from pathlib import Path


def _rows(path: Path) -> list[dict]:
    rows = []
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return rows


def _table(rows: list[dict], cols: list[str]) -> str:
    head = "| " + " | ".join(cols) + " |"
    sep = "|" + "|".join("---" for _ in cols) + "|"
    body = [
        "| " + " | ".join(str(r.get(c, "—")) for c in cols) + " |"
        for r in rows
    ]
    return "\n".join([head, sep, *body])


def main() -> None:
    out = Path(sys.argv[1] if len(sys.argv) > 1 else "chip_evidence_p2")
    if not out.is_dir():
        print(f"no artifact dir {out}", file=sys.stderr)
        raise SystemExit(1)

    sections: list[str] = []
    missing: list[str] = []

    def handle(name: str, title: str, cols: list[str] | None = None):
        path = out / name
        if not path.exists():
            missing.append(name)
            return
        rows = _rows(path)
        good = [r for r in rows if "error" not in r]
        bad = [r for r in rows if "error" in r]
        parts = [f"### {title} (`{name}`)"]
        if good:
            parts.append(
                _table(good, cols or sorted({k for r in good for k in r}))
            )
        if bad:
            parts.append(
                f"{len(bad)} errored cell(s): "
                + "; ".join(
                    f"{r.get('cell', r.get('seq', '?'))}: {str(r['error'])[:80]}"
                    for r in bad
                )
            )
        if not rows:
            parts.append("(no JSON rows — see the matching .log)")
        sections.append("\n\n".join(parts))

    handle("bench.json", "Bench (window-1 runbook name)")
    handle("bench_sweep.json", "Bench auto-sweep")
    handle("bench_c128.json", "Chunked-CE batch-128 cell")
    handle(
        "decode.json", "Decode sweep (window-1 runbook name)",
        ["batch", "n_kv_heads", "ms_per_step", "tokens_per_sec"],
    )
    handle(
        "longctx.json", "Long context",
        ["seq", "batch", "window", "tokens_per_sec", "mfu", "peak_hbm_gb"],
    )
    handle(
        "longctx_window.json", "Windowed long context",
        ["seq", "batch", "window", "tokens_per_sec", "mfu", "peak_hbm_gb"],
    )
    handle(
        "mask_ab.json", "Masked vs assume_packed A/B",
        ["cell", "tokens_per_sec", "mfu", "step_time_ms"],
    )
    handle(
        "diag_decode.json", "Decode attribution",
        ["batch", "n_kv_heads", "ms_per_token", "attribution_ms"],
    )
    handle(
        "family.json", "Family cells (gpt/llama/qwen2/gemma)",
        ["family", "tokens_per_sec", "mfu", "step_time_ms", "params"],
    )
    handle(
        "speculative.json", "Speculative bounds",
        ["cell", "ms_per_token", "speedup_vs_plain", "mean_accepted"],
    )
    handle(
        "lora_ab.json", "LoRA vs full fine-tune A/B",
        ["cell", "trainable_params", "tokens_per_sec", "step_time_ms"],
    )
    handle("bpe_headline.json", "BPE headline train")

    compiled = out / "tpu_compiled.log"
    if compiled.exists():
        tail = compiled.read_text().splitlines()[-1:]
        sections.append("### Compiled-kernel suite\n\n```\n" + "\n".join(tail) + "\n```")
    else:
        missing.append("tpu_compiled.log")

    print(f"## Chip evidence from `{out}/`\n")
    print("\n\n".join(sections))
    if missing:
        print(
            "\n\nNOT COVERED (file absent): " + ", ".join(sorted(missing)),
        )


if __name__ == "__main__":
    main()
