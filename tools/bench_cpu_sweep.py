"""Sweep CPU-fallback bench geometries: pick the shape bench.py uses.

bench.py's forced-CPU child must clear the 0.30-MFU bar against the
nominal 2e11 FLOP/s CPU peak (utils/hw.py) on whatever host the round
driver lands on. This sweep reproduces how the committed shape
(L2 d1280 h8 ff5120 V1024 T128 B16) was chosen in round 5: wide blocks
keep a single core's FMA pipes busy where the old L2/d128 smoke shape
measured only 0.17-0.23 across rounds 2-4. Measured landscape on the
round-5 1-core host (MFU): d128 0.17, d256 0.22, d512 0.28, d768 0.30,
d1024 0.25 (weights fall out of cache at L2), d1280 0.37 (best, both
L1 and L2), d1536 0.33. Full methodology note in bench.py.

Always pins the CPU backend — the point is the CPU-fallback landscape,
never whatever accelerator the host has. Uses the shared tools/ cell
harness (build_train_cell / measure_cell: median of device_get-synced
per-step times), so its timing discipline matches the other sweeps.

Usage (repo root, ~2-4 min per shape on one core):

    python tools/bench_cpu_sweep.py
    python tools/bench_cpu_sweep.py --shapes 1280,2,16 1536,2,8

Each --shapes entry is d_model,depth,batch (d_ff = 4*d_model; n_heads =
the largest of 8/4/2/1 dividing d_model). Emits one JSON line per shape.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def measure(d_model: int, depth: int, batch: int, *, seq: int = 128,
            vocab: int = 1024, steps: int = 3) -> dict:
    from _bench_common import build_train_cell, make_batch, measure_cell

    from llmtrain_tpu.config.schemas import RunConfig
    from llmtrain_tpu.utils.hw import mfu as compute_mfu

    n_heads = next(h for h in (8, 4, 2, 1) if d_model % h == 0)
    cfg = RunConfig.model_validate(
        {
            "run": {"name": "cpusweep", "device": "cpu"},
            "model": {
                "name": "gpt",
                "block_size": seq,
                "d_model": d_model,
                "n_layers": depth,
                "n_heads": n_heads,
                "d_ff": 4 * d_model,
                "dropout": 0.0,
                "vocab_size": vocab,
                "dtype": "float32",
                "attention": "dense",
                "extra": {"loss_impl": "dense", "assume_packed": True},
            },
            "data": {"name": "dummy_text"},
            "trainer": {"micro_batch_size": batch, "grad_accum_steps": 1, "warmup_steps": 0},
        }
    )
    step_fn, state, n_params = build_train_cell(cfg)
    batch_dict = make_batch(batch, seq, vocab)
    m = measure_cell(step_fn, state, batch_dict, steps)
    tps = batch * seq / m["step_time_s"]
    return {
        "d_model": d_model,
        "depth": depth,
        "batch": batch,
        "mfu": round(
            compute_mfu(tps, n_params=n_params, n_layers=depth, seq_len=seq,
                        d_model=d_model), 4),
        "tokens_per_sec": round(tps, 1),
        "step_time_ms": round(m["step_time_s"] * 1e3, 1),
        "compile_s": round(m["compile_s"], 1),
        "params": n_params,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--shapes",
        nargs="+",
        default=["128,2,4", "512,2,8", "1280,2,16", "1280,1,16"],
        help="d_model,depth,batch per entry (d_ff = 4*d_model)",
    )
    args = ap.parse_args()
    for spec in args.shapes:
        d, depth, batch = (int(x) for x in spec.split(","))
        try:
            row = measure(d, depth, batch)
        except Exception as exc:  # noqa: BLE001 — report per shape
            row = {"d_model": d, "depth": depth, "batch": batch,
                   "error": str(exc)[:200]}
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
