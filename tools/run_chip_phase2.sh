#!/usr/bin/env bash
# Phase-2 on-chip evidence: the steps the first live windows didn't cover.
#
#     bash tools/run_chip_phase2.sh [outdir]
#
# Designed around how axon windows actually die (r4 + r5 evidence):
#   - windows are short (~10-35 min) and can wedge on a LARGE program's
#     remote compile (r4: seq 16384; r5: seq 8192) — after which every
#     TPU client hangs until its watchdog;
#   - so every step is gated by a fresh compile-verified probe: a dead
#     tunnel aborts the runbook (exit 1) instead of burning hours of
#     watchdogs, and tools/chip_watch.sh resumes watching;
#   - steps are RESUME-AWARE: a step is banked iff its artifact holds
#     its TERMINAL marker (summary line / last cell), so a window that
#     dies mid-step re-runs that step, not the banked ones;
#   - each step gets MAX_ATTEMPTS fired windows before the runbook
#     gives up on it (a deterministically-failing step must not refire
#     every ~2 min for the watch loop's whole budget);
#   - small-program steps run first; the known window-killers (16k/32k
#     long-context compiles) run last so a wedge costs only themselves.
#
# Exit 0 = nothing left to try (all banked or given up): watch stands
# down. Exit 1 = work remains for a future window: watch keeps arming.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-chip_evidence_p2}"
MAX_ATTEMPTS="${CHIP_P2_MAX_ATTEMPTS:-2}"
mkdir -p "$OUT"

log() { echo "[chip-p2] $*" >&2; }

# ---- banked predicates: keyed on each artifact's TERMINAL output ----
banked_suite()    { grep -Eq "= [0-9]+ passed in" "$OUT/tpu_compiled.log" 2>/dev/null \
                    && ! grep -Eq "[0-9]+ (failed|error)" "$OUT/tpu_compiled.log"; }
banked_mask_ab()  { grep -q "mask_overhead_pct" "$OUT/mask_ab.json" 2>/dev/null; }
# A bench artifact is banked only if a SUCCESSFUL line landed: the
# all-attempts-failed error line also carries "vs_baseline" (0.0), so
# key on the success-only '"backend": "tpu"' detail field instead.
banked_sweep()    { grep -q '"backend": "tpu"' "$OUT/bench_sweep.json" 2>/dev/null; }
banked_c128()     { grep -q '"backend": "tpu"' "$OUT/bench_c128.json" 2>/dev/null; }
banked_family()   { grep '"family": "gpt"' "$OUT/family.json" 2>/dev/null | grep -q '"mfu"' \
                    && grep '"family": "llama"' "$OUT/family.json" 2>/dev/null | grep -q '"mfu"'; }
banked_spec()     { grep '"cell": "speculative_fresh_draft"' "$OUT/speculative.json" 2>/dev/null \
                    | grep -q '"ms_per_token"'; }
banked_lora_ab()  { grep -q "speedup_lora_vs_full" "$OUT/lora_ab.json" 2>/dev/null; }
banked_decode()   { grep -q '"batch": 32, "n_kv_heads": 4' "$OUT/diag_decode.json" 2>/dev/null; }
banked_bpe()      { grep -q "final_val_loss" "$OUT/bpe_headline.json" 2>/dev/null; }
banked_longctx()  { grep -q "\"seq\": $1, \"batch\": 1, \"attention\": \"flash\", \"window\": 0, \"backend\": \"tpu\"" \
                        "$OUT/longctx.json" 2>/dev/null; }
banked_lc_win()   { grep -q "\"seq\": 16384, \"batch\": 1, \"attention\": \"flash\", \"window\": 1024, \"backend\": \"tpu\"" \
                        "$OUT/longctx_window.json" 2>/dev/null; }

attempts() { cat "$OUT/.attempts_$1" 2>/dev/null || echo 0; }
mark_attempt() { echo $(( $(attempts "$1") + 1 )) >"$OUT/.attempts_$1"; }

# should_run NAME BANKED_FN [ARGS...] -> 0 iff unbanked and under cap
should_run() {
    local name="$1"; shift
    if "$@"; then log "$name already banked — skip"; return 1; fi
    if [ "$(attempts "$name")" -ge "$MAX_ATTEMPTS" ]; then
        log "$name hit $MAX_ATTEMPTS attempts without banking — giving up"
        return 1
    fi
    return 0
}

# A step is open iff it is unbanked AND still has attempts left.
open_steps() {
    local n=0
    banked_suite   || [ "$(attempts suite)"   -ge "$MAX_ATTEMPTS" ] || n=$((n + 1))
    banked_mask_ab || [ "$(attempts mask_ab)" -ge "$MAX_ATTEMPTS" ] || n=$((n + 1))
    banked_sweep   || [ "$(attempts sweep)"   -ge "$MAX_ATTEMPTS" ] || n=$((n + 1))
    banked_c128    || [ "$(attempts c128)"    -ge "$MAX_ATTEMPTS" ] || n=$((n + 1))
    banked_family  || [ "$(attempts family)"  -ge "$MAX_ATTEMPTS" ] || n=$((n + 1))
    banked_spec    || [ "$(attempts spec)"    -ge "$MAX_ATTEMPTS" ] || n=$((n + 1))
    banked_lora_ab || [ "$(attempts lora_ab)" -ge "$MAX_ATTEMPTS" ] || n=$((n + 1))
    banked_decode  || [ "$(attempts decode)"  -ge "$MAX_ATTEMPTS" ] || n=$((n + 1))
    if [ -f runs/pytok8k.json ]; then
        banked_bpe || [ "$(attempts bpe)" -ge "$MAX_ATTEMPTS" ] || n=$((n + 1))
    fi
    local T
    for T in 8192 16384 32768; do
        banked_longctx "$T" || [ "$(attempts "lc_$T")" -ge "$MAX_ATTEMPTS" ] || n=$((n + 1))
    done
    banked_lc_win || [ "$(attempts lc_win)" -ge "$MAX_ATTEMPTS" ] || n=$((n + 1))
    echo "$n"
}

# Stand down BEFORE probing: a fully-banked (or given-up) outdir must
# not need a live tunnel to report completion.
if [ "$(open_steps)" -eq 0 ]; then
    log "nothing left to try — standing down (see $OUT/ for artifacts)"
    exit 0
fi

# Fresh compile-verified probe. A wedged tunnel hangs even tiny
# programs, so a 180 s timeout separates alive from dead reliably.
gate() {
    log "gate: probing TPU before step $1..."
    if ! timeout 180 python tools/tpu_probe.py >"$OUT/probe.log" 2>&1; then
        log "gate: tunnel dead before step $1 — aborting (watch loop resumes)"
        exit 1
    fi
}

gate "start"

if should_run suite banked_suite; then
    log "1/8 compiled-kernel suite (masks, GQA, bf16 bwd, chunked CE)..."
    mark_attempt suite
    timeout 2400 env LLMTRAIN_TEST_TPU=1 python -m pytest tests/test_tpu_compiled.py -v \
        >"$OUT/tpu_compiled.log" 2>&1 || log "compiled suite failed/partial"
    tail -2 "$OUT/tpu_compiled.log" || true
    gate "post-1"
fi

if should_run mask_ab banked_mask_ab; then
    log "2/8 masked-vs-packed A/B + GQA train deltas..."
    mark_attempt mask_ab
    timeout 3000 python tools/bench_mask_ab.py \
        >"$OUT/mask_ab.json" 2>"$OUT/mask_ab.log" || log "mask A/B failed/partial"
    tail -1 "$OUT/mask_ab.json" || true
    gate "post-2"
fi

if should_run sweep banked_sweep; then
    log "5/8 bench auto-sweep with room to climb (deadline 1500s)..."
    mark_attempt sweep
    timeout 1800 env LLMTRAIN_BENCH_DEADLINE_SEC=1500 LLMTRAIN_BENCH_TPU_TIMEOUT=1600 \
        LLMTRAIN_BENCH_NO_FALLBACK=1 python bench.py \
        >"$OUT/bench_sweep.json" 2>"$OUT/bench_sweep.log" || log "bench sweep failed"
    tail -1 "$OUT/bench_sweep.json" || true
    gate "post-5"
fi

if should_run family banked_family; then
    log "7/8 model-family cells: gpt vs llama at matched scale..."
    mark_attempt family
    timeout 1200 python tools/bench_family.py \
        >"$OUT/family.json" 2>"$OUT/family.log" || log "family cells failed/partial"
    tail -2 "$OUT/family.json" || true
    gate "post-7"
fi

if should_run spec banked_spec; then
    log "7b/8 speculative-decode bounds (self/fresh draft, gamma=4)..."
    mark_attempt spec
    timeout 1200 python tools/bench_speculative.py \
        >"$OUT/speculative.json" 2>"$OUT/speculative.log" \
        || log "speculative cells failed/partial"
    tail -2 "$OUT/speculative.json" || true
    gate "post-7b"
fi

if should_run lora_ab banked_lora_ab; then
    log "7c/8 LoRA vs full fine-tune A/B (frozen-backward DCE on chip)..."
    mark_attempt lora_ab
    timeout 1200 python tools/bench_lora.py \
        >"$OUT/lora_ab.json" 2>"$OUT/lora_ab.log" \
        || log "lora A/B failed/partial"
    tail -1 "$OUT/lora_ab.json" || true
    gate "post-7c"
fi

if should_run decode banked_decode; then
    log "4/8 decode attribution (layers/vocab/sampler/bf16-cast ablations)..."
    mark_attempt decode
    timeout 2400 python tools/diag_decode.py --batches 1,8,32 --kv-heads 0,4 \
        >"$OUT/diag_decode.json" 2>"$OUT/diag_decode.log" \
        || log "decode diag failed/partial"
    gate "post-4"
fi

if [ -f runs/pytok8k.json ]; then
    if should_run bpe banked_bpe; then
        log "8/8 BPE headline train (tokenizer at runs/pytok8k.json)..."
        mark_attempt bpe
        timeout 5400 python -m llmtrain_tpu train \
            --config configs/presets/gpt_pycorpus_bpe_tpu.yaml \
            --run-id chip-evidence-bpe --json \
            >"$OUT/bpe_headline.json" 2>"$OUT/bpe_headline.log" \
            || log "BPE headline failed/partial"
        gate "post-8"
    fi
else
    log "8/8 no tokenizer file — BPE headline not attempted on this host"
fi

# The batch-128 compile proved itself a window-killer in this round's
# first live window (600 s TPU attempt timed out, tunnel wedged right
# after) — so it runs down here with the other known killers, after
# every cheap step has banked.
if should_run c128 banked_c128; then
    log "6/8 chunked-CE batch-128 cell (runs after 8/8: window-killer)..."
    mark_attempt c128
    timeout 1200 env LLMTRAIN_BENCH_BATCH=128 LLMTRAIN_BENCH_CE=chunked \
        LLMTRAIN_BENCH_NO_FALLBACK=1 python bench.py \
        >"$OUT/bench_c128.json" 2>"$OUT/bench_c128.log" || log "c128 cell failed"
    tail -1 "$OUT/bench_c128.json" || true
    gate "post-6"
fi

# Long-context rows LAST, one subprocess per T with its own watchdog:
# a wedge on one T costs only that row plus the next gate, not the
# rest of the runbook (r5: the single-process 4-seq sweep died at 8192
# and took the window's remaining value with it).
for T in 8192 16384 32768; do
    if should_run "lc_$T" banked_longctx "$T"; then
        log "3/8 longctx T=$T..."
        mark_attempt "lc_$T"
        timeout 900 python tools/bench_longctx.py --seqs "$T" \
            >>"$OUT/longctx.json" 2>"$OUT/longctx_$T.log" \
            || log "longctx T=$T failed/partial"
        gate "post-3-T$T"
    fi
done

if should_run lc_win banked_lc_win; then
    log "3b/8 sliding-window long-context cell (O(T·W) vs full causal)..."
    mark_attempt lc_win
    timeout 1500 python tools/bench_longctx.py --seqs 8192,16384 --window 1024 \
        >"$OUT/longctx_window.json" 2>"$OUT/longctx_window.log" \
        || log "windowed longctx failed/partial"
    tail -2 "$OUT/longctx_window.json" || true
fi

left="$(open_steps)"
log "pass complete — $left step(s) still open (artifacts in $OUT/)."
[ "$left" -eq 0 ] || exit 1
exit 0
