#!/usr/bin/env bash
# Phase-2 on-chip evidence: the steps the first live window didn't cover
# (r4: tunnel died after ~35 min, having banked bench/decode/longctx-4k8k).
#
#     bash tools/run_chip_phase2.sh [outdir]
#
# Same contract as run_chip_evidence.sh: probe with a hard timeout, every
# step watchdogged and independent, artifacts land in <outdir>.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-chip_evidence_p2}"
mkdir -p "$OUT"

log() { echo "[chip-p2] $*" >&2; }

log "probing TPU backend + compile helper (240s timeout)..."
# tools/tpu_probe.py: backend init + tiny jitted matmul + device_get
# sync — a dead remote_compile helper fails here instead of hanging
# every armed step to its watchdog (r4 incident).
if ! timeout 240 python tools/tpu_probe.py >"$OUT/probe.log" 2>&1; then
    log "TPU backend or compile helper unreachable — aborting (see $OUT/probe.log)"
    exit 1
fi
log "TPU live (compile path verified)."

log "1/8 compiled-kernel suite (masks, GQA, bf16 bwd, chunked CE)..."
timeout 2400 env LLMTRAIN_TEST_TPU=1 python -m pytest tests/test_tpu_compiled.py -v \
    >"$OUT/tpu_compiled.log" 2>&1 || log "compiled suite failed/partial"
tail -2 "$OUT/tpu_compiled.log" || true

log "2/8 masked-vs-packed A/B + GQA train deltas..."
timeout 3000 python tools/bench_mask_ab.py \
    >"$OUT/mask_ab.json" 2>"$OUT/mask_ab.log" || log "mask A/B failed/partial"
tail -1 "$OUT/mask_ab.json" || true

log "3/8 long-context sweep (fixed per-step sync; retry 16k/32k)..."
timeout 3600 python tools/bench_longctx.py --seqs 4096,8192,16384,32768 \
    >"$OUT/longctx.json" 2>"$OUT/longctx.log" || log "longctx failed/partial"

log "3b/8 sliding-window long-context cell (O(T·W) vs full causal)..."
timeout 1500 python tools/bench_longctx.py --seqs 8192,16384 --window 1024 \
    >"$OUT/longctx_window.json" 2>"$OUT/longctx_window.log" \
    || log "windowed longctx failed/partial"
tail -2 "$OUT/longctx_window.json" || true

log "4/8 decode attribution (layers/vocab/sampler/bf16-cast ablations)..."
timeout 2400 python tools/diag_decode.py --batches 1,8,32 --kv-heads 0,4 \
    >"$OUT/diag_decode.json" 2>"$OUT/diag_decode.log" \
    || log "decode diag failed/partial"

log "5/8 bench auto-sweep with room to climb (deadline 1500s)..."
# TPU_TIMEOUT must rise with DEADLINE_SEC: the parent watchdog kills the
# child at TPU_TIMEOUT regardless of the child's sweep budget.
timeout 1800 env LLMTRAIN_BENCH_DEADLINE_SEC=1500 LLMTRAIN_BENCH_TPU_TIMEOUT=1600 \
    LLMTRAIN_BENCH_NO_FALLBACK=1 python bench.py \
    >"$OUT/bench_sweep.json" 2>"$OUT/bench_sweep.log" || log "bench sweep failed"
tail -1 "$OUT/bench_sweep.json" || true

log "6/8 chunked-CE batch-128 cell (the HBM-freed retune)..."
timeout 1200 env LLMTRAIN_BENCH_BATCH=128 LLMTRAIN_BENCH_CE=chunked \
    LLMTRAIN_BENCH_NO_FALLBACK=1 python bench.py \
    >"$OUT/bench_c128.json" 2>"$OUT/bench_c128.log" || log "c128 cell failed"
tail -1 "$OUT/bench_c128.json" || true

log "7/8 model-family cells: gpt vs llama at matched scale..."
timeout 1200 python tools/bench_family.py \
    >"$OUT/family.json" 2>"$OUT/family.log" || log "family cells failed/partial"
tail -2 "$OUT/family.json" || true

log "7b/8 speculative-decode bounds (self/fresh draft, gamma=4)..."
timeout 1200 python tools/bench_speculative.py \
    >"$OUT/speculative.json" 2>"$OUT/speculative.log" \
    || log "speculative cells failed/partial"
tail -2 "$OUT/speculative.json" || true

log "8/8 BPE headline train (tokenizer already at runs/pytok8k.json)..."
if [ -f runs/pytok8k.json ]; then
    timeout 5400 python -m llmtrain_tpu train \
        --config configs/presets/gpt_pycorpus_bpe_tpu.yaml \
        --run-id chip-evidence-bpe --json \
        >"$OUT/bpe_headline.json" 2>"$OUT/bpe_headline.log" \
        || log "BPE headline failed/partial"
else
    log "no tokenizer file — skipping BPE headline train"
fi

log "done — artifacts in $OUT/. Fold the numbers into RESULTS.md."
