"""LoRA vs full fine-tune train-step A/B at matched shape.

Measures the claim behind models/lora.py's frozen-aware FLOP model
(utils/hw.py): freezing the base skips its dW backward, so a LoRA step
should run ~(6N + 12LTd)/(4N + 2n + 12LTd) faster than full fine-tuning
at the same shape. Emits one JSON line per cell plus a summary with the
measured vs predicted speedup.

Usage (repo root):

    python tools/bench_lora.py                    # chip shape
    JAX_PLATFORMS=cpu python tools/bench_lora.py --cpu-smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")


def _cell(name: str, *, lora: dict | None, cpu_smoke: bool, steps: int,
          batch: int) -> dict:
    from _bench_common import build_train_cell, make_batch, measure_cell
    from llmtrain_tpu.config.schemas import RunConfig
    from llmtrain_tpu.utils.hw import transformer_flops_per_token

    if cpu_smoke:
        dims = dict(d_model=64, n_layers=2, n_heads=4, d_ff=256,
                    vocab_size=512)
        seq = 128
    else:  # GPT-2-small, the headline shape
        dims = dict(d_model=768, n_layers=12, n_heads=12, d_ff=3072,
                    vocab_size=50257)
        seq = 512
    extra = {"tokenizer": "byte", "assume_packed": True}
    if lora is not None:
        extra["lora"] = lora
    cfg = RunConfig.model_validate(
        {
            "run": {"name": f"lora-ab-{name}",
                    "device": "cpu" if cpu_smoke else "tpu"},
            "model": {
                "name": "gpt",
                "block_size": seq,
                "dropout": 0.0,
                "dtype": "float32" if cpu_smoke else "bfloat16",
                "attention": "dense" if cpu_smoke else "flash",
                "extra": extra,
                **dims,
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "micro_batch_size": batch,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
            },
            "mlflow": {"enabled": False},
        }
    )
    step_fn, state, n_params = build_train_cell(cfg)
    n_trainable = (
        sum(int(x.size) for x in jax.tree.leaves(state.params["lora"]))
        if lora is not None
        else n_params
    )
    m = measure_cell(step_fn, state, make_batch(batch, seq, dims["vocab_size"]),
                     steps)
    toks = batch * seq / m["step_time_s"]
    return {
        "cell": name,
        "backend": jax.default_backend(),
        "params": n_params,
        "trainable_params": n_trainable,
        "batch": batch,
        "seq": seq,
        "step_time_ms": round(m["step_time_s"] * 1e3, 2),
        "tokens_per_sec": round(toks, 1),
        "compile_s": round(m["compile_s"], 1),
        "loss": m["loss"],
        "flops_per_token": transformer_flops_per_token(
            n_params=n_params,
            n_layers=dims["n_layers"],
            seq_len=seq,
            d_model=dims["d_model"],
            n_trainable_params=n_trainable,
        ),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=0, help="0 = auto per mode")
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--cpu-smoke", action="store_true")
    args = ap.parse_args()
    batch = args.batch or (4 if args.cpu_smoke else 64)
    steps = min(args.steps, 3) if args.cpu_smoke else args.steps

    rows = {}
    for name, lora in (
        ("full", None),
        (f"lora_r{args.rank}", {"rank": args.rank, "alpha": 2 * args.rank}),
    ):
        try:
            row = _cell(name, lora=lora, cpu_smoke=args.cpu_smoke,
                        steps=steps, batch=batch)
        except Exception as exc:  # noqa: BLE001 — per-cell isolation
            row = {"cell": name, "error": str(exc)[:500]}
        rows[name] = row
        print(json.dumps(row), flush=True)

    full = rows.get("full", {})
    lora_row = rows.get(f"lora_r{args.rank}", {})
    if "step_time_ms" in full and "step_time_ms" in lora_row:
        print(
            json.dumps(
                {
                    "speedup_lora_vs_full": round(
                        full["step_time_ms"] / lora_row["step_time_ms"], 3
                    ),
                    "predicted_speedup": round(
                        full["flops_per_token"] / lora_row["flops_per_token"],
                        3,
                    ),
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
