"""Logical-axis → mesh-axis rules and sharding computation.

Model code annotates parameters and activations with *logical* names
(``vocab``/``embed``/``heads``/``mlp`` for params, ``batch``/``length``/
``act_*`` for activations — see models/gpt.py). This module maps them onto
the physical mesh axes (data/fsdp/tensor/sequence/pipeline/expert):

* pure data parallel: every param rule lands on a size-1 axis → replicated
  params, batch sharded over (data, fsdp). Gradient sync is the psum XLA
  inserts for the replicated-param gradient — the moral equivalent of DDP's
  all-reduce hook (reference trainer.py:88-91), but fused into the step.
* FSDP: param ``embed`` axes shard over ``fsdp``; XLA all-gathers just-in-time.
* Tensor parallel: ``heads``/``mlp``/``vocab`` shard over ``tensor`` —
  Megatron-style column/row splits fall out of the einsum shardings.
* Sequence parallel: activation ``length`` shards over ``sequence``
  (ring attention in ops/ring_attention.py extends this to attention itself).
"""

from __future__ import annotations

from typing import Any

import jax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# fmt: off
DEFAULT_LOGICAL_AXIS_RULES = (
    # activations
    ("batch", ("data", "fsdp", "expert")),
    ("length", "sequence"),
    ("act_embed", None),
    ("act_mlp", "tensor"),
    ("act_heads", "tensor"),
    ("act_kv", None),
    ("act_vocab", "tensor"),
    # MoE dispatch layout (models/moe.py): the leading expert dim shards
    # over the mesh `expert` axis while the token-group dim keeps the
    # remaining batch axes — the reshard between the two IS the all-to-all.
    ("act_expert", "expert"),
    ("act_expert_group", ("data", "fsdp")),
    # params
    ("vocab", "tensor"),
    ("embed", "fsdp"),
    ("mlp", "tensor"),
    ("heads", "tensor"),
    ("kv", None),
    ("qkv", None),
    ("position", None),
    # Norm scales (models/llama.py RMSNorm): replicated — a (D,) vector
    # gains nothing from fsdp and an embed→fsdp mapping forces an
    # inefficient embed-wise grad reshard for the dscale reduction.
    ("norm", None),
    ("expert", "expert"),
    # Stacked-layer params (models/gpt_pipeline.py): the leading layer dim
    # shards over pipeline stages; the per-layer dims reuse the standard
    # names above (heads/mlp -> tensor), so DP x PP x TP composes.
    ("layers", "pipeline"),
)
# fmt: on


def ambient_mesh() -> Mesh | None:
    """The mesh from an enclosing ``with mesh:`` block, if any.

    Single home for the private-API access (jax._src churns; one site to
    fix) — used by ring attention and the pipeline model to decide whether
    a parallel axis is available at trace time.
    """
    from jax._src import mesh as mesh_lib

    physical = mesh_lib.thread_resources.env.physical_mesh
    return None if physical.empty else physical


def data_parallel_degree(mesh: Mesh) -> int:
    """Number of batch shards = product of the axes 'batch' maps onto.

    The ``expert`` axis carries batch shards too: dense params replicate
    over it while MoE expert weights shard over it (GShard layout), so
    non-MoE compute is never duplicated across expert devices.
    """
    return mesh.shape["data"] * mesh.shape["fsdp"] * mesh.shape.get("expert", 1)


def batch_sharding(mesh: Mesh, *, with_accum_dim: bool = False) -> NamedSharding:
    """Sharding for (accum, B, T) or (B, T) token batches."""
    batch_axes = ("data", "fsdp", "expert")
    if with_accum_dim:
        return NamedSharding(mesh, P(None, batch_axes, "sequence"))
    return NamedSharding(mesh, P(batch_axes, "sequence"))


# Leaves whose unsatisfiable sharding spec was already repaired (and warned
# about) once this process — keyed by (tree path, shape, spec) so distinct
# leaves each warn exactly once and re-derivations stay silent.
_REPAIR_WARNED: set[tuple] = set()


def _spec_fits(mesh: Mesh, spec, shape: tuple) -> bool:
    """Every sharded dim of ``shape`` is divisible by its mapped axis product."""
    for dim, axes in zip(shape, spec):
        if axes is None:
            continue
        names = (axes,) if isinstance(axes, str) else axes
        shards = 1
        for name in names:
            shards *= mesh.shape[name]
        if dim % shards != 0:
            return False
    return True


def state_shardings(mesh: Mesh, abstract_tree: Any, rules=DEFAULT_LOGICAL_AXIS_RULES):
    """NamedShardings for a pytree whose leaves may carry logical metadata.

    Leaves without metadata (e.g. the dummy model, optimizer scalars) get
    fully-replicated shardings. So do leaves that inherited a param's
    logical names but not its shape — optimizers that reduce over param
    dims (optax.adafactor's factored ``v_row``/``v_col``, rank reduced by
    one, and its shape-(1,) placeholders) carry the full spec through the
    flax boxes, and applying it to the reduced array is a pjit error.
    Repairs: spec longer than the rank, and any leaf whose spec the mesh
    cannot satisfy (a sharded dim not divisible by the mapped axis
    product — which previously surfaced as an opaque pjit error at jit
    time) fall back to replicated, the latter with a one-time warning
    NAMING the leaf so a silently-unsharded giant embedding is visible.
    """
    logical_spec = nn.get_partition_spec(abstract_tree)
    shardings = nn.logical_to_mesh_sharding(logical_spec, mesh, list(rules))

    def finalize(path, sharding: Any, leaf: Any) -> Any:
        value = nn.meta.unbox(leaf)
        shape = getattr(value, "shape", None)
        if shape is None or not isinstance(sharding, NamedSharding):
            return sharding
        if len(sharding.spec) > len(shape):
            return replicated(mesh)
        if not _spec_fits(mesh, sharding.spec, tuple(shape)):
            if tuple(shape) != (1,):
                # (1,) placeholders (adafactor) are structural noise; a
                # full-rank leaf losing its sharding is worth one warning.
                key = (jax.tree_util.keystr(path), tuple(shape), str(sharding.spec))
                if key not in _REPAIR_WARNED:
                    _REPAIR_WARNED.add(key)
                    from ..utils.logging import get_logger

                    get_logger().warning(
                        "sharding spec %s does not divide leaf %s with shape "
                        "%s on mesh %s; storing this leaf REPLICATED (pick "
                        "dims divisible by the mapped axis sizes to shard it)",
                        sharding.spec,
                        jax.tree_util.keystr(path),
                        tuple(shape),
                        dict(mesh.shape),
                    )
            return replicated(mesh)
        return sharding

    return jax.tree_util.tree_map_with_path(
        finalize,
        shardings,
        abstract_tree,
        is_leaf=lambda s: isinstance(s, NamedSharding),
    )


# Axes whose product is the data-parallel degree — the replicas that hold
# redundant optimizer-state copies, i.e. the ZeRO partitioning dimension.
ZERO_PARTITION_AXES = ("data", "fsdp", "expert")


def opt_state_shardings(
    mesh: Mesh,
    abstract_state: Any,
    rules=DEFAULT_LOGICAL_AXIS_RULES,
    *,
    subject: str = "optimizer-state",
):
    """ZeRO-style shardings: partition every optimizer-state leaf across
    the combined data-parallel axes (``data``/``fsdp``/``expert``).

    The weight-update sharding of Xu et al. (arXiv:2004.13336): replicas
    that hold redundant copies of the AdamW moments each keep only a
    1/N_dp shard instead. Per-leaf derivation starts from the param-
    inherited spec (:func:`state_shardings` — the moments carry the flax
    ``Partitioned`` metadata through optax's init) and then APPENDS the
    data-parallel axes the spec does not already use to the first dim
    that can absorb them: the dim's size must be divisible by its
    existing shard product times the free-axis product. Leaves with no
    such dim (scalars like Adam's ``count``, indivisible shapes,
    adafactor's ``(1,)`` placeholders) keep their base spec — replicated
    across the dp axes — with a one-time warning for non-trivial leaves,
    so the fallback is visible instead of silently eating the memory win.

    Applying the same derivation to the abstract PARAM tree yields the
    gradient layout of ZeRO stage 2 (reduce-scattered grads) — the
    train step's ``grad_shardings`` constraint (training/train_step.py).
    """
    base = state_shardings(mesh, abstract_state, rules)
    free_template = [a for a in ZERO_PARTITION_AXES if mesh.shape.get(a, 1) > 1]
    if not free_template:
        return base

    def extend(path, sharding: Any, leaf: Any) -> Any:
        value = nn.meta.unbox(leaf)
        shape = getattr(value, "shape", None)
        if shape is None or not shape or not isinstance(sharding, NamedSharding):
            return sharding  # scalars / non-array leaves stay replicated
        spec = list(sharding.spec) + [None] * (len(shape) - len(sharding.spec))
        used: set[str] = set()
        for axes in spec:
            if axes is None:
                continue
            used.update((axes,) if isinstance(axes, str) else axes)
        free = [a for a in free_template if a not in used]
        if not free:
            return sharding
        free_product = 1
        for a in free:
            free_product *= mesh.shape[a]
        for i, dim in enumerate(shape):
            axes = spec[i]
            names = () if axes is None else (
                (axes,) if isinstance(axes, str) else tuple(axes)
            )
            current = 1
            for name in names:
                current *= mesh.shape[name]
            if dim % (current * free_product) == 0:
                spec[i] = tuple(names) + tuple(free)
                return NamedSharding(mesh, P(*spec))
        if _leaf_size(shape) > 1:
            key = ("zero", subject, jax.tree_util.keystr(path), tuple(shape))
            if key not in _REPAIR_WARNED:
                _REPAIR_WARNED.add(key)
                from ..utils.logging import get_logger

                get_logger().warning(
                    "ZeRO: %s leaf %s with shape %s has no dim "
                    "divisible by the data-parallel product %d; this leaf "
                    "stays REPLICATED across the %s axes",
                    subject,
                    jax.tree_util.keystr(path),
                    tuple(shape),
                    free_product,
                    "/".join(free),
                )
        return sharding

    return jax.tree_util.tree_map_with_path(
        extend,
        base,
        abstract_state,
        is_leaf=lambda s: isinstance(s, NamedSharding),
    )


def _leaf_size(shape: tuple) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def host_memory_kind(mesh: Mesh) -> str | None:
    """``"pinned_host"`` when the mesh devices expose a host memory space
    jit shardings can target (TPU backends with the memories API), else
    None — callers fall back to an explicit host round-trip. The CPU
    backend only exposes ``unpinned_host``, which IS device memory there,
    so offloading to it would be a no-op pretending otherwise."""
    try:
        device = mesh.devices.flat[0]
        kinds = {m.kind for m in device.addressable_memories()}
    except Exception:  # noqa: BLE001 — memories API is backend-optional
        return None
    return "pinned_host" if "pinned_host" in kinds else None


def with_memory_kind(shardings: Any, kind: str) -> Any:
    """Re-target every NamedSharding leaf of a sharding tree at ``kind``."""
    return jax.tree.map(
        lambda s: s.with_memory_kind(kind) if isinstance(s, NamedSharding) else s,
        shardings,
        is_leaf=lambda s: isinstance(s, NamedSharding),
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_axis_sizes(mesh: Mesh) -> dict[str, int]:
    """``{axis: size}`` for every named mesh axis — the topology record a
    checkpoint manifest carries (resilience/elastic.py validates a resume
    against it)."""
    return {name: int(size) for name, size in mesh.shape.items()}


def reshard_state(tree: Any, shardings: Any) -> Any:
    """Lay a (restored) state pytree out onto the current mesh's shardings.

    This is the elastic-resume entry point: a checkpoint holds FULL host
    arrays, so landing them on a mesh with a different data-parallel/fsdp
    degree is purely a placement decision against the sharding tree
    computed for the NEW mesh. Implemented as a jit'd identity with
    ``out_shardings`` — NOT ``jax.device_put`` — because on the CPU
    backend device_put can alias the host numpy buffers zero-copy, and
    the first train step then DONATES those buffers (donate_argnums);
    XLA writing into memory numpy still owns corrupts the heap (segfault
    reproduced by the chaos harness on jax 0.4.37). The jit identity's
    outputs are XLA-owned copies, which makes them safely donatable."""
    return jax.jit(lambda s: s, out_shardings=shardings)(tree)
