"""Logical-axis → mesh-axis rules and sharding computation.

Model code annotates parameters and activations with *logical* names
(``vocab``/``embed``/``heads``/``mlp`` for params, ``batch``/``length``/
``act_*`` for activations — see models/gpt.py). This module maps them onto
the physical mesh axes (data/fsdp/tensor/sequence/pipeline/expert):

* pure data parallel: every param rule lands on a size-1 axis → replicated
  params, batch sharded over (data, fsdp). Gradient sync is the psum XLA
  inserts for the replicated-param gradient — the moral equivalent of DDP's
  all-reduce hook (reference trainer.py:88-91), but fused into the step.
* FSDP: param ``embed`` axes shard over ``fsdp``; XLA all-gathers just-in-time.
* Tensor parallel: ``heads``/``mlp``/``vocab`` shard over ``tensor`` —
  Megatron-style column/row splits fall out of the einsum shardings.
* Sequence parallel: activation ``length`` shards over ``sequence``
  (ring attention in ops/ring_attention.py extends this to attention itself).
"""

from __future__ import annotations

from typing import Any

import jax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# fmt: off
DEFAULT_LOGICAL_AXIS_RULES = (
    # activations
    ("batch", ("data", "fsdp", "expert")),
    ("length", "sequence"),
    ("act_embed", None),
    ("act_mlp", "tensor"),
    ("act_heads", "tensor"),
    ("act_kv", None),
    ("act_vocab", "tensor"),
    # MoE dispatch layout (models/moe.py): the leading expert dim shards
    # over the mesh `expert` axis while the token-group dim keeps the
    # remaining batch axes — the reshard between the two IS the all-to-all.
    ("act_expert", "expert"),
    ("act_expert_group", ("data", "fsdp")),
    # params
    ("vocab", "tensor"),
    ("embed", "fsdp"),
    ("mlp", "tensor"),
    ("heads", "tensor"),
    ("kv", None),
    ("qkv", None),
    ("position", None),
    # Norm scales (models/llama.py RMSNorm): replicated — a (D,) vector
    # gains nothing from fsdp and an embed→fsdp mapping forces an
    # inefficient embed-wise grad reshard for the dscale reduction.
    ("norm", None),
    ("expert", "expert"),
    # Stacked-layer params (models/gpt_pipeline.py): the leading layer dim
    # shards over pipeline stages; the per-layer dims reuse the standard
    # names above (heads/mlp -> tensor), so DP x PP x TP composes.
    ("layers", "pipeline"),
)
# fmt: on


def ambient_mesh() -> Mesh | None:
    """The mesh from an enclosing ``with mesh:`` block, if any.

    Single home for the private-API access (jax._src churns; one site to
    fix) — used by ring attention and the pipeline model to decide whether
    a parallel axis is available at trace time.
    """
    from jax._src import mesh as mesh_lib

    physical = mesh_lib.thread_resources.env.physical_mesh
    return None if physical.empty else physical


def data_parallel_degree(mesh: Mesh) -> int:
    """Number of batch shards = product of the axes 'batch' maps onto.

    The ``expert`` axis carries batch shards too: dense params replicate
    over it while MoE expert weights shard over it (GShard layout), so
    non-MoE compute is never duplicated across expert devices.
    """
    return mesh.shape["data"] * mesh.shape["fsdp"] * mesh.shape.get("expert", 1)


def batch_sharding(mesh: Mesh, *, with_accum_dim: bool = False) -> NamedSharding:
    """Sharding for (accum, B, T) or (B, T) token batches."""
    batch_axes = ("data", "fsdp", "expert")
    if with_accum_dim:
        return NamedSharding(mesh, P(None, batch_axes, "sequence"))
    return NamedSharding(mesh, P(batch_axes, "sequence"))


def state_shardings(mesh: Mesh, abstract_tree: Any, rules=DEFAULT_LOGICAL_AXIS_RULES):
    """NamedShardings for a pytree whose leaves may carry logical metadata.

    Leaves without metadata (e.g. the dummy model, optimizer scalars) get
    fully-replicated shardings. So do leaves that inherited a param's
    logical names but not its shape — optimizers that reduce over param
    dims (optax.adafactor's factored ``v_row``/``v_col``, rank reduced by
    one, and its shape-(1,) placeholders) carry the full spec through the
    flax boxes, and applying it to the reduced array is a pjit error.
    The repair is deliberately NARROW: spec longer than the rank, or a
    1-element leaf whose spec the mesh cannot satisfy (adafactor's (1,)
    placeholders carrying an ``embed``-style spec). A shape-(1,) leaf
    whose spec IS satisfiable (all mapped axes size 1) keeps it, and a
    full-rank param whose dim the mesh axis doesn't divide still fails
    loudly at jit time instead of silently losing its sharding.
    """
    logical_spec = nn.get_partition_spec(abstract_tree)
    shardings = nn.logical_to_mesh_sharding(logical_spec, mesh, list(rules))

    def spec_fits(sharding: NamedSharding, shape: tuple) -> bool:
        for dim, axes in zip(shape, sharding.spec):
            if axes is None:
                continue
            names = (axes,) if isinstance(axes, str) else axes
            shards = 1
            for name in names:
                shards *= mesh.shape[name]
            if dim % shards != 0:
                return False
        return True

    def finalize(sharding: Any, leaf: Any) -> Any:
        value = nn.meta.unbox(leaf)
        shape = getattr(value, "shape", None)
        if shape is None or not isinstance(sharding, NamedSharding):
            return sharding
        if len(sharding.spec) > len(shape) or (
            tuple(shape) == (1,) and not spec_fits(sharding, tuple(shape))
        ):
            return replicated(mesh)
        return sharding

    return jax.tree.map(
        finalize,
        shardings,
        abstract_tree,
        is_leaf=lambda s: isinstance(s, NamedSharding),
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_axis_sizes(mesh: Mesh) -> dict[str, int]:
    """``{axis: size}`` for every named mesh axis — the topology record a
    checkpoint manifest carries (resilience/elastic.py validates a resume
    against it)."""
    return {name: int(size) for name, size in mesh.shape.items()}


def reshard_state(tree: Any, shardings: Any) -> Any:
    """Lay a (restored) state pytree out onto the current mesh's shardings.

    This is the elastic-resume entry point: a checkpoint holds FULL host
    arrays, so landing them on a mesh with a different data-parallel/fsdp
    degree is purely a placement decision against the sharding tree
    computed for the NEW mesh. Implemented as a jit'd identity with
    ``out_shardings`` — NOT ``jax.device_put`` — because on the CPU
    backend device_put can alias the host numpy buffers zero-copy, and
    the first train step then DONATES those buffers (donate_argnums);
    XLA writing into memory numpy still owns corrupts the heap (segfault
    reproduced by the chaos harness on jax 0.4.37). The jit identity's
    outputs are XLA-owned copies, which makes them safely donatable."""
    return jax.jit(lambda s: s, out_shardings=shardings)(tree)
