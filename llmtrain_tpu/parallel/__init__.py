"""Parallelism: logical-axis sharding rules and mesh-aware helpers.

This package is the TPU-native replacement for the reference's DDP wrapper
(reference trainer.py:84-91) and DistributedSampler wiring: parallelism is a
*property of shardings* on the jit-compiled train step, not code. XLA inserts
the collectives (psum/all-gather/reduce-scatter) implied by the shardings.
"""

from .sharding import (
    DEFAULT_LOGICAL_AXIS_RULES,
    batch_sharding,
    data_parallel_degree,
    state_shardings,
)

__all__ = [
    "DEFAULT_LOGICAL_AXIS_RULES",
    "batch_sharding",
    "data_parallel_degree",
    "state_shardings",
]
