"""GPipe pipeline parallelism over the mesh ``pipeline`` axis.

New TPU capability beyond the reference (data parallelism is its only
strategy — reference trainer.py:87-91; SURVEY §2.3 records PP as absent).
Design is TPU-first, not a port: stages are SPMD programs under
``shard_map``, activations hop stages over ICI with ``lax.ppermute``, and
the whole schedule — microbatch rotation, bubble, drain — is ONE
``lax.scan`` inside the jit-compiled train step. The backward schedule
falls out of differentiating the forward (ppermute transposes to the
reverse permutation), so GPipe's backward pass needs no extra code.

Layout contract: every parameter leaf carries its layer dim LEADING and
sharded over ``pipeline`` (logical axis ``"layers"``); activations are
batch-sharded over the data axes and replicated over ``pipeline``. With S
stages and M microbatches the bubble fraction is (S-1)/(M+S-1).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # jax < 0.8
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

BATCH_AXES = ("data", "fsdp", "expert")


def pipeline_degree(mesh: jax.sharding.Mesh | None) -> int:
    return int(mesh.shape.get("pipeline", 1)) if mesh is not None else 1


def gpipe_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    params: Any,
    x: jax.Array,
    mesh: jax.sharding.Mesh,
    *,
    n_microbatches: int,
    axis: str = "pipeline",
    remat_stage: bool = True,
) -> jax.Array:
    """Run ``x`` through all layers with GPipe scheduling over ``axis``.

    ``params``: pytree whose every leaf has a leading layer dim divisible by
    the stage count (sharded over ``axis``); ``stage_fn(stage_params, h)``
    applies one stage's worth of layers. ``x``: (B, T, D) activations with B
    sharded over the data axes. Returns (B, T, D) after all layers,
    replicated over ``axis`` (non-final stages receive the result via psum).
    """
    n_stages = pipeline_degree(mesh)
    if n_stages == 1:
        return stage_fn(params, x)
    if n_microbatches < 1:
        raise ValueError(f"n_microbatches must be >= 1, got {n_microbatches}")

    fn = jax.checkpoint(stage_fn) if remat_stage else stage_fn
    batch_axes = tuple(a for a in BATCH_AXES if a in mesh.shape)
    x_spec = P(batch_axes, *([None] * (x.ndim - 1)))
    p_specs = jax.tree.map(lambda a: P(axis, *([None] * (a.ndim - 1))), params)

    def inner(p: Any, x_local: jax.Array) -> jax.Array:
        stage = jax.lax.axis_index(axis)
        batch = x_local.shape[0]
        if batch % n_microbatches != 0:
            raise ValueError(
                f"per-shard batch {batch} not divisible by "
                f"n_microbatches {n_microbatches}"
            )
        mb = batch // n_microbatches
        xm = x_local.reshape(n_microbatches, mb, *x_local.shape[1:])
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state_in, out_buf = carry
            # Stage 0 feeds microbatch t (clamped garbage during drain
            # ticks — it never reaches the output buffer); later stages
            # consume what the previous stage sent last tick.
            x_t = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, n_microbatches - 1), keepdims=False
            )
            inp = jnp.where(stage == 0, x_t, state_in)
            out = fn(p, inp)
            # The final stage finishes microbatch t-(S-1) at tick t.
            m = t - (n_stages - 1)
            idx = jnp.clip(m, 0, n_microbatches - 1)
            write = (stage == n_stages - 1) & (m >= 0)
            cur = jax.lax.dynamic_index_in_dim(out_buf, idx, keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(write, out, cur), idx, 0
            )
            state_out = jax.lax.ppermute(out, axis, perm)
            return (state_out, out_buf), None

        # The carry varies over `axis` (each stage computes different
        # values), but the zero init doesn't — declare it varying so the
        # scan carry types line up under shard_map's vma tracking.
        if hasattr(jax.lax, "pcast"):
            mark_varying = lambda a: jax.lax.pcast(a, (axis,), to="varying")  # noqa: E731
        else:  # older jax spells it pvary
            mark_varying = lambda a: jax.lax.pvary(a, (axis,))  # noqa: E731
        init = jax.tree.map(
            mark_varying, (jnp.zeros_like(xm[0]), jnp.zeros_like(xm))
        )
        (_, out_buf), _ = jax.lax.scan(
            tick, init, jnp.arange(n_microbatches + n_stages - 1)
        )
        # Only the final stage ever wrote its buffer; every other stage
        # holds zeros, so a psum broadcasts the result to all stages.
        y = jax.lax.psum(out_buf, axis)
        return y.reshape(x_local.shape)

    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(p_specs, x_spec),
        out_specs=x_spec,
    )(params, x)


__all__ = ["gpipe_apply", "pipeline_degree", "BATCH_AXES"]
