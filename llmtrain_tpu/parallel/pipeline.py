"""GPipe pipeline parallelism over the mesh ``pipeline`` axis.

New TPU capability beyond the reference (data parallelism is its only
strategy — reference trainer.py:87-91; SURVEY §2.3 records PP as absent).
Design is TPU-first, not a port: stages are SPMD programs under
``shard_map``, activations hop stages over ICI with ``lax.ppermute``, and
the whole schedule — microbatch rotation, bubble, drain — is ONE
``lax.scan`` inside the jit-compiled train step. The backward schedule
falls out of differentiating the forward (ppermute transposes to the
reverse permutation), so GPipe's backward pass needs no extra code.

Layout contract: every parameter leaf carries its layer dim LEADING and
sharded over ``pipeline`` (logical axis ``"layers"``); activations are
batch-sharded over the data axes and replicated over ``pipeline``. With S
stages and M microbatches the bubble fraction is (S-1)/(M+S-1).

``virtual_chunks=v > 1`` selects the interleaved (Megatron-style) schedule:
each stage holds v non-contiguous layer chunks (stage s owns global chunks
s, s+S, s+2S, …), and every microbatch makes v passes around the stage
ring — the ``ppermute`` from the last stage back to stage 0 carries it
into its next chunk round. Bubble shrinks to (S-1)/(v·M+S-1) at the cost
of v× activation hops. Requires M >= S so a returning microbatch never
overtakes its own re-entry slot.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # jax < 0.8
    from jax.experimental.shard_map import shard_map

BATCH_AXES = ("data", "fsdp", "expert")


def pipeline_degree(mesh: jax.sharding.Mesh | None) -> int:
    return int(mesh.shape.get("pipeline", 1)) if mesh is not None else 1


def _interleave_permutation(n_layers: int, n_stages: int, v: int) -> np.ndarray:
    """Row order that makes a CONTIGUOUS shard hold strided chunks.

    shard_map splits the leading dim contiguously: device s gets rows
    [s·v·Lc, (s+1)·v·Lc). For the interleaved schedule device s must hold
    global chunks s, s+S, …, s+(v-1)S, i.e. layers r·S·Lc + s·Lc + j. The
    permutation lays those out so device s's local rows are ordered
    (round r, layer-in-chunk j).
    """
    lc = n_layers // (n_stages * v)
    return np.asarray(
        [
            r * n_stages * lc + s * lc + j
            for s in range(n_stages)
            for r in range(v)
            for j in range(lc)
        ],
        dtype=np.int32,
    )


def gpipe_apply(
    stage_fn: Callable[..., jax.Array],
    params: Any,
    x: jax.Array,
    mesh: jax.sharding.Mesh,
    *,
    n_microbatches: int,
    axis: str = "pipeline",
    remat_stage: bool = True,
    virtual_chunks: int = 1,
    param_specs: Any | None = None,
    mask: jax.Array | None = None,
) -> jax.Array:
    """Run ``x`` through all layers with pipeline scheduling over ``axis``.

    ``params``: pytree whose every leaf has a leading layer dim divisible by
    ``stage_count * virtual_chunks`` (sharded over ``axis``);
    ``stage_fn(stacked_layers, h)`` applies the given layers in order.
    ``x``: (B, T, D) activations with B sharded over the data axes. Returns
    (B, T, D) after all layers, replicated over ``axis`` (non-final stages
    receive the result via psum).

    ``mask``: optional (B, T) per-token padding mask. It does NOT ride the
    stage ring — each tick's stage knows which microbatch it is processing
    (work item t - stage), so the matching mask slice is indexed from the
    replicated-over-``axis`` array and passed as ``stage_fn``'s third
    argument.

    ``param_specs``: optional pytree of PartitionSpecs (matching ``params``)
    for the NON-layer dims — e.g. tensor-parallel sharding of head/mlp dims;
    every spec's dim 0 must be the ``axis`` entry. Default: non-layer dims
    replicated. When a leaf is tensor-sharded, ``stage_fn`` is responsible
    for the matching collectives (it runs inside shard_map — nothing is
    automatic).
    """
    n_stages = pipeline_degree(mesh)
    if n_stages == 1:
        return stage_fn(params, x) if mask is None else stage_fn(params, x, mask)
    n_micro = n_microbatches
    v = virtual_chunks
    if n_micro < 1:
        raise ValueError(f"n_microbatches must be >= 1, got {n_micro}")
    if v < 1:
        raise ValueError(f"virtual_chunks must be >= 1, got {v}")
    if v > 1 and n_micro < n_stages:
        raise ValueError(
            f"interleaved schedule needs n_microbatches ({n_micro}) >= "
            f"stage count ({n_stages}): a microbatch returns to stage 0 "
            "S ticks after entering and must not overtake its re-entry slot"
        )

    n_layers = jax.tree.leaves(params)[0].shape[0]
    if n_layers % (n_stages * v) != 0:
        raise ValueError(
            f"layer count {n_layers} must divide stages x virtual_chunks "
            f"({n_stages} x {v})"
        )
    layers_per_chunk = n_layers // (n_stages * v)

    if v > 1:
        # Reorder rows so contiguous shard s = its strided chunk set; the
        # gather's transpose routes chunk grads back automatically.
        # Deliberate tradeoff: this runs per step and moves ~(v-1)/v of the
        # stage params across the pipeline axis each forward (+ the
        # scatter-add in backward). Storing params pre-permuted would
        # avoid it but ties the CHECKPOINT layout to (stages, chunks) —
        # resuming on a different mesh would silently reorder layers.
        # Params are layout-independent; the traffic is bounded and
        # amortized against the bubble savings (docs/perf.md).
        perm_rows = jnp.asarray(_interleave_permutation(n_layers, n_stages, v))
        params = jax.tree.map(lambda a: jnp.take(a, perm_rows, axis=0), params)

    fn = jax.checkpoint(stage_fn) if remat_stage else stage_fn
    batch_axes = tuple(a for a in BATCH_AXES if a in mesh.shape)
    x_spec = P(batch_axes, *([None] * (x.ndim - 1)))
    if param_specs is not None:
        p_specs = param_specs
        for spec in jax.tree.leaves(p_specs, is_leaf=lambda s: isinstance(s, P)):
            if not spec or spec[0] != axis:
                raise ValueError(
                    f"param_specs must shard dim 0 over {axis!r}, got {spec}"
                )
    else:
        p_specs = jax.tree.map(lambda a: P(axis, *([None] * (a.ndim - 1))), params)

    masked = mask is not None

    def inner(p: Any, x_local: jax.Array, *rest: jax.Array) -> jax.Array:
        stage = jax.lax.axis_index(axis)
        batch = x_local.shape[0]
        if batch % n_micro != 0:
            raise ValueError(
                f"per-shard batch {batch} not divisible by n_microbatches {n_micro}"
            )
        mb = batch // n_micro
        xm = x_local.reshape(n_micro, mb, *x_local.shape[1:])
        mask_m = None
        if masked:
            (mask_local,) = rest
            mask_m = mask_local.reshape(n_micro, mb, *mask_local.shape[1:])
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        last = n_stages - 1

        def round_of(k):
            return jnp.clip(jnp.maximum(k, 0) // n_micro, 0, v - 1)

        def micro_of(k):
            return jnp.clip(jnp.maximum(k, 0) - round_of(k) * n_micro, 0, n_micro - 1)

        def chunk_params(r):
            return jax.tree.map(
                lambda a: jax.lax.dynamic_slice_in_dim(
                    a, r * layers_per_chunk, layers_per_chunk, axis=0
                ),
                p,
            )

        def write_at(buf, idx, value, enable):
            cur = jax.lax.dynamic_index_in_dim(buf, idx, keepdims=False)
            return jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(enable, value, cur), idx, 0
            )

        def tick(carry, t):
            state_in, ret_buf, out_buf = carry

            # Stage 0: bank the activation returning from the last stage
            # (work item t-S finished its round at tick t-1) for its next
            # chunk round. With M >= S the write at tick k+S always lands
            # at or before the read at tick k+M.
            k_ret = t - n_stages
            bank = (stage == 0) & (k_ret >= 0) & (k_ret < (v - 1) * n_micro)
            ret_buf = write_at(ret_buf, micro_of(k_ret), state_in, bank)

            # Stage 0 input for work item t: a fresh microbatch in round 0,
            # the banked activation afterwards. Clamped garbage during
            # drain ticks never reaches the output buffer.
            r0, m0 = round_of(t), micro_of(t)
            fresh = jax.lax.dynamic_index_in_dim(xm, m0, keepdims=False)
            banked = jax.lax.dynamic_index_in_dim(ret_buf, m0, keepdims=False)
            x0 = jnp.where(r0 == 0, fresh, banked)
            inp = jnp.where(stage == 0, x0, state_in)

            # This stage processes work item t - stage, whose round picks
            # which of the stage's local chunks to run.
            if masked:
                m_mb = jax.lax.dynamic_index_in_dim(
                    mask_m, micro_of(t - stage), keepdims=False
                )
                out = fn(chunk_params(round_of(t - stage)), inp, m_mb)
            else:
                out = fn(chunk_params(round_of(t - stage)), inp)

            # The final stage finishes work item t-(S-1); final-round items
            # are results.
            k_out = t - last
            done = (stage == last) & (k_out >= (v - 1) * n_micro) & (k_out < v * n_micro)
            out_buf = write_at(out_buf, micro_of(k_out), out, done)

            state_out = jax.lax.ppermute(out, axis, perm)
            return (state_out, ret_buf, out_buf), None

        # The carry varies over `axis` (each stage computes different
        # values), but the zero init doesn't — declare it varying so the
        # scan carry types line up under shard_map's vma tracking.
        if hasattr(jax.lax, "pcast"):
            mark_varying = lambda a: jax.lax.pcast(a, (axis,), to="varying")  # noqa: E731
        elif hasattr(jax.lax, "pvary"):  # older jax spells it pvary
            mark_varying = lambda a: jax.lax.pvary(a, (axis,))  # noqa: E731
        else:  # pre-vma jax (< 0.5): no varying-type tracking to satisfy
            mark_varying = lambda a: a  # noqa: E731
        # v == 1 never banks (round 0 reads fresh microbatches only), so the
        # return buffer shrinks to one slot; out-of-range dynamic indices
        # clamp per XLA semantics and the clamped reads are never selected.
        ret_init = jnp.zeros_like(xm) if v > 1 else jnp.zeros_like(xm[:1])
        init = jax.tree.map(
            mark_varying, (jnp.zeros_like(xm[0]), ret_init, jnp.zeros_like(xm))
        )
        (_, _, out_buf), _ = jax.lax.scan(
            tick, init, jnp.arange(v * n_micro + n_stages - 1)
        )
        # Only the final stage ever wrote its buffer; every other stage
        # holds zeros, so a psum broadcasts the result to all stages.
        y = jax.lax.psum(out_buf, axis)
        return y.reshape(x_local.shape)

    in_specs: tuple = (p_specs, x_spec)
    operands: tuple = (params, x)
    if masked:
        in_specs = (*in_specs, P(batch_axes, None))
        operands = (*operands, mask)
    return shard_map(
        inner,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=x_spec,
    )(*operands)


__all__ = ["gpipe_apply", "pipeline_degree", "BATCH_AXES"]
