"""Non-finite guard primitives for the jitted train step.

A single NaN/Inf in the loss or gradients — an overflow in bf16 attention
logits, a poisonous batch, a flaky chip — would otherwise flow through the
optimizer and corrupt the params AND the Adam moments irreversibly. The
guard computes one ``all_finite`` flag over loss and every gradient leaf and
masks the whole optimizer update behind ``jax.lax.cond`` (the optax
``apply_if_finite`` pattern): a skipped step keeps params/opt_state
bit-identical while ``step`` still advances, so the deterministic sampler
moves past the bad batch instead of re-feeding it forever.

The trainer counts CONSECUTIVE skipped updates on device (a scalar in the
TrainState, so the hot loop stays sync-free) and aborts with
:class:`NonFiniteLossError` once the run of skips crosses the configured
cap — persistent non-finiteness means divergence, not a bad batch.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


class NonFiniteLossError(RuntimeError):
    """Raised by the trainer when ``max_consecutive_nonfinite`` optimizer
    updates in a row had to be skipped by the non-finite guard."""


def tree_all_finite(*trees: Any) -> jax.Array:
    """Scalar bool: every leaf of every tree is fully finite.

    Per-leaf ``isfinite().all()`` reductions are combined with ``&`` so XLA
    fuses them into the step's existing epilogue; no host sync happens here.
    """
    flag = jnp.bool_(True)
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
                flag = flag & jnp.isfinite(leaf).all()
    return flag


__all__ = ["NonFiniteLossError", "tree_all_finite"]
