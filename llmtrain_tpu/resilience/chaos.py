"""Seeded chaos-recovery harness: repeated hard-kill → resume cycles with
machine-checked invariants.

The atomic commit protocol (training/checkpoint.py) and elastic resume
(resilience/elastic.py) each make a local guarantee; this module is the
capstone that turns them into one provable end-to-end contract — "die
anywhere, resume, and the trajectory is the one an uninterrupted run
would have produced". It is a SUPERVISOR: every training segment is a
real ``python -m llmtrain_tpu train`` subprocess, every kill a real
``SIGKILL`` delivered by the config-driven fault plan at a step drawn
from a seeded schedule (including a window forced INSIDE the async
checkpoint write via ``faults.kill_during_checkpoint``, and a cycle that
corrupts the newest committed payload to prove torn files are never
selected).

After every cycle the harness asserts:

* the newest committed checkpoint is loadable (manifest verifies, payload
  parses) — a crash can cost progress since the last commit, never the
  ability to resume;
* no torn/uncommitted checkpoint is ever selected — each segment's
  "resumed from" step equals the newest VALID commit observed before it
  launched;

and after the final (uninterrupted) cycle:

* the completed run's logged loss trajectory is bitwise-equal to an
  uninterrupted reference run's at every overlapping step, and the final
  checkpoints' params/opt_state are bitwise-identical tree-wide.

The segment/invariant machinery lives in ``resilience/harness.py`` and is
shared with the multi-tenant fleet storm (``fleet/chaos.py``): this module
keeps the single-job drill and its ``llmtrain chaos`` CLI contract.
Driven by ``make verify-elastic``; see docs/robustness.md.
"""

from __future__ import annotations

import json
import random
import time
from pathlib import Path
from typing import Any

import yaml

from ..utils.logging import get_logger
from .harness import (
    KILL_RETURNCODES as _KILL_RETURNCODES,
)
from .harness import (
    RESUMED_RE as _RESUMED_RE,  # noqa: F401 — re-exported for drills/tests
)
from .harness import (
    DrillInvariantError,
    aligned_log_every,
    derive_segment_config,
    next_save_boundary,
    run_train_segment,
)
from .harness import (
    assert_newest_loadable as _harness_assert_newest_loadable,
)
from .harness import (
    log_size as _log_size,
)
from .harness import (
    newest_committed_step as _newest_committed_step,
)
from .harness import (
    segment_resumed_step as _segment_resumed_step,
)
from .harness import (
    summary_of as _harness_summary_of,
)
from .harness import (
    trees_bitwise_equal as _trees_bitwise_equal,
)

logger = get_logger()


class ChaosInvariantError(DrillInvariantError):
    """A recovery invariant failed — the crash-consistency contract is
    broken (this is the harness's whole reason to exist, so it is loud)."""


def _derive_config(
    resolved: dict[str, Any],
    *,
    root_dir: str,
    max_steps: int,
    save_every: int,
    log_every: int,
    faults: dict[str, Any] | None,
) -> dict[str, Any]:
    """One chaos segment's config (harness.derive_segment_config with this
    drill's historical signature — kept because tests and docs pin it)."""
    return derive_segment_config(
        resolved,
        root_dir=root_dir,
        max_steps=max_steps,
        save_every=save_every,
        log_every=log_every,
        faults=faults,
    )


def _assert_newest_loadable(ckpt_dir: Path) -> int:
    return _harness_assert_newest_loadable(ckpt_dir, error_cls=ChaosInvariantError)


def _run_segment(cfg_path: Path, run_id: str, *, timeout_sec: float, label: str):
    return run_train_segment(
        cfg_path,
        run_id,
        timeout_sec=timeout_sec,
        label=label,
        error_cls=ChaosInvariantError,
    )


def _summary_of(proc, label: str) -> dict[str, Any]:
    return _harness_summary_of(
        proc.stdout or "",
        returncode=proc.returncode,
        stderr=proc.stderr or "",
        label=label,
        error_cls=ChaosInvariantError,
    )


def _next_save_boundary(last_step: int, save_every: int, max_steps: int) -> int | None:
    return next_save_boundary(last_step, save_every, max_steps)


def run_chaos(
    config_path: str | Path,
    *,
    cycles: int = 5,
    seed: int = 0,
    max_steps: int | None = None,
    save_every: int | None = None,
    work_dir: str | Path | None = None,
    timeout_sec: float = 600.0,
) -> dict[str, Any]:
    """Run the seeded kill/resume schedule; returns the result record.

    ``cycles`` is the number of KILLED segments (≥1; a final uninterrupted
    segment always follows). The schedule is a pure function of ``seed``
    and the observed commit progress. Raises :class:`ChaosInvariantError`
    the moment any invariant breaks.
    """
    from ..config import load_and_validate_config
    from ..training.checkpoint import CheckpointManager

    cfg, _, resolved = load_and_validate_config(str(config_path))
    steps = int(max_steps or cfg.trainer.max_steps)
    save = int(save_every or min(cfg.trainer.save_every_steps, max(1, steps // 3)))
    save = max(1, min(save, steps))
    # Interval means are only comparable when every resume point (a save
    # boundary) is also a log boundary: pick the largest log cadence that
    # divides the save cadence.
    log_every = aligned_log_every(save, cfg.trainer.log_every_steps)
    work = Path(work_dir) if work_dir is not None else Path(cfg.output.root_dir) / (
        f"chaos_{cfg.run.name}_s{seed}"
    )
    work.mkdir(parents=True, exist_ok=True)
    runs_root = work / "runs"
    if runs_root.exists():
        # The runs tree is this harness's own scratch: a rerun with the
        # same seed must start from zero, not --auto-resume last drill's
        # completed runs (which would execute 0 steps, log an empty
        # trajectory, and falsely fail the bitwise comparison).
        import shutil

        shutil.rmtree(runs_root)

    def write_cfg(name: str, faults: dict[str, Any] | None) -> Path:
        payload = _derive_config(
            resolved,
            root_dir=str(runs_root),
            max_steps=steps,
            save_every=save,
            log_every=log_every,
            faults=faults,
        )
        path = work / name
        path.write_text(yaml.safe_dump(payload, sort_keys=False), encoding="utf-8")
        return path

    # ---------------------------------------------------------- reference
    ref_cfg = write_cfg("reference.yaml", None)
    started = time.perf_counter()
    ref_proc = _run_segment(
        ref_cfg, "reference", timeout_sec=timeout_sec, label="reference"
    )
    if ref_proc.returncode != 0:
        raise ChaosInvariantError(
            f"uninterrupted reference run failed (exit {ref_proc.returncode}): "
            f"{(ref_proc.stderr or '')[-2000:]}"
        )
    ref_summary = _summary_of(ref_proc, "reference")
    ref_dir = runs_root / "reference"

    # ------------------------------------------------------- kill schedule
    rng = random.Random(f"llmtrain-chaos:{seed}")
    chaos_dir = runs_root / "chaos"
    ckpt_dir = chaos_dir / "checkpoints"
    cycle_records: list[dict[str, Any]] = []
    completed_early = False
    for i in range(max(1, cycles)):
        last = _newest_committed_step(ckpt_dir) if ckpt_dir.is_dir() else 0
        if last >= steps:
            completed_early = True
            break
        boundary = _next_save_boundary(last, save, steps)
        # Cycle 1 (0-based) always aims inside the async checkpoint write;
        # cycle 2 corrupts a committed payload post-write. Both degrade to
        # a plain kill when no save boundary remains before max_steps.
        if i == min(1, max(1, cycles) - 1) and boundary is not None:
            mode = "kill_during_checkpoint"
            faults = {"kill_at_step": boundary, "kill_during_checkpoint": True}
            kill_step = boundary
        elif i == 2 and boundary is not None and boundary < steps and last > 0:
            # Only once an earlier commit exists to fall back to: the
            # injection destroys the newest committed payload, and the
            # invariant under test is that selection skips it — not that a
            # run survives losing its only checkpoint.
            mode = "corrupt_then_kill"
            kill_step = rng.randint(boundary + 1, steps)
            faults = {
                "corrupt_checkpoint_at_step": boundary,
                "corrupt_mode": "truncate",
                "kill_at_step": kill_step,
            }
        else:
            mode = "kill"
            kill_step = rng.randint(last + 1, steps)
            faults = {"kill_at_step": kill_step}
        cfg_path = write_cfg(f"cycle_{i:02d}.yaml", faults)
        expected_resume = last if last > 0 else None
        log_file = chaos_dir / "logs" / cfg.logging.file_name
        log_offset = _log_size(log_file)
        proc = _run_segment(
            cfg_path, "chaos", timeout_sec=timeout_sec, label=f"cycle {i}"
        )
        record: dict[str, Any] = {
            "cycle": i,
            "mode": mode,
            "kill_step": kill_step,
            "resumed_from_expected": expected_resume,
            "returncode": proc.returncode,
        }
        if proc.returncode == 0:
            # The kill landed at/after the final step's save: the segment
            # completed. Later cycles have nothing left to kill.
            record["completed"] = True
            cycle_records.append(record)
            completed_early = True
            newest = _assert_newest_loadable(ckpt_dir)
            record["newest_committed_step"] = newest
            break
        if proc.returncode not in _KILL_RETURNCODES:
            raise ChaosInvariantError(
                f"cycle {i} exited {proc.returncode} instead of dying to "
                f"SIGKILL; stderr tail: {(proc.stderr or '')[-2000:]}"
            )
        # Invariant: restorability survived the kill.
        newest = _assert_newest_loadable(ckpt_dir)
        record["newest_committed_step"] = newest
        # Invariant: the segment resumed from the newest VALID commit
        # observed before launch — selecting a torn/uncommitted step would
        # show up right here.
        resumed = _segment_resumed_step(log_file, log_offset)
        record["resumed_from_observed"] = resumed
        if expected_resume is not None and resumed != expected_resume:
            raise ChaosInvariantError(
                f"cycle {i} resumed from step {resumed}, expected the newest "
                f"valid commit {expected_resume} — selection picked a "
                "checkpoint it should not have"
            )
        cycle_records.append(record)

    # ----------------------------------------------------------- final run
    final_summary: dict[str, Any]
    if completed_early and cycle_records and cycle_records[-1].get("completed"):
        final_summary = _summary_of(proc, "final")
    else:
        final_cfg = write_cfg("final.yaml", None)
        final_proc = _run_segment(
            final_cfg, "chaos", timeout_sec=timeout_sec, label="final"
        )
        if final_proc.returncode != 0:
            raise ChaosInvariantError(
                f"final uninterrupted segment failed (exit "
                f"{final_proc.returncode}): {(final_proc.stderr or '')[-2000:]}"
            )
        final_summary = _summary_of(final_proc, "final")

    # --------------------------------------------------------- comparison
    ref_result = ref_summary.get("train_result") or {}
    chaos_result = final_summary.get("train_result") or {}
    mismatches: list[str] = []
    if ref_result.get("final_step") != chaos_result.get("final_step"):
        mismatches.append(
            f"final_step {chaos_result.get('final_step')} != "
            f"{ref_result.get('final_step')}"
        )
    if ref_result.get("final_loss") != chaos_result.get("final_loss"):
        mismatches.append(
            f"final_loss {chaos_result.get('final_loss')!r} != "
            f"{ref_result.get('final_loss')!r} (bitwise)"
        )

    # Loss trajectory: every interval the final segment logged must match
    # the reference bitwise at the same global step.
    overlap = 0
    try:
        ref_traj = {
            int(s): v
            for s, v in json.loads((ref_dir / "report.json").read_text())["loss"][
                "trajectory"
            ]
        }
        chaos_traj = json.loads((chaos_dir / "report.json").read_text())["loss"][
            "trajectory"
        ]
    except (OSError, KeyError, ValueError) as exc:
        mismatches.append(f"loss trajectories unreadable: {exc}")
    else:
        for s, v in chaos_traj:
            s = int(s)
            if s not in ref_traj:
                continue
            overlap += 1
            if ref_traj[s] != v:
                mismatches.append(
                    f"train/loss at step {s}: {v!r} != {ref_traj[s]!r} (bitwise)"
                )
        if overlap == 0:
            mismatches.append("no overlapping trajectory points to compare")

    # Final checkpoints: params/opt_state bitwise-identical tree-wide.
    ref_newest = CheckpointManager(ref_dir / "checkpoints").latest_valid_checkpoint()
    chaos_newest = CheckpointManager(ckpt_dir).latest_valid_checkpoint()
    if ref_newest is None or chaos_newest is None:
        mismatches.append("missing final checkpoint on one side")
    else:
        ref_payload = CheckpointManager.load(ref_newest)
        chaos_payload = CheckpointManager.load(chaos_newest)
        if int(ref_payload["step"]) != int(chaos_payload["step"]):
            mismatches.append(
                f"final checkpoint steps differ: {int(chaos_payload['step'])} "
                f"vs {int(ref_payload['step'])}"
            )
        for key in ("params", "opt_state"):
            diff = _trees_bitwise_equal(ref_payload[key], chaos_payload[key], key)
            if diff is not None:
                mismatches.append(diff)

    if mismatches:
        raise ChaosInvariantError(
            "chaos run diverged from the uninterrupted reference: "
            + "; ".join(mismatches)
        )

    # ------------------------------------------------------------- goodput
    # The wall-clock cost of all those kills, attributed post-hoc from the
    # chaos run dir's durable artifacts (telemetry/goodput.py). Two gates:
    # the ledger must BALANCE (categories sum to total wall-clock within
    # 1% — an unbalanced ledger means segments went missing), and
    # productive share must clear the configured floor.
    from ..telemetry.goodput import compute_goodput

    goodput = compute_goodput(chaos_dir)
    if goodput is not None:
        wall = goodput["wall_clock_sec"]
        attributed = sum(goodput["categories"].values())
        if wall > 0 and abs(attributed - wall) > 0.01 * wall + 0.05:
            raise ChaosInvariantError(
                f"goodput ledger does not balance: {attributed:.2f}s "
                f"attributed vs {wall:.2f}s wall-clock — segment "
                "artifacts are missing or mis-ordered"
            )
        floor = cfg.resilience.chaos.min_goodput_frac
        if goodput["goodput_frac"] < floor:
            raise ChaosInvariantError(
                f"goodput_frac {goodput['goodput_frac']:.4f} below the "
                f"configured floor resilience.chaos.min_goodput_frac="
                f"{floor:.4f} (ledger: {goodput['categories']})"
            )

    kill_cycles = [r for r in cycle_records if not r.get("completed")]
    return {
        "seed": seed,
        "max_steps": steps,
        "save_every": save,
        "log_every": log_every,
        "cycles": cycle_records,
        "kills_delivered": len(kill_cycles),
        "kill_during_checkpoint_cycles": sum(
            1 for r in cycle_records if r["mode"] == "kill_during_checkpoint"
        ),
        "trajectory_points_compared": overlap,
        "final_step": chaos_result.get("final_step"),
        "final_loss": chaos_result.get("final_loss"),
        "reference_final_loss": ref_result.get("final_loss"),
        "bitwise_match": True,
        "goodput": goodput,
        "work_dir": str(work),
        "wall_time_sec": round(time.perf_counter() - started, 2),
    }


__all__ = ["ChaosInvariantError", "run_chaos"]
