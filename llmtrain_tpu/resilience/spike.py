"""Loss-spike detection with checkpoint auto-rollback support.

A loss spike that the non-finite guard cannot catch — still finite, but an
order of magnitude above trend — usually means the optimizer state was
poisoned a few steps back (bad batch × high LR, bf16 overflow that rounded
to a huge finite value). Waiting it out costs wall-clock and often never
recovers; the production move (TorchTitan, MegaScale) is to restore the
last good checkpoint and step PAST the offending data window.

:class:`LossSpikeDetector` keeps a bias-corrected rolling EWMA of the train
loss and flags an observation that exceeds ``factor ×`` the trend once at
least ``min_history`` steps have been observed. The spike itself is NOT
folded into the EWMA (one poisoned value would inflate the trend and mask a
second spike). The detector's state round-trips through the checkpoint
payload (``state()``/``load_state()``) so a preempted-and-resumed run keeps
its armed trend instead of re-warming from scratch.

The trainer consumes this at log-interval boundaries — the same place it
already syncs losses to host — so detection adds zero extra device syncs.
"""

from __future__ import annotations

import math


class LossSpikeDetector:
    def __init__(
        self,
        *,
        factor: float,
        beta: float = 0.9,
        min_history: int = 20,
    ) -> None:
        if factor <= 1.0:
            raise ValueError("spike factor must be > 1")
        if not 0.0 < beta < 1.0:
            raise ValueError("ewma beta must be in (0, 1)")
        self._factor = factor
        self._beta = beta
        self._min_history = max(1, min_history)
        self._acc = 0.0  # biased EWMA accumulator
        self._count = 0  # finite observations folded in

    @property
    def trend(self) -> float | None:
        """Bias-corrected EWMA of the observed losses (None before any)."""
        if self._count == 0:
            return None
        return self._acc / (1.0 - self._beta**self._count)

    @property
    def armed(self) -> bool:
        return self._count >= self._min_history

    def observe(self, loss: float) -> bool:
        """Feed one train-loss value; True means "this is a spike".

        Non-finite losses return False and leave the trend untouched — the
        non-finite guard owns that failure mode. A flagged spike is also
        kept out of the trend so consecutive spikes keep firing.
        """
        if not math.isfinite(loss):
            return False
        trend = self.trend
        if self.armed and trend is not None and loss > self._factor * trend:
            return True
        self._acc = self._beta * self._acc + (1.0 - self._beta) * loss
        self._count += 1
        return False

    # ------------------------------------------------------- checkpoint I/O

    def state(self) -> dict[str, float]:
        return {"spike_ewma_acc": float(self._acc), "spike_obs": int(self._count)}

    def load_state(self, state: dict) -> None:
        self._acc = float(state.get("spike_ewma_acc", 0.0))
        self._count = int(state.get("spike_obs", 0))


class RollbackBudgetExceededError(RuntimeError):
    """Raised when loss spikes keep recurring past ``max_rollbacks`` —
    repeated rollback means the run diverges deterministically and a human
    (or sweep controller) must change the config, not the scheduler."""


__all__ = ["LossSpikeDetector", "RollbackBudgetExceededError"]
