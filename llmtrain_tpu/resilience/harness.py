"""Shared drill machinery for subprocess-supervising recovery harnesses.

The single-job chaos drill (``resilience/chaos.py``, ``llmtrain chaos``)
and the multi-tenant fleet storm (``fleet/chaos.py``, ``llmtrain fleet
--storm``) prove the same crash-consistency contract at different scales:
run REAL ``python -m llmtrain_tpu train`` subprocesses, interrupt them,
and machine-check that every restart resumed from the newest valid
commit and that the completed trajectory is bitwise-identical to an
uninterrupted reference. This module holds the pieces both supervisors
need — segment launching, summary parsing, commit inspection, resumed-
step log parsing, and the bitwise tree comparator — so the fleet drill
IMPORTS the invariants instead of copy-pasting them (and a fix to one
drill is automatically a fix to the other).

Every function that asserts an invariant takes an ``error_cls`` so each
harness raises its own loud, named error type (``ChaosInvariantError``,
``FleetInvariantError``) while sharing one implementation.
"""

from __future__ import annotations

import json
import re
import subprocess
import sys
from pathlib import Path
from typing import Any

from ..utils.logging import get_logger

logger = get_logger()


class DrillInvariantError(RuntimeError):
    """Base class for "a recovery invariant failed" — the contract the
    drills exist to prove is broken, so failures are loud and typed."""


# The trainer logs exactly this on restore; both drills parse it to learn
# which commit a segment actually selected at launch.
RESUMED_RE = re.compile(r"resumed from .*step_(\d{6,})\.ckpt at step (\d+)")

# SIGKILL surfaces as -9 from Popen (or 128+9 through a shell).
KILL_RETURNCODES = (-9, 137)
# SIGTERM that killed the process before the trainer's handler could turn
# it into a clean preemption exit (e.g. during interpreter startup).
TERM_RETURNCODES = (-15, 143)


def deep_merge(base: dict[str, Any], overrides: dict[str, Any]) -> dict[str, Any]:
    """Recursive dict merge (overrides win; nested dicts merge key-wise).

    Returns a new dict; neither input is mutated. Non-dict override
    values replace wholesale — a tenant overriding ``model.extra`` keeps
    the base's untouched keys, but overriding a list replaces the list.
    """
    out = dict(base)
    for key, value in overrides.items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = deep_merge(out[key], value)
        else:
            out[key] = value
    return out


def derive_segment_config(
    resolved: dict[str, Any],
    *,
    root_dir: str,
    max_steps: int,
    save_every: int,
    log_every: int,
    faults: dict[str, Any] | None,
    overrides: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """One drill segment's config: the user's run, re-rooted into the
    harness work dir, with cadence pinned and the segment's fault plan
    installed. Tracker/endpoint integrations are forced off — segments
    are killed mid-flight and must not strand external state (and fleet
    tenants must not fight over one Prometheus port). ``overrides`` are
    deep-merged first (fleet tenants customize lr/LoRA/etc. this way)."""
    cfg = json.loads(json.dumps(resolved))  # deep copy, JSON-safe by construction
    if overrides:
        cfg = deep_merge(cfg, overrides)
    cfg.setdefault("output", {})["root_dir"] = root_dir
    trainer = cfg.setdefault("trainer", {})
    trainer["max_steps"] = max_steps
    trainer["save_every_steps"] = save_every
    trainer["log_every_steps"] = log_every
    # Eval adds wall-clock without touching the trajectory contract.
    trainer["eval_every_steps"] = max_steps
    cfg.setdefault("mlflow", {})["enabled"] = False
    cfg.setdefault("telemetry", {})["prometheus"] = False
    resilience = cfg.setdefault("resilience", {})
    resilience["faults"] = dict(faults or {})
    return cfg


def aligned_log_every(save_every: int, log_every: int) -> int:
    """Largest log cadence that divides the save cadence.

    Interval loss means are only comparable across a resume when every
    resume point (a save boundary) is also a log boundary; both drills
    pin their derived configs with this.
    """
    if save_every % log_every != 0:
        return save_every
    return log_every


def newest_committed_step(ckpt_dir: Path) -> int:
    """Step of the newest verifying commit, 0 when none exists.

    Full-scan semantics (legacy fallback + orphan-stage adoption): only
    call this when no writer owns the directory — between a drill's
    segments, never on a live run (see :func:`newest_committed_step_live`).
    """
    from ..training.checkpoint import CheckpointManager

    newest = CheckpointManager(ckpt_dir).latest_valid_checkpoint()
    if newest is None:
        return 0
    return int(newest.stem.split("_")[1])


def newest_committed_step_live(ckpt_dir: Path, *, mgr: Any = None) -> int:
    """Side-effect-free newest-commit probe, safe on a LIVE run's dir.

    The full scan (``latest_valid_checkpoint``) ADOPTS a verifying
    payload that has no manifest by synthesizing one — the pre-manifest
    migration path. On a live directory that "unmanifested payload" is
    simply a commit in flight (payload renamed, manifest publish pending),
    and the adoption write races the writer's own manifest rename (found
    by the fleet storm: the tenant's async writer crashed on its vanished
    ``.tmp``). This probe consults committed manifests ONLY and writes
    nothing: an in-flight step stays invisible until its publish, which
    is exactly the atomic-commit reading of the directory.

    Pass a reusable read-side ``mgr`` (CheckpointManager) when probing at
    a high cadence: its (path, size, mtime) verify cache then
    short-circuits re-hashing an unchanged newest payload.
    """
    if mgr is None:
        from ..training.checkpoint import CheckpointManager

        mgr = CheckpointManager(ckpt_dir)
    for path in reversed(mgr.all_manifests()):
        if mgr.verify_manifest(path):
            return int(path.stem.split("_")[1])
    return 0


def assert_newest_loadable(
    ckpt_dir: Path, *, error_cls: type[Exception] = DrillInvariantError
) -> int:
    """Invariant: the newest committed checkpoint must load. Returns its
    step (0 when the dir holds no checkpoints yet — a kill before the
    first commit costs progress, not restorability)."""
    from ..training.checkpoint import (
        CheckpointManager,
        read_manifest,
    )

    mgr = CheckpointManager(ckpt_dir)
    if not mgr.all_checkpoints() and not mgr.all_manifests():
        return 0
    newest = mgr.latest_valid_checkpoint()
    if newest is None:
        raise error_cls(
            f"checkpoints exist under {ckpt_dir} but none verifies — "
            "the run lost its ability to resume"
        )
    if read_manifest(newest) is None:
        raise error_cls(f"selected checkpoint {newest.name} has no commit manifest")
    payload = mgr.load(newest)  # raises CheckpointError on damage
    return int(payload["step"])


def log_size(log_file: Path) -> int:
    """Current byte length of a shared train.log (0 when absent) —
    recorded before a segment launches so its restore point is read from
    ITS appended region only."""
    try:
        return log_file.stat().st_size
    except OSError:
        return 0


def segment_resumed_step(log_file: Path, offset: int) -> int | None:
    """The segment's launch-time restore point: the FIRST "resumed from"
    line appended past ``offset``. First, not last — a mid-segment spike
    rollback logs the same line for its restore, and mistaking that for
    the auto-resume selection would fail the torn-selection invariant on
    a correct run."""
    try:
        with log_file.open("rb") as fh:
            fh.seek(offset)
            text = fh.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    match = RESUMED_RE.search(text)
    if match is None:
        return None
    return int(match.group(2))


def trees_bitwise_equal(a: Any, b: Any, path: str = "") -> str | None:
    """None when the (nested dict / array) trees match bitwise; otherwise
    a human-readable path to the first mismatch."""
    import numpy as np

    if isinstance(a, dict) or isinstance(b, dict):
        if not (isinstance(a, dict) and isinstance(b, dict)):
            return f"{path}: node/leaf structure differs"
        if sorted(a) != sorted(b):
            return f"{path}: keys differ ({sorted(a)} vs {sorted(b)})"
        for key in a:
            sub = trees_bitwise_equal(a[key], b[key], f"{path}/{key}")
            if sub is not None:
                return sub
        return None
    aa, bb = np.asarray(a), np.asarray(b)
    if aa.dtype != bb.dtype or aa.shape != bb.shape:
        return f"{path}: dtype/shape differ ({aa.dtype}{aa.shape} vs {bb.dtype}{bb.shape})"
    if not np.array_equal(aa, bb, equal_nan=True):
        return f"{path}: values differ"
    return None


def train_segment_command(cfg_path: Path | str, run_id: str) -> list[str]:
    """The real-CLI invocation both drills supervise: auto-resume so a
    respawn continues from the newest commit, --json so the summary is
    machine-parseable off stdout."""
    return [
        sys.executable,
        "-m",
        "llmtrain_tpu",
        "train",
        "--config",
        str(cfg_path),
        "--run-id",
        run_id,
        "--auto-resume",
        "--json",
    ]


def run_train_segment(
    cfg_path: Path,
    run_id: str,
    *,
    timeout_sec: float,
    label: str,
    error_cls: type[Exception] = DrillInvariantError,
    env: dict[str, str] | None = None,
) -> subprocess.CompletedProcess:
    """Blocking one-segment run (the chaos drill and fleet references);
    the fleet supervisor multiplexes tenants with Popen instead."""
    cmd = train_segment_command(cfg_path, run_id)
    logger.info("drill: launching %s segment (%s)", label, cfg_path.name)
    try:
        return subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_sec, env=env
        )
    except subprocess.TimeoutExpired as exc:
        raise error_cls(
            f"{label} segment exceeded {timeout_sec:.0f}s — a resumed run "
            "must make progress, not wedge"
        ) from exc


def summary_of(
    stdout: str,
    *,
    returncode: int | None,
    stderr: str = "",
    label: str,
    error_cls: type[Exception] = DrillInvariantError,
) -> dict[str, Any]:
    """Last JSON object line on a segment's stdout (the --json run
    summary); raises ``error_cls`` when a segment that should have
    completed printed none."""
    for line in reversed((stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    raise error_cls(
        f"{label} segment (exit {returncode}) printed no summary JSON; "
        f"stderr tail: {(stderr or '')[-2000:]}"
    )


def next_save_boundary(last_step: int, save_every: int, max_steps: int) -> int | None:
    boundary = ((last_step // save_every) + 1) * save_every
    return boundary if boundary <= max_steps else None


__all__ = [
    "DrillInvariantError",
    "KILL_RETURNCODES",
    "RESUMED_RE",
    "TERM_RETURNCODES",
    "aligned_log_every",
    "assert_newest_loadable",
    "deep_merge",
    "derive_segment_config",
    "log_size",
    "newest_committed_step",
    "newest_committed_step_live",
    "next_save_boundary",
    "run_train_segment",
    "segment_resumed_step",
    "summary_of",
    "train_segment_command",
    "trees_bitwise_equal",
]
