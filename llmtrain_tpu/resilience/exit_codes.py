"""Process exit-code taxonomy: clean / fatal / retryable-infra.

A multi-host job that just "exits 1" tells the orchestrator nothing: a
deterministic divergence (retry = burn the same TPU hours again) and a
flaky rendezvous (retry = the run completes) look identical. Following the
sysexits EX_TEMPFAIL convention, failures here are classified into three
documented classes the k8s layer consumes (``k8s/entrypoint.sh`` logs the
class; ``k8s/job.yaml``'s ``podFailurePolicy`` fails the Job fast on fatal
codes and lets retryable ones burn the backoff budget):

==== ======================= ==============================================
code class                   meaning
==== ======================= ==============================================
0    clean                   run completed (incl. preemption save + exit)
1    fatal (training)        deterministic failure — divergence, bad data,
                             bug; retrying reproduces it
2    fatal (config)          invalid config/CLI usage, or an incompatible
                             resume topology change (elastic.py); retrying
                             is useless
75   retryable infra         EX_TEMPFAIL — transient environment failure
                             (rendezvous, dataset fetch, storage blip);
                             the orchestrator should restart the pod
76   retryable hang          the hang watchdog hard-exited a stalled run
                             (stuck collective / wedged host); restart
==== ======================= ==============================================

This module is deliberately dependency-free (no jax, no pydantic) so the
CLI and k8s tooling can import it without dragging in the runtime.
"""

from __future__ import annotations

EXIT_OK = 0
EXIT_TRAIN_FAILURE = 1
EXIT_CONFIG_ERROR = 2
# sysexits.h EX_TEMPFAIL: "temporary failure, user is invited to retry".
EXIT_RETRYABLE_INFRA = 75
# Dedicated code for watchdog-detected stalls, distinct from generic infra
# failures so a fleet can count hangs separately; still retryable.
EXIT_HANG_DETECTED = 76

RETRYABLE_EXIT_CODES = frozenset({EXIT_RETRYABLE_INFRA, EXIT_HANG_DETECTED})
FATAL_EXIT_CODES = frozenset({EXIT_TRAIN_FAILURE, EXIT_CONFIG_ERROR})


def is_retryable(code: int) -> bool:
    """True when the orchestrator should restart the pod for this code."""
    return code in RETRYABLE_EXIT_CODES


class RetryableInfraError(RuntimeError):
    """Raise (or wrap a cause with) this to mark a failure as transient
    infrastructure trouble: the CLI maps it to :data:`EXIT_RETRYABLE_INFRA`
    so the orchestrator restarts the pod instead of failing the Job."""


# Exception types that are transient by nature even when nobody wrapped
# them: network/storage hiccups and timeouts. OSError at large is NOT here
# — a missing file or permission error is deterministic.
_RETRYABLE_TYPES: tuple[type[BaseException], ...] = (
    RetryableInfraError,
    TimeoutError,
    ConnectionError,
    InterruptedError,
)


def _exception_chain(exc: BaseException):
    """``exc`` and its cause/context chain, cycle-safe.

    Mirrors traceback display rules: explicit ``__cause__`` always counts;
    implicit ``__context__`` only when not suppressed — ``raise X from
    None`` deliberately severs the chain, so a deterministic error raised
    while HANDLING a transient one must not inherit "retryable" from the
    exception its author disowned.
    """
    seen: set[int] = set()
    node: BaseException | None = exc
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        yield node
        nxt = node.__cause__
        if nxt is None and not node.__suppress_context__:
            nxt = node.__context__
        node = nxt


def exit_code_for_exception(exc: BaseException) -> int:
    """Map an exception escaping a CLI handler onto the taxonomy.

    Walks the cause/context chain so a retryable root cause wrapped by a
    generic layer (``RuntimeError(...) from TimeoutError``) still
    classifies as retryable. Deterministic training failures (divergence,
    exhausted rollback budget) are explicitly fatal: retrying replays the
    same math. Unknown exceptions default to fatal — claiming "retryable"
    for a genuine bug would loop the orchestrator forever.
    """
    # Local imports: keep this module importable without jax/pydantic.
    from ..autotune.plan import MeshPlanError
    from .elastic import TopologyMismatchError
    from .faults import InjectedFault
    from .guard import NonFiniteLossError
    from .spike import RollbackBudgetExceededError

    for node in _exception_chain(exc):
        # An incompatible topology change is a CONFIG problem: the same
        # config replays the same mismatch, so the orchestrator must not
        # burn restarts on it. An infeasible mesh plan (axis sizes vs
        # device count / capability rules, autotune/plan.py) is the same
        # class: deterministic from config, restarting cannot help.
        if isinstance(node, (TopologyMismatchError, MeshPlanError)):
            return EXIT_CONFIG_ERROR
    for node in _exception_chain(exc):
        # Deterministic divergence beats any wrapped transient error.
        if isinstance(node, (NonFiniteLossError, RollbackBudgetExceededError)):
            return EXIT_TRAIN_FAILURE
    for node in _exception_chain(exc):
        # InjectedFault simulates flaky infra (dataset load, rendezvous) —
        # classifying it retryable lets tests drive the taxonomy end to end.
        if isinstance(node, _RETRYABLE_TYPES) or isinstance(node, InjectedFault):
            return EXIT_RETRYABLE_INFRA
    return EXIT_TRAIN_FAILURE


__all__ = [
    "EXIT_OK",
    "EXIT_TRAIN_FAILURE",
    "EXIT_CONFIG_ERROR",
    "EXIT_RETRYABLE_INFRA",
    "EXIT_HANG_DETECTED",
    "RETRYABLE_EXIT_CODES",
    "FATAL_EXIT_CODES",
    "RetryableInfraError",
    "exit_code_for_exception",
    "is_retryable",
]
