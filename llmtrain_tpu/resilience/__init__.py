"""Fault-tolerance layer: non-finite guard, loss-spike rollback, fault
injection, retry, hang watchdog, and the exit-code taxonomy — see
docs/robustness.md.

The reference framework (and PAPER.md §2.4) has no elastic-recovery
machinery: a NaN loss corrupts the optimizer state, a truncated checkpoint
kills resume, a flaky rendezvous kills the pod — and a stuck collective
stalls the whole job without ever raising. This package supplies the
survivable-failure semantics production pre-training treats as table
stakes, wired through config (``resilience:`` section), the jitted train
step, the trainer loop, the checkpoint manager, the CLI's exit codes, and
the k8s liveness/restart machinery — with every recovery path exercised
end to end by the config-driven fault-injection harness.
"""

from .exit_codes import (
    EXIT_CONFIG_ERROR,
    EXIT_HANG_DETECTED,
    EXIT_OK,
    EXIT_RETRYABLE_INFRA,
    EXIT_TRAIN_FAILURE,
    RETRYABLE_EXIT_CODES,
    RetryableInfraError,
    exit_code_for_exception,
    is_retryable,
)
from .faults import FaultPlan, InjectedFault, retry
from .guard import NonFiniteLossError, tree_all_finite
from .spike import LossSpikeDetector, RollbackBudgetExceededError
from .watchdog import (
    HangWatchdog,
    ProgressBeacon,
    StragglerTracker,
    heartbeat_age_seconds,
)

__all__ = [
    "EXIT_CONFIG_ERROR",
    "EXIT_HANG_DETECTED",
    "EXIT_OK",
    "EXIT_RETRYABLE_INFRA",
    "EXIT_TRAIN_FAILURE",
    "FaultPlan",
    "HangWatchdog",
    "InjectedFault",
    "LossSpikeDetector",
    "NonFiniteLossError",
    "ProgressBeacon",
    "RETRYABLE_EXIT_CODES",
    "RetryableInfraError",
    "RollbackBudgetExceededError",
    "StragglerTracker",
    "exit_code_for_exception",
    "heartbeat_age_seconds",
    "is_retryable",
    "retry",
    "tree_all_finite",
]
