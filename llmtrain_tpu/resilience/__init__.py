"""Fault-tolerance layer: non-finite guard, loss-spike rollback, fault
injection, full-jitter retry, hang watchdog, the exit-code taxonomy,
elastic topology-change validation (``elastic.py``), and the seeded
chaos-recovery harness (``chaos.py``, ``llmtrain chaos``) — see
docs/robustness.md.

The reference framework (and PAPER.md §2.4) has no elastic-recovery
machinery: a NaN loss corrupts the optimizer state, a truncated checkpoint
kills resume, a flaky rendezvous kills the pod — and a stuck collective
stalls the whole job without ever raising. This package supplies the
survivable-failure semantics production pre-training treats as table
stakes, wired through config (``resilience:`` section), the jitted train
step, the trainer loop, the checkpoint manager, the CLI's exit codes, and
the k8s liveness/restart machinery — with every recovery path exercised
end to end by the config-driven fault-injection harness.
"""

from .exit_codes import (
    EXIT_CONFIG_ERROR,
    EXIT_HANG_DETECTED,
    EXIT_OK,
    EXIT_RETRYABLE_INFRA,
    EXIT_TRAIN_FAILURE,
    RETRYABLE_EXIT_CODES,
    RetryableInfraError,
    exit_code_for_exception,
    is_retryable,
)
from .elastic import (
    TopologyMismatchError,
    classify_topology_change,
    describe_topology,
    resume_batch_index,
)
from .faults import FaultPlan, InjectedFault, retry, retry_rng
from .guard import NonFiniteLossError, tree_all_finite
from .spike import LossSpikeDetector, RollbackBudgetExceededError
from .watchdog import (
    HangWatchdog,
    ProgressBeacon,
    StragglerTracker,
    heartbeat_age_seconds,
)

__all__ = [
    "EXIT_CONFIG_ERROR",
    "EXIT_HANG_DETECTED",
    "EXIT_OK",
    "EXIT_RETRYABLE_INFRA",
    "EXIT_TRAIN_FAILURE",
    "FaultPlan",
    "HangWatchdog",
    "InjectedFault",
    "LossSpikeDetector",
    "NonFiniteLossError",
    "ProgressBeacon",
    "RETRYABLE_EXIT_CODES",
    "RetryableInfraError",
    "RollbackBudgetExceededError",
    "StragglerTracker",
    "TopologyMismatchError",
    "classify_topology_change",
    "describe_topology",
    "exit_code_for_exception",
    "heartbeat_age_seconds",
    "is_retryable",
    "resume_batch_index",
    "retry",
    "retry_rng",
    "tree_all_finite",
]
