"""Fault-tolerance layer: non-finite guard, loss-spike rollback, fault
injection, and retry — see docs/robustness.md.

The reference framework (and PAPER.md §2.4) has no elastic-recovery
machinery: a NaN loss corrupts the optimizer state, a truncated checkpoint
kills resume, a flaky rendezvous kills the pod. This package supplies the
survivable-failure semantics production pre-training treats as table
stakes, wired through config (``resilience:`` section), the jitted train
step, the trainer loop, and the checkpoint manager — with every recovery
path exercised end to end by the config-driven fault-injection harness.
"""

from .faults import FaultPlan, InjectedFault, retry
from .guard import NonFiniteLossError, tree_all_finite
from .spike import LossSpikeDetector, RollbackBudgetExceededError

__all__ = [
    "FaultPlan",
    "InjectedFault",
    "LossSpikeDetector",
    "NonFiniteLossError",
    "RollbackBudgetExceededError",
    "retry",
    "tree_all_finite",
]
