"""Config-driven fault injection + exponential-backoff retry.

Recovery code that is never executed is recovery code that does not work.
This module turns every failure mode the resilience layer claims to survive
into a deterministic, config-driven injection so tier-1 tests (and chaos
drills on real clusters) exercise the ACTUAL recovery paths end to end:

* ``nan_loss_at_step`` — compiled into the jitted train step (see
  ``training/train_step.py``): loss and grads are poisoned with NaN for a
  window of optimizer steps, driving the real non-finite guard.
* ``spike_loss_at_step`` — one-shot host-side scaling of the observed loss,
  driving the real spike detector → checkpoint rollback. One-shot by
  design: the replayed step after the rollback must not re-spike.
* ``sigterm_at_step`` / ``preempt_at_step`` — ``os.kill(os.getpid(),
  SIGTERM)``, driving the real preemption handler, durable save, and clean
  exit. ``preempt_at_step`` is the preemption-named twin the fleet storm
  schedule uses (fleet/chaos.py); they share one one-shot delivery slot.
* ``kill_at_step`` / ``kill_during_checkpoint`` — ``SIGKILL``, i.e. a real
  crash with zero cleanup; the during-checkpoint variant dies between a
  save's staged files and its manifest publish, driving the atomic-commit
  protocol and the chaos harness (resilience/chaos.py).
* ``corrupt_checkpoint_at_step`` — truncates or garbles the newest
  checkpoint file on disk after its save, driving sidecar verification,
  ``latest_valid_checkpoint`` backward scan, and prune protection.
* ``dataset_load_failures`` / ``distributed_init_failures`` — make the
  first N attempts raise :class:`InjectedFault`, driving the
  :func:`retry` wiring in the trainer and CLI.

Everything defaults to "inject nothing"; a default-constructed plan is a
set of cheap no-op calls in the trainer loop.
"""

from __future__ import annotations

import os
import random
import signal
import time
from typing import Any, Callable, TypeVar

from ..config.schemas import FaultInjectionConfig
from ..utils.logging import get_logger

logger = get_logger()

T = TypeVar("T")


class InjectedFault(RuntimeError):
    """The exception every injected flaky-operation failure raises —
    distinct from real errors so tests can assert the injection fired."""


def retry(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base_delay: float = 0.05,
    max_delay: float = 5.0,
    description: str = "operation",
    exceptions: tuple[type[BaseException], ...] = (Exception,),
    sleep: Callable[[float], None] = time.sleep,
    jitter: bool = True,
    rng: random.Random | None = None,
) -> T:
    """Run ``fn`` with full-jitter exponential backoff.

    Attempt ``k`` sleeps ``uniform(0, min(max_delay, base·2^(k-1)))`` —
    AWS-style FULL jitter, not a fixed ladder: when a shared dependency
    (HF hub, the rendezvous coordinator, NFS) hiccups, every host's retry
    clock starts at the same moment, and deterministic delays march the
    whole fleet back in lockstep as a thundering herd. Jitter decorrelates
    them. Pass a seeded ``rng`` for reproducible schedules (the trainer
    and CLI seed theirs from ``(run.seed, process index)`` so delays are
    deterministic per rank but different across ranks); ``jitter=False``
    restores the fixed base, 2·base, 4·base ladder. The final failure
    re-raises the original exception unchanged so callers' error handling
    (CLI exit codes, test asserts) sees the real cause, not a retry
    wrapper.
    """
    if jitter and rng is None:
        # OS-entropy seeded: still decorrelated across hosts when the
        # caller doesn't thread a seed through.
        rng = random.Random()
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except exceptions as exc:
            if attempt == attempts:
                raise
            cap = min(max_delay, base_delay * (2 ** (attempt - 1)))
            delay = rng.uniform(0.0, cap) if jitter else cap
            logger.warning(
                "%s failed (attempt %d/%d: %s); retrying in %.2fs",
                description,
                attempt,
                attempts,
                exc,
                delay,
            )
            if delay > 0:
                sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover


def retry_rng(seed: int, process_index: int = 0) -> random.Random:
    """Seeded backoff-jitter RNG: deterministic per (seed, rank) — tests
    can pin the exact delays — while different ranks draw different
    schedules, which is the whole anti-thundering-herd point."""
    return random.Random(f"llmtrain-retry:{seed}:{process_index}")


class FaultPlan:
    """Mutable one-shot bookkeeping over a frozen FaultInjectionConfig."""

    def __init__(self, cfg: FaultInjectionConfig | None) -> None:
        self._cfg = cfg or FaultInjectionConfig()
        self._sigterm_fired = False
        self._corrupt_fired = False
        self._spike_fired = False
        self._hang_fired = False
        self._kill_taken = False
        self._flaky_counts: dict[str, int] = {}
        # Telemetry hook: called as observer(kind, step) right before an
        # injection fires, so fired faults land on the run's event
        # timeline. Best-effort — a broken observer never blocks the
        # injection (chaos drills measure the REAL recovery path).
        self.observer: Callable[[str, int], None] | None = None

    def _notify(self, kind: str, step: int) -> None:
        if self.observer is None:
            return
        try:
            self.observer(kind, step)
        except Exception:  # noqa: BLE001 — telemetry must not alter the drill
            pass

    @classmethod
    def from_config(cls, cfg: FaultInjectionConfig | None) -> "FaultPlan":
        return cls(cfg)

    # ----------------------------------------------------------- train step

    def nan_window(self) -> tuple[int, int] | None:
        """(first poisoned optimizer step, window length) for the jitted
        step, or None when NaN injection is off."""
        if self._cfg.nan_loss_at_step is None:
            return None
        return (self._cfg.nan_loss_at_step, self._cfg.nan_loss_steps)

    # ------------------------------------------------------------ host side

    def maybe_sigterm(self, step: int) -> None:
        """Deliver SIGTERM to ourselves once, at EXACTLY the configured step
        — through the real OS signal path so the trainer's preemption
        handler (and nothing else) turns it into a durable save. Exact
        equality, not >=: a resumed run starting past the step must not
        re-fire the injection. ``preempt_at_step`` is the same delivery
        with the preemption-shaped name (the schema forbids setting both);
        the telemetry instant is tagged with whichever knob fired."""
        kind = "sigterm"
        at = self._cfg.sigterm_at_step
        if at is None:
            at = self._cfg.preempt_at_step
            kind = "preempt"
        if at is None or self._sigterm_fired or step != at:
            return
        self._sigterm_fired = True
        self._notify(kind, step)
        logger.warning("fault injection: delivering SIGTERM at step %d", step)
        os.kill(os.getpid(), signal.SIGTERM)

    def maybe_kill(self, step: int) -> None:
        """SIGKILL ourselves at EXACTLY the configured step — the
        hardest-possible crash: no Python handler runs, no drain, no
        preemption save. What survives on disk is whatever the atomic
        commit protocol already published; the chaos harness
        (resilience/chaos.py) asserts resume works from exactly that.
        With ``kill_during_checkpoint`` set, the kill belongs to the
        checkpoint writer instead (see :meth:`take_checkpoint_kill`) and
        this step-loop call never fires. Exact equality, not >=: a
        resumed run starting past the step must not re-fire."""
        at = self._cfg.kill_at_step
        if at is None or self._cfg.kill_during_checkpoint or step != at:
            return
        self._notify("kill", step)
        logger.warning("fault injection: delivering SIGKILL at step %d", step)
        os.kill(os.getpid(), signal.SIGKILL)

    def take_checkpoint_kill(self, step: int) -> bool:
        """True exactly once, for the save whose async write should die
        mid-commit (``kill_during_checkpoint``): the first save at/after
        ``kill_at_step`` (or the first save at all when unset). The
        checkpoint manager performs the actual SIGKILL between its staged
        files and the manifest publish — inside the write, on the writer
        thread, while the step loop runs on."""
        if not self._cfg.kill_during_checkpoint or self._kill_taken:
            return False
        at = self._cfg.kill_at_step
        if at is not None and step < at:
            return False
        self._kill_taken = True
        self._notify("kill_during_checkpoint", step)
        return True

    def maybe_hang(self, step: int, *, site: str = "host") -> None:
        """Block the calling thread FOR REAL at exactly the configured step
        (one-shot). No exception, no signal — the genuinely hang-shaped
        failure mode: from outside, the process is alive and doing nothing,
        which is exactly what the watchdog (resilience/watchdog.py) must
        detect and kill. With ``hang_duration_sec`` set the thread resumes
        afterwards (a controllable straggler stand-in); without it the
        block is indefinite and only the watchdog's ``os._exit`` (or the
        pod's liveness probe) ends the process. Exact equality, not >=:
        a resumed run starting past the step must not re-hang.

        ``site`` selects where the injection fires: the trainer's step
        loop calls with "host" (the default), the batch prefetcher's
        assembly thread with "prefetcher"; ``hang_in_prefetcher`` in the
        config picks which call actually blocks — a prefetcher hang
        starves the consumer on the queue instead of blocking the loop
        directly, and the watchdog must catch both signatures.
        """
        at = self._cfg.hang_at_step
        target_site = "prefetcher" if self._cfg.hang_in_prefetcher else "host"
        if at is None or self._hang_fired or step != at or site != target_site:
            return
        self._hang_fired = True
        self._notify(f"hang_{site}", step)
        duration = self._cfg.hang_duration_sec
        logger.warning(
            "fault injection: hanging the %s at step %d (%s)",
            "prefetch thread" if site == "prefetcher" else "host step loop",
            step,
            f"{duration:g}s" if duration is not None else "indefinitely",
        )
        deadline = None if duration is None else time.monotonic() + duration
        while deadline is None or time.monotonic() < deadline:
            time.sleep(0.05)

    def poison_host_losses(self, losses: Any, first_step: int) -> Any:
        """Scale the configured step's host-observed loss (one-shot).

        ``losses`` is the interval's loss vector; ``first_step`` is the
        optimizer step its first entry belongs to. Returns the (possibly
        copied and modified) vector.
        """
        at = self._cfg.spike_loss_at_step
        if at is None or self._spike_fired:
            return losses
        idx = at - first_step
        if 0 <= idx < len(losses):
            self._spike_fired = True
            self._notify("spike_loss", at)
            losses = losses.copy()
            losses[idx] = losses[idx] * self._cfg.spike_loss_scale
            logger.warning(
                "fault injection: scaled observed loss of step %d by x%g",
                at,
                self._cfg.spike_loss_scale,
            )
        return losses

    def maybe_corrupt_checkpoint(self, step: int, ckpt_mgr: Any) -> None:
        """Damage the newest checkpoint file after its save (one-shot).

        Drains the manager's async write first so the damage lands on the
        completed file, not a half-written tmp.
        """
        at = self._cfg.corrupt_checkpoint_at_step
        if at is None or self._corrupt_fired or step < at or ckpt_mgr is None:
            return
        ckpt_mgr.wait_pending()
        newest = ckpt_mgr.latest_checkpoint()
        if newest is None:
            return
        self._corrupt_fired = True
        self._notify("corrupt_checkpoint", step)
        data = newest.read_bytes()
        if self._cfg.corrupt_mode == "truncate":
            newest.write_bytes(data[: max(1, len(data) // 2)])
        else:  # garbage: flip a swath of bytes mid-file
            mid = len(data) // 2
            newest.write_bytes(
                data[:mid] + bytes(b ^ 0xFF for b in data[mid : mid + 64]) + data[mid + 64 :]
            )
        logger.warning(
            "fault injection: %s newest checkpoint %s after step-%d save",
            self._cfg.corrupt_mode + "d",
            newest.name,
            step,
        )

    # --------------------------------------------------------- flaky wiring

    def flaky(self, kind: str, fn: Callable[[], T]) -> Callable[[], T]:
        """Wrap ``fn`` so its first N calls raise InjectedFault, where N is
        the configured failure count for ``kind`` ("dataset_load" or
        "distributed_init"). With N == 0 the original callable is returned
        untouched."""
        budget = {
            "dataset_load": self._cfg.dataset_load_failures,
            "distributed_init": self._cfg.distributed_init_failures,
        }.get(kind, 0)
        if budget <= 0:
            return fn

        def wrapped() -> T:
            used = self._flaky_counts.get(kind, 0)
            if used < budget:
                self._flaky_counts[kind] = used + 1
                raise InjectedFault(
                    f"injected {kind} failure {used + 1}/{budget}"
                )
            return fn()

        return wrapped


__all__ = ["FaultPlan", "InjectedFault", "retry", "retry_rng"]
