"""Hang watchdog, progress beacon / heartbeat file, and straggler telemetry.

Crash-shaped failures raise; *hang-shaped* failures don't. A stuck
collective, a wedged data fetch, or a straggling host stalls the whole
multi-host job without ever raising, and a Job with no liveness signal
burns accelerator time until a human notices. This module supplies the
three signals production training treats as table stakes (TorchTitan's
hang detection, MinT's self-classifying jobs — see PAPERS.md):

* :class:`ProgressBeacon` — each optimizer step records (step, monotonic
  time) and touches a heartbeat file whose mtime freshness a k8s
  ``livenessProbe`` exec can check from outside the process.
* :class:`HangWatchdog` — a daemon thread that, when no progress lands
  within ``stall_timeout_sec``, dumps every thread's stack plus JAX
  device diagnostics to ``{report_dir}/hang_report_*.txt`` and hard-exits
  with the *retryable* :data:`~.exit_codes.EXIT_HANG_DETECTED` so the
  orchestrator restarts the pod instead of waiting on a dead collective.
  ``os._exit`` is deliberate: a hung XLA collective cannot be unwound by
  an exception, and a blocked main thread never reaches ``sys.exit``.
* :class:`StragglerTracker` — per-host step wall-times (allgathered by the
  trainer at log boundaries) reduced to max/median skew, with a
  persistent-straggler warning when the same host stays slowest.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from pathlib import Path
from typing import Any, Callable

import numpy as np

from ..utils.logging import get_logger
from .exit_codes import EXIT_HANG_DETECTED

logger = get_logger()


class ProgressBeacon:
    """Shared (step, monotonic time) progress record + heartbeat file.

    ``touch`` is called from the training loop once per step; the watchdog
    thread reads ``age_seconds`` without taking locks on the hot path's
    behalf (a single tuple assignment is atomic under the GIL, and the
    lock only guards the compound read-modify-write of the heartbeat
    rate limit).
    """

    def __init__(
        self,
        heartbeat_path: str | Path | None = None,
        *,
        heartbeat_interval_sec: float = 1.0,
    ) -> None:
        self._heartbeat_path = Path(heartbeat_path) if heartbeat_path else None
        self._heartbeat_interval = max(0.0, heartbeat_interval_sec)
        self._lock = threading.Lock()
        self._step = 0
        self._stamp = time.monotonic()
        self._last_heartbeat = -float("inf")

    @property
    def heartbeat_path(self) -> Path | None:
        return self._heartbeat_path

    def touch(self, step: int) -> None:
        """Record progress at ``step`` and (rate-limited) touch the
        heartbeat file. Never raises: liveness reporting must not be able
        to kill the run it reports on."""
        now = time.monotonic()
        with self._lock:
            self._step = step
            self._stamp = now
            write_heartbeat = (
                self._heartbeat_path is not None
                and now - self._last_heartbeat >= self._heartbeat_interval
            )
            if write_heartbeat:
                self._last_heartbeat = now
        if write_heartbeat:
            try:
                self._heartbeat_path.parent.mkdir(parents=True, exist_ok=True)
                self._heartbeat_path.touch()
            except OSError as exc:
                logger.warning("heartbeat touch failed (%s); continuing", exc)

    def snapshot(self) -> tuple[int, float]:
        """(last recorded step, seconds since it was recorded)."""
        with self._lock:
            return self._step, time.monotonic() - self._stamp

    @property
    def age_seconds(self) -> float:
        return self.snapshot()[1]


def heartbeat_age_seconds(path: str | Path) -> float | None:
    """Seconds since the heartbeat file was last touched (wall clock), or
    None when it does not exist — the same freshness computation the k8s
    ``livenessProbe`` exec performs with ``stat``."""
    try:
        return max(0.0, time.time() - Path(path).stat().st_mtime)
    except OSError:
        return None


def _format_thread_stacks() -> str:
    """Stack traces of every live thread, with names — the payload a hang
    post-mortem actually needs (which collective, which lock, which IO)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, "unknown")
        chunks.append(f"--- thread {name} (ident {ident}) ---")
        chunks.append("".join(traceback.format_stack(frame)))
    return "\n".join(chunks)


def _format_jax_diagnostics() -> str:
    """Best-effort JAX backend/device/memory snapshot. Every probe is
    individually guarded: a wedged runtime may fail any of them, and the
    report must still be written."""
    lines = []
    try:
        import jax

        lines.append(f"jax {jax.__version__}, backend {jax.default_backend()}")
        lines.append(
            f"process {jax.process_index()}/{jax.process_count()}, "
            f"{jax.local_device_count()} local device(s)"
        )
        for dev in jax.local_devices():
            entry = f"  {dev}"
            try:
                stats = dev.memory_stats()
                if stats:
                    used = stats.get("bytes_in_use")
                    limit = stats.get("bytes_limit")
                    if used is not None:
                        entry += f"  bytes_in_use={used}"
                    if limit is not None:
                        entry += f"  bytes_limit={limit}"
            except Exception:  # noqa: BLE001 — memory_stats is optional per backend
                pass
            lines.append(entry)
        try:
            live = len(list(jax.live_arrays()))
            lines.append(f"live arrays: {live}")
        except Exception:  # noqa: BLE001
            pass
    except Exception as exc:  # noqa: BLE001 — report must be written regardless
        lines.append(f"jax diagnostics unavailable: {exc}")
    return "\n".join(lines)


def write_hang_report(
    report_dir: str | Path,
    *,
    step: int,
    stall_seconds: float,
    stall_timeout_sec: float,
    process_index: int = 0,
    thread_stacks: str | None = None,
) -> Path | None:
    """Write ``hang_report_{utc}_p{rank}.txt`` with all-thread stacks and
    JAX diagnostics. Returns the path, or None when the write itself
    failed (logged; the watchdog still exits)."""
    stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
    target = Path(report_dir) / f"hang_report_{stamp}_p{process_index}.txt"
    body = "\n".join(
        [
            f"HANG REPORT — no training progress for {stall_seconds:.1f}s "
            f"(stall_timeout_sec={stall_timeout_sec:g})",
            f"last completed dispatch: step {step}",
            f"pid {os.getpid()}, process_index {process_index}",
            "",
            "== thread stacks ==",
            thread_stacks if thread_stacks is not None else _format_thread_stacks(),
            "",
            "== jax diagnostics ==",
            _format_jax_diagnostics(),
            "",
        ]
    )
    try:
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(body, encoding="utf-8")
        return target
    except OSError as exc:
        logger.error("failed to write hang report %s: %s", target, exc)
        return None


class HangWatchdog:
    """Daemon thread that hard-exits the process when the beacon stalls.

    ``exit_fn`` defaults to ``os._exit`` — the only exit that works when
    the main thread is blocked inside a dead collective (``sys.exit`` in a
    non-main thread only raises SystemExit in that thread, and atexit
    handlers can themselves deadlock on the wedged runtime). Tests inject
    a recording ``exit_fn`` instead.

    ``on_hang`` runs after the report is written and before the exit —
    the trainer uses it to drain-or-abandon the in-flight async checkpoint
    write with a bounded timeout; any exception it raises is logged and
    does not stop the exit.
    """

    def __init__(
        self,
        beacon: ProgressBeacon,
        *,
        stall_timeout_sec: float,
        report_dir: str | Path | None = None,
        poll_interval_sec: float | None = None,
        process_index: int = 0,
        exit_code: int = EXIT_HANG_DETECTED,
        exit_fn: Callable[[int], Any] = os._exit,
        on_hang: Callable[[], Any] | None = None,
        timeline: Any | None = None,
    ) -> None:
        if stall_timeout_sec <= 0:
            raise ValueError("stall_timeout_sec must be positive")
        self._beacon = beacon
        self._timeout = float(stall_timeout_sec)
        # Poll ~10x per timeout window so detection latency stays within
        # ~10% of the configured timeout, without busy-waiting sub-second
        # timeouts harder than needed.
        self._poll = (
            float(poll_interval_sec)
            if poll_interval_sec is not None
            else max(0.05, self._timeout / 10.0)
        )
        self._report_dir = Path(report_dir) if report_dir is not None else None
        self._process_index = process_index
        self._exit_code = exit_code
        self._exit_fn = exit_fn
        self._on_hang = on_hang
        # Optional EventTimeline flushed as the LAST act before exit_fn:
        # on_hang already flushes it, but on_hang rides the bounded worker
        # and can be abandoned wholesale when the drain wedges — this
        # direct flush is what keeps the hang's badput attributable in
        # telemetry/goodput.py even then (flush never raises by contract).
        self._timeline = timeline
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.fired = False
        self.report_path: Path | None = None

    @property
    def armed(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def arm(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="hang-watchdog", daemon=True
        )
        self._thread.start()
        logger.info(
            "hang watchdog armed: stall_timeout_sec=%g (retryable exit %d on stall)",
            self._timeout,
            self._exit_code,
        )

    def disarm(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=max(1.0, 2 * self._poll))
            self._thread = None

    def __enter__(self) -> "HangWatchdog":
        self.arm()
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.disarm()

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            step, age = self._beacon.snapshot()
            if age >= self._timeout:
                self._fire(step, age)
                return

    # Bound on the post-detection work (report write, jax probes, on_hang
    # drain) before the exit proceeds regardless: every one of those can
    # block on the SAME wedged runtime/storage being diagnosed, and the
    # exit-76 guarantee outranks a complete report.
    _FIRE_WORK_TIMEOUT_SEC = 30.0

    def _fire(self, step: int, age: float) -> None:
        self.fired = True
        # Detection notice + stacks FIRST, as raw stderr writes — not via
        # logging: a FileHandler on the same wedged PVC that caused the
        # hang would block logger.critical forever (while holding the
        # logging lock), and the exit-76 guarantee outranks everything.
        # Raw stderr is pure-python and cannot touch the wedged storage.
        stacks = _format_thread_stacks()
        try:
            sys.stderr.write(
                f"HANG DETECTED: no training progress for {age:.1f}s "
                f"(timeout {self._timeout:g}s, last step {step}); dumping "
                f"stacks and exiting {self._exit_code} (retryable) so the "
                "orchestrator restarts this pod\n"
                "== hang watchdog thread stacks ==\n" + stacks + "\n"
            )
            sys.stderr.flush()
        except OSError:  # pragma: no cover - stderr gone
            pass

        def slow_work() -> None:
            # Logging lives INSIDE the bounded worker for the same reason:
            # a handler on dead storage must not hold the exit hostage.
            logger.critical(
                "HANG DETECTED: no training progress for %.1fs (timeout "
                "%gs, last step %d); exiting %d (retryable)",
                age,
                self._timeout,
                step,
                self._exit_code,
            )
            if self._report_dir is not None:
                self.report_path = write_hang_report(
                    self._report_dir,
                    step=step,
                    stall_seconds=age,
                    stall_timeout_sec=self._timeout,
                    process_index=self._process_index,
                    thread_stacks=stacks,
                )
                if self.report_path is not None:
                    logger.critical("hang report written to %s", self.report_path)
            if self._on_hang is not None:
                try:
                    self._on_hang()
                except Exception as exc:  # noqa: BLE001 — the exit must proceed
                    logger.error("watchdog on_hang hook failed: %s", exc)

        # Daemon helper + bounded join: report/diagnostics/drain get their
        # chance, but a PVC or runtime wedge cannot hold the exit hostage.
        worker = threading.Thread(
            target=slow_work, name="hang-watchdog-report", daemon=True
        )
        worker.start()
        worker.join(self._FIRE_WORK_TIMEOUT_SEC)
        if worker.is_alive():
            # Raw stderr, not logging: the worker may be blocked INSIDE a
            # logging handler, holding the lock logger.error would need.
            try:
                sys.stderr.write(
                    f"hang report/drain still blocked after "
                    f"{self._FIRE_WORK_TIMEOUT_SEC:.0f}s; exiting without it\n"
                )
                sys.stderr.flush()
            except OSError:  # pragma: no cover - stderr gone
                pass
        if self._timeline is not None:
            try:
                self._timeline.flush()
            except Exception:  # noqa: BLE001 — the exit-76 guarantee wins
                pass
        self._exit_fn(self._exit_code)


class StragglerTracker:
    """Fold per-host step wall-times into skew telemetry.

    ``observe`` takes the allgathered per-host mean step times of one log
    interval and returns a report dict; when the SAME host stays slowest
    with skew above ``skew_factor`` for ``patience`` consecutive
    intervals, ``persistent`` flips True — the trainer logs that as a
    warning (a transient GC pause or rebalance is noise; the same host
    being 2x slower every interval is a sick host).
    """

    def __init__(self, *, skew_factor: float = 2.0, patience: int = 3) -> None:
        if skew_factor <= 1.0:
            raise ValueError("skew_factor must be > 1")
        self._skew_factor = skew_factor
        self._patience = max(1, patience)
        self._streak_host: int | None = None
        self._streak = 0

    def observe(self, per_host_step_time: np.ndarray) -> dict[str, Any]:
        times = np.asarray(per_host_step_time, dtype=np.float64).reshape(-1)
        slowest = int(np.argmax(times))
        t_max = float(times[slowest])
        # Median over the OTHER hosts: on small host counts the straggler
        # itself would drag the plain median up and mask its own skew
        # (2 hosts: max/median(all) can never exceed 2 - epsilon).
        others = np.delete(times, slowest) if times.size > 1 else times
        t_med = float(np.median(others))
        skew = t_max / t_med if t_med > 0 else 1.0
        if skew >= self._skew_factor:
            self._streak = self._streak + 1 if slowest == self._streak_host else 1
            self._streak_host = slowest
        else:
            self._streak_host = None
            self._streak = 0
        return {
            "max_sec": t_max,
            "median_sec": t_med,
            "skew": skew,
            "slowest_host": slowest,
            "streak": self._streak,
            "persistent": self._streak >= self._patience,
        }


__all__ = [
    "HangWatchdog",
    "ProgressBeacon",
    "StragglerTracker",
    "heartbeat_age_seconds",
    "write_hang_report",
]
