"""Elastic topology-change classification for checkpoint resume.

A run that dies on 8 processes must be able to come back on whatever
capacity the cluster returns — 4, 2, 1 — without changing the training
math (the Varuna/Bamboo elastic-recovery argument, PAPERS.md). In this
framework that contract is checkable up front: checkpoints store FULL
host arrays, the data stream is a pure function of ``(seed, global batch
index)``, and RNG folds from the step alone, so a resume reproduces the
exact trajectory iff

* the **global** micro-batch (``micro_batch_size × data-parallel degree``)
  is unchanged — the sampler's batch contents depend on nothing else;
* ``grad_accum_steps`` is unchanged — it defines how micro-batches group
  into optimizer steps, i.e. the meaning of "step N";
* the model-parallel axes (``tensor``/``sequence``/``pipeline``) are
  unchanged — re-partitioning the contraction dimensions reorders the
  floating-point reductions inside the step, which silently breaks the
  identical-trajectory guarantee the resume claims.

Re-sharding over the BATCH axes (``data``/``fsdp``/``expert``) is the
elastic case: params/optimizer state land on the new mesh through
``parallel/sharding.py`` and the sampler offsets recompute from the
manifest-recorded global-batch progress. Everything else aborts with
:class:`TopologyMismatchError` — mapped to exit code 2 (config error) by
``resilience/exit_codes.py``, because retrying the same config replays
the same mismatch.

Deliberately dependency-free (dict math only): the exit-code taxonomy and
the chaos harness import it without dragging in jax.
"""

from __future__ import annotations

from typing import Any

# Mesh axes whose resize is a pure re-shard of batch-dim data (elastic);
# all other axes re-partition the model math itself.
ELASTIC_AXES = ("data", "fsdp", "expert")
MODEL_AXES = ("tensor", "sequence", "pipeline")


class TopologyMismatchError(RuntimeError):
    """The saved and current topologies cannot produce the same trajectory
    (tensor-parallel degree changed, global batch changed, ...). Exit
    code 2: deterministic config problem, retrying replays it."""


def describe_topology(
    mesh_sizes: dict[str, int],
    *,
    data_parallel: int,
    global_micro_batch: int,
    micro_batch_size: int,
    grad_accum_steps: int,
    num_processes: int = 1,
) -> dict[str, Any]:
    """The topology block a checkpoint manifest records (and resume
    validates against). Plain ints/dicts only — it must survive JSON."""
    return {
        "mesh": {k: int(v) for k, v in mesh_sizes.items()},
        "data_parallel": int(data_parallel),
        "global_micro_batch": int(global_micro_batch),
        "micro_batch_size": int(micro_batch_size),
        "grad_accum_steps": int(grad_accum_steps),
        "num_processes": int(num_processes),
    }


def classify_topology_change(
    saved: dict[str, Any] | None, current: dict[str, Any]
) -> dict[str, Any]:
    """Compare a manifest's topology block against the resuming run's.

    Returns ``{"elastic": bool, "changes": [str, ...]}`` when the resume
    can proceed (``elastic`` means the mesh changed but only over batch
    axes — param/optimizer state re-shards, trajectory is preserved), or
    raises :class:`TopologyMismatchError` with an actionable message when
    it cannot. ``saved=None`` (pre-manifest checkpoint, synthesized
    manifest) validates nothing: the topology is unknown, resume proceeds
    as it always did.
    """
    if not saved:
        return {"elastic": False, "changes": []}
    changes: list[str] = []
    saved_mesh = saved.get("mesh") or {}
    cur_mesh = current.get("mesh") or {}
    for axis in MODEL_AXES:
        was, now = int(saved_mesh.get(axis, 1)), int(cur_mesh.get(axis, 1))
        if was != now:
            raise TopologyMismatchError(
                f"checkpoint was saved with mesh axis {axis!r}={was} but this "
                f"run uses {axis}={now}: re-partitioning the {axis} axis "
                "changes the in-step reduction order, so the resumed "
                "trajectory would silently diverge from the saved run. "
                "Restore on a mesh with the same "
                f"{'/'.join(MODEL_AXES)} degrees (batch axes "
                f"{'/'.join(ELASTIC_AXES)} may change freely)."
            )
    saved_global = saved.get("global_micro_batch")
    cur_global = current.get("global_micro_batch")
    if saved_global is not None and int(saved_global) != int(cur_global):
        raise TopologyMismatchError(
            f"checkpoint was saved with a global micro-batch of "
            f"{int(saved_global)} (micro_batch_size "
            f"{saved.get('micro_batch_size')} x data-parallel "
            f"{saved.get('data_parallel')}) but this run produces "
            f"{int(cur_global)} (micro_batch_size "
            f"{current.get('micro_batch_size')} x data-parallel "
            f"{current.get('data_parallel')}): the deterministic sampler "
            "maps (seed, batch index) -> examples through the GLOBAL batch "
            "size, so changing it re-deals the data stream. To resume on a "
            "different world size, scale trainer.micro_batch_size inversely "
            "so micro_batch_size x data_parallel stays constant."
        )
    saved_accum = saved.get("grad_accum_steps")
    if saved_accum is not None and int(saved_accum) != int(
        current.get("grad_accum_steps")
    ):
        raise TopologyMismatchError(
            f"checkpoint was saved with grad_accum_steps="
            f"{int(saved_accum)} but this run uses "
            f"{int(current.get('grad_accum_steps'))}: accumulation defines "
            "how micro-batches group into optimizer steps, so step numbers "
            "(and the resume point) would mean different data. Keep "
            "grad_accum_steps fixed across resumes."
        )
    for axis in ELASTIC_AXES:
        was, now = int(saved_mesh.get(axis, 1)), int(cur_mesh.get(axis, 1))
        if was != now:
            changes.append(f"{axis}: {was} -> {now}")
    saved_procs = saved.get("num_processes")
    if saved_procs is not None and int(saved_procs) != int(
        current.get("num_processes", 1)
    ):
        changes.append(
            f"processes: {int(saved_procs)} -> {int(current.get('num_processes', 1))}"
        )
    return {"elastic": bool(changes), "changes": changes}


def resume_batch_index(
    saved_data: dict[str, Any] | None, *, step: int, grad_accum_steps: int
) -> int:
    """First global micro-batch index the resumed run consumes, recomputed
    from the manifest's recorded progress.

    The sampler is stateless — batch ``b`` is a function of ``(seed, b)``
    — so "sampler state" is exactly one integer: how many global
    micro-batches the saved run had consumed (its rollback-advanced
    ``data_offset`` included). When the manifest predates that record (or
    was synthesized), the index falls back to pure step math, which is the
    pre-elastic behavior."""
    base = step * grad_accum_steps
    if not saved_data:
        return base
    consumed = saved_data.get("consumed_micro_batches")
    if consumed is None:
        return base
    return int(consumed)


__all__ = [
    "ELASTIC_AXES",
    "MODEL_AXES",
    "TopologyMismatchError",
    "classify_topology_change",
    "describe_topology",
    "resume_batch_index",
]
