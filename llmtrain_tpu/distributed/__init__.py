"""Multi-process runtime + named device mesh.

Parity target: reference ``src/llmtrain/distributed/__init__.py`` (DDPState,
setup_ddp, teardown_ddp) re-imagined for JAX:

* ``DistState`` mirrors ``DDPState`` (frozen, ``is_main == (rank == 0)``
  invariant enforced in ``__post_init__``, reference :28-31).
* ``setup_distributed`` mirrors ``setup_ddp``'s contract — idempotent with a
  warning (reference :75-93), env-var-first resolution with config fallback
  (reference :100-118) — but rendezvous is ``jax.distributed.initialize``
  (coordinator over DCN) instead of a gloo process group. The same env names
  (RANK/WORLD_SIZE/MASTER_ADDR/MASTER_PORT) are honoured so the K8s
  IndexedJob bootstrap carries over unchanged; JAX-native names
  (JAX_PROCESS_ID/JAX_NUM_PROCESSES/JAX_COORDINATOR_ADDRESS) win over them.
* There is no DDP wrapper to build: gradient sync is a sharding property of
  the jit-compiled train step (see ``llmtrain_tpu/parallel``), with XLA
  emitting psum/reduce-scatter over ICI.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax

from ..config.schemas import DistributedConfig, MeshConfig
from ..utils.logging import get_logger

_DEFAULT_COORDINATOR_PORT = 29500

# Module-level idempotency latch (the analogue of torch's
# dist.is_initialized() check, reference distributed/__init__.py:75).
_ACTIVE_STATE: "DistState | None" = None
_JAX_DIST_INITIALIZED = False


@dataclass(frozen=True)
class DistState:
    """Resolved multi-process topology for this process.

    ``process_index``/``num_processes`` are the JAX names for the reference's
    rank/world_size; ``is_main`` gates all filesystem and tracker I/O.
    """

    process_index: int
    num_processes: int
    local_device_count: int
    is_main: bool
    coordinator: str | None = None

    def __post_init__(self) -> None:
        if self.num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        if not (0 <= self.process_index < self.num_processes):
            raise ValueError("process_index must be in [0, num_processes)")
        if self.is_main != (self.process_index == 0):
            raise ValueError("is_main must equal (process_index == 0)")

    # Reference-compatible aliases (DDPState.rank / .world_size).
    @property
    def rank(self) -> int:
        return self.process_index

    @property
    def world_size(self) -> int:
        return self.num_processes


def _env_int(*names: str) -> int | None:
    for name in names:
        raw = os.environ.get(name)
        if raw is not None and raw != "":
            try:
                return int(raw)
            except ValueError as exc:
                raise ValueError(f"Environment variable {name}={raw!r} is not an integer") from exc
    return None


def _resolve_int(env_names: tuple[str, ...], config_value: int | None, default: int) -> int:
    env_val = _env_int(*env_names)
    if env_val is not None:
        return env_val
    if config_value is not None:
        return config_value
    return default


def resolve_topology(cfg: DistributedConfig) -> tuple[int, int, str | None]:
    """Resolve (process_id, num_processes, coordinator) env-first.

    JAX-native env names beat torch-compat names beat config values beat
    defaults — mirroring reference distributed/__init__.py:100-118.
    """
    num_processes = _resolve_int(("JAX_NUM_PROCESSES", "WORLD_SIZE"), cfg.num_processes, 1)
    explicit_process_id = _env_int("JAX_PROCESS_ID", "RANK")
    if explicit_process_id is None:
        explicit_process_id = cfg.process_id
    if explicit_process_id is None and num_processes > 1:
        # Fail fast with a diagnosable error instead of letting every process
        # claim rank 0 and hang in rendezvous until the timeout.
        raise ValueError(
            "Multi-process run (num_processes "
            f"= {num_processes}) but process id is unset; set RANK/JAX_PROCESS_ID "
            "or distributed.process_id"
        )
    process_id = explicit_process_id if explicit_process_id is not None else 0

    # "" counts as unset for the address, matching _env_int's empty-as-unset rule.
    coordinator = os.environ.get("JAX_COORDINATOR_ADDRESS") or None
    if coordinator is None:
        addr = os.environ.get("MASTER_ADDR") or cfg.coordinator_addr
        port = _resolve_int(("MASTER_PORT",), cfg.coordinator_port, _DEFAULT_COORDINATOR_PORT)
        coordinator = f"{addr}:{port}" if addr else None
    return process_id, num_processes, coordinator


def configure_platform(device: str) -> None:
    """Pin the JAX platform to match ``run.device`` BEFORE backend init.

    Required on hosts whose sitecustomize registers an accelerator PJRT
    plugin: with a plugin registered, ``jax.process_index()`` consults the
    plugin's backend and can report 0 in every process unless the platform
    is pinned via jax.config (the JAX_PLATFORMS env var alone is not
    honoured once the plugin is registered). ``tpu`` leaves the default
    accelerator backend in place.
    """
    if device != "cpu":
        return
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception as exc:  # backend already initialized — too late to switch
        get_logger().warning("could not pin jax platform to cpu: %s", exc)


def resolve_compilation_cache_dir(config_dir: str | None = None) -> str | None:
    """The directory ``configure_compilation_cache`` will use, or None when
    disabled via ``LLMTRAIN_COMPILATION_CACHE=off``. Single owner of the
    env-token and default-path conventions (bench.py's cache telemetry
    reads it too).

    Precedence: the ``LLMTRAIN_COMPILATION_CACHE`` env var (including the
    "off" disable tokens) beats ``config_dir`` (``run.compilation_cache_dir``
    from the config) beats the built-in default — the same env-beats-config
    rule every other knob in this module follows.
    """
    env = os.environ.get("LLMTRAIN_COMPILATION_CACHE", "")
    low = env.lower()
    if low in ("off", "0", "false", "no", "disable"):
        return None
    if low in ("on", "1", "true", "yes"):
        env = ""  # boolean-ish enable: use the default dir, not a dir named "true"
    return (
        env
        or config_dir
        or os.path.join(os.path.expanduser("~"), ".cache", "llmtrain_tpu", "jax")
    )


def configure_compilation_cache(config_dir: str | None = None) -> None:
    """Enable JAX's persistent compilation cache (new capability; the
    reference has no compiled artifacts to cache).

    On the tunneled TPU a first compile costs 20-40s; caching it on disk
    makes repeated runs (bench watchdog attempts, auto-sweep candidates,
    podFailurePolicy-restarted k8s Jobs) pay it once. Default dir:
    ``~/.cache/llmtrain_tpu/jax`` (stable across CWDs so identical programs
    actually hit); ``run.compilation_cache_dir`` in the config (passed here
    as ``config_dir``) overrides the default, and the
    ``LLMTRAIN_COMPILATION_CACHE`` env var overrides both (``off`` disables).
    Safe to call multiple times."""
    path = resolve_compilation_cache_dir(config_dir)
    if path is None:
        return
    try:
        # Cache everything that took noticeable compile time; tiny programs
        # aren't worth the disk round-trip. Set BEFORE the dir: the cache
        # activates on the dir update, so a jax version missing this tuning
        # knob degrades to its default threshold instead of no cache.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as exc:  # unknown config on this jax version
        get_logger().warning("compilation cache tuning unavailable: %s", exc)
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(path))
    except Exception as exc:  # unknown config on old jax, unwritable dir, ...
        get_logger().warning("compilation cache disabled: %s", exc)


def _tpu_autodetect_available(cfg: DistributedConfig) -> bool:
    """True when a MULTI-host TPU pod-slice env can drive a bare
    ``initialize()`` and no explicit topology was given (explicit env/config
    always wins). Single-host slices need no distributed init at all."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h.strip()]) < 2:
        return False
    explicit = (
        _env_int("JAX_NUM_PROCESSES", "WORLD_SIZE") is not None
        or cfg.num_processes is not None
    )
    return not explicit


def setup_distributed(cfg: DistributedConfig) -> DistState:
    """Initialize the JAX distributed runtime (idempotent).

    With one process this is a no-op beyond resolving the topology. With
    several, all processes block in ``jax.distributed.initialize`` until the
    coordinator has heard from everyone — the process-group boundary the
    reference hits in ``dist.init_process_group`` (reference :130-136).
    """
    global _ACTIVE_STATE, _JAX_DIST_INITIALIZED
    logger = get_logger()

    if _ACTIVE_STATE is not None:
        logger.warning("distributed runtime already initialized; returning existing state")
        return _ACTIVE_STATE

    if _tpu_autodetect_available(cfg):
        # GKE TPU pod slice: the TPU runtime env (TPU_WORKER_ID /
        # TPU_WORKER_HOSTNAMES, injected by the GKE webhook) lets JAX derive
        # coordinator + process ids itself — no explicit topology needed.
        jax.distributed.initialize()
        _JAX_DIST_INITIALIZED = True
        state = DistState(
            process_index=jax.process_index(),
            num_processes=jax.process_count(),
            local_device_count=jax.local_device_count(),
            is_main=jax.process_index() == 0,
            coordinator=None,
        )
        _ACTIVE_STATE = state
        logger.info(
            "distributed runtime auto-initialized from TPU environment: "
            "process %d/%d, %d local device(s)",
            state.process_index,
            state.num_processes,
            state.local_device_count,
        )
        return state

    process_id, num_processes, coordinator = resolve_topology(cfg)

    if num_processes > 1:
        if coordinator is None:
            raise ValueError(
                "Multi-process run requires a coordinator address "
                "(set MASTER_ADDR/MASTER_PORT, JAX_COORDINATOR_ADDRESS, "
                "or distributed.coordinator_addr/coordinator_port)"
            )
        init_kwargs: dict = dict(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=None,
            initialization_timeout=cfg.timeout_sec,
        )
        # The shutdown barrier must tolerate the same straggler skew as
        # startup: on oversubscribed hosts (N procs per core in CI) ranks
        # can reach teardown minutes apart, and jax's 300 s default then
        # kills otherwise-green runs at the very end. The knob only exists
        # on newer jax — gate on the signature so older versions rendezvous
        # instead of dying on an unexpected kwarg.
        import inspect

        if (
            "shutdown_timeout_seconds"
            in inspect.signature(jax.distributed.initialize).parameters
        ):
            init_kwargs["shutdown_timeout_seconds"] = max(300, cfg.timeout_sec)
        try:
            jax.distributed.initialize(**init_kwargs)
        except Exception:
            # A failed connect (coordinator not up yet — the case the CLI's
            # backoff retry exists for) leaves jax's global distributed
            # state partially set; without a teardown every later attempt
            # dies on "initialize should only be called once" instead of
            # actually retrying the rendezvous. shutdown() resets
            # client/service to None, making initialize callable again.
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
            raise
        _JAX_DIST_INITIALIZED = True
        process_id = jax.process_index()
        num_processes = jax.process_count()

    state = DistState(
        process_index=process_id,
        num_processes=num_processes,
        local_device_count=jax.local_device_count(),
        is_main=process_id == 0,
        coordinator=coordinator,
    )
    _ACTIVE_STATE = state
    logger.info(
        "distributed runtime ready: process %d/%d, %d local device(s)",
        state.process_index,
        state.num_processes,
        state.local_device_count,
    )
    return state


def teardown_distributed() -> None:
    """Shut down the distributed runtime if this process started it."""
    global _ACTIVE_STATE, _JAX_DIST_INITIALIZED
    if _JAX_DIST_INITIALIZED:
        jax.distributed.shutdown()
        _JAX_DIST_INITIALIZED = False
    _ACTIVE_STATE = None


def active_state() -> DistState | None:
    return _ACTIVE_STATE


def allgather_any(flag: bool) -> bool:
    """Cross-process OR of a local boolean (collective: EVERY process must
    call this at the same point). Single-process: identity. The consensus
    primitive for "did ANY rank see it" decisions — preemption stop,
    loss-spike rollback — where acting on a local-only flag would desync
    the ranks into a deadlocked collective."""
    if jax.process_count() == 1:
        return bool(flag)
    import numpy as np
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.asarray([bool(flag)]))
    return bool(np.asarray(gathered).any())


def allgather_scalar(value: float) -> "list[float]":
    """Per-process list of a local scalar, indexed by process id
    (collective). Single-process: one-element list. Feeds the straggler
    telemetry's per-host step times."""
    import numpy as np

    if jax.process_count() == 1:
        return [float(value)]
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.asarray([float(value)]))
    return [float(x) for x in np.asarray(gathered).reshape(-1)]


def broadcast_int_from_main(value: int) -> int:
    """Every process returns process 0's value (collective). Single-process:
    identity. Used where rank 0 owns the decision (e.g. which checkpoint
    step a rollback restores) and the others must follow it exactly."""
    if jax.process_count() == 1:
        return int(value)
    import numpy as np
    from jax.experimental import multihost_utils

    agreed = multihost_utils.broadcast_one_to_all(np.int64(value))
    return int(np.asarray(agreed))


MESH_AXES = ("data", "fsdp", "tensor", "sequence", "pipeline", "expert")


def resolve_mesh_axes(mesh_cfg: MeshConfig, device_count: int) -> dict[str, int]:
    """Materialize axis sizes, expanding a single ``-1`` wildcard.

    The math lives in the mesh planner (autotune/plan.py) — one owner for
    wildcard/divisibility resolution across trainer, fleet, and tuner.
    Failures raise ``MeshPlanError`` (a ValueError subclass mapped to the
    config exit code 2) instead of surfacing as an opaque pjit error.
    """
    from ..autotune.plan import resolve_axis_sizes

    return resolve_axis_sizes(mesh_cfg.axis_sizes(), device_count)


def build_mesh(mesh_cfg: MeshConfig | None = None, devices=None) -> jax.sharding.Mesh:
    """Build the global named device mesh.

    Axis order puts ``data`` outermost (slowest-varying) so data-parallel
    replicas span hosts/DCN while tensor/sequence shards stay within a host's
    ICI neighbourhood — the layout recommended by the scaling playbook.
    """
    from jax.experimental import mesh_utils

    if mesh_cfg is None:
        mesh_cfg = MeshConfig()
    if devices is None:
        devices = jax.devices()
    sizes = resolve_mesh_axes(mesh_cfg, len(devices))
    shape = tuple(sizes[a] for a in MESH_AXES)
    device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    return jax.sharding.Mesh(device_array, MESH_AXES)
