"""Probe-fit orchestration for ``llmtrain tune``.

Survivors of the analytic pruning pass (autotune/search.py) run as short
seeded training fits in budget-aware subprocesses — the bench.py
scenario-child pattern: each candidate gets its own ``llmtrain train``
child with a derived config, a wall-clock timeout, and a pinned device
topology, and is scored from the run's durable ``report.json``
(``perf_attribution`` measured MFU, PR 10's substrate). The untuned
config is always probed first and is exempt from the probe cap, so the
emitted winner's measured MFU is >= the untuned baseline's by
construction.

The emitted artifact is the ORIGINAL config dump with only the winning
plan's overrides merged in — probe-only knobs (max_steps, cadences,
output dir) never leak into it — re-validated through RunConfig before
it is written, so ``llmtrain train --config <emitted>`` accepts it
unchanged.
"""

from __future__ import annotations

import json
import logging
import os
import re
import subprocess
import sys
import time
from pathlib import Path
from typing import Any

from ..resilience.harness import deep_merge
from .plan import MeshPlan, caps_from_config, plan_from_config
from .search import enumerate_candidates, prune_candidates, resolve_hbm_limit

logger = logging.getLogger("llmtrain")

# Probe fits must finish, not train: huge cadences disable eval/save, and
# warmup is clamped to 0 so the warmup<=max_steps validator holds at tiny
# probe step counts.
_NEVER = 10**9


def _probe_overrides(
    plan: MeshPlan, *, probe_steps: int, workdir: str, run_id: str
) -> dict[str, Any]:
    return deep_merge(
        plan.config_overrides(),
        {
            "trainer": {
                "max_steps": probe_steps,
                "warmup_steps": 0,
                "log_every_steps": 1,
                "eval_every_steps": _NEVER,
                "save_every_steps": _NEVER,
            },
            "telemetry": {
                "prometheus": False,
                "report": True,
                "perf_attribution": True,
            },
            "mlflow": {"enabled": False},
            "output": {"root_dir": workdir, "run_id": run_id},
        },
    )


def _pin_child_topology(env: dict[str, str], device_count: int) -> dict[str, str]:
    """The plan was resolved against the parent's device count; a probe
    child on the cpu backend must see exactly the same — strip any
    inherited host-device-count flag and pin our own (bench.py idiom)."""
    if env.get("JAX_PLATFORMS", "").lower() not in ("", "cpu"):
        return env
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append(f"--xla_force_host_platform_device_count={device_count}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def _run_probe(
    base_dump: dict[str, Any],
    plan: MeshPlan,
    *,
    config_cls: type,
    workdir: Path,
    run_id: str,
    probe_steps: int,
    timeout_sec: float,
    device_count: int,
) -> dict[str, Any]:
    """One candidate probe fit in a subprocess. Returns a measurement
    record; ``status`` != "ok" carries the failure reason instead of a
    score."""
    import yaml

    record: dict[str, Any] = {"key": plan.key(), "run_id": run_id}
    if plan.activation_tiers:
        # The tier ladder, named explicitly (it is also suffixed into the
        # key) so perf_gate's tuned-plan "winner changed" notes and report
        # consumers see which activation regime the winner runs.
        record["activation_tiers"] = plan.activation_tiers
    dump = deep_merge(
        base_dump,
        _probe_overrides(
            plan, probe_steps=probe_steps, workdir=str(workdir), run_id=run_id
        ),
    )
    try:
        config_cls.model_validate(dump)
    except Exception as exc:  # pydantic.ValidationError
        record.update(status="invalid-config", reason=str(exc))
        return record

    cfg_path = workdir / f"{run_id}.yaml"
    cfg_path.write_text(yaml.safe_dump(dump, sort_keys=False))

    env = _pin_child_topology(dict(os.environ), device_count)
    cmd = [
        sys.executable,
        "-m",
        "llmtrain_tpu",
        "train",
        "--config",
        str(cfg_path),
        "--json",
    ]
    start = time.monotonic()
    try:
        proc = subprocess.run(
            cmd, env=env, capture_output=True, text=True, timeout=timeout_sec
        )
    except subprocess.TimeoutExpired:
        record.update(
            status="timeout",
            reason=f"probe exceeded tune.probe_timeout_sec={timeout_sec:g}",
        )
        return record
    record["probe_wall_sec"] = round(time.monotonic() - start, 3)
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
        record.update(
            status="failed",
            reason=f"train exited {proc.returncode}: " + " | ".join(tail),
        )
        return record

    report_path = workdir / run_id / "report.json"
    try:
        report = json.loads(report_path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        record.update(status="no-report", reason=f"{report_path}: {exc}")
        return record

    throughput = report.get("throughput") or {}
    attribution = report.get("perf_attribution") or {}
    mfu_block = attribution.get("mfu") or {}
    mfu = mfu_block.get("measured")
    if mfu is None:
        mfu = throughput.get("mfu")
    if mfu is None:
        record.update(
            status="no-score",
            reason="report.json has neither perf_attribution.mfu.measured "
            "nor throughput.mfu",
        )
        return record
    record.update(
        status="ok",
        mfu=float(mfu),
        step_time_sec=throughput.get("step_time_sec"),
        tokens_per_sec=throughput.get("tokens_per_sec"),
        roofline_class=attribution.get("roofline", {}).get("class"),
        mfu_reconciled=mfu_block.get("reconciled"),
        mfu_ratio=mfu_block.get("ratio_analytical_over_measured"),
    )
    return record


def _slug(key: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", key)


def run_tune(
    cfg: Any,
    base_dump: dict[str, Any],
    *,
    workdir: str | Path,
    output_path: str | Path,
    device_count: int | None = None,
) -> dict[str, Any]:
    """The full tune: enumerate -> prune analytically -> probe survivors
    -> emit the winner as a loadable config at ``output_path``.

    ``base_dump`` is the resolved-but-unmodified config dict (what
    ``cfg.model_dump()`` or the loader produced); the emitted YAML is
    this dump plus the winning plan's overrides only. Returns the tune
    report (also written to ``{workdir}/tune_report.json``) — it lists
    every enumerated candidate's fate: pruned (with reason), measured
    (with score), or budget-skipped. No silent caps.
    """
    from ..registry import get_model_adapter, initialize_registries
    from ..telemetry.profiling import resolve_peaks

    started = time.monotonic()
    workdir = Path(workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    config_cls = type(cfg)

    if device_count is None:
        import jax

        device_count = jax.device_count()
    initialize_registries()
    adapter = get_model_adapter(cfg.model.name)
    caps = caps_from_config(cfg, adapter=adapter)
    peaks = resolve_peaks(None, cfg.telemetry.device_peaks)
    device_kind = str(peaks.get("device_kind", "cpu"))
    tune_cfg = cfg.tune
    seed = tune_cfg.seed if tune_cfg.seed is not None else cfg.run.seed
    hbm_limit = resolve_hbm_limit(device_kind, tune_cfg.hbm_limit_bytes)

    baseline_plan = plan_from_config(cfg, device_count, adapter=adapter)
    candidates = enumerate_candidates(
        cfg,
        device_count,
        seed=seed,
        microbatch_candidates=tune_cfg.microbatch_candidates,
        search_mesh=tune_cfg.search_mesh,
        search_remat=tune_cfg.search_remat,
        search_zero=tune_cfg.search_zero,
    )
    pruning = prune_candidates(
        candidates,
        cfg,
        device_count=device_count,
        caps=caps,
        peaks=peaks,
        hbm_limit_bytes=hbm_limit,
        max_probes=tune_cfg.max_probes,
        baseline_topology=(
            baseline_plan.describe_topology() if tune_cfg.preserve_topology else None
        ),
    )
    survivors = pruning["survivors"]
    logger.info(
        "tune: %d candidates enumerated, %d pruned analytically, "
        "%d survivors to probe (+ baseline)",
        pruning["enumerated"],
        len(pruning["pruned"]),
        len(survivors),
    )

    # Baseline first, always, and exempt from the probe cap: the winner's
    # measured MFU can then never fall below the untuned config's.
    deadline = started + tune_cfg.budget_sec
    measured: list[dict[str, Any]] = []
    baseline_record = _run_probe(
        base_dump,
        baseline_plan,
        config_cls=config_cls,
        workdir=workdir,
        run_id="probe_baseline",
        probe_steps=tune_cfg.probe_steps,
        timeout_sec=tune_cfg.probe_timeout_sec,
        device_count=device_count,
    )
    baseline_record["baseline"] = True
    measured.append(baseline_record)

    probed_keys = {baseline_plan.key()}
    for idx, cand in enumerate(survivors):
        plan = cand.plan
        assert plan is not None
        if plan.key() in probed_keys:
            measured.append(
                {
                    "key": plan.key(),
                    "status": "deduplicated",
                    "reason": "identical to an already-probed plan",
                    "predicted": cand.predicted.get("predicted_step_ms"),
                }
            )
            continue
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            measured.append(
                {
                    "key": plan.key(),
                    "status": "budget-skipped",
                    "reason": f"tune.budget_sec={tune_cfg.budget_sec:g} exhausted",
                }
            )
            continue
        probed_keys.add(plan.key())
        record = _run_probe(
            base_dump,
            plan,
            config_cls=config_cls,
            workdir=workdir,
            run_id=f"probe_{idx:02d}_{_slug(plan.key())}",
            probe_steps=tune_cfg.probe_steps,
            timeout_sec=min(tune_cfg.probe_timeout_sec, remaining),
            device_count=device_count,
        )
        record["predicted_step_ms"] = cand.predicted.get("predicted_step_ms")
        measured.append(record)

    scored = [m for m in measured if m.get("status") == "ok"]
    plans_by_key = {baseline_plan.key(): baseline_plan}
    for cand in survivors:
        if cand.plan is not None:
            plans_by_key.setdefault(cand.plan.key(), cand.plan)
    if scored:
        winner_record = max(
            scored,
            key=lambda m: (m["mfu"], -(m.get("step_time_sec") or float("inf"))),
        )
        winner_plan = plans_by_key[winner_record["key"]]
    else:
        # Nothing measured successfully (budget 0, broken backend...):
        # fall back to the baseline plan so the emitted config is still
        # legal and equivalent to the input.
        winner_record = {"key": baseline_plan.key(), "status": "fallback-baseline"}
        winner_plan = baseline_plan

    emitted = deep_merge(base_dump, winner_plan.config_overrides())
    config_cls.model_validate(emitted)
    import yaml

    output_path = Path(output_path)
    output_path.parent.mkdir(parents=True, exist_ok=True)
    output_path.write_text(yaml.safe_dump(emitted, sort_keys=False))

    report = {
        "device_count": device_count,
        "device_kind": device_kind,
        "seed": seed,
        "hbm_limit_bytes": hbm_limit,
        "enumerated": pruning["enumerated"],
        "pruned": pruning["pruned"],
        "survivors": [c.plan.key() for c in survivors if c.plan is not None],
        "measured": measured,
        "baseline": baseline_record,
        "winner": winner_record,
        "output_config": str(output_path),
        "elapsed_sec": round(time.monotonic() - started, 3),
        "budget_sec": tune_cfg.budget_sec,
    }
    (workdir / "tune_report.json").write_text(json.dumps(report, indent=2))
    return report


__all__ = ["run_tune"]
