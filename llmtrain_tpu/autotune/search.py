"""Candidate enumeration + analytical pruning for ``llmtrain tune``.

The search space is mesh shape x microbatch x activation regime (remat /
tier ladder) x zero stage.  Every
candidate is scored *analytically* first — the PaLM FLOP model
(utils/hw.py), the plan-level HBM prediction (autotune/plan.py), and the
``DEVICE_PEAKS`` roofline (telemetry/profiling.py) — and infeasible or
dominated candidates are discarded before any device time is spent.
Pruning is observable by contract: every discarded candidate lands in the
result with a named reason (``topology-illegal``, ``infeasible-hbm``,
``dominated``, ``probe-budget``) — no silent caps.

When a jax backend is available, :func:`lowered_candidate_cost` replaces
the analytic byte estimate with XLA's own ``cost_analysis`` via
``lower_cost_profile`` (trace+lower only — no compile, nothing executes),
so the roofline class the pruner ranks on is the compiler's count, not a
hand model.  The analytic path remains the fallback (and the pure-unit
test surface).

Import-light on purpose: jax is only touched inside
:func:`lowered_candidate_cost`.
"""

from __future__ import annotations

import logging
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

from ..resilience.elastic import TopologyMismatchError, classify_topology_change
from ..telemetry.profiling import classify_roofline, gradient_collective_bytes
from ..utils.hw import transformer_flops_per_token
from ..config.activation_tiers import canonical_tier_spec, parse_activation_tiers
from .plan import (
    MESH_AXES,
    MeshPlan,
    MeshPlanError,
    ModelCaps,
    estimate_param_count,
    plan_layer_tiers,
    predict_hbm_bytes,
    resolve_plan,
)

logger = logging.getLogger("llmtrain")

# Recompute-FLOPs factor by activation tier, applied as the mean over
# layers. none re-runs nothing; full/offload re-run the forward inside
# the backward (the classic ~4/3 on 6N); selective replays only the cheap
# elementwise ops between saved matmul outputs.
TIER_FLOPS_FACTOR: dict[str, float] = {
    "none": 1.0,
    "selective": 1.1,
    "full": 4.0 / 3.0,
    "offload": 4.0 / 3.0,
}

# Host<->device staging bandwidth for the offload tier's analytical time
# term (bytes/s) — a PCIe4/DMA-class placeholder, deliberately coarse:
# it only has to rank offload ladders against recompute, not predict
# wall-clock.
HOST_DMA_BYTES_PER_SEC = 100e9

# Per-device HBM capacity by device kind (bytes), substring-matched like
# DEVICE_PEAKS (longest key wins). These bound the feasibility half of the
# pruning pass; ``tune.hbm_limit_bytes`` overrides. The cpu row is an
# emulated-device placeholder generous enough for every smoke shape yet
# small enough that deliberately-oversized test candidates still prune.
DEVICE_HBM_BYTES: dict[str, float] = {
    "v4": 32e9,
    "v5e": 16e9,
    "v5 lite": 16e9,
    "v5p": 95e9,
    "v6e": 32e9,
    "v6 lite": 32e9,
    "cpu": 8e9,
}


def resolve_hbm_limit(
    device_kind: str | None, override: float | None = None
) -> float:
    """Per-device HBM budget for feasibility pruning (bytes)."""
    if override:
        return float(override)
    kind = (device_kind or "cpu").lower()
    best, limit = "", DEVICE_HBM_BYTES["cpu"]
    for key, cap in DEVICE_HBM_BYTES.items():
        if key in kind and len(key) > len(best):
            best, limit = key, cap
    return limit


def _factorizations(n: int, slots: int) -> list[tuple[int, ...]]:
    """All ordered tuples of ``slots`` positive ints whose product is n."""
    if slots == 1:
        return [(n,)]
    out: list[tuple[int, ...]] = []
    for d in range(1, n + 1):
        if n % d == 0:
            out.extend((d, *rest) for rest in _factorizations(n // d, slots - 1))
    return out


@dataclass
class Candidate:
    """One enumerated layout, before/after scoring.

    ``plan`` is None until :func:`prune_candidates` validates the raw
    knobs — enumeration is deliberately broader than what can run, so
    that illegal layouts show up in the tune report with their pruning
    reason instead of being silently never generated.
    """

    mesh_sizes: dict[str, int]
    micro_batch_size: int
    remat: bool
    zero_stage: int
    # Tier-ladder spec ("" = legacy remat flag only) — carried into the
    # plan key and tune_report.json so a "winner changed" note names the
    # ladder, not just a remat bit.
    activation_tiers: str = ""
    plan: MeshPlan | None = None
    predicted: dict[str, Any] = field(default_factory=dict)

    def key(self) -> str:
        if self.plan is not None:
            return self.plan.key()
        mesh = ".".join(f"{a[0]}{self.mesh_sizes.get(a, 1)}" for a in MESH_AXES)
        base = (
            f"{mesh}|mb{self.micro_batch_size}"
            f"|remat{int(self.remat)}|zero{self.zero_stage}"
        )
        if self.activation_tiers:
            return f"{base}|act={self.activation_tiers}"
        return base


def enumerate_candidates(
    cfg: Any,
    device_count: int,
    *,
    seed: int,
    microbatch_candidates: list[int] | None = None,
    search_mesh: bool = True,
    search_remat: bool = True,
    search_zero: bool = True,
) -> list[Candidate]:
    """The full candidate grid, in a deterministic seeded order.

    Mesh shapes are every factorization of ``device_count`` over the six
    named axes (capability filtering happens in the pruning pass, with
    reasons); microbatches default to {mb/2, mb, 2mb} around the config's
    value; remat and zero stage toggle when their search knob is on.
    The list is built in canonical sorted order, then shuffled with
    ``random.Random(seed)`` — same seed, same order, every run.
    """
    base_mb = int(cfg.trainer.micro_batch_size)
    zero_cfg = cfg.trainer.zero
    base_zero = int(zero_cfg.stage) if zero_cfg.enabled else 0

    if search_mesh:
        shapes = sorted(_factorizations(device_count, len(MESH_AXES)))
        # On a dense model the expert axis is just more data parallelism
        # (parallel/sharding.py) — every expert>1 shape is semantically
        # identical to a data-axis twin already in the grid, so skip the
        # duplicates rather than spend probes on them. MoE models keep
        # them: expert placement is a real layout choice there.
        n_experts = int((cfg.model.extra or {}).get("n_experts", 0) or 0)
        if n_experts <= 0:
            expert_slot = MESH_AXES.index("expert")
            shapes = [s for s in shapes if s[expert_slot] == 1]
    else:
        from .plan import resolve_axis_sizes

        fixed = resolve_axis_sizes(cfg.distributed.mesh.axis_sizes(), device_count)
        shapes = [tuple(fixed[a] for a in MESH_AXES)]

    if microbatch_candidates:
        mbs = sorted({int(m) for m in microbatch_candidates if int(m) >= 1})
    else:
        mbs = sorted({m for m in (base_mb // 2, base_mb, base_mb * 2) if m >= 1})
    zeros = [0, 1, 2] if search_zero else [base_zero]

    # Activation axis: (remat, tier-ladder) pairs. The legacy remat
    # toggle IS the all-none / all-full ladder pair (plan_layer_tiers
    # maps remat0 -> none:*, remat1 -> full:*), so those ladders stay as
    # the unsuffixed remat0/remat1 keys; searching additionally proposes
    # the offload-bottom-K ladder (bottom-of-stack residuals are the
    # cheapest to stage — they are reused last in the backward pass).
    # A config that already pins a tier spec forces every candidate to
    # carry an explicit spec: the emitted overrides deep-merge over the
    # base config, and an override without a spec would silently inherit
    # the base ladder under a key that claims plain remat.
    n_layers = int(cfg.model.n_layers)
    base_spec = str((cfg.model.extra or {}).get("activation_tiers", "") or "")
    if base_spec:
        base_spec = canonical_tier_spec(
            parse_activation_tiers(base_spec, n_layers)
        )
    k = max(1, n_layers // 4)
    if n_layers > k:
        offload_ladder = f"offload:0-{k - 1},full:{k}-{n_layers - 1}"
    else:
        offload_ladder = "offload:*"
    offload_ladder = canonical_tier_spec(
        parse_activation_tiers(offload_ladder, n_layers)
    )
    if base_spec:
        if search_remat:
            specs = list(dict.fromkeys(
                [base_spec, "none:*", "full:*", offload_ladder]
            ))
        else:
            specs = [base_spec]
        activations = [(False, s) for s in specs]
    elif search_remat:
        activations = [(False, ""), (True, ""), (False, offload_ladder)]
    else:
        activations = [(bool(cfg.model.remat), "")]

    grid = [
        Candidate(
            mesh_sizes=dict(zip(MESH_AXES, shape)),
            micro_batch_size=mb,
            remat=remat,
            zero_stage=z,
            activation_tiers=tiers,
        )
        for shape in shapes
        for mb in mbs
        for remat, tiers in activations
        for z in zeros
    ]
    random.Random(seed).shuffle(grid)
    return grid


def analytic_candidate_cost(
    plan: MeshPlan, cfg: Any, *, n_params: int | None = None
) -> dict[str, float]:
    """Per-device flops / bytes / collective bytes of one train micro-step
    under ``plan`` — the pure fallback when no backend is available to
    lower against (and the cross-check the tests pin).

    FLOPs come from the PaLM 6N model; remat re-runs the forward pass, a
    ~4/3 factor on 6N.  Bytes are a coarse traffic model: three passes
    over the resident param/grad shard plus the layer activations read+
    written twice each — enough for roofline *class* ranking, which is
    all the pruner consumes.
    """
    m = cfg.model
    if n_params is None:
        n_params = estimate_param_count(
            d_model=m.d_model,
            n_layers=m.n_layers,
            d_ff=m.d_ff,
            vocab_size=int(m.vocab_size or 50257),
            block_size=m.block_size,
            tie_embeddings=m.tie_embeddings,
            n_experts=int((m.extra or {}).get("n_experts", 0) or 0),
        )
    flops_per_token = transformer_flops_per_token(
        n_params=n_params,
        n_layers=m.n_layers,
        seq_len=m.block_size,
        d_model=m.d_model,
    )
    tokens_global = plan.global_micro_batch * m.block_size
    tiers = plan_layer_tiers(plan, m.n_layers)
    remat_factor = sum(TIER_FLOPS_FACTOR[t] for t in tiers) / len(tiers)
    flops = flops_per_token * tokens_global / plan.device_count * remat_factor

    dtype_b = 2 if m.dtype == "bfloat16" else 4
    model_shard = max(
        plan.axes["tensor"] * plan.axes["pipeline"] * plan.axes["fsdp"], 1
    )
    param_bytes = n_params * dtype_b / model_shard
    tokens_dev = tokens_global / plan.device_count
    act_bytes = tokens_dev * m.d_model * m.n_layers * 4.0 * dtype_b
    bytes_accessed = param_bytes * 3.0 + act_bytes
    collective = gradient_collective_bytes(
        plan.axes, n_params * 4.0 / model_shard
    )
    # Offload tier staging traffic: each offloaded block-input residual
    # crosses the host link twice per step (D2H after forward, H2D before
    # its backward). Separate from bytes_accessed — it rides the DMA
    # engines, not HBM (ranked via HOST_DMA_BYTES_PER_SEC in the pruner).
    n_offload = sum(1 for t in tiers if t == "offload")
    offload_bytes = tokens_dev * m.d_model * dtype_b * 2.0 * n_offload
    return {
        "flops": float(flops),
        "bytes_accessed": float(bytes_accessed),
        "collective_bytes": float(collective),
        "offload_bytes": float(offload_bytes),
        "n_params": float(n_params),
        "source": "analytic",
    }


def lowered_candidate_cost(cfg: Any, plan: MeshPlan) -> dict[str, float] | None:
    """XLA-counted cost of one train micro-step: jit a value_and_grad of
    the adapter's loss over abstract (eval_shape) params + ShapeDtypeStruct
    batches, then ``lower_cost_profile`` it — trace+lower only, NO
    compile, nothing executes, no device memory is touched.  Returns None
    on any failure (the analytic model stands in); per-device figures via
    ``n_chips=plan.device_count`` like the trainer's attribution path.
    """
    try:
        import jax
        import jax.numpy as jnp

        from ..registry import get_model_adapter, initialize_registries
        from ..telemetry.profiling import lower_cost_profile

        initialize_registries()
        adapter = get_model_adapter(cfg.model.name)()
        model = adapter.build_model(cfg)
        tokens = jax.ShapeDtypeStruct(
            (plan.global_micro_batch, cfg.model.block_size), jnp.int32
        )
        batch = {"input_ids": tokens, "labels": tokens}
        params = jax.eval_shape(
            lambda: adapter.init_params(model, cfg, jax.random.key(0))
        )

        def loss_fn(p, b):
            loss, _ = adapter.compute_loss(model, p, b, deterministic=True)
            return loss

        jitted = jax.jit(jax.value_and_grad(loss_fn))
        prof = lower_cost_profile(
            jitted, (params, batch), name="tune_candidate",
            n_chips=plan.device_count,
        )
        if prof is None:
            return None
        grad_bytes = sum(
            leaf.size * 4.0 for leaf in jax.tree_util.tree_leaves(params)
        )
        model_shard = max(
            plan.axes["tensor"] * plan.axes["pipeline"] * plan.axes["fsdp"], 1
        )
        tiers = plan_layer_tiers(plan, cfg.model.n_layers)
        remat_factor = sum(TIER_FLOPS_FACTOR[t] for t in tiers) / len(tiers)
        dtype_b = 2 if cfg.model.dtype == "bfloat16" else 4
        tokens_dev = (
            plan.global_micro_batch * cfg.model.block_size / plan.device_count
        )
        n_offload = sum(1 for t in tiers if t == "offload")
        return {
            "flops": float(prof["flops"]) * remat_factor,
            "bytes_accessed": float(prof["bytes_accessed"]),
            "collective_bytes": gradient_collective_bytes(
                plan.axes, grad_bytes / model_shard
            ),
            "offload_bytes": float(
                tokens_dev * cfg.model.d_model * dtype_b * 2.0 * n_offload
            ),
            "source": "lowered",
        }
    except Exception as exc:  # noqa: BLE001 — analytic fallback stands in
        logger.debug("candidate lowering failed: %s", exc)
        return None


def prune_candidates(
    candidates: list[Candidate],
    cfg: Any,
    *,
    device_count: int,
    caps: ModelCaps,
    peaks: Mapping[str, float],
    hbm_limit_bytes: float,
    max_probes: int,
    baseline_topology: Mapping[str, Any] | None = None,
    cost_fn: Callable[[MeshPlan], dict[str, float] | None] | None = None,
) -> dict[str, Any]:
    """The analytical pruning pass: validate, score, discard — with a
    recorded reason per discarded candidate.

    Returns ``{"survivors": [Candidate...], "pruned": [{key, reason}...],
    "enumerated": N}``.  Survivors carry their ``predicted`` block
    (roofline class, analytical ms, HBM prediction).  Ordering of
    survivors is best-predicted-first (total analytical ms ascending,
    ties by key, so the order is deterministic).

    ``baseline_topology`` (a manifest topology block) turns on the resume
    constraint: candidates the elastic matrix would reject on resume
    (model-axis or global-batch changes, resilience/elastic.py) prune as
    topology-illegal — the tune then only proposes plans a running
    checkpoint could adopt.

    ``cost_fn`` overrides the per-plan cost source (e.g. a closure over
    :func:`lowered_candidate_cost`); None falls back to the analytic
    model.  A cost_fn returning None for a plan also falls back.
    """
    m = cfg.model
    n_params = estimate_param_count(
        d_model=m.d_model,
        n_layers=m.n_layers,
        d_ff=m.d_ff,
        vocab_size=int(m.vocab_size or 50257),
        block_size=m.block_size,
        tie_embeddings=m.tie_embeddings,
        n_experts=int((m.extra or {}).get("n_experts", 0) or 0),
    )
    dtype_b = 2 if m.dtype == "bfloat16" else 4
    pdtype_b = 2 if m.param_dtype == "bfloat16" else 4
    # The HBM feasibility check must charge the logits buffer the run
    # will actually pay (dense vs chunked vs fused CE) — same resolution
    # the adapter performs at build time.
    from .plan import config_loss_impl

    loss_impl, ce_chunk = config_loss_impl(cfg)

    pruned: list[dict[str, str]] = []
    scored: list[Candidate] = []
    for cand in candidates:
        try:
            plan = resolve_plan(
                mesh_sizes=cand.mesh_sizes,
                device_count=device_count,
                caps=caps,
                micro_batch_size=cand.micro_batch_size,
                grad_accum_steps=cfg.trainer.grad_accum_steps,
                remat=cand.remat,
                zero_stage=cand.zero_stage,
                attention=cfg.model.attention,
                model_name=cfg.model.name,
                activation_tiers=cand.activation_tiers,
            )
        except MeshPlanError as exc:
            pruned.append({"key": cand.key(), "reason": f"topology-illegal: {exc}"})
            continue
        cand.plan = plan
        if baseline_topology is not None:
            try:
                classify_topology_change(
                    dict(baseline_topology), plan.describe_topology()
                )
            except TopologyMismatchError as exc:
                first = str(exc).split(":")[0]
                pruned.append(
                    {"key": cand.key(), "reason": f"topology-illegal (resume): {first}"}
                )
                continue

        cost = cost_fn(plan) if cost_fn is not None else None
        if cost is None:
            cost = analytic_candidate_cost(plan, cfg, n_params=n_params)
        roof = classify_roofline(
            flops=cost["flops"],
            bytes_accessed=cost["bytes_accessed"],
            collective_bytes=cost.get("collective_bytes", 0.0),
            peaks=peaks,
        )
        # Offload staging rides the host DMA link, a resource the
        # roofline's three peaks don't model — append it as its own
        # serial term (conservative: no overlap credit).
        offload_ms = (
            cost.get("offload_bytes", 0.0) / HOST_DMA_BYTES_PER_SEC * 1e3
        )
        predicted_ms = sum(roof["analytical_ms"].values()) + offload_ms
        hbm = predict_hbm_bytes(
            plan,
            n_params=n_params,
            d_model=m.d_model,
            n_layers=m.n_layers,
            vocab_size=int(m.vocab_size or 50257),
            block_size=m.block_size,
            dtype_bytes=dtype_b,
            param_dtype_bytes=pdtype_b,
            loss_impl=loss_impl,
            ce_chunk=ce_chunk,
        )
        # Rank on time PER TOKEN, not raw step time: candidates differ in
        # global batch, and a half-size microbatch "wins" raw step time
        # while losing throughput — exactly the bias a tuner must not have.
        tokens = plan.global_micro_batch * m.block_size
        cand.predicted = {
            "cost": cost,
            "roofline": roof,
            "predicted_step_ms": round(predicted_ms, 6),
            "predicted_us_per_token": round(predicted_ms * 1e3 / tokens, 6),
            "offload_ms": round(offload_ms, 6),
            "hbm": hbm,
            "hbm_limit_bytes": hbm_limit_bytes,
        }
        if hbm["total_bytes"] > hbm_limit_bytes:
            pruned.append(
                {
                    "key": cand.key(),
                    "reason": (
                        f"infeasible-hbm: predicted "
                        f"{hbm['total_bytes'] / 2**30:.2f} GiB per device > "
                        f"limit {hbm_limit_bytes / 2**30:.2f} GiB"
                    ),
                }
            )
            continue
        scored.append(cand)

    # Dominated-candidate pruning: A dominates B when A is no worse on
    # both predicted axes (time per token, HBM) and strictly better on one.
    scored.sort(key=lambda c: (c.predicted["predicted_us_per_token"], c.key()))
    survivors: list[Candidate] = []
    for cand in scored:
        t_c = cand.predicted["predicted_us_per_token"]
        h_c = cand.predicted["hbm"]["total_bytes"]
        dominator = next(
            (
                s
                for s in survivors
                if s.predicted["predicted_us_per_token"] <= t_c
                and s.predicted["hbm"]["total_bytes"] <= h_c
                and (
                    s.predicted["predicted_us_per_token"] < t_c
                    or s.predicted["hbm"]["total_bytes"] < h_c
                )
            ),
            None,
        )
        if dominator is not None:
            pruned.append(
                {
                    "key": cand.key(),
                    "reason": (
                        f"dominated: {dominator.key()} predicts both a "
                        "per-token time and an HBM footprint no worse "
                        f"({dominator.predicted['predicted_us_per_token']:.4f}"
                        f"us/tok vs {t_c:.4f}us/tok)"
                    ),
                }
            )
            continue
        survivors.append(cand)

    # The probe-budget cap is itself a recorded pruning reason, never a
    # silent truncation (acceptance criterion: no silent caps).
    if len(survivors) > max_probes:
        for rank, cand in enumerate(survivors[max_probes:], start=max_probes + 1):
            pruned.append(
                {
                    "key": cand.key(),
                    "reason": (
                        f"probe-budget: ranked #{rank} by predicted time "
                        f"per token; tune.max_probes is {max_probes}"
                    ),
                }
            )
        survivors = survivors[:max_probes]

    return {
        "survivors": survivors,
        "pruned": pruned,
        "enumerated": len(candidates),
    }


__all__ = [
    "Candidate",
    "DEVICE_HBM_BYTES",
    "HOST_DMA_BYTES_PER_SEC",
    "TIER_FLOPS_FACTOR",
    "analytic_candidate_cost",
    "enumerate_candidates",
    "lowered_candidate_cost",
    "prune_candidates",
    "resolve_hbm_limit",
]
