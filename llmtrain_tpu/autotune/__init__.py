"""Mesh planning and auto-tuning.

``plan`` is pure (no jax) and re-exported eagerly; ``search`` and
``tune`` pull heavier deps and are imported lazily via module
``__getattr__`` so that ``from llmtrain_tpu.autotune import MeshPlan``
stays cheap for the config/CLI validation paths.
"""

from __future__ import annotations

from .plan import (
    MESH_AXES,
    MeshPlan,
    MeshPlanError,
    ModelCaps,
    caps_from_config,
    estimate_param_count,
    plan_from_config,
    predict_hbm_bytes,
    resolve_axis_sizes,
    resolve_plan,
)

_LAZY = {"search", "tune"}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "MESH_AXES",
    "MeshPlan",
    "MeshPlanError",
    "ModelCaps",
    "caps_from_config",
    "estimate_param_count",
    "plan_from_config",
    "predict_hbm_bytes",
    "resolve_axis_sizes",
    "resolve_plan",
    "search",
    "tune",
]
