"""Declarative mesh planning: one validated object per parallelism layout.

Before this module, the rules that decide whether a mesh layout can run
were scattered: wildcard resolution in ``distributed/__init__.py``,
pipeline capability in the Trainer, GQA/tensor divisibility in
``models/gpt.py:validate_mesh``, microbatch/pipeline coupling in
``models/gpt_pipeline.py``, expert-axis wiring in ``models/moe.py`` +
``parallel/sharding.py``, and the resume topology matrix in
``resilience/elastic.py``.  A layout that passed one layer could still
die in the next as an opaque pjit/XLA sharding error deep inside trainer
setup.  :class:`MeshPlan` pulls every rule into one validated object:

* axis sizes (``data``/``fsdp``/``tensor``/``sequence``/``pipeline``/
  ``expert``, incl. the ``-1`` wildcard) resolved against the device
  count — :func:`resolve_axis_sizes` is now the single owner of that
  math (``distributed.resolve_mesh_axes`` delegates here);
* model capability flags (``supports_pipeline``, attention kind vs the
  ``sequence`` axis, MoE expert count vs the ``expert`` axis) and
  divisibility rules (heads/KV-heads over ``tensor``, microbatch over
  ``pipeline_microbatches``, context over ``sequence``);
* the same topology matrix elastic resume enforces:
  :meth:`MeshPlan.describe_topology` emits exactly the manifest block
  ``resilience/elastic.py`` validates, so a plan is checkpoint/manifest
  -legal by construction (``mesh_axis_sizes`` round-trips).

Every violation raises :class:`MeshPlanError` — a *named* error mapped to
exit code 2 (config error) by ``resilience/exit_codes.py``, because
retrying the same layout replays the same mismatch.

Deliberately dependency-free (dict math only, like elastic.py): the CLI
``plan`` path, the search enumerator, and the tests import it without
dragging in jax.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Mapping

from ..config.activation_tiers import canonical_tier_spec, parse_activation_tiers
from ..resilience.elastic import ELASTIC_AXES, MODEL_AXES, describe_topology

# Canonical axis order — must match distributed.MESH_AXES (physical
# iteration order: data outermost so replicas span hosts, tensor/sequence
# shards ride ICI). distributed/__init__.py asserts the two stay in sync.
MESH_AXES = ("data", "fsdp", "tensor", "sequence", "pipeline", "expert")

# Axes whose product is the data-parallel degree (parallel/sharding.py
# data_parallel_degree: batch shards over all three).
assert set(ELASTIC_AXES) | set(MODEL_AXES) == set(MESH_AXES)


class MeshPlanError(ValueError):
    """A parallelism layout that cannot run: axis sizes don't divide the
    device count, the global micro-batch, or a model dimension, or the
    model lacks a capability the layout requires.  Deterministic config
    problem — ``resilience/exit_codes.py`` maps it to exit code 2, and the
    message names the axis and the rule instead of surfacing later as an
    opaque pjit/XLA sharding error."""


def resolve_axis_sizes(
    sizes: Mapping[str, int], device_count: int
) -> dict[str, int]:
    """Materialize axis sizes against ``device_count``, expanding one
    ``-1`` wildcard.  Single owner of the wildcard/divisibility math —
    ``distributed.resolve_mesh_axes`` delegates here.

    Raises :class:`MeshPlanError` when more than one axis is a wildcard,
    when the fixed axes don't divide the device count, or when the
    resolved product mismatches it.
    """
    out = {axis: int(sizes.get(axis, 1)) for axis in MESH_AXES}
    for axis, v in out.items():
        if v == 0 or v < -1:
            raise MeshPlanError(
                f"mesh axis {axis!r} must be a positive int or -1 (got {v})"
            )
    wildcards = [axis for axis, v in out.items() if v == -1]
    if len(wildcards) > 1:
        raise MeshPlanError(
            f"at most one mesh axis may be -1 (wildcard); got {wildcards}"
        )
    fixed = math.prod(v for v in out.values() if v != -1)
    if wildcards:
        if device_count % fixed != 0:
            raise MeshPlanError(
                f"device count {device_count} not divisible by fixed mesh "
                f"axes product {fixed} (axes {dict(out)}) — the "
                f"{wildcards[0]!r} wildcard cannot be filled"
            )
        out[wildcards[0]] = device_count // fixed
        fixed *= out[wildcards[0]]
    if fixed != device_count:
        raise MeshPlanError(
            f"mesh axes {dict(out)} multiply to {fixed} but {device_count} "
            "devices are available — axis sizes must exactly tile the "
            "device count"
        )
    return out


@dataclass(frozen=True)
class ModelCaps:
    """Capability flags + divisibility inputs a plan validates against.

    Built from a config (and optionally the registered adapter class) by
    :func:`caps_from_config`; constructed directly in pure unit tests.
    """

    n_heads: int
    block_size: int
    supports_pipeline: bool = False
    attention: str = "dense"
    n_kv_heads: int = 0
    n_experts: int = 0
    pipeline_microbatches: int = 4
    # Layer count, consumed by activation-tier spec validation; 0 =
    # unknown (pure unit tests constructing ModelCaps directly) — tier
    # specs then pass through unvalidated.
    n_layers: int = 0


def caps_from_config(cfg: Any, adapter: Any | None = None) -> ModelCaps:
    """Derive :class:`ModelCaps` from a ``RunConfig`` (+ optional adapter
    class/instance for the ``supports_pipeline`` flag — registry lookup is
    the caller's job so this module stays import-light)."""
    extra = dict(cfg.model.extra or {})
    return ModelCaps(
        n_heads=int(cfg.model.n_heads),
        block_size=int(cfg.model.block_size),
        supports_pipeline=bool(getattr(adapter, "supports_pipeline", False)),
        attention=str(cfg.model.attention),
        n_kv_heads=int(extra.get("n_kv_heads", 0) or 0),
        n_experts=int(extra.get("n_experts", 0) or 0),
        pipeline_microbatches=int(extra.get("pipeline_microbatches", 4) or 4),
        n_layers=int(cfg.model.n_layers),
    )


@dataclass(frozen=True)
class MeshPlan:
    """One fully-resolved, validated parallelism layout.

    Construct via :func:`resolve_plan` (which validates) — a directly-
    instantiated MeshPlan carries no legality guarantee.  ``axes`` always
    holds all six concrete sizes (no wildcard survives resolution).
    """

    axes: dict[str, int]
    device_count: int
    micro_batch_size: int
    grad_accum_steps: int
    remat: bool = False
    zero_stage: int = 0  # 0 = ZeRO off; 1/2 per trainer.zero.stage
    attention: str = "dense"
    model_name: str = ""
    # Canonical per-layer activation-tier spec (config/activation_tiers.py),
    # "" = unset (the legacy remat flag above describes the layout).
    activation_tiers: str = ""

    @property
    def data_parallel(self) -> int:
        """Combined batch-sharding degree (parallel/sharding.py
        data_parallel_degree: data x fsdp x expert)."""
        return math.prod(self.axes[a] for a in ELASTIC_AXES)

    @property
    def model_parallel(self) -> int:
        return math.prod(self.axes[a] for a in MODEL_AXES)

    @property
    def global_micro_batch(self) -> int:
        return self.micro_batch_size * self.data_parallel

    def mesh_axis_sizes(self) -> dict[str, int]:
        """Round-trips with ``parallel.sharding.mesh_axis_sizes(mesh)`` of
        the built mesh — the exact dict checkpoint manifests record."""
        return {axis: int(self.axes[axis]) for axis in MESH_AXES}

    def describe_topology(self, *, num_processes: int = 1) -> dict[str, Any]:
        """The manifest topology block (resilience/elastic.py) this plan
        produces — a plan is checkpoint-legal by construction because
        resume validation consumes exactly this dict."""
        return describe_topology(
            self.mesh_axis_sizes(),
            data_parallel=self.data_parallel,
            global_micro_batch=self.global_micro_batch,
            micro_batch_size=self.micro_batch_size,
            grad_accum_steps=self.grad_accum_steps,
            num_processes=num_processes,
        )

    def key(self) -> str:
        """Compact stable identity, e.g. ``d2.f2.t1.s1.p1.e2|mb4|remat0|zero1``
        (``|act=<spec>`` appended only when a tier ladder is set, so every
        pre-tier key string is unchanged)."""
        mesh = ".".join(f"{a[0]}{self.axes[a]}" for a in MESH_AXES)
        base = f"{mesh}|mb{self.micro_batch_size}|remat{int(self.remat)}|zero{self.zero_stage}"
        if self.activation_tiers:
            return f"{base}|act={self.activation_tiers}"
        return base

    def config_overrides(self) -> dict[str, Any]:
        """The config fields this plan pins, as a nested dict that deep-
        merges into a ``RunConfig.model_dump()`` — the emitted tuned YAML
        and the probe configs are both built through this, so what the
        tuner measured is exactly what ``llmtrain train`` later runs."""
        overrides: dict[str, Any] = {
            "distributed": {"mesh": self.mesh_axis_sizes()},
            "trainer": {
                "micro_batch_size": self.micro_batch_size,
                "zero": {
                    "enabled": self.zero_stage > 0,
                    "stage": self.zero_stage if self.zero_stage > 0 else 1,
                },
            },
            "model": {"remat": self.remat},
        }
        if self.activation_tiers:
            # Tiers subsume remat; pin remat off so the merged config
            # passes the schema's mutual-exclusion check.
            overrides["model"] = {
                "remat": False,
                "extra": {"activation_tiers": self.activation_tiers},
            }
        return overrides


def resolve_plan(
    *,
    mesh_sizes: Mapping[str, int],
    device_count: int,
    caps: ModelCaps,
    micro_batch_size: int,
    grad_accum_steps: int = 1,
    remat: bool = False,
    zero_stage: int = 0,
    attention: str | None = None,
    model_name: str = "",
    activation_tiers: str = "",
) -> MeshPlan:
    """Resolve + validate one layout into a :class:`MeshPlan`.

    Every rule that used to fail later (or not at all until pjit) lives
    here, each with a named :class:`MeshPlanError`:

    * axis sizes tile the device count (wildcard included);
    * ``pipeline > 1`` needs ``supports_pipeline`` and
      ``micro_batch_size % pipeline_microbatches == 0`` (the global
      micro-batch must divide by dp x microbatches — gpt_pipeline);
    * ``sequence > 1`` with the ring/ulysses kernels needs
      ``block_size % sequence == 0``; ulysses additionally shards heads,
      so ``n_heads % sequence == 0`` (dense attention on a sequence axis
      is legal as-is — GSPMD inserts the comms);
    * ``tensor > 1`` needs ``n_heads % tensor == 0`` (and
      ``n_kv_heads % tensor`` for GQA — models/gpt.py validate_mesh);
    * ``expert > 1`` on a MoE model needs ``n_experts % expert == 0``
      (models/moe.py layout); on a dense model the axis only carries
      batch shards and is always legal;
    * ``zero_stage`` in {0, 1, 2} (trainer.zero.stage).
    """
    axes = resolve_axis_sizes(mesh_sizes, device_count)
    att = caps.attention if attention is None else attention
    if micro_batch_size < 1:
        raise MeshPlanError(
            f"micro_batch_size must be >= 1 (got {micro_batch_size})"
        )
    if zero_stage not in (0, 1, 2):
        raise MeshPlanError(
            f"zero_stage must be 0 (off), 1 or 2 (got {zero_stage})"
        )

    pp = axes["pipeline"]
    if pp > 1:
        if not caps.supports_pipeline:
            raise MeshPlanError(
                f"mesh axis 'pipeline' is {pp} but model "
                f"{model_name or '?'!r} does not stack its layers for "
                "pipeline stages; use a pipeline-capable model (e.g. "
                "'gpt_pipeline') or set pipeline to 1"
            )
        m = max(caps.pipeline_microbatches, 1)
        if micro_batch_size % m != 0:
            raise MeshPlanError(
                f"trainer.micro_batch_size ({micro_batch_size}) must be "
                f"divisible by model.extra.pipeline_microbatches ({m}) on "
                "a pipeline mesh — otherwise the global micro-batch "
                "cannot split into pipeline microbatches"
            )

    # A sequence axis is legal with ANY attention (dense just lets GSPMD
    # insert the comms — tests/test_distributed.py pins that the layouts
    # agree); the ring/ulysses kernels additionally need exact shards.
    sp = axes["sequence"]
    if sp > 1 and att in ("ring", "ulysses"):
        if caps.block_size % sp != 0:
            raise MeshPlanError(
                f"model.block_size ({caps.block_size}) must be divisible "
                f"by the mesh sequence axis ({sp}) — each {att} shard "
                "holds an equal context slice"
            )
        if att == "ulysses" and caps.n_heads % sp != 0:
            raise MeshPlanError(
                f"model.n_heads ({caps.n_heads}) must be divisible by the "
                f"mesh sequence axis ({sp}) — ulysses all-to-alls between "
                "sequence shards and head shards"
            )

    tp = axes["tensor"]
    if tp > 1:
        if caps.n_heads % tp != 0:
            raise MeshPlanError(
                f"model.n_heads ({caps.n_heads}) must be divisible by the "
                "mesh tensor axis "
                f"({tp}) — attention heads shard over tensor parallelism"
            )
        if caps.n_kv_heads and caps.n_kv_heads % tp != 0:
            raise MeshPlanError(
                f"model.extra.n_kv_heads ({caps.n_kv_heads}) must be "
                f"divisible by the mesh tensor axis ({tp}) — K/V heads "
                "shard over tensor parallelism like query heads do"
            )

    # `expert` with a dense model is legal — the axis then only carries
    # batch shards (it is one of the ELASTIC data-parallel axes,
    # parallel/sharding.py). Only a MoE model adds the divisibility rule.
    ep = axes["expert"]
    if ep > 1 and caps.n_experts > 0 and caps.n_experts % ep != 0:
        raise MeshPlanError(
            f"model.extra.n_experts ({caps.n_experts}) must be "
            f"divisible by the mesh expert axis ({ep}) — each shard "
            "holds an equal expert slice"
        )

    tiers_spec = str(activation_tiers or "")
    if tiers_spec:
        if remat:
            raise MeshPlanError(
                "model.remat: true conflicts with activation_tiers; tiers "
                "subsume the remat flag"
            )
        if caps.n_layers > 0:
            try:
                tiers_spec = canonical_tier_spec(
                    parse_activation_tiers(tiers_spec, caps.n_layers)
                )
            except ValueError as exc:
                raise MeshPlanError(f"activation_tiers: {exc}") from exc

    return MeshPlan(
        axes=axes,
        device_count=device_count,
        micro_batch_size=int(micro_batch_size),
        grad_accum_steps=int(grad_accum_steps),
        remat=bool(remat),
        zero_stage=int(zero_stage),
        attention=att,
        model_name=model_name,
        activation_tiers=tiers_spec,
    )


def plan_from_config(
    cfg: Any, device_count: int, *, adapter: Any | None = None
) -> MeshPlan:
    """The plan the *current* config resolves to on ``device_count``
    devices — the identity/baseline candidate of every tune, and the
    object ``llmtrain plan`` prints."""
    caps = caps_from_config(cfg, adapter)
    zero = cfg.trainer.zero
    return resolve_plan(
        mesh_sizes=cfg.distributed.mesh.axis_sizes(),
        device_count=device_count,
        caps=caps,
        micro_batch_size=cfg.trainer.micro_batch_size,
        grad_accum_steps=cfg.trainer.grad_accum_steps,
        remat=cfg.model.remat,
        zero_stage=int(zero.stage) if zero.enabled else 0,
        attention=cfg.model.attention,
        model_name=cfg.model.name,
        activation_tiers=str(
            (cfg.model.extra or {}).get("activation_tiers", "") or ""
        ),
    )


# --------------------------------------------------------------------------
# Analytic memory model (per-device HBM prediction)
# --------------------------------------------------------------------------

# Device-resident activation copies of [tokens, d_model] per layer by
# tier. none=14 / full=2 are the pre-tier all-or-nothing model (the exact
# values the old `2.0 if remat else 14.0` used); selective keeps the ~6
# matmul outputs dots_saveable pins; offload keeps ~1 (the in-flight
# staging buffer) and parks the block boundary on the host instead.
TIER_ACT_COPIES: dict[str, float] = {
    "none": 14.0,
    "selective": 6.0,
    "full": 2.0,
    "offload": 1.0,
}

# Host-RAM copies of [tokens, d_model] per offload layer: the block-input
# residual, double-buffered so the D2H of layer i overlaps layer i+1.
OFFLOAD_HOST_COPIES = 2.0


def plan_layer_tiers(plan: MeshPlan, n_layers: int) -> tuple[str, ...]:
    """The per-layer tier list a plan implies: the parsed spec when set,
    else the legacy remat flag mapped to all-``full``/all-``none``."""
    if plan.activation_tiers:
        return parse_activation_tiers(plan.activation_tiers, n_layers)
    return ("full",) * n_layers if plan.remat else ("none",) * n_layers


def estimate_param_count(
    *,
    d_model: int,
    n_layers: int,
    d_ff: int,
    vocab_size: int,
    block_size: int,
    tie_embeddings: bool = True,
    n_experts: int = 0,
) -> int:
    """Analytic transformer parameter count (GPT-shaped: QKVO + MLP +
    norms + embeddings).  An estimate for *relative* feasibility ranking,
    not an exact census — MoE multiplies the MLP block by ``n_experts``
    (plus the router), LoRA/quant variants are close enough."""
    attn = 4 * d_model * d_model + 4 * d_model  # QKVO kernels + biases
    mlp = 2 * d_model * d_ff + d_model + d_ff  # up/down kernels + biases
    if n_experts > 0:
        mlp = mlp * n_experts + d_model * n_experts  # experts + router
    norms = 4 * d_model  # 2 LayerNorms (scale+bias) per block
    per_layer = attn + mlp + norms
    embed = vocab_size * d_model + block_size * d_model + 2 * d_model
    head = 0 if tie_embeddings else vocab_size * d_model
    return int(n_layers * per_layer + embed + head)


def config_loss_impl(cfg) -> tuple[str, int]:
    """``(loss_impl, ce_chunk)`` the planner should assume for ``cfg`` —
    resolved by the SAME selection authority the GPT adapter family runs
    at build time (ops/fused_ce.py:resolve_loss_impl), so an `llmtrain
    plan` verdict charges the logits buffer the run will actually pay.
    An invalid explicit value resolves to "dense" here: config validation
    owns that error, and a feasibility estimate must not mask it."""
    extra = dict(getattr(cfg.model, "extra", {}) or {})
    from ..ops.fused_ce import resolve_loss_impl

    try:
        impl = resolve_loss_impl(
            extra.get("loss_impl"),
            vocab_size=int(cfg.model.vocab_size or 50257),
            ce_auto_vocab=int(extra.get("ce_auto_vocab", 32768) or 32768),
            interpret=bool(extra.get("pallas_interpret", False)),
        )
    except ValueError:
        impl = "dense"
    return impl, int(extra.get("ce_chunk", 8192) or 8192)


def predict_hbm_bytes(
    plan: MeshPlan,
    *,
    n_params: int,
    d_model: int,
    n_layers: int,
    vocab_size: int,
    block_size: int,
    dtype_bytes: int = 4,
    param_dtype_bytes: int = 4,
    loss_impl: str = "dense",
    ce_chunk: int = 8192,
) -> dict[str, float]:
    """Predicted per-device HBM footprint of a training step under this
    plan — the feasibility half of the analytical pruning pass.

    The model (documented in docs/perf.md "Mesh planning"): parameters
    and gradients shard over the model-parallel axes x fsdp; AdamW keeps
    two moments, sharded further over the full data-parallel degree when
    ZeRO is on; activations scale with the per-device token count
    (batch / dp, context / sequence) and drop to the sqrt-ish remat
    checkpoint footprint with ``remat``; the logits buffer
    ``mb x T x V`` is counted separately because it dominates small
    models and is what the streamed/fused CE paths shrink: ``loss_impl``
    (resolve via :func:`config_loss_impl`) charges the full buffer under
    "dense", a ``tokens x min(ce_chunk, V)`` block under "chunked_ce",
    and nothing under "fused_ce" — the Pallas kernel keeps every logits
    tile in VMEM (ops/fused_ce.py).
    """
    model_shard = plan.axes["tensor"] * plan.axes["pipeline"] * plan.axes["fsdp"]
    if plan.axes["expert"] > 1:
        model_shard *= plan.axes["expert"]  # MoE: experts shard the MLP
    params_b = n_params * param_dtype_bytes / max(model_shard, 1)
    grads_b = n_params * dtype_bytes / max(model_shard, 1)
    # Opt state mirrors the param sharding; ZeRO additionally partitions
    # it over the data-parallel degree, so the combined shard factor is
    # the whole device count (parallel/sharding.py opt_state_shardings).
    opt_shard = plan.device_count if plan.zero_stage > 0 else max(model_shard, 1)
    opt_b = 2 * n_params * 4.0 / max(opt_shard, 1)  # AdamW m+v, f32
    # Per-device activation tokens: batch shards over dp, context over
    # sequence. Device-resident copies of [tokens, d_model] per layer come
    # from the layer's activation tier (TIER_ACT_COPIES — none=14 dense,
    # full=2 block boundaries, offload additionally parks the boundary in
    # host RAM, tracked separately since it spends no HBM).
    tokens = (
        plan.micro_batch_size
        * (block_size / max(plan.axes["sequence"], 1))
    )
    try:
        tiers = plan_layer_tiers(plan, n_layers)
    except ValueError as exc:
        raise MeshPlanError(f"activation_tiers: {exc}") from exc
    per_copy = tokens * d_model * dtype_bytes
    by_tier: dict[str, float] = {}
    host_b = 0.0
    for tier in tiers:
        by_tier[tier] = by_tier.get(tier, 0.0) + per_copy * TIER_ACT_COPIES[tier]
        if tier == "offload":
            host_b += per_copy * OFFLOAD_HOST_COPIES
    acts_b = sum(by_tier.values())
    if loss_impl == "fused_ce":
        logits_b = 0.0
    elif loss_impl == "chunked_ce":
        logits_b = tokens * min(ce_chunk, vocab_size) * 4.0  # CE runs f32
    else:
        logits_b = tokens * vocab_size * 4.0  # CE runs f32
    total = params_b + grads_b + opt_b + acts_b + logits_b
    return {
        "loss_impl": loss_impl,
        "params_bytes": round(params_b),
        "grads_bytes": round(grads_b),
        "opt_state_bytes": round(opt_b),
        "activation_bytes": round(acts_b),
        "activation_bytes_by_tier": {t: round(v) for t, v in by_tier.items()},
        "activation_host_bytes": round(host_b),
        "logits_bytes": round(logits_b),
        "total_bytes": round(total),
    }


__all__ = [
    "MESH_AXES",
    "MeshPlan",
    "MeshPlanError",
    "ModelCaps",
    "OFFLOAD_HOST_COPIES",
    "TIER_ACT_COPIES",
    "caps_from_config",
    "config_loss_impl",
    "estimate_param_count",
    "plan_from_config",
    "plan_layer_tiers",
    "predict_hbm_bytes",
    "resolve_axis_sizes",
    "resolve_plan",
]
