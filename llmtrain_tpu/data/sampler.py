"""Deterministic, stateless batch sampling.

Replaces the reference's ``DistributedSampler`` + epoch iteration
(reference data/hf_text.py:182-198) and its resume-by-replay batch skipping
(reference trainer.py:336-347, explicitly unsafe under DDP) with a pure
function: the examples making up global micro-batch ``b`` are a function of
``(seed, b)`` only. Every process computes the same global index list and
slices out its own shard, so resume and multi-host sharding are exact by
construction.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


def _epoch_permutation(num_examples: int, seed: int, epoch: int) -> np.ndarray:
    rng = np.random.default_rng(np.random.SeedSequence(entropy=seed, spawn_key=(epoch,)))
    return rng.permutation(num_examples)


@dataclass(frozen=True)
class DeterministicSampler:
    """Maps a global micro-batch index to example indices.

    ``batch_size`` is the *global* micro-batch size (per-replica batch ×
    data-parallel degree). Incomplete trailing batches are dropped, matching
    torch DataLoader ``drop_last`` semantics for stable shapes under jit.
    """

    num_examples: int
    batch_size: int
    seed: int
    shuffle: bool = True

    def __post_init__(self) -> None:
        if self.num_examples < 1:
            raise ValueError("dataset has no examples")

    @property
    def batches_per_epoch(self) -> int:
        return max(1, self.num_examples // self.batch_size)

    def batch_indices(self, batch_index: int) -> np.ndarray:
        """Example indices of global micro-batch ``batch_index`` (0-based).

        Datasets smaller than one global micro-batch (tiny smoke datasets ×
        wide data-parallel meshes) wrap deterministically: the epoch
        permutation is tiled until the batch is full.
        """
        epoch, pos = divmod(batch_index, self.batches_per_epoch)
        if self.shuffle:
            perm = _epoch_permutation(self.num_examples, self.seed, epoch)
        else:
            perm = np.arange(self.num_examples)
        if self.num_examples < self.batch_size:
            reps = -(-self.batch_size // self.num_examples)
            perm = np.tile(perm, reps)
        return perm[pos * self.batch_size : (pos + 1) * self.batch_size]

    def progress(self, batch_index: int) -> dict:
        """Resumable progress record for global micro-batch ``batch_index``
        (the NEXT batch to consume). The sampler is stateless, so this is
        the entire "sampler state" a checkpoint manifest needs: the resumed
        run re-derives identical batches from (seed, index) alone — on any
        data-parallel world size, since sharding happens after the global
        indices are fixed (see ``resilience/elastic.py``)."""
        epoch, pos = divmod(batch_index, self.batches_per_epoch)
        return {
            "seed": int(self.seed),
            "global_micro_batch": int(self.batch_size),
            "consumed_micro_batches": int(batch_index),
            "epoch": int(epoch),
            "position_in_epoch": int(pos),
            "consumed_examples": int(batch_index) * int(self.batch_size),
            "shuffle": bool(self.shuffle),
        }

    def shard_indices(self, batch_index: int, shard: int, num_shards: int) -> np.ndarray:
        """This process's contiguous slice of the global batch.

        Slicing is contiguous (not strided) so the host slice matches the
        ``data``-axis sharding layout of the global device array.
        """
        if self.batch_size % num_shards != 0:
            raise ValueError(
                f"global micro-batch {self.batch_size} not divisible by {num_shards} shards"
            )
        per_shard = self.batch_size // num_shards
        full = self.batch_indices(batch_index)
        return full[shard * per_shard : (shard + 1) * per_shard]
