"""Offline-trainable byte-level BPE tokenizer.

The reference's tokenizer is tiktoken's downloaded gpt2 BPE (reference
models/gpt.py:210-212) — unusable on air-gapped hosts. ``ByteTokenizer``
(tokenizers.py) removes the network dependency but pays ~4.3 bytes/word;
this module closes the gap: train a byte-level BPE **on the local corpus
itself** and use it through the same tokenizer interface (``n_vocab``,
``encode``/``encode_np``/``decode``, ``eot_token``).

Construction is the standard byte-level BPE (Sennrich et al.; the gpt2
construction minus the bytes↔unicode remap, which only exists so merges
can be stored as printable text): start from the 256 byte symbols,
repeatedly merge the most frequent adjacent pair within pre-tokens.
Pre-tokenization is a simplified gpt2-style split (leading space binds to
the following word) — documented as NOT merge-compatible with tiktoken's
gpt2 vocabulary; it is for training new tokenizers, not re-implementing
that one.

Training keeps pair counts incrementally (only words containing the
merged pair are touched per iteration), so a multi-MB corpus trains in
seconds-to-tens-of-seconds once, after which ``data/local_text.py``'s
token cache makes it free.

Usage:
    python -m llmtrain_tpu train-tokenizer --input corpus/ \
        --vocab-size 8192 --output tok8k.json
    # then in the run config:
    model:
      extra: {tokenizer: "bpe:tok8k.json"}
"""

from __future__ import annotations

import json
import re
from collections import Counter, defaultdict
from pathlib import Path

import numpy as np

# Leading space binds to the word that follows (gpt2-style), so merges can
# learn " the"-like units; runs of other whitespace stay separate tokens.
_PRETOKEN_RE = re.compile(r" ?[A-Za-z]+| ?[0-9]+| ?[^\sA-Za-z0-9]+|\s+")

_EOT = "<|endoftext|>"


def _merge(ids: list[int], pair: tuple[int, int], new_id: int) -> list[int]:
    """Replace every non-overlapping occurrence of ``pair`` (left to right)."""
    out: list[int] = []
    i, n = 0, len(ids)
    a, b = pair
    while i < n:
        if i + 1 < n and ids[i] == a and ids[i + 1] == b:
            out.append(new_id)
            i += 2
        else:
            out.append(ids[i])
            i += 1
    return out


def train_bpe(
    text: str,
    vocab_size: int,
    *,
    special_tokens: tuple[str, ...] = (_EOT,),
) -> "BPETokenizer":
    """Learn ``vocab_size - 256 - len(special_tokens)`` merges from ``text``.

    Deterministic: ties in pair frequency break toward the numerically
    smallest pair, so the same corpus always yields the same vocabulary.
    Stops early if no pair occurs at least twice.
    """
    n_merges = vocab_size - 256 - len(special_tokens)
    if n_merges < 0:
        raise ValueError(
            f"vocab_size {vocab_size} too small: need >= {256 + len(special_tokens)}"
        )

    word_counts = Counter(_PRETOKEN_RE.findall(text))
    words: list[tuple[list[int], int]] = [
        (list(w.encode("utf-8")), c) for w, c in word_counts.items()
    ]

    pair_counts: dict[tuple[int, int], int] = defaultdict(int)
    pair_words: dict[tuple[int, int], set[int]] = defaultdict(set)
    for wi, (ids, c) in enumerate(words):
        for p in zip(ids, ids[1:]):
            pair_counts[p] += c
            pair_words[p].add(wi)

    merges: list[tuple[int, int]] = []
    for new_id in range(256, 256 + n_merges):
        if not pair_counts:
            break
        best = max(pair_counts.items(), key=lambda kv: (kv[1], -kv[0][0], -kv[0][1]))
        pair, count = best
        if count < 2:
            break
        merges.append(pair)
        # Only words that (may) contain the pair change; update their pair
        # contributions in place. pair_words sets may hold stale indices
        # (a word that lost the pair in an earlier merge) — harmless, the
        # re-count below is driven by the word's actual ids.
        for wi in list(pair_words.pop(pair, ())):
            ids, c = words[wi]
            for p in zip(ids, ids[1:]):
                pair_counts[p] -= c
                if pair_counts[p] <= 0:
                    del pair_counts[p]
            new_ids = _merge(ids, pair, new_id)
            words[wi] = (new_ids, c)
            for p in zip(new_ids, new_ids[1:]):
                pair_counts[p] = pair_counts.get(p, 0) + c
                pair_words[p].add(wi)
        pair_counts.pop(pair, None)

    return BPETokenizer(merges, special_tokens=special_tokens)


class BPETokenizer:
    """Byte-level BPE with the repo's tokenizer interface.

    ids: ``[0, 256)`` raw bytes, ``[256, 256+len(merges))`` merged units in
    rank order, then special tokens. ``encode`` never emits specials (the
    pre-tokenizer cannot produce them); they exist for ``eot_token``
    plumbing (generation.py early-stop) and decode.
    """

    def __init__(
        self,
        merges: list[tuple[int, int]],
        *,
        special_tokens: tuple[str, ...] = (_EOT,),
    ) -> None:
        self._merges = [tuple(m) for m in merges]
        self._rank = {p: r for r, p in enumerate(self._merges)}
        self._special = tuple(special_tokens)
        vocab: list[bytes] = [bytes([i]) for i in range(256)]
        for a, b in self._merges:
            vocab.append(vocab[a] + vocab[b])
        self._vocab = vocab
        self.n_vocab = 256 + len(self._merges) + len(self._special)
        self._cache: dict[str, list[int]] = {}
        # Native cold-word encoder (llmtrain_tpu/native, C via ctypes);
        # None on hosts without a C compiler — the Python loop below is
        # the correctness reference either way.
        from ..native import fastbpe_encoder

        self._native = fastbpe_encoder(self._merges)

    # -- tiktoken-compatible surface ------------------------------------
    @property
    def eot_token(self) -> int | None:
        if _EOT in self._special:
            return 256 + len(self._merges) + self._special.index(_EOT)
        return None

    @property
    def fingerprint(self) -> str:
        """Distinguishes same-size vocabularies in data caches."""
        import hashlib

        h = hashlib.sha256()
        for a, b in self._merges:
            h.update(f"{a},{b};".encode())
        h.update("|".join(self._special).encode())
        return h.hexdigest()[:12]

    def _encode_word(self, word: str) -> list[int]:
        ids = self._cache.get(word)
        if ids is not None:
            return ids
        if self._native is not None:
            ids = self._native.encode_word(word)
        else:
            ids = list(word.encode("utf-8"))
            while len(ids) >= 2:
                ranked = [
                    (r, i)
                    for i, p in enumerate(zip(ids, ids[1:]))
                    if (r := self._rank.get(p)) is not None
                ]
                if not ranked:
                    break
                rank, _ = min(ranked)
                ids = _merge(ids, self._merges[rank], 256 + rank)
        if len(self._cache) < 1_000_000:
            self._cache[word] = ids
        return ids

    def encode(self, text: str) -> list[int]:
        out: list[int] = []
        for word in _PRETOKEN_RE.findall(text):
            out.extend(self._encode_word(word))
        return out

    def encode_np(self, text: str) -> np.ndarray:
        return np.asarray(self.encode(text), dtype=np.int32)

    def decode(self, ids) -> str:
        pieces: list[bytes] = []
        base = 256 + len(self._merges)
        for i in np.asarray(ids, dtype=np.int64).tolist():
            if 0 <= i < base:
                pieces.append(self._vocab[i])
            elif base <= i < self.n_vocab:
                pieces.append(self._special[i - base].encode("utf-8"))
            else:
                raise ValueError(f"token id {i} out of range [0, {self.n_vocab})")
        return b"".join(pieces).decode("utf-8", errors="replace")

    # -- persistence -----------------------------------------------------
    def save(self, path: str | Path) -> None:
        payload = {
            "format": "llmtrain-bpe",
            "version": 1,
            "merges": [list(m) for m in self._merges],
            "special_tokens": list(self._special),
        }
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_suffix(p.suffix + ".tmp")
        tmp.write_text(json.dumps(payload))
        tmp.replace(p)

    @classmethod
    def load(cls, path: str | Path) -> "BPETokenizer":
        payload = json.loads(Path(path).read_text())
        if payload.get("format") != "llmtrain-bpe" or payload.get("version") != 1:
            raise ValueError(f"{path}: not a llmtrain-bpe v1 vocabulary file")
        return cls(
            [tuple(m) for m in payload["merges"]],
            special_tokens=tuple(payload["special_tokens"]),
        )


__all__ = ["BPETokenizer", "train_bpe"]
