"""Local-filesystem text data module: glob → read → tokenize → window.

Fully offline counterpart of ``hf_text`` (same flat-stream token cache and
``TokenWindowDataset`` windows; reference behavior spec at
src/llmtrain/data/hf_text.py:108-174): instead of a HuggingFace dataset it
concatenates the text of local files matched by glob patterns, so training
works with zero network egress — e.g. on a source-code corpus.

Config::

    data:
      name: "local_text"
      extra:
        globs: ["/usr/local/lib/python3.12/**/*.py"]
        val_fraction: 0.01   # tail of the token stream held out for eval
        format: "text"       # or "jsonl": one JSON object per line,
        text_key: "text"     #   text under this key (jsonl only)

Train/val are a deterministic head/tail split of the single token stream
(files sorted lexicographically), so the split is stable across runs and
processes.
"""

from __future__ import annotations

import glob
import hashlib
from pathlib import Path
from typing import Any

import numpy as np

from ..config.schemas import RunConfig
from ..registry.data import register_data_module
from .base import (
    DataModule,
    IndexedDataset,
    load_token_cache,
    validate_split_documents,
    write_token_cache,
)
from .hf_text import TokenWindowDataset

_DEFAULT_VAL_FRACTION = 0.01


@register_data_module("local_text")
class LocalTextDataModule(DataModule):
    """Serves fixed token windows over a corpus of local text files."""

    known_extra_keys = frozenset(
        {"globs", "val_fraction", "format", "text_key", "split_documents"}
    )

    def __init__(self) -> None:
        self._train: TokenWindowDataset | None = None
        self._val: TokenWindowDataset | None = None

    def setup(self, cfg: RunConfig, tokenizer: Any | None = None) -> None:
        if tokenizer is None:
            raise ValueError("local_text requires a tokenizer from the model adapter")
        globs = cfg.data.extra.get("globs")
        if not globs or not isinstance(globs, (list, tuple)):
            raise ValueError("local_text requires data.extra.globs (list of glob patterns)")
        val_fraction = float(cfg.data.extra.get("val_fraction", _DEFAULT_VAL_FRACTION))
        if not 0.0 <= val_fraction < 1.0:
            raise ValueError(f"val_fraction must be in [0, 1), got {val_fraction}")
        fmt = cfg.data.extra.get("format", "text")
        if fmt not in ("text", "jsonl"):
            raise ValueError(f"local_text format must be 'text' or 'jsonl', got {fmt!r}")

        files = sorted({f for pattern in globs for f in glob.glob(pattern, recursive=True)})
        files = [f for f in files if Path(f).is_file()]
        if not files:
            raise ValueError(f"local_text globs matched no files: {globs}")

        split_docs = bool(cfg.data.extra.get("split_documents", False))
        if split_docs:
            validate_split_documents(cfg)
        tokens, doc_starts = self._load_or_build_cache(
            cfg, files, tokenizer, fmt=fmt, need_docs=split_docs
        )
        n_train = len(tokens) - int(len(tokens) * val_fraction)
        train_tokens, val_tokens = tokens[:n_train], tokens[n_train:]
        train_docs = val_docs = None
        if split_docs:
            train_docs = doc_starts[doc_starts < n_train]
            # The val stream may open mid-document; positions before its
            # first boundary get ordinal 0, made 1-based by the window's
            # local renumbering.
            val_docs = doc_starts[doc_starts >= n_train] - n_train

        self._train = TokenWindowDataset(
            train_tokens, cfg.model.block_size,
            doc_starts=train_docs, split_documents=split_docs,
        )
        if len(self._train) == 0:
            raise ValueError(
                f"corpus too small: {len(train_tokens)} train tokens for "
                f"block_size {cfg.model.block_size}"
            )
        val_ds = TokenWindowDataset(
            val_tokens, cfg.model.block_size,
            doc_starts=val_docs, split_documents=split_docs,
        )
        self._val = val_ds if len(val_ds) > 0 else None

    def _load_or_build_cache(
        self, cfg: RunConfig, files: list[str], tokenizer: Any, *,
        fmt: str = "text", need_docs: bool = False,
    ) -> tuple[np.ndarray, np.ndarray | None]:
        text_key = str(cfg.data.extra.get("text_key", "text"))
        # Key by file list + size + mtime (size alone misses equal-length
        # edits) + parse mode + tokenizer identity — token ids from a
        # different tokenizer would silently corrupt training (hf_text's
        # cache rule).
        h = hashlib.sha256()
        # text_key only matters in jsonl mode; hashing it in text mode would
        # invalidate the cache on an irrelevant config change. The "r2"
        # marker versions the jsonl ingestion: per-RECORD encoding (for
        # document boundaries) can merge BPE tokens differently than the
        # old joined-text encode, so pre-change jsonl caches must not be
        # silently reused. Text-mode streams are unchanged — no bump.
        h.update(f"{fmt}:{text_key + ':r2' if fmt == 'jsonl' else ''};".encode())
        for f in files:
            st = Path(f).stat()
            h.update(f.encode())
            h.update(f"{st.st_size}:{st.st_mtime_ns}".encode())
        from .tokenizers import tokenizer_cache_id

        tok_id = tokenizer_cache_id(tokenizer)
        cache_path = (
            Path(cfg.data.cache_dir) / "processed" / f"local__{h.hexdigest()[:16]}__{tok_id}.npy"
        )
        cached = load_token_cache(cache_path, need_docs=need_docs)
        if cached is not None:
            return cached

        encode_np = getattr(tokenizer, "encode_np", None)
        sep = np.asarray(tokenizer.encode("\n\n"), dtype=np.int32)
        pieces: list[np.ndarray] = []
        doc_starts: list[int] = []
        total = 0
        for f in files:
            raw = Path(f).read_text(encoding="utf-8", errors="ignore")
            # Document granularity: the whole file in text mode, one JSON
            # record in jsonl mode — so split_documents boundaries match
            # what a reader would call a document, not the file layout.
            for text in self._extract_documents(f, raw, fmt, text_key):
                if not text:
                    continue
                if encode_np is not None:
                    ids = encode_np(text)
                else:
                    ids = np.asarray(tokenizer.encode(text), dtype=np.int32)
                if ids.size:
                    # The boundary marker belongs to the document it
                    # follows: newline keeps documents separated without
                    # inventing an out-of-vocab separator id.
                    doc_starts.append(total)
                    pieces.append(ids)
                    pieces.append(sep)
                    total += ids.size + sep.size
        tokens = (
            np.concatenate(pieces).astype(np.int32)
            if pieces
            else np.zeros((0,), dtype=np.int32)
        )
        starts_arr = np.asarray(doc_starts, dtype=np.int64)
        write_token_cache(cache_path, tokens, starts_arr)
        return tokens, (starts_arr if need_docs else None)

    @staticmethod
    def _extract_documents(
        path: str, raw: str, fmt: str, text_key: str
    ) -> list[str]:
        """Raw file content → list of document texts. "text" yields the
        whole file as one document; "jsonl" parses one JSON object per
        line and yields each ``text_key`` field as its own document (so
        ``split_documents`` boundaries are per record, not per file)."""
        if fmt == "text":
            return [raw]
        import json

        docs: list[str] = []
        # split("\n"), not splitlines(): the latter also splits on U+2028/
        # U+2029/U+0085, which are legal unescaped inside JSON strings
        # (ensure_ascii=False corpora), and would shear valid objects apart.
        for lineno, line in enumerate(raw.split("\n"), start=1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as exc:
                raise ValueError(f"{path}:{lineno}: invalid JSON line: {exc}") from exc
            val = obj.get(text_key) if isinstance(obj, dict) else None
            if not isinstance(val, str):
                raise ValueError(
                    f"{path}:{lineno}: expected a string field {text_key!r} "
                    f"in each JSONL object"
                )
            docs.append(val)
        return docs

    def train_dataset(self) -> IndexedDataset:
        if self._train is None:
            raise RuntimeError("setup must be called before train_dataset")
        return self._train

    def val_dataset(self) -> IndexedDataset | None:
        if self._train is None:
            raise RuntimeError("setup must be called before val_dataset")
        return self._val


__all__ = ["LocalTextDataModule"]
