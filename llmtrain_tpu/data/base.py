"""Data-module plugin contract.

Parity target: reference ``src/llmtrain/data/base.py`` (DataModule ABC with
setup/train_dataloader/val_dataloader, :11-24). The TPU design replaces
stateful torch DataLoaders + DistributedSampler with *indexable datasets*:
``setup`` prepares arrays, ``train_dataset``/``val_dataset`` return objects
supporting random access by example index. Batch order, sharding across
processes, and resume position are then pure functions of (seed, step) —
see ``llmtrain_tpu.data.sampler`` — which is what makes bitwise resume and
multi-host determinism possible without the reference's single-process
skip-ahead hack (reference trainer.py:336-347).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Protocol, runtime_checkable

import numpy as np

from ..config.schemas import RunConfig


@runtime_checkable
class IndexedDataset(Protocol):
    """Random-access dataset of fixed-shape tokenized examples."""

    def __len__(self) -> int: ...

    def get_examples(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        """Gather a batch: each value has leading dim ``len(indices)``."""
        ...


class DataModule(ABC):
    """Prepares train/val datasets for a run."""

    # Extra-dict keys this module understands (config/extras.py warns on
    # others); None disables the check.
    known_extra_keys: frozenset[str] | None = None

    @abstractmethod
    def setup(self, cfg: RunConfig, tokenizer: Any | None) -> None:
        """Load/tokenize/cache data. Called once before training."""

    @abstractmethod
    def train_dataset(self) -> IndexedDataset:
        """The training split (must be non-empty)."""

    @abstractmethod
    def val_dataset(self) -> IndexedDataset | None:
        """The validation split, or None if the module has no val data."""
