"""Data-module plugin contract.

Parity target: reference ``src/llmtrain/data/base.py`` (DataModule ABC with
setup/train_dataloader/val_dataloader, :11-24). The TPU design replaces
stateful torch DataLoaders + DistributedSampler with *indexable datasets*:
``setup`` prepares arrays, ``train_dataset``/``val_dataset`` return objects
supporting random access by example index. Batch order, sharding across
processes, and resume position are then pure functions of (seed, step) —
see ``llmtrain_tpu.data.sampler`` — which is what makes bitwise resume and
multi-host determinism possible without the reference's single-process
skip-ahead hack (reference trainer.py:336-347).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Protocol, runtime_checkable

import numpy as np

from ..config.schemas import RunConfig


@runtime_checkable
class IndexedDataset(Protocol):
    """Random-access dataset of fixed-shape tokenized examples."""

    def __len__(self) -> int: ...

    def get_examples(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        """Gather a batch: each value has leading dim ``len(indices)``."""
        ...


class DataModule(ABC):
    """Prepares train/val datasets for a run."""

    # Extra-dict keys this module understands (config/extras.py warns on
    # others); None disables the check.
    known_extra_keys: frozenset[str] | None = None

    @abstractmethod
    def setup(self, cfg: RunConfig, tokenizer: Any | None) -> None:
        """Load/tokenize/cache data. Called once before training."""

    @abstractmethod
    def train_dataset(self) -> IndexedDataset:
        """The training split (must be non-empty)."""

    @abstractmethod
    def val_dataset(self) -> IndexedDataset | None:
        """The validation split, or None if the module has no val data."""


def load_token_cache(cache_path, *, need_docs: bool):
    """Read a flat-token cache + its ``.docs.npy`` sidecar (doc starts).

    Returns ``(tokens, doc_starts_or_None)`` on a hit, ``None`` on a miss.
    Raises when ``need_docs`` but the sidecar is absent — an old cache
    from before ``split_documents`` existed must be rebuilt. Shared by
    hf_text and local_text so the protocol (and its failure text) cannot
    drift.
    """
    import numpy as np

    if not cache_path.exists():
        return None
    tokens = np.load(cache_path, mmap_mode="r")
    docs_path = cache_path.with_suffix(".docs.npy")
    if not need_docs:
        return tokens, None
    if docs_path.exists():
        return tokens, np.load(docs_path)
    raise ValueError(
        f"token cache {cache_path} predates document offsets "
        "(data.extra.split_documents); delete it to rebuild"
    )


def write_token_cache(cache_path, tokens, doc_starts) -> None:
    """Atomically publish tokens + doc-starts sidecar.

    The SIDECAR is published first: a concurrent rank (or a crash
    between the two renames) must never observe tokens-without-sidecar,
    which ``load_token_cache`` treats as a stale pre-split_documents
    cache. Per-process tmp names keep concurrent cold-cache builders off
    each other's files.
    """
    import os

    import numpy as np

    cache_path.parent.mkdir(parents=True, exist_ok=True)
    docs_path = cache_path.with_suffix(".docs.npy")
    tmp_docs = docs_path.with_suffix(f".tmp{os.getpid()}.npy")
    np.save(tmp_docs, doc_starts)
    tmp_docs.replace(docs_path)
    tmp = cache_path.with_suffix(f".tmp{os.getpid()}.npy")
    np.save(tmp, tokens)
    tmp.replace(cache_path)


def validate_split_documents(cfg: RunConfig) -> None:
    """Config combinations ``split_documents`` cannot serve, failed loudly.

    Ring/Ulysses are fine: segment masks ride both sequence-parallel
    schemes (the ring rotates key segments with their K/V shards and
    keeps the unrotated local shard as the query segments;
    tests/test_ops.py::TestSequenceParallelMasks pins parity).
    """
    if cfg.model.extra.get("assume_packed"):
        raise ValueError(
            "data.extra.split_documents emits segment masks, but "
            "model.extra.assume_packed drops the mask operand — the "
            "cross-document masking would be silently lost; unset one"
        )
