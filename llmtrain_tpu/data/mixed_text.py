"""Weighted mixture of local text corpora.

Beyond-reference capability (the reference serves exactly one dataset
per run): pretraining-style corpus mixing — N local corpora, each with a
sampling weight, served as ONE deterministic dataset. Each source is a
full ``local_text`` pipeline (glob → tokenize → window, shared token
caches), so a corpus already cached by a standalone run is reused.

Config::

    data:
      name: "mixed_text"
      extra:
        sources:
          - {globs: ["corpusA/**/*.py"], weight: 3.0}
          - {globs: ["corpusB/**/*.txt"], weight: 1.0, format: "text"}
        # per-source keys: globs (required), weight (default 1.0),
        # val_fraction / format / text_key / split_documents as local_text

The mixture is a pure function of ``run.seed``: window ``i`` of the
epoch draws its source from a seeded categorical over the weights and
its example from that source's stream in order (wrapping around when a
heavily-weighted corpus is smaller than its share). Stateless like
``data/sampler.py``, so multi-process sharding and exact resume need no
extra machinery. Validation is the plain concatenation of the sources'
validation splits — a fixed set, no weighting.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..config.schemas import RunConfig
from ..registry.data import register_data_module
from .base import DataModule, IndexedDataset
from .local_text import LocalTextDataModule

_SOURCE_KEYS = frozenset(
    {"globs", "weight", "val_fraction", "format", "text_key", "split_documents"}
)


class WeightedMixDataset:
    """One epoch over N datasets with per-source sampling weights.

    The source of window ``i`` and its position within that source are
    fixed at construction from ``seed`` — the same (seed, sources) pair
    always yields the same epoch, on every process.

    Slot counts are EXACT (weights realized by construction, not by
    sampling), and the epoch length is ``max_s ceil(size_s / p_s)`` —
    the smallest epoch in which every source is covered in FULL at its
    weight. Under-weighted corpora therefore stretch the epoch rather
    than silently losing their tail, and over-weighted small corpora
    wrap (repeat), the standard mixing semantics. Footprint: 6 bytes
    per slot (int16 source id + int32 ordinal).
    """

    # int32 ordinals + a sane ceiling on how far a tiny weight may
    # stretch the epoch before it is clearly a misconfiguration.
    _MAX_SLOTS = 1 << 31

    def __init__(
        self, datasets: list[Any], weights: list[float], seed: int
    ) -> None:
        sizes = np.asarray([len(d) for d in datasets], dtype=np.float64)
        p = np.asarray(weights, dtype=np.float64)
        p = p / p.sum()
        total = int(np.ceil(sizes / p).max())
        if total >= self._MAX_SLOTS:
            raise ValueError(
                f"mixed_text epoch needs {total:,} slots to cover every "
                "source at these weights — rebalance the weights or shrink "
                "the under-weighted corpus"
            )
        # Exact per-source slot counts: floor shares, largest-remainder
        # rounding, then a full-coverage floor.
        shares = np.floor(p * total).astype(np.int64)
        remainder = p * total - shares
        for _ in range(total - int(shares.sum())):
            k = int(np.argmax(remainder))
            shares[k] += 1
            remainder[k] = -1.0
        # Full-coverage floor LAST (share >= size holds by the epoch
        # formula; rounding must not dip below it). The epoch absorbs the
        # <= n_sources extra slots instead of truncating a source's tail.
        shares = np.maximum(shares, sizes.astype(np.int64))
        self._datasets = datasets
        slots = np.repeat(np.arange(len(datasets), dtype=np.int16), shares)
        rng = np.random.default_rng(seed)
        self._src = rng.permutation(slots)
        # Occurrence ordinal: the j-th window drawn from source s reads
        # that source's j-th example (mod its size).
        self._ord = np.empty(len(self._src), dtype=np.int32)
        for s in range(len(datasets)):
            mask = self._src == s
            self._ord[mask] = np.arange(int(mask.sum()), dtype=np.int32)

    def __len__(self) -> int:
        return len(self._src)

    def source_histogram(self) -> np.ndarray:
        """Windows drawn per source over the epoch (for tests/logs)."""
        return np.bincount(self._src, minlength=len(self._datasets))

    def get_examples(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        indices = np.asarray(indices, dtype=np.int64)
        src = self._src[indices]
        out: dict[str, np.ndarray] | None = None
        for s in np.unique(src):
            rows = np.nonzero(src == s)[0]
            ds = self._datasets[int(s)]
            local = self._ord[indices[rows]] % len(ds)
            examples = ds.get_examples(local)
            if out is None:
                out = {
                    k: np.empty((len(indices),) + v.shape[1:], dtype=v.dtype)
                    for k, v in examples.items()
                }
            if set(examples) != set(out):
                raise ValueError(
                    f"mixed_text sources emit different batch keys: "
                    f"{sorted(out)} vs {sorted(examples)} — use the same "
                    "split_documents setting on every source"
                )
            for k, v in examples.items():
                out[k][rows] = v
        assert out is not None  # indices is never empty in practice
        return out


class ConcatDataset:
    """Plain concatenation of datasets (the mixture's validation set)."""

    def __init__(self, datasets: list[Any]) -> None:
        self._datasets = datasets
        sizes = np.asarray([len(d) for d in datasets], dtype=np.int64)
        self._starts = np.concatenate([[0], np.cumsum(sizes)])

    def __len__(self) -> int:
        return int(self._starts[-1])

    def get_examples(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        indices = np.asarray(indices, dtype=np.int64)
        which = np.searchsorted(self._starts, indices, side="right") - 1
        out: dict[str, np.ndarray] | None = None
        for s in np.unique(which):
            rows = np.nonzero(which == s)[0]
            local = indices[rows] - self._starts[s]
            examples = self._datasets[int(s)].get_examples(local)
            if out is None:
                out = {
                    k: np.empty((len(indices),) + v.shape[1:], dtype=v.dtype)
                    for k, v in examples.items()
                }
            for k, v in examples.items():
                out[k][rows] = v
        assert out is not None
        return out


@register_data_module("mixed_text")
class MixedTextDataModule(DataModule):
    """Weighted mixture of ``local_text`` corpora as one dataset."""

    known_extra_keys = frozenset({"sources"})

    def __init__(self) -> None:
        self._train: WeightedMixDataset | None = None
        self._val: ConcatDataset | None = None

    def setup(self, cfg: RunConfig, tokenizer: Any | None = None) -> None:
        sources = cfg.data.extra.get("sources")
        if not isinstance(sources, (list, tuple)) or not sources:
            raise ValueError(
                "mixed_text requires data.extra.sources: a non-empty list of "
                "{globs, weight, ...} mappings"
            )
        # Config-only validation FIRST: a disagreement must fail in
        # milliseconds, not after tokenizing multi-GB corpora.
        split_settings: set[bool] = set()
        for i, source in enumerate(sources):
            if not isinstance(source, dict):
                raise ValueError(f"mixed_text source #{i} must be a mapping")
            unknown = sorted(set(source) - _SOURCE_KEYS)
            if unknown:
                raise ValueError(
                    f"mixed_text source #{i}: unknown keys {unknown}; "
                    f"expected {sorted(_SOURCE_KEYS)}"
                )
            if float(source.get("weight", 1.0)) <= 0:
                raise ValueError(
                    f"mixed_text source #{i}: weight must be > 0, got "
                    f"{source.get('weight')}"
                )
            split_settings.add(bool(source.get("split_documents", False)))
        if len(split_settings) > 1:
            raise ValueError(
                "mixed_text sources must agree on split_documents: mixing "
                "segment-masked and unmasked windows in one batch is invalid"
            )

        trains: list[Any] = []
        vals: list[Any] = []
        weights: list[float] = []
        for i, source in enumerate(sources):
            weight = float(source.get("weight", 1.0))
            # Each source IS a local_text pipeline over a synthesized
            # config — same validation, same token caches.
            raw = cfg.model_dump()
            raw["data"]["name"] = "local_text"
            raw["data"]["extra"] = {
                k: v for k, v in source.items() if k != "weight"
            }
            sub_cfg = RunConfig.model_validate(raw)
            sub = LocalTextDataModule()
            try:
                sub.setup(sub_cfg, tokenizer)
            except ValueError as exc:
                raise ValueError(f"mixed_text source #{i}: {exc}") from exc
            trains.append(sub.train_dataset())
            if sub.val_dataset() is not None:
                vals.append(sub.val_dataset())
            weights.append(weight)
        self._train = WeightedMixDataset(trains, weights, cfg.run.seed)
        self._val = ConcatDataset(vals) if vals else None

    def train_dataset(self) -> IndexedDataset:
        if self._train is None:
            raise RuntimeError("setup must be called before train_dataset")
        return self._train

    def val_dataset(self) -> IndexedDataset | None:
        if self._train is None:
            raise RuntimeError("setup must be called before val_dataset")
        return self._val


__all__ = ["ConcatDataset", "MixedTextDataModule", "WeightedMixDataset"]
