"""Offline tokenizers.

The reference's only tokenizer is tiktoken's downloaded gpt2 BPE
(reference models/gpt.py:210-212), which makes every training run depend on
network egress at startup. The byte-level tokenizer below is the
zero-dependency fallback: 256-symbol vocabulary, UTF-8 bytes as token ids —
the ByT5/byte-level-GPT construction. Select it with
``model.extra.tokenizer: "byte"``.
"""

from __future__ import annotations

import numpy as np


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: token id == byte value, vocab 256."""

    n_vocab = 256

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def encode_np(self, text: str) -> np.ndarray:
        """Vectorized encode — the fast path for corpus preprocessing."""
        return np.frombuffer(text.encode("utf-8"), dtype=np.uint8).astype(np.int32)

    def decode(self, ids) -> str:
        arr = np.asarray(ids, dtype=np.int64)
        if arr.size and (arr.min() < 0 or arr.max() > 255):
            raise ValueError("byte tokenizer ids must be in [0, 255]")
        return bytes(arr.astype(np.uint8).tolist()).decode("utf-8", errors="replace")


def build_tokenizer(name: str):
    """Resolve a tokenizer by config name.

    "gpt2" (tiktoken, needs network), "byte" (offline fallback), or
    "bpe:<path>" — a vocabulary trained offline with the
    ``train-tokenizer`` CLI subcommand (data/bpe.py).
    """
    if name == "byte":
        return ByteTokenizer()
    if name == "gpt2":
        import tiktoken

        return tiktoken.get_encoding("gpt2")
    if name.startswith("bpe:"):
        from .bpe import BPETokenizer

        return BPETokenizer.load(name[len("bpe:") :])
    raise ValueError(
        f"unknown tokenizer {name!r}; expected 'gpt2', 'byte', or 'bpe:<path>'"
    )


def tokenizer_cache_id(tokenizer) -> str:
    """Identity string for token-cache keys (hf_text.py, local_text.py).

    Token ids from a different tokenizer would silently corrupt training,
    so caches key on class + vocab size + content fingerprint (the latter
    distinguishes same-size trained vocabularies, data/bpe.py).
    """
    return (
        f"{type(tokenizer).__name__}{getattr(tokenizer, 'n_vocab', 'x')}"
        f"{getattr(tokenizer, 'fingerprint', '')}"
    )


__all__ = ["ByteTokenizer", "build_tokenizer", "tokenizer_cache_id"]
