"""Offline tokenizers.

The reference's only tokenizer is tiktoken's downloaded gpt2 BPE
(reference models/gpt.py:210-212), which makes every training run depend on
network egress at startup. The byte-level tokenizer below is the
zero-dependency fallback: 256-symbol vocabulary, UTF-8 bytes as token ids —
the ByT5/byte-level-GPT construction. Select it with
``model.extra.tokenizer: "byte"``.
"""

from __future__ import annotations

import numpy as np


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: token id == byte value, vocab 256."""

    n_vocab = 256

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def encode_np(self, text: str) -> np.ndarray:
        """Vectorized encode — the fast path for corpus preprocessing."""
        return np.frombuffer(text.encode("utf-8"), dtype=np.uint8).astype(np.int32)

    def decode(self, ids) -> str:
        arr = np.asarray(ids, dtype=np.int64)
        if arr.size and (arr.min() < 0 or arr.max() > 255):
            raise ValueError("byte tokenizer ids must be in [0, 255]")
        return bytes(arr.astype(np.uint8).tolist()).decode("utf-8", errors="replace")


class HFTokenizer:
    """Wrapper over a HuggingFace ``tokenizer.json`` file (offline).

    The companion of the HF-Llama checkpoint interop
    (interop/llama_hf.py): import the weights, point
    ``model.extra.tokenizer: "hf:<tokenizer.json>"`` at the matching
    fast-tokenizer file, and text generation speaks the checkpoint's own
    vocabulary — no network, no transformers pipeline. Exposes the same
    protocol the rest of the stack expects (``n_vocab``/``encode``/
    ``decode``/``eot_token``/``fingerprint``).
    """

    def __init__(self, path: str) -> None:
        from tokenizers import Tokenizer  # bundled with transformers

        self._tok = Tokenizer.from_file(path)
        # Size by the HIGHEST id, not the token count: tokenizer.json id
        # spaces can have holes (special tokens above a non-contiguous
        # base vocab), and an embedding sized by count would silently
        # clamp out-of-range ids onto the last row under jit.
        vocab_ids = self._tok.get_vocab(with_added_tokens=True).values()
        self.n_vocab = max(
            int(self._tok.get_vocab_size(with_added_tokens=True)),
            (max(vocab_ids) + 1) if vocab_ids else 0,
        )
        import hashlib
        from pathlib import Path

        self.fingerprint = hashlib.sha256(
            Path(path).read_bytes()
        ).hexdigest()[:12]
        # End-of-text id for generation early-stop, when the vocab has a
        # conventional marker.
        vocab = self._tok.get_vocab(with_added_tokens=True)
        for marker in ("</s>", "<|endoftext|>", "<eos>", "[SEP]"):
            if marker in vocab:
                self.eot_token = vocab[marker]
                break

    def encode(self, text: str) -> list[int]:
        return list(self._tok.encode(text, add_special_tokens=False).ids)

    def decode(self, ids) -> str:
        arr = np.asarray(ids, dtype=np.int64)
        return self._tok.decode(arr.tolist(), skip_special_tokens=False)


def build_tokenizer(name: str):
    """Resolve a tokenizer by config name.

    "gpt2" (tiktoken, needs network), "byte" (offline fallback),
    "bpe:<path>" — a vocabulary trained offline with the
    ``train-tokenizer`` CLI subcommand (data/bpe.py) — or
    "hf:<tokenizer.json>" — a HuggingFace fast-tokenizer file (the
    companion of HF-Llama checkpoint import).
    """
    if name == "byte":
        return ByteTokenizer()
    if name == "gpt2":
        import tiktoken

        return tiktoken.get_encoding("gpt2")
    if name.startswith("bpe:"):
        from .bpe import BPETokenizer

        return BPETokenizer.load(name[len("bpe:") :])
    if name.startswith("hf:"):
        return HFTokenizer(name[len("hf:") :])
    raise ValueError(
        f"unknown tokenizer {name!r}; expected 'gpt2', 'byte', 'bpe:<path>', "
        "or 'hf:<tokenizer.json>'"
    )


def tokenizer_cache_id(tokenizer) -> str:
    """Identity string for token-cache keys (hf_text.py, local_text.py).

    Token ids from a different tokenizer would silently corrupt training,
    so caches key on class + vocab size + content fingerprint (the latter
    distinguishes same-size trained vocabularies, data/bpe.py).
    """
    return (
        f"{type(tokenizer).__name__}{getattr(tokenizer, 'n_vocab', 'x')}"
        f"{getattr(tokenizer, 'fingerprint', '')}"
    )


__all__ = ["ByteTokenizer", "HFTokenizer", "build_tokenizer", "tokenizer_cache_id"]
