"""Data plugins: the DataModule contract, sampler, and built-in modules."""

from .base import DataModule, IndexedDataset
from .prefetch import BatchPrefetcher, PrefetcherClosedError
from .sampler import DeterministicSampler

__all__ = [
    "BatchPrefetcher",
    "DataModule",
    "DeterministicSampler",
    "IndexedDataset",
    "PrefetcherClosedError",
]
