"""Data plugins: the DataModule contract, sampler, and built-in modules."""

from .base import DataModule, IndexedDataset
from .sampler import DeterministicSampler

__all__ = ["DataModule", "DeterministicSampler", "IndexedDataset"]
