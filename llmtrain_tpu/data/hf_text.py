"""HuggingFace text data module: tokenize → concatenate → window.

Parity target: reference ``src/llmtrain/data/hf_text.py`` — ``load_dataset``
with cache_dir (:81-86), tokenize→concatenate→slice into ``block_size + 1``
windows yielding ``input_ids = chunk[:-1]`` / ``labels = chunk[1:]`` /
all-ones attention_mask (:108-174), processed-split disk cache keyed by
dataset/config/split (:97-106).

TPU-first divergence: instead of materializing a HF dataset of per-window
rows, the tokenized stream is stored as ONE flat int32 numpy array (cached as
``.npy``) and windows are cut at access time — zero-copy random access, an
order of magnitude less cache space, and gather-friendly for the
deterministic index-based sampler. The window content is identical:
non-overlapping ``block_size + 1`` chunks of the concatenated stream.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any

import numpy as np

from ..config.schemas import RunConfig
from ..registry.data import register_data_module
from .base import DataModule, IndexedDataset


class TokenWindowDataset:
    """Non-overlapping (block_size+1)-token windows over a flat stream."""

    def __init__(self, tokens: np.ndarray, block_size: int) -> None:
        if tokens.ndim != 1:
            raise ValueError(f"token stream must be 1-D, got shape {tokens.shape}")
        self._tokens = tokens
        self._block_size = block_size
        self._chunk = block_size + 1
        self._num_windows = len(tokens) // self._chunk

    def __len__(self) -> int:
        return self._num_windows

    def get_examples(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        starts = np.asarray(indices, dtype=np.int64) * self._chunk
        # Gather all windows in one vectorized fancy-index.
        offsets = np.arange(self._chunk, dtype=np.int64)
        chunks = self._tokens[starts[:, None] + offsets[None, :]]
        input_ids = np.ascontiguousarray(chunks[:, :-1], dtype=np.int32)
        labels = np.ascontiguousarray(chunks[:, 1:], dtype=np.int32)
        return {
            "input_ids": input_ids,
            "labels": labels,
            "attention_mask": np.ones_like(input_ids),
        }


@register_data_module("hf_text")
class HFTextDataModule(DataModule):
    """Loads a HuggingFace text dataset and serves fixed token windows."""

    known_extra_keys = frozenset()

    def __init__(self) -> None:
        self._cfg: RunConfig | None = None
        self._train: TokenWindowDataset | None = None
        self._val: TokenWindowDataset | None = None

    def setup(self, cfg: RunConfig, tokenizer: Any | None = None) -> None:
        if tokenizer is None:
            raise ValueError("hf_text requires a tokenizer from the model adapter")
        if cfg.data.dataset_name is None:
            raise ValueError("hf_text requires data.dataset_name")
        text_column = cfg.data.text_column or "text"
        self._cfg = cfg

        train_tokens = self._prepare_split(cfg, cfg.data.train_split, tokenizer, text_column)
        self._train = TokenWindowDataset(train_tokens, cfg.model.block_size)
        self._val = None
        if cfg.data.val_split:
            val_tokens = self._prepare_split(cfg, cfg.data.val_split, tokenizer, text_column)
            val_ds = TokenWindowDataset(val_tokens, cfg.model.block_size)
            if len(val_ds) > 0:
                self._val = val_ds

    def _token_cache_path(self, cfg: RunConfig, split: str, tokenizer: Any) -> Path:
        dataset_name = (cfg.data.dataset_name or "unknown").replace("/", "__")
        dataset_config = (cfg.data.dataset_config or "default").replace("/", "__")
        # Key the cache by tokenizer identity too: reusing token ids produced
        # by a different tokenizer would silently corrupt training.
        from .tokenizers import tokenizer_cache_id

        tok_id = tokenizer_cache_id(tokenizer)
        return (
            Path(cfg.data.cache_dir)
            / "processed"
            / f"{dataset_name}__{dataset_config}__{tok_id}__{split}.npy"
        )

    def _prepare_split(
        self, cfg: RunConfig, split: str, tokenizer: Any, text_column: str
    ) -> np.ndarray:
        cache_path = self._token_cache_path(cfg, split, tokenizer)
        if cache_path.exists():
            return np.load(cache_path, mmap_mode="r")

        from datasets import load_dataset

        raw = load_dataset(
            cfg.data.dataset_name,
            cfg.data.dataset_config,
            split=split,
            cache_dir=cfg.data.cache_dir,
        )
        tokens = self._tokenize_stream(raw, tokenizer, text_column)
        cache_path.parent.mkdir(parents=True, exist_ok=True)
        # np.save appends ".npy" unless the name already ends with it.
        # Per-process tmp name: concurrent ranks building a cold cache must
        # not scribble into each other's file before the atomic rename.
        tmp = cache_path.with_suffix(f".tmp{os.getpid()}.npy")
        np.save(tmp, tokens)
        tmp.replace(cache_path)
        return tokens

    @staticmethod
    def _tokenize_stream(raw_dataset: Any, tokenizer: Any, text_column: str) -> np.ndarray:
        """Encode every row's text column and concatenate into one stream."""
        pieces: list[np.ndarray] = []
        batch_encode = getattr(tokenizer, "encode_ordinary_batch", None)
        texts = (str(t) for t in raw_dataset[text_column] if t is not None)
        if batch_encode is not None:
            # tiktoken fast path: parallel batch encoding without special tokens.
            encoded_lists = batch_encode(list(texts))
            pieces = [np.asarray(ids, dtype=np.int32) for ids in encoded_lists if ids]
        else:
            for text in texts:
                ids = tokenizer.encode(text)
                if not isinstance(ids, list):
                    raise ValueError("Tokenizer encode output must be a list of token ids.")
                if ids:
                    pieces.append(np.asarray(ids, dtype=np.int32))
        if not pieces:
            return np.zeros((0,), dtype=np.int32)
        return np.concatenate(pieces)

    def train_dataset(self) -> IndexedDataset:
        if self._train is None:
            raise RuntimeError("setup must be called before train_dataset")
        return self._train

    def val_dataset(self) -> IndexedDataset | None:
        if self._cfg is None:
            raise RuntimeError("setup must be called before val_dataset")
        return self._val


__all__ = ["HFTextDataModule", "TokenWindowDataset"]
