"""HuggingFace text data module: tokenize → concatenate → window.

Parity target: reference ``src/llmtrain/data/hf_text.py`` — ``load_dataset``
with cache_dir (:81-86), tokenize→concatenate→slice into ``block_size + 1``
windows yielding ``input_ids = chunk[:-1]`` / ``labels = chunk[1:]`` /
all-ones attention_mask (:108-174), processed-split disk cache keyed by
dataset/config/split (:97-106).

TPU-first divergence: instead of materializing a HF dataset of per-window
rows, the tokenized stream is stored as ONE flat int32 numpy array (cached as
``.npy``) and windows are cut at access time — zero-copy random access, an
order of magnitude less cache space, and gather-friendly for the
deterministic index-based sampler. The window content is identical:
non-overlapping ``block_size + 1`` chunks of the concatenated stream.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import numpy as np

from ..config.schemas import RunConfig
from ..registry.data import register_data_module
from .base import (
    DataModule,
    IndexedDataset,
    load_token_cache,
    validate_split_documents,
    write_token_cache,
)


class TokenWindowDataset:
    """Non-overlapping (block_size+1)-token windows over a flat stream.

    With ``doc_starts`` (sorted document start offsets into the stream)
    and ``split_documents=True``, ``attention_mask`` carries SEGMENT ids
    instead of all-ones: within each window, tokens of the same document
    share one nonzero id (1-based, local to the window), the attention
    paths mask cross-document pairs (equal-id semantics, models/gpt.py
    dense_attention and the Pallas kernels), and positions whose LABEL
    belongs to the next document get mask 0 — a cross-document
    next-token prediction is noise, and as keys those document-final
    tokens serve no same-document query anyway.
    """

    def __init__(
        self,
        tokens: np.ndarray,
        block_size: int,
        *,
        doc_starts: np.ndarray | None = None,
        split_documents: bool = False,
    ) -> None:
        if tokens.ndim != 1:
            raise ValueError(f"token stream must be 1-D, got shape {tokens.shape}")
        if split_documents and doc_starts is None:
            raise ValueError("split_documents=True requires doc_starts")
        self._tokens = tokens
        self._doc_starts = (
            np.asarray(doc_starts, dtype=np.int64) if doc_starts is not None else None
        )
        self._split = bool(split_documents)
        self._block_size = block_size
        self._chunk = block_size + 1
        self._num_windows = len(tokens) // self._chunk

    def __len__(self) -> int:
        return self._num_windows

    def get_examples(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        starts = np.asarray(indices, dtype=np.int64) * self._chunk
        # Gather all windows in one vectorized fancy-index.
        offsets = np.arange(self._chunk, dtype=np.int64)
        positions = starts[:, None] + offsets[None, :]
        chunks = self._tokens[positions]
        input_ids = np.ascontiguousarray(chunks[:, :-1], dtype=np.int32)
        labels = np.ascontiguousarray(chunks[:, 1:], dtype=np.int32)
        if self._split:
            # Document ordinal per position (1-based via 'right'), then
            # renumbered locally so ids stay small per window.
            doc = np.searchsorted(self._doc_starts, positions, side="right")
            seg_in, seg_lab = doc[:, :-1], doc[:, 1:]
            local = seg_in - seg_in.min(axis=1, keepdims=True) + 1
            mask = np.where(seg_in == seg_lab, local, 0).astype(np.int32)
        else:
            mask = np.ones_like(input_ids)
        return {
            "input_ids": input_ids,
            "labels": labels,
            "attention_mask": mask,
        }


@register_data_module("hf_text")
class HFTextDataModule(DataModule):
    """Loads a HuggingFace text dataset and serves fixed token windows."""

    known_extra_keys = frozenset({"split_documents"})

    def __init__(self) -> None:
        self._cfg: RunConfig | None = None
        self._train: TokenWindowDataset | None = None
        self._val: TokenWindowDataset | None = None

    def setup(self, cfg: RunConfig, tokenizer: Any | None = None) -> None:
        if tokenizer is None:
            raise ValueError("hf_text requires a tokenizer from the model adapter")
        if cfg.data.dataset_name is None:
            raise ValueError("hf_text requires data.dataset_name")
        text_column = cfg.data.text_column or "text"
        split_docs = bool(cfg.data.extra.get("split_documents", False))
        if split_docs:
            validate_split_documents(cfg)
        self._cfg = cfg

        train_tokens, train_docs = self._prepare_split(
            cfg, cfg.data.train_split, tokenizer, text_column, need_docs=split_docs
        )
        self._train = TokenWindowDataset(
            train_tokens, cfg.model.block_size,
            doc_starts=train_docs, split_documents=split_docs,
        )
        self._val = None
        if cfg.data.val_split:
            val_tokens, val_docs = self._prepare_split(
                cfg, cfg.data.val_split, tokenizer, text_column, need_docs=split_docs
            )
            val_ds = TokenWindowDataset(
                val_tokens, cfg.model.block_size,
                doc_starts=val_docs, split_documents=split_docs,
            )
            if len(val_ds) > 0:
                self._val = val_ds

    def _token_cache_path(self, cfg: RunConfig, split: str, tokenizer: Any) -> Path:
        dataset_name = (cfg.data.dataset_name or "unknown").replace("/", "__")
        dataset_config = (cfg.data.dataset_config or "default").replace("/", "__")
        # Key the cache by tokenizer identity too: reusing token ids produced
        # by a different tokenizer would silently corrupt training.
        from .tokenizers import tokenizer_cache_id

        tok_id = tokenizer_cache_id(tokenizer)
        return (
            Path(cfg.data.cache_dir)
            / "processed"
            / f"{dataset_name}__{dataset_config}__{tok_id}__{split}.npy"
        )

    def _prepare_split(
        self, cfg: RunConfig, split: str, tokenizer: Any, text_column: str,
        *, need_docs: bool = False,
    ) -> tuple[np.ndarray, np.ndarray | None]:
        cache_path = self._token_cache_path(cfg, split, tokenizer)
        cached = load_token_cache(cache_path, need_docs=need_docs)
        if cached is not None:
            return cached

        from datasets import load_dataset

        raw = load_dataset(
            cfg.data.dataset_name,
            cfg.data.dataset_config,
            split=split,
            cache_dir=cfg.data.cache_dir,
        )
        tokens, doc_starts = self._tokenize_stream(raw, tokenizer, text_column)
        write_token_cache(cache_path, tokens, doc_starts)
        return tokens, (doc_starts if need_docs else None)

    @staticmethod
    def _tokenize_stream(
        raw_dataset: Any, tokenizer: Any, text_column: str
    ) -> tuple[np.ndarray, np.ndarray]:
        """Encode every row's text column and concatenate into one stream.

        Also returns the document START offsets into the stream (one per
        encoded row) — the boundary structure ``split_documents`` needs.
        """
        pieces: list[np.ndarray] = []
        batch_encode = getattr(tokenizer, "encode_ordinary_batch", None)
        texts = (str(t) for t in raw_dataset[text_column] if t is not None)
        if batch_encode is not None:
            # tiktoken fast path: parallel batch encoding without special tokens.
            encoded_lists = batch_encode(list(texts))
            pieces = [np.asarray(ids, dtype=np.int32) for ids in encoded_lists if ids]
        else:
            for text in texts:
                ids = tokenizer.encode(text)
                if not isinstance(ids, list):
                    raise ValueError("Tokenizer encode output must be a list of token ids.")
                if ids:
                    pieces.append(np.asarray(ids, dtype=np.int32))
        if not pieces:
            return np.zeros((0,), dtype=np.int32), np.zeros((0,), dtype=np.int64)
        lengths = np.asarray([len(p) for p in pieces], dtype=np.int64)
        doc_starts = np.concatenate([[0], np.cumsum(lengths)[:-1]])
        return np.concatenate(pieces), doc_starts

    def train_dataset(self) -> IndexedDataset:
        if self._train is None:
            raise RuntimeError("setup must be called before train_dataset")
        return self._train

    def val_dataset(self) -> IndexedDataset | None:
        if self._cfg is None:
            raise RuntimeError("setup must be called before val_dataset")
        return self._val


__all__ = ["HFTextDataModule", "TokenWindowDataset"]
