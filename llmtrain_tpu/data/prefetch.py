"""Background batch prefetcher: overlap host assembly + H2D with compute.

docs/perf.md's rule — "the host must never be in the loop" — was violated
by the step loop itself: ``Trainer.fit`` assembled every global batch
synchronously before dispatching the step, so the per-step numpy gathers
and the host→device transfer sat on the critical path instead of hiding
behind the device queue. :class:`BatchPrefetcher` moves that work onto a
daemon thread that runs the deterministic index math *ahead* of the
consumer and keeps a bounded queue of fully-formed global device arrays,
so the loop's ``get(step)`` normally returns immediately.

Correctness requirements (the hard part, see docs/robustness.md):

* **Determinism** — batches are a pure function of ``(seed, step,
  data_offset)``; the prefetcher only changes *when* they are assembled,
  never *what* is assembled, so loss trajectories are bitwise identical
  with prefetch on vs. off (tests/test_prefetch.py pins this, including
  across resume and rollback).
* **Rollback** — a loss-spike rollback mutates the trainer's
  ``_data_offset`` and replays a window. Every queued batch assembled
  under the old offset is invalid. :meth:`reseek` bumps a generation
  counter, drains the queue, and repositions the producer; the consumer
  discards any entry whose generation tag is stale (the consumer-side
  check is authoritative — the producer-side check merely avoids wasted
  work).
* **Shutdown** — SIGTERM preemption or an exception can break the loop
  while the queue is full and the producer is blocked in ``put``.
  :meth:`close` sets the stop event, drains the queue so the producer
  unblocks, and joins with a bounded timeout — a producer wedged inside
  a hung dataset fetch is abandoned (daemon thread), never waited on.
* **Error transparency** — an assembly exception is re-raised in the
  consumer at the next ``get``, preserving the original exception object
  so callers' error handling (CLI exit codes, test asserts) sees the
  real cause.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

from ..utils.logging import get_logger

logger = get_logger()

# How long a blocked producer put / consumer get sleeps between checks of
# the stop/generation state. Purely an internal responsiveness bound.
_POLL_SEC = 0.05


class PrefetcherClosedError(RuntimeError):
    """``get`` was called on a prefetcher that has been closed."""


class BatchPrefetcher:
    """Bounded look-ahead queue of assembled batches, keyed by step.

    ``assemble(step)`` must be a deterministic function of the step (plus
    any state — like the trainer's data offset — that is only mutated
    under the :meth:`reseek` protocol). ``depth`` bounds how many
    assembled batches may exist ahead of the consumer, which bounds the
    extra device memory the pipeline holds (depth batches queued plus one
    in flight in the producer).
    """

    def __init__(
        self,
        assemble: Callable[[int], Any],
        *,
        depth: int,
        start_step: int,
        name: str = "batch-prefetch",
        before_assemble: Callable[[int], None] | None = None,
        timeline: Any | None = None,
    ) -> None:
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1 (0 = don't construct one)")
        self._assemble = assemble
        self._before_assemble = before_assemble
        # Telemetry hook (telemetry/timeline.py): the producer records a
        # prefetch_assemble span per batch so the trace shows host
        # assembly overlapping device compute — the whole point of the
        # async pipeline, now visible instead of inferred.
        self._timeline = timeline
        self._name = name
        self._queue: queue.Queue[tuple[int, int, Any]] = queue.Queue(maxsize=depth)
        self._lock = threading.Lock()
        self._generation = 0
        self._next_step = start_step
        self._stop = threading.Event()
        self._error: BaseException | None = None
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- producer

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                gen = self._generation
                step = self._next_step
                self._next_step += 1
            try:
                if self._before_assemble is not None:
                    # Fault-injection hook (resilience.faults.hang_in_
                    # prefetcher): a REAL block here strands the consumer
                    # on the queue, which is exactly the stall the hang
                    # watchdog must detect from outside.
                    self._before_assemble(step)
                if self._timeline is not None:
                    with self._timeline.span(
                        "prefetch_assemble", cat="data", step=step
                    ):
                        batch = self._assemble(step)
                else:
                    batch = self._assemble(step)
            except BaseException as exc:  # noqa: BLE001 — re-raised in consumer
                with self._lock:
                    self._error = exc
                return
            # Hand over, unless a reseek invalidated this batch mid-flight
            # or the consumer is gone. The generation re-check before each
            # put attempt keeps a blocked producer from stuffing a stale
            # batch into the queue a reseek just drained.
            while not self._stop.is_set():
                with self._lock:
                    if self._generation != gen:
                        break  # stale: drop it, loop back for the new position
                try:
                    self._queue.put((gen, step, batch), timeout=_POLL_SEC)
                    break
                except queue.Full:
                    continue

    # ------------------------------------------------------------- consumer

    def get(self, step: int) -> Any:
        """The assembled batch for optimizer step ``step`` (blocking).

        The caller drives steps in order; after a rollback it must call
        :meth:`reseek` before resuming. Stale-generation entries are
        discarded silently. A producer error is re-raised here — but only
        once the queue is empty, so batches assembled before the failure
        are still consumed and the run fails at the same step the
        synchronous path would have failed at.
        """
        while True:
            # close() is only ever called by the consumer thread itself, so
            # this check cannot race with normal consumption.
            if self._stop.is_set():
                raise PrefetcherClosedError("prefetcher is closed")
            try:
                gen, got_step, batch = self._queue.get(timeout=_POLL_SEC)
            except queue.Empty:
                if self._error is not None:
                    raise self._error
                continue
            with self._lock:
                if gen != self._generation:
                    continue  # assembled before the last reseek
            if got_step != step:
                # With in-order consumption and the reseek protocol this is
                # unreachable; fail loudly rather than training on the
                # wrong data if a future caller breaks the protocol.
                raise RuntimeError(
                    f"prefetcher out of sync: queued step {got_step}, "
                    f"consumer wants {step}"
                )
            return batch

    def reseek(self, step: int) -> None:
        """Invalidate everything queued or in flight and restart the
        producer's cursor at ``step`` — the rollback hook: the trainer
        mutates ``_data_offset`` first, then reseeks, so every batch the
        replay consumes is assembled under the post-rollback offset.

        The drain runs INSIDE the lock: the producer can only pick up the
        new (generation, step) cursor under this same lock, so nothing
        assembled for the new generation can reach the queue before the
        drain finishes — draining after releasing would race a fast
        producer and eat its first valid replay batches. At most one
        in-flight OLD-generation item can land mid-drain (a put does not
        hold the lock); the consumer's generation check discards it.

        A producer that died on a PRE-reseek assembly error is revived
        with the error cleared: that failure belongs to the invalidated
        generation (the synchronous path would re-assemble the replay
        window under the new offset and may well succeed), so surfacing
        it after a rollback would abort a run the escape-hatch path
        completes.
        """
        with self._lock:
            self._generation += 1
            self._next_step = step
            self._drain()
            revive = self._error is not None and not self._stop.is_set()
            if revive:
                self._error = None
        if revive:
            # The producer thread returns right after setting _error, so
            # a fresh thread (not a resurrection race) picks up the new
            # generation's cursor.
            self._thread = threading.Thread(
                target=self._run, name=self._name, daemon=True
            )
            self._thread.start()

    def close(self, timeout: float = 5.0) -> None:
        """Stop the producer and release anything it is blocked on.

        Bounded: a producer wedged inside a hung assembly (dead storage,
        injected hang) is abandoned to die with the process — the exit
        path must never deadlock on the pipeline it is tearing down."""
        self._stop.set()
        self._drain()
        self._thread.join(timeout)
        if self._thread.is_alive():
            logger.warning(
                "prefetch thread still blocked in assembly after %.1fs; "
                "abandoning it (daemon)",
                timeout,
            )

    @property
    def closed(self) -> bool:
        return self._stop.is_set()

    @property
    def queue_depth(self) -> int:
        """Batches currently queued ahead of the consumer (approximate —
        qsize is advisory under concurrency; published as a telemetry
        gauge, never used for control flow)."""
        return self._queue.qsize()

    def _drain(self) -> None:
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                return


__all__ = ["BatchPrefetcher", "PrefetcherClosedError"]
