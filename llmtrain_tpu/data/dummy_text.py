"""Synthetic text data module — the fast fake data backend for tests.

Parity target: reference ``src/llmtrain/data/dummy_text.py`` — per-index
seeded random tokens with labels = input copy (:33-51), caps seq_len<=8 /
examples<=128 / val = num/5 capped 32 / val seed = seed+1000 (:68-87).
Random access replaces the torch Dataset/DataLoader pair (see data/base.py).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..config.schemas import RunConfig
from ..registry.data import register_data_module
from .base import DataModule, IndexedDataset


class _DummyTextDataset:
    """Each example is a deterministic function of (seed, index)."""

    def __init__(
        self,
        num_examples: int,
        seq_len: int,
        vocab_size: int,
        deterministic: bool,
        seed: int,
    ) -> None:
        self._num_examples = num_examples
        self._seq_len = seq_len
        self._vocab_size = vocab_size
        self._deterministic = deterministic
        self._seed = seed

    def __len__(self) -> int:
        return self._num_examples

    def get_examples(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        batch = np.empty((len(indices), self._seq_len), dtype=np.int32)
        for row, index in enumerate(indices):
            seed = self._seed + int(index) if self._deterministic else None
            rng = np.random.default_rng(seed)
            batch[row] = rng.integers(0, self._vocab_size, size=self._seq_len, dtype=np.int32)
        return {
            "input_ids": batch,
            "labels": batch.copy(),
            "attention_mask": np.ones_like(batch),
        }


@register_data_module("dummy_text")
class DummyTextDataModule(DataModule):
    """Synthetic text data for dry-run smoke tests."""

    known_extra_keys = frozenset()

    def __init__(self) -> None:
        self._train: _DummyTextDataset | None = None
        self._val: _DummyTextDataset | None = None

    def setup(self, cfg: RunConfig, tokenizer: Any | None = None) -> None:
        del tokenizer
        vocab_size = cfg.model.vocab_size or 128
        # Keep synthetic batches tiny so unit tests are fast and stable.
        seq_len = max(2, min(cfg.model.block_size, 8))
        requested = cfg.trainer.max_steps * cfg.trainer.micro_batch_size
        num_examples = max(1, min(requested, 128))
        self._train = _DummyTextDataset(
            num_examples=num_examples,
            seq_len=seq_len,
            vocab_size=vocab_size,
            deterministic=cfg.run.deterministic,
            seed=cfg.run.seed,
        )
        val_examples = max(1, min(num_examples // 5, 32))
        self._val = _DummyTextDataset(
            num_examples=val_examples,
            seq_len=seq_len,
            vocab_size=vocab_size,
            deterministic=cfg.run.deterministic,
            seed=cfg.run.seed + 1000,
        )

    def train_dataset(self) -> IndexedDataset:
        if self._train is None:
            raise RuntimeError("setup must be called before train_dataset")
        return self._train

    def val_dataset(self) -> IndexedDataset | None:
        if self._val is None:
            raise RuntimeError("setup must be called before val_dataset")
        return self._val


__all__ = ["DummyTextDataModule", "_DummyTextDataset"]
