"""SLO-aware overload control for the serving tier (docs/serving.md
"Overload and SLOs").

The continuous-batching scheduler's admission queue used to be an
unbounded FIFO: a traffic burst grew queue wait without limit until
waiters timed out and the abandoned-request shedder cleaned up AFTER the
device had been promised work it could never deliver on time. MinT
(PAPERS.md) argues SLO percentiles must be first-class scheduling
inputs; this module is that control layer — saturation degrades
predictably instead of collapsing:

* **Bounded, deadline-aware admission** — a queue cap plus an EWMA
  predicted-queue-wait estimator: a request whose deadline cannot
  plausibly be met is rejected AT SUBMIT (fast, with a retry-after
  hint) instead of queueing to die.
* **Priority classes** — a weighted-round-robin multi-class queue
  (``interactive``/``batch`` by default) with optional per-class token
  buckets; batch never starves interactive, and interactive never
  starves batch (every WRR cycle visits every class).
* **Brownout with hysteresis** — sustained pressure (predicted wait
  over the high watermark for N consecutive ticks) enters a degraded
  mode that clamps ``max_new_tokens`` and disables speculative
  drafting to protect TTFT; it exits only after the pressure signal
  holds below a LOWER watermark, so the mode cannot flap.
* **Retry budget** — a fixed-window cap the router spends on failover
  retries, so an overloaded fleet is never DDoS'd by its own front
  tier.

Every decision lands as ``llmtrain_serve_rejected_total{reason}`` /
``llmtrain_serve_brownout`` / predicted-wait gauges plus timeline
instants (scheduler.py publishes them; this module only counts).

Threading: the scheduler calls admission/tick/observe methods under its
own lock or from its single scheduler thread; the token buckets and the
HTTP-boundary client gate carry their own locks because handler threads
hit them directly.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Iterable

# Rejection taxonomy — the {reason} label on
# llmtrain_serve_rejected_total and the ``reason`` field of 429 bodies.
REASON_QUEUE_FULL = "queue_full"
REASON_RATE_LIMITED = "rate_limited"
REASON_DEADLINE_UNMEETABLE = "deadline_unmeetable"
REASON_DEADLINE_EXCEEDED = "deadline_exceeded"
REASON_RETRY_BUDGET = "retry_budget_exhausted"

REJECT_REASONS = (
    REASON_QUEUE_FULL,
    REASON_RATE_LIMITED,
    REASON_DEADLINE_UNMEETABLE,
    REASON_DEADLINE_EXCEEDED,
    REASON_RETRY_BUDGET,
)


def rejected_counter(reason: str) -> str:
    """Registry counter key for one rejection reason. The embedded label
    survives Prometheus rendering (telemetry/prometheus.py splits it
    back out), so every reason is one labeled series of
    ``llmtrain_serve_rejected_total``."""
    return f'serve/rejected{{reason="{reason}"}}'


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/sec, ``burst`` capacity.

    Injectable ``clock`` for deterministic tests; thread-safe (the HTTP
    per-client gate shares buckets across handler threads).
    """

    def __init__(
        self,
        rate: float,
        burst: float,
        *,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if rate <= 0:
            raise ValueError(f"token bucket rate must be > 0, got {rate}")
        if burst < 1:
            raise ValueError(f"token bucket burst must be >= 1, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._stamp = clock()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        self._tokens = min(
            self.burst, self._tokens + (now - self._stamp) * self.rate
        )
        self._stamp = now

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill(self._clock())
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available (0 if they are)."""
        with self._lock:
            self._refill(self._clock())
            if self._tokens >= n:
                return 0.0
            return (n - self._tokens) / self.rate


class EwmaWaitEstimator:
    """EWMA of the observed queue-wait cost PER QUEUE POSITION.

    Each admission reports ``(actual wait, queue depth at submit)``; the
    per-position cost ``wait / (depth + 1)`` feeds an EWMA, and the
    predicted wait for a NEW arrival is ``per_position * (depth + 1)``.
    ``prior_ms`` seeds the estimate so the very first requests are not
    admitted blind with a zero prediction.
    """

    def __init__(self, beta: float = 0.8, prior_ms: float = 50.0) -> None:
        if not 0.0 < beta < 1.0:
            raise ValueError(f"ewma beta must be in (0, 1), got {beta}")
        self.beta = float(beta)
        self._per_slot_ms = float(prior_ms)
        self.samples = 0

    def observe(self, wait_ms: float, depth_at_submit: int) -> None:
        per_slot = max(0.0, float(wait_ms)) / max(1, int(depth_at_submit) + 1)
        self._per_slot_ms = (
            self.beta * self._per_slot_ms + (1.0 - self.beta) * per_slot
        )
        self.samples += 1

    @property
    def per_slot_ms(self) -> float:
        return self._per_slot_ms

    def predicted_wait_ms(self, depth: int) -> float:
        return self._per_slot_ms * (max(0, int(depth)) + 1)


class WeightedClassQueue:
    """Multi-class admission queue with weighted-round-robin dequeue.

    Drop-in for the scheduler's ``deque`` surface (``append`` /
    ``appendleft`` / ``popleft`` / ``len`` / truthiness). ``popleft``
    walks a weight-expanded WRR schedule, so with
    ``{"interactive": 4, "batch": 1}`` a backlogged queue drains 4
    interactive per batch — and EVERY cycle visits every class, so no
    class starves. ``appendleft`` pushes back to the front of the
    request's own class (the pool-full retry path).
    """

    def __init__(self, weights: dict[str, int], default_class: str) -> None:
        if not weights:
            raise ValueError("weighted queue needs at least one class")
        if default_class not in weights:
            raise ValueError(
                f"default class {default_class!r} is not one of "
                f"{sorted(weights)}"
            )
        for name, w in weights.items():
            if int(w) < 1:
                raise ValueError(f"class {name!r} weight must be >= 1")
        self.default_class = default_class
        self._queues: dict[str, deque] = {name: deque() for name in weights}
        self._schedule = [
            name for name, w in weights.items() for _ in range(int(w))
        ]
        self._cursor = 0

    def class_of(self, req: Any) -> str:
        cls = getattr(req, "priority", None)
        return cls if cls in self._queues else self.default_class

    def append(self, req: Any) -> None:
        self._queues[self.class_of(req)].append(req)

    def appendleft(self, req: Any) -> None:
        self._queues[self.class_of(req)].appendleft(req)

    def popleft(self) -> Any:
        n = len(self._schedule)
        for off in range(n):
            name = self._schedule[(self._cursor + off) % n]
            if self._queues[name]:
                self._cursor = (self._cursor + off + 1) % n
                return self._queues[name].popleft()
        raise IndexError("pop from an empty WeightedClassQueue")

    def sweep(self, predicate: Callable[[Any], bool]) -> list[Any]:
        """Remove and return every queued request matching ``predicate``
        (deadline shedding) without disturbing relative order."""
        out: list[Any] = []
        for q in self._queues.values():
            kept = deque()
            for req in q:
                if predicate(req):
                    out.append(req)
                else:
                    kept.append(req)
            q.clear()
            q.extend(kept)
        return out

    def depths(self) -> dict[str, int]:
        return {name: len(q) for name, q in self._queues.items()}

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def __bool__(self) -> bool:
        return any(self._queues.values())

    def __iter__(self) -> Iterable[Any]:
        for q in self._queues.values():
            yield from q


class Brownout:
    """Hysteresis state machine over a scalar pressure signal (ms).

    Enters after ``enter_ticks`` CONSECUTIVE ticks at/above ``high_ms``;
    exits after ``exit_ticks`` consecutive ticks BELOW ``low_ms``. The
    gap between the watermarks is what keeps the mode from flapping at
    the threshold.
    """

    def __init__(
        self,
        *,
        high_ms: float,
        low_ms: float,
        enter_ticks: int = 3,
        exit_ticks: int = 3,
    ) -> None:
        if low_ms >= high_ms:
            raise ValueError(
                f"brownout low watermark ({low_ms}) must be below the high "
                f"watermark ({high_ms})"
            )
        self.high_ms = float(high_ms)
        self.low_ms = float(low_ms)
        self.enter_ticks = int(enter_ticks)
        self.exit_ticks = int(exit_ticks)
        self.active = False
        self.entries = 0
        self.exits = 0
        self._over = 0
        self._under = 0

    def tick(self, pressure_ms: float) -> str | None:
        """Feed one pressure sample; returns "entered"/"exited" on a
        transition, else None."""
        if not self.active:
            self._over = self._over + 1 if pressure_ms >= self.high_ms else 0
            if self._over >= self.enter_ticks:
                self.active = True
                self.entries += 1
                self._over = 0
                self._under = 0
                return "entered"
            return None
        self._under = self._under + 1 if pressure_ms < self.low_ms else 0
        if self._under >= self.exit_ticks:
            self.active = False
            self.exits += 1
            self._over = 0
            self._under = 0
            return "exited"
        return None


class RetryBudget:
    """Fixed-window cap on router failover retries.

    ``budget`` spends per ``window_sec`` window; the window resets
    wholesale (fixed, not sliding — cheap and good enough to bound the
    retry amplification factor). Thread-safe: failovers run on the
    router's per-request threads.
    """

    def __init__(
        self,
        budget: int,
        window_sec: float,
        *,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if budget < 0:
            raise ValueError(f"retry budget must be >= 0, got {budget}")
        if window_sec <= 0:
            raise ValueError(f"retry window must be > 0, got {window_sec}")
        self.budget = int(budget)
        self.window_sec = float(window_sec)
        self._clock = clock
        self._window_start = clock()
        self._spent = 0
        self._lock = threading.Lock()

    def _roll(self, now: float) -> None:
        if now - self._window_start >= self.window_sec:
            self._window_start = now
            self._spent = 0

    def try_spend(self) -> bool:
        with self._lock:
            self._roll(self._clock())
            if self._spent < self.budget:
                self._spent += 1
                return True
            return False

    def remaining(self) -> int:
        with self._lock:
            self._roll(self._clock())
            return self.budget - self._spent


class ClientRateGate:
    """Per-client token buckets at the HTTP boundary, keyed by the
    ``X-Client-Id`` header (clients without one share the anonymous
    bucket). LRU-capped so a client-id cardinality attack cannot grow
    the map without bound."""

    def __init__(
        self,
        rate_rps: float,
        burst: int,
        *,
        max_clients: int = 1024,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.rate_rps = float(rate_rps)
        self.burst = int(burst)
        self.max_clients = int(max_clients)
        self._clock = clock
        self._buckets: OrderedDict[str, TokenBucket] = OrderedDict()
        self._lock = threading.Lock()

    def check(self, client_id: str) -> float | None:
        """None = admit; else the retry-after hint (seconds)."""
        with self._lock:
            bucket = self._buckets.get(client_id)
            if bucket is None:
                bucket = TokenBucket(
                    self.rate_rps, self.burst, clock=self._clock
                )
                self._buckets[client_id] = bucket
            self._buckets.move_to_end(client_id)
            while len(self._buckets) > self.max_clients:
                self._buckets.popitem(last=False)
        if bucket.try_acquire():
            return None
        return bucket.retry_after()


class OverloadController:
    """The scheduler-side overload policy: admission verdicts, queue-wait
    learning, deadline shedding, and the brownout state machine.

    One controller per scheduler. Its ``queue`` (a WeightedClassQueue)
    replaces the scheduler's FIFO deque; the scheduler calls
    ``admission_check`` under its submit lock, ``observe_queue_wait`` at
    each admission, and ``tick`` once per step.
    """

    def __init__(
        self,
        *,
        queue_cap: int = 64,
        default_deadline_ms: float = 0.0,
        ewma_beta: float = 0.8,
        prior_wait_ms: float = 50.0,
        class_weights: dict[str, int] | None = None,
        default_class: str = "interactive",
        class_rate_rps: dict[str, float] | None = None,
        class_burst: dict[str, int] | None = None,
        brownout_high_ms: float = 500.0,
        brownout_low_ms: float = 100.0,
        brownout_enter_ticks: int = 3,
        brownout_exit_ticks: int = 3,
        brownout_max_new_tokens: int = 16,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if queue_cap < 1:
            raise ValueError(f"queue_cap must be >= 1, got {queue_cap}")
        weights = dict(class_weights or {"interactive": 4, "batch": 1})
        self.queue_cap = int(queue_cap)
        self.default_deadline_ms = float(default_deadline_ms)
        self.class_weights = weights
        self.default_class = default_class
        self.brownout_max_new_tokens = int(brownout_max_new_tokens)
        self._clock = clock
        self.estimator = EwmaWaitEstimator(ewma_beta, prior_wait_ms)
        self.queue = WeightedClassQueue(weights, default_class)
        self.brownout = Brownout(
            high_ms=brownout_high_ms,
            low_ms=brownout_low_ms,
            enter_ticks=brownout_enter_ticks,
            exit_ticks=brownout_exit_ticks,
        )
        self.buckets: dict[str, TokenBucket] = {}
        for name, rate in (class_rate_rps or {}).items():
            if name not in weights:
                raise ValueError(
                    f"class_rate_rps names unknown class {name!r} "
                    f"(classes: {sorted(weights)})"
                )
            burst = (class_burst or {}).get(name, max(1, int(rate)))
            self.buckets[name] = TokenBucket(rate, burst, clock=clock)
        # Counters (scheduler thread + submit threads): one lock.
        self._lock = threading.Lock()
        self.rejected: dict[str, int] = {}
        self.shed = 0
        self._last_pressure_ms = 0.0

    @classmethod
    def from_config(cls, cfg: Any, **overrides: Any) -> "OverloadController":
        """Build from a ``serving.overload`` config section
        (config/schemas.py OverloadConfig — duck-typed, so tests can
        pass a namespace)."""
        kwargs = dict(
            queue_cap=cfg.queue_cap,
            default_deadline_ms=cfg.default_deadline_ms,
            ewma_beta=cfg.ewma_beta,
            prior_wait_ms=cfg.prior_wait_ms,
            class_weights=dict(cfg.classes),
            default_class=cfg.default_class,
            class_rate_rps=dict(cfg.class_rate_rps),
            class_burst=dict(cfg.class_burst),
            brownout_high_ms=cfg.brownout_high_ms,
            brownout_low_ms=cfg.brownout_low_ms,
            brownout_enter_ticks=cfg.brownout_enter_ticks,
            brownout_exit_ticks=cfg.brownout_exit_ticks,
            brownout_max_new_tokens=cfg.brownout_max_new_tokens,
        )
        kwargs.update(overrides)
        return cls(**kwargs)

    # ---------------------------------------------------------- admission

    def admission_check(
        self, req: Any, depth: int
    ) -> tuple[str, float] | None:
        """Admission verdict for one submitted request: None admits,
        otherwise ``(reason, retry_after_sec)`` rejects. Checked in
        cheapness order — the queue cap costs a comparison, the bucket a
        refill, the deadline a multiply. The predicted wait the verdict
        was decided on is stamped onto the request (best-effort) so a
        rejection's distributed trace shows WHY it was turned away."""
        try:
            req.admission_predicted_wait_ms = round(
                self.estimator.predicted_wait_ms(depth), 3
            )
        except Exception:  # noqa: BLE001 — annotation only, never reject on it
            pass
        if depth >= self.queue_cap:
            return (
                REASON_QUEUE_FULL,
                max(0.001, self.estimator.per_slot_ms * self.queue_cap / 1e3),
            )
        bucket = self.buckets.get(self.queue.class_of(req))
        if bucket is not None and not bucket.try_acquire():
            return (REASON_RATE_LIMITED, max(0.001, bucket.retry_after()))
        deadline_ms = getattr(req, "deadline_ms", None)
        if deadline_ms:
            predicted = self.estimator.predicted_wait_ms(depth)
            if predicted > float(deadline_ms):
                return (
                    REASON_DEADLINE_UNMEETABLE,
                    max(0.001, (predicted - float(deadline_ms)) / 1e3),
                )
        return None

    def observe_queue_wait(self, wait_ms: float, depth_at_submit: int) -> None:
        self.estimator.observe(wait_ms, depth_at_submit)

    def predicted_wait_ms(self, depth: int) -> float:
        return self.estimator.predicted_wait_ms(depth)

    def note_rejection(self, reason: str, *, shed: bool = False) -> None:
        with self._lock:
            self.rejected[reason] = self.rejected.get(reason, 0) + 1
            if shed:
                self.shed += 1

    # ----------------------------------------------------------- brownout

    def tick(self, depth: int) -> str | None:
        """One scheduler-step pressure sample; returns the brownout
        transition ("entered"/"exited") when one fires."""
        self._last_pressure_ms = self.estimator.predicted_wait_ms(depth)
        return self.brownout.tick(self._last_pressure_ms)

    @property
    def in_brownout(self) -> bool:
        return self.brownout.active

    @property
    def shedding_active(self) -> bool:
        """Eager past-deadline shedding runs under SUSTAINED overload
        (brownout, or pressure at/above the high watermark right now) —
        in calm seas a late request still gets served."""
        return (
            self.brownout.active
            or self._last_pressure_ms >= self.brownout.high_ms
        )

    def clamp_new_tokens(self, max_new_tokens: int) -> int:
        if self.in_brownout:
            return min(int(max_new_tokens), self.brownout_max_new_tokens)
        return int(max_new_tokens)

    def past_deadline(self, req: Any, now: float | None = None) -> bool:
        deadline_ms = getattr(req, "deadline_ms", None)
        if not deadline_ms:
            return False
        now = self._clock() if now is None else now
        return (now - req.submitted_t) * 1e3 > float(deadline_ms)

    # ---------------------------------------------------------- telemetry

    def stats(self) -> dict[str, Any]:
        with self._lock:
            rejected = dict(self.rejected)
            shed = self.shed
        return {
            "queue_cap": self.queue_cap,
            "queue_depths": self.queue.depths(),
            "predicted_wait_ms": round(self._last_pressure_ms, 3),
            "per_slot_wait_ms": round(self.estimator.per_slot_ms, 3),
            "in_brownout": self.in_brownout,
            "brownout_entries": self.brownout.entries,
            "brownout_exits": self.brownout.exits,
            "rejected": rejected,
            "rejected_total": sum(rejected.values()),
            "shed": shed,
        }


__all__ = [
    "Brownout",
    "ClientRateGate",
    "EwmaWaitEstimator",
    "OverloadController",
    "REASON_DEADLINE_EXCEEDED",
    "REASON_DEADLINE_UNMEETABLE",
    "REASON_QUEUE_FULL",
    "REASON_RATE_LIMITED",
    "REASON_RETRY_BUDGET",
    "REJECT_REASONS",
    "RetryBudget",
    "TokenBucket",
    "WeightedClassQueue",
    "rejected_counter",
]
