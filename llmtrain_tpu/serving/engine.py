"""Paged decode engine: bucketed jitted prefill/decode over the block pool.

The execution layer of the continuous-batching server (scheduler.py owns
WHEN sequences join/leave; this module owns HOW a step runs):

* **Two programs, shape-bucketed.** ``prefill`` runs one joining
  sequence's prompt (padded to a prompt-length bucket) through the paged
  model, writing its K/V blocks and sampling its first token; ``decode``
  advances every in-flight sequence one token (batch padded to a
  batch-size bucket). XLA compiles once per bucket, so the total compile
  count is bounded by ``len(prompt_buckets) + len(batch_buckets)`` — a
  budget :meth:`compile_stats` exposes and tests assert
  (tests/test_serving_engine.py), because unbounded recompilation is the
  classic way a JAX server falls over in production.
* **Per-row sampling with per-request seeds.** Greedy rows take the raw
  argmax; sampled rows replay ``generate()``'s exact recipe —
  temperature scale, top-k/top-p filter (same thresholds as
  ``generation.filter_logits``), then ``categorical(fold_in(key(seed),
  emit_index))`` — per ROW, so a batched decode emits the same tokens the
  single-request path would (the exactness contract the acceptance test
  pins under greedy decoding).
* **Shared pool cache.** The paged cache is batch-shape-independent
  (models/gpt.py ``_paged_decode_attention``), so every bucket's program
  reads/writes the SAME donated cache buffers — join/evict never copies
  K/V.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import get_logger
from .paged_kv import PagedKVPool

logger = get_logger()


def _round_up_buckets(limit: int, *, start: int = 1) -> list[int]:
    """Powers of two up to (and always including) ``limit``."""
    buckets: list[int] = []
    b = start
    while b < limit:
        buckets.append(b)
        b *= 2
    buckets.append(limit)
    return buckets


def bucket_for(n: int, buckets: list[int]) -> int:
    """Smallest bucket >= n; raises when n exceeds every bucket."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds the largest bucket ({buckets[-1]})")


def _filter_rows(
    scaled: jax.Array, top_ks: jax.Array, top_ps: jax.Array
) -> jax.Array:
    """Per-row top-k / top-p masking with DYNAMIC knobs.

    Same thresholds as ``generation.filter_logits`` (kth-largest value;
    exclusive-cumulative-mass nucleus cut) but per row and data-dependent,
    so one compiled program serves every sampling configuration —
    per-request knobs must not multiply the compile count. ``top_ks <= 0``
    and ``top_ps`` outside (0, 1) disable the respective filter, matching
    generate()'s out-of-band conventions.
    """
    v = scaled.shape[-1]
    # top-k: threshold at each row's k-th largest (k clamped into [1, V]).
    desc = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)  # (B, V)
    k_idx = jnp.clip(top_ks - 1, 0, v - 1)
    kth = jnp.take_along_axis(desc, k_idx[:, None], axis=-1)  # (B, 1)
    kth = jnp.where((top_ks > 0)[:, None], kth, -jnp.inf)
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    # top-p composes AFTER top-k, on the masked logits (filter_logits
    # order): keep the smallest descending-prob prefix whose EXCLUSIVE
    # cumulative mass is < p (always keeps the argmax).
    desc = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)
    probs = jax.nn.softmax(desc, axis=-1)
    exclusive = jnp.cumsum(probs, axis=-1) - probs
    keep = exclusive < top_ps[:, None]
    thr = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    active = ((top_ps > 0.0) & (top_ps < 1.0))[:, None]
    return jnp.where(active & (scaled < thr), -jnp.inf, scaled)


def _sample_rows(
    logits: jax.Array,  # (B, V) f32
    seeds: jax.Array,  # (B,) uint32 — per-request rng seed
    emit_idx: jax.Array,  # (B,) int32 — tokens already emitted by the row
    temps: jax.Array,  # (B,) f32; 0 = greedy
    top_ks: jax.Array,  # (B,) int32; <=0 disables
    top_ps: jax.Array,  # (B,) f32; outside (0,1) disables
) -> jax.Array:
    """One sampling decision per row, generate()-exact per request.

    Greedy rows bypass the filter entirely (raw argmax — _sample_next's
    temperature==0 short-circuit); sampled rows draw
    ``categorical(fold_in(key(seed), emit_idx), filtered)`` — the same
    key schedule generate() uses for a batch of one.
    """
    greedy_tok = jnp.argmax(logits, axis=-1)
    safe_t = jnp.where(temps > 0.0, temps, 1.0)
    scaled = _filter_rows(logits / safe_t[:, None], top_ks, top_ps)

    def one(seed: jax.Array, i: jax.Array, row: jax.Array) -> jax.Array:
        key = jax.random.fold_in(jax.random.key(seed), i)
        return jax.random.categorical(key, row)

    sampled = jax.vmap(one)(seeds, emit_idx, scaled)
    return jnp.where(temps == 0.0, greedy_tok, sampled).astype(jnp.int32)


def _prefill_impl(
    model: Any,
    params: Any,
    cache: Any,
    prompt: jax.Array,  # (1, Tb) padded
    true_len: jax.Array,  # (1,) int32
    block_tables: jax.Array,  # (1, MB) int32
    seeds: jax.Array,
    temps: jax.Array,
    top_ks: jax.Array,
    top_ps: jax.Array,
) -> tuple[Any, jax.Array]:
    logits, mutated = model.apply(
        {"params": params, "cache": cache},
        prompt,
        deterministic=True,
        positions=jnp.zeros((prompt.shape[0],), jnp.int32),
        block_tables=block_tables,
        mutable=["cache"],
    )
    # Sample at the LAST REAL position; padded positions' K/V landed in
    # the null block and padded-row logits are garbage nobody reads.
    last = jnp.take_along_axis(
        logits.astype(jnp.float32), (true_len - 1)[:, None, None], axis=1
    )[:, 0]
    tok = _sample_rows(
        last, seeds, jnp.zeros_like(true_len), temps, top_ks, top_ps
    )
    return mutated["cache"], tok


def _decode_impl(
    model: Any,
    params: Any,
    cache: Any,
    tokens: jax.Array,  # (B,) int32 — each row's last emitted token
    positions: jax.Array,  # (B,) int32 — that token's absolute position
    block_tables: jax.Array,  # (B, MB) int32
    seeds: jax.Array,
    emit_idx: jax.Array,
    temps: jax.Array,
    top_ks: jax.Array,
    top_ps: jax.Array,
) -> tuple[Any, jax.Array]:
    logits, mutated = model.apply(
        {"params": params, "cache": cache},
        tokens[:, None],
        deterministic=True,
        positions=positions,
        block_tables=block_tables,
        mutable=["cache"],
    )
    tok = _sample_rows(
        logits[:, -1].astype(jnp.float32), seeds, emit_idx, temps, top_ks, top_ps
    )
    return mutated["cache"], tok


class PagedDecodeEngine:
    """Bucketed paged-KV decode over one model + params.

    Owns the device cache (donated through every step), the host-side
    pool allocator, the bucket policy, and the compile accounting. The
    scheduler calls :meth:`prefill` / :meth:`decode`; nothing here
    decides admission.
    """

    def __init__(
        self,
        model: Any,
        params: Any,
        *,
        block_tokens: int = 16,
        num_blocks: int | None = None,
        max_batch_slots: int = 8,
        prompt_buckets: list[int] | None = None,
        batch_buckets: list[int] | None = None,
    ) -> None:
        if not hasattr(model, "for_paged_decoding"):
            raise ValueError(
                "paged serving needs a model exposing for_paged_decoding(); "
                f"{type(model).__name__} does not"
            )
        self.model = model
        self.params = params
        self.block_size = int(model.block_size)
        self.block_tokens = int(block_tokens)
        self.max_blocks_per_seq = -(-self.block_size // self.block_tokens)
        if num_blocks is None:
            # Default: every slot can host a worst-case sequence, + null.
            num_blocks = 1 + max_batch_slots * self.max_blocks_per_seq
        self.max_batch_slots = int(max_batch_slots)
        self.prompt_buckets = sorted(
            prompt_buckets or _round_up_buckets(self.block_size, start=8)
        )
        self.batch_buckets = sorted(
            batch_buckets or _round_up_buckets(self.max_batch_slots)
        )
        if self.prompt_buckets[-1] > self.block_size:
            raise ValueError(
                f"largest prompt bucket ({self.prompt_buckets[-1]}) exceeds "
                f"the model block_size ({self.block_size})"
            )
        if self.batch_buckets[-1] != self.max_batch_slots:
            raise ValueError(
                f"largest batch bucket ({self.batch_buckets[-1]}) must equal "
                f"max_batch_slots ({self.max_batch_slots})"
            )
        self.decode_model = model.for_paged_decoding(
            num_blocks=num_blocks, block_tokens=self.block_tokens
        )
        self.pool = PagedKVPool(num_blocks, self.block_tokens)

        # Zero cache pytree from an eval_shape trace — no param init work
        # (the generation.py idiom). Cache shapes are batch-INDEPENDENT
        # (the pool is shared), so one cache serves every bucket.
        mb = self.max_blocks_per_seq
        var_shapes = jax.eval_shape(
            lambda: self.decode_model.init(
                jax.random.key(0),
                jnp.zeros((1, 1), jnp.int32),
                deterministic=True,
                positions=jnp.zeros((1,), jnp.int32),
                block_tables=jnp.zeros((1, mb), jnp.int32),
            )
        )
        self._cache_struct = var_shapes["cache"]
        self._cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), self._cache_struct
        )
        # Bumped whenever a failed step forces a cache rebuild: the
        # scheduler compares epochs to learn that in-flight KV was lost.
        self.cache_epoch = 0

        # Per-engine CLOSURES under the jits: jax keys the pjit program
        # cache on the underlying callable, so wrapping the module-level
        # impls directly would make every engine in the process share one
        # cache and `_cache_size()` count other engines' programs. A fresh
        # function object per engine keeps the compile accounting local
        # (and the closed-over model off the static-argument hash path).
        def _prefill_bound(params: Any, cache: Any, *rest: Any) -> Any:
            return _prefill_impl(self.decode_model, params, cache, *rest)

        def _decode_bound(params: Any, cache: Any, *rest: Any) -> Any:
            return _decode_impl(self.decode_model, params, cache, *rest)

        self._prefill_jit = jax.jit(_prefill_bound, donate_argnums=(1,))
        self._decode_jit = jax.jit(_decode_bound, donate_argnums=(1,))
        self._prefill_shapes: set[int] = set()
        self._decode_shapes: set[int] = set()

    # --------------------------------------------------------- validation

    def validate_request(self, prompt_len: int, max_new_tokens: int) -> str | None:
        """Why this engine can never serve the request, or None if it can.

        Checked at ADMISSION (scheduler) and at the HTTP boundary (400,
        not a late 500): the model's context bound, the largest prompt
        bucket (prefill cannot pad past it), and the pool's total
        capacity — a request whose worst-case block need exceeds the
        whole pool would otherwise sit at the FIFO head forever, starving
        everything behind it (try_reserve can only say "not yet").
        """
        prompt_len, total = int(prompt_len), int(prompt_len) + int(max_new_tokens)
        if total > self.block_size:
            return (
                f"prompt+max_new_tokens ({total}) exceeds the model "
                f"block_size ({self.block_size})"
            )
        if prompt_len > self.prompt_buckets[-1]:
            return (
                f"prompt length ({prompt_len}) exceeds the largest "
                f"serving prompt bucket ({self.prompt_buckets[-1]})"
            )
        capacity = self.pool.num_blocks - 1
        need = self.pool.blocks_needed(total)
        if need > capacity:
            return (
                f"request needs {need} worst-case KV blocks but the pool "
                f"only holds {capacity} — raise serving.num_blocks or "
                f"lower max_new_tokens"
            )
        return None

    # ----------------------------------------------------------- stepping

    def prefill(
        self,
        prompt_ids: np.ndarray,  # (Tp,) int32
        table_padded: list[int],
        *,
        seed: int,
        temperature: float,
        top_k: int | None,
        top_p: float | None,
    ) -> int:
        """Run one joining sequence's prompt; returns its first token."""
        tp = int(prompt_ids.shape[0])
        tb = bucket_for(tp, self.prompt_buckets)
        self._prefill_shapes.add(tb)
        prompt = np.zeros((1, tb), np.int32)
        prompt[0, :tp] = prompt_ids
        try:
            cache, tok = self._prefill_jit(
                self.params,
                self._cache,
                jnp.asarray(prompt),
                jnp.asarray([tp], jnp.int32),
                jnp.asarray([table_padded], jnp.int32),
                jnp.asarray([seed & 0xFFFFFFFF], jnp.uint32),
                jnp.asarray([temperature], jnp.float32),
                jnp.asarray([0 if top_k is None else top_k], jnp.int32),
                jnp.asarray([0.0 if top_p is None else top_p], jnp.float32),
            )
        except Exception:
            self._recover_cache_after_error()
            raise
        self._cache = cache
        return int(tok[0])

    def decode(self, rows: list[dict[str, Any]]) -> list[int]:
        """Advance every row one token; returns next tokens, row-aligned.

        Each row dict: ``token`` (last emitted), ``position`` (its
        absolute position), ``table`` (padded physical ids), ``seed``,
        ``emit_idx``, ``temperature``, ``top_k``, ``top_p``. The batch is
        padded to a batch bucket with null-table greedy rows whose output
        is discarded.
        """
        n = len(rows)
        if n == 0:
            return []
        bb = bucket_for(n, self.batch_buckets)
        self._decode_shapes.add(bb)
        mb = self.max_blocks_per_seq

        def col(key: str, fill: Any, dtype: Any) -> np.ndarray:
            out = np.full((bb,), fill, dtype=dtype)
            for i, r in enumerate(rows):
                out[i] = r[key]
            return out

        tables = np.zeros((bb, mb), np.int32)
        for i, r in enumerate(rows):
            tables[i] = r["table"]
        try:
            cache, tok = self._decode_jit(
                self.params,
                self._cache,
                jnp.asarray(col("token", 0, np.int32)),
                jnp.asarray(col("position", 0, np.int32)),
                jnp.asarray(tables),
                jnp.asarray(
                    np.array(
                        [r["seed"] & 0xFFFFFFFF for r in rows] + [0] * (bb - n),
                        dtype=np.uint32,
                    )
                ),
                jnp.asarray(col("emit_idx", 0, np.int32)),
                jnp.asarray(col("temperature", 0.0, np.float32)),
                jnp.asarray(col("top_k", 0, np.int32)),
                jnp.asarray(col("top_p", 0.0, np.float32)),
            )
        except Exception:
            self._recover_cache_after_error()
            raise
        self._cache = cache
        return [int(t) for t in np.asarray(jax.device_get(tok))[:n]]

    def _recover_cache_after_error(self) -> None:
        """Donation safety: a jitted call that fails at RUNTIME has already
        consumed (deleted) the donated cache buffers, so without recovery
        every later prefill/decode would die on "Array has been deleted" —
        one transient device error would wedge the server for good.
        Trace-time failures never donate: a still-live cache (and the
        in-flight KV it holds) is kept untouched; a deleted one is rebuilt
        zeroed and ``cache_epoch`` bumped so the scheduler fails the
        in-flight sequences whose KV went with it.
        """
        leaves = jax.tree.leaves(self._cache)
        if any(
            leaf.is_deleted()
            for leaf in leaves
            if isinstance(leaf, jax.Array)
        ):
            self._cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), self._cache_struct
            )
            self.cache_epoch += 1

    # --------------------------------------------------------- accounting

    def cost_profile(
        self,
        *,
        peaks: dict[str, float] | None = None,
        full: bool = True,
        top_k: int = 10,
    ) -> list[dict[str, Any]]:
        """AOT cost profiles of the prefill/decode programs at their
        LARGEST buckets (worst-case per-step cost; smaller buckets are
        strictly cheaper). Nothing executes and nothing is donated —
        profiling works against abstract shapes, so the live cache and
        in-flight KV stay untouched. ``full=False`` skips the XLA compile
        (cost totals only). Failed profiles are dropped, not raised.
        """
        from ..telemetry import profiling

        if peaks is None:
            peaks = profiling.resolve_peaks()
        sds = jax.ShapeDtypeStruct
        param_structs = jax.tree.map(
            lambda x: sds(jnp.shape(x), x.dtype), self.params
        )
        cache_structs = jax.tree.map(
            lambda s: sds(s.shape, s.dtype), self._cache_struct
        )
        mb = self.max_blocks_per_seq
        tb = self.prompt_buckets[-1]
        bb = self.batch_buckets[-1]
        prefill_args = (
            param_structs,
            cache_structs,
            sds((1, tb), jnp.int32),   # prompt
            sds((1,), jnp.int32),      # true_len
            sds((1, mb), jnp.int32),   # block_tables
            sds((1,), jnp.uint32),     # seeds
            sds((1,), jnp.float32),    # temps
            sds((1,), jnp.int32),      # top_ks
            sds((1,), jnp.float32),    # top_ps
        )
        decode_args = (
            param_structs,
            cache_structs,
            sds((bb,), jnp.int32),     # tokens
            sds((bb,), jnp.int32),     # positions
            sds((bb, mb), jnp.int32),  # block_tables
            sds((bb,), jnp.uint32),    # seeds
            sds((bb,), jnp.int32),     # emit_idx
            sds((bb,), jnp.float32),   # temps
            sds((bb,), jnp.int32),     # top_ks
            sds((bb,), jnp.float32),   # top_ps
        )
        profiles: list[dict[str, Any]] = []
        for name, jitted, args in (
            (f"prefill_T{tb}", self._prefill_jit, prefill_args),
            (f"decode_B{bb}", self._decode_jit, decode_args),
        ):
            if full:
                prof = profiling.aot_profile(
                    jitted, args, name=name, peaks=peaks, top_k=top_k
                )
            else:
                prof = profiling.lower_cost_profile(jitted, args, name=name)
            if prof is not None:
                profiles.append(prof)
        return profiles

    def compile_stats(self) -> dict[str, Any]:
        """Bucket usage + compiled-program counts (the bounded-compile
        contract: programs <= prompt_buckets + batch_buckets, asserted by
        tests and reported by the load harness)."""
        stats: dict[str, Any] = {
            "prompt_buckets": list(self.prompt_buckets),
            "batch_buckets": list(self.batch_buckets),
            "prefill_shapes_used": sorted(self._prefill_shapes),
            "decode_shapes_used": sorted(self._decode_shapes),
            "budget": len(self.prompt_buckets) + len(self.batch_buckets),
        }
        try:  # jax's own cache entry count, when the API exists (0.4.x)
            stats["prefill_programs"] = int(self._prefill_jit._cache_size())
            stats["decode_programs"] = int(self._decode_jit._cache_size())
        except Exception:  # noqa: BLE001 — accounting is best-effort
            stats["prefill_programs"] = len(self._prefill_shapes)
            stats["decode_programs"] = len(self._decode_shapes)
        stats["within_budget"] = (
            stats["prefill_programs"] + stats["decode_programs"]
            <= stats["budget"]
        )
        return stats


__all__ = [
    "PagedDecodeEngine",
    "bucket_for",
]
