"""Paged decode engine: bucketed jitted prefill/decode over the block pool.

The execution layer of the continuous-batching server (scheduler.py owns
WHEN sequences join/leave; this module owns HOW a step runs):

* **Two programs, shape-bucketed.** ``prefill`` runs one joining
  sequence's prompt (padded to a prompt-length bucket) through the paged
  model, writing its K/V blocks and sampling its first token; ``decode``
  advances every in-flight sequence one token (batch padded to a
  batch-size bucket). XLA compiles once per bucket, so the total compile
  count is bounded by ``len(prompt_buckets) + len(batch_buckets)`` — a
  budget :meth:`compile_stats` exposes and tests assert
  (tests/test_serving_engine.py), because unbounded recompilation is the
  classic way a JAX server falls over in production.
* **Per-row sampling with per-request seeds.** Greedy rows take the raw
  argmax; sampled rows replay ``generate()``'s exact recipe —
  temperature scale, top-k/top-p filter (same thresholds as
  ``generation.filter_logits``), then ``categorical(fold_in(key(seed),
  emit_index))`` — per ROW, so a batched decode emits the same tokens the
  single-request path would (the exactness contract the acceptance test
  pins under greedy decoding).
* **Shared pool cache.** The paged cache is batch-shape-independent
  (models/gpt.py ``_paged_decode_attention``), so every bucket's program
  reads/writes the SAME donated cache buffers — join/evict never copies
  K/V.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import get_logger
from .paged_kv import PagedKVPool

logger = get_logger()


def _round_up_buckets(limit: int, *, start: int = 1) -> list[int]:
    """Powers of two up to (and always including) ``limit``."""
    buckets: list[int] = []
    b = start
    while b < limit:
        buckets.append(b)
        b *= 2
    buckets.append(limit)
    return buckets


def bucket_for(n: int, buckets: list[int]) -> int:
    """Smallest bucket >= n; raises when n exceeds every bucket."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds the largest bucket ({buckets[-1]})")


def _filter_rows(
    scaled: jax.Array, top_ks: jax.Array, top_ps: jax.Array
) -> jax.Array:
    """Per-row top-k / top-p masking with DYNAMIC knobs.

    Same thresholds as ``generation.filter_logits`` (kth-largest value;
    exclusive-cumulative-mass nucleus cut) but per row and data-dependent,
    so one compiled program serves every sampling configuration —
    per-request knobs must not multiply the compile count. ``top_ks <= 0``
    and ``top_ps`` outside (0, 1) disable the respective filter, matching
    generate()'s out-of-band conventions.
    """
    v = scaled.shape[-1]
    # top-k: threshold at each row's k-th largest (k clamped into [1, V]).
    desc = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)  # (B, V)
    k_idx = jnp.clip(top_ks - 1, 0, v - 1)
    kth = jnp.take_along_axis(desc, k_idx[:, None], axis=-1)  # (B, 1)
    kth = jnp.where((top_ks > 0)[:, None], kth, -jnp.inf)
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    # top-p composes AFTER top-k, on the masked logits (filter_logits
    # order): keep the smallest descending-prob prefix whose EXCLUSIVE
    # cumulative mass is < p (always keeps the argmax).
    desc = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)
    probs = jax.nn.softmax(desc, axis=-1)
    exclusive = jnp.cumsum(probs, axis=-1) - probs
    keep = exclusive < top_ps[:, None]
    thr = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    active = ((top_ps > 0.0) & (top_ps < 1.0))[:, None]
    return jnp.where(active & (scaled < thr), -jnp.inf, scaled)


def _sample_rows(
    logits: jax.Array,  # (B, V) f32
    seeds: jax.Array,  # (B,) uint32 — per-request rng seed
    emit_idx: jax.Array,  # (B,) int32 — tokens already emitted by the row
    temps: jax.Array,  # (B,) f32; 0 = greedy
    top_ks: jax.Array,  # (B,) int32; <=0 disables
    top_ps: jax.Array,  # (B,) f32; outside (0,1) disables
) -> jax.Array:
    """One sampling decision per row, generate()-exact per request.

    Greedy rows bypass the filter entirely (raw argmax — _sample_next's
    temperature==0 short-circuit); sampled rows draw
    ``categorical(fold_in(key(seed), emit_idx), filtered)`` — the same
    key schedule generate() uses for a batch of one.
    """
    greedy_tok = jnp.argmax(logits, axis=-1)
    safe_t = jnp.where(temps > 0.0, temps, 1.0)
    scaled = _filter_rows(logits / safe_t[:, None], top_ks, top_ps)

    def one(seed: jax.Array, i: jax.Array, row: jax.Array) -> jax.Array:
        key = jax.random.fold_in(jax.random.key(seed), i)
        return jax.random.categorical(key, row)

    sampled = jax.vmap(one)(seeds, emit_idx, scaled)
    return jnp.where(temps == 0.0, greedy_tok, sampled).astype(jnp.int32)


def _prefill_impl(
    model: Any,
    params: Any,
    cache: Any,
    prompt: jax.Array,  # (1, Tb) padded
    true_len: jax.Array,  # (1,) int32
    offsets: jax.Array,  # (1,) int32 — absolute position of prompt[0]
    block_tables: jax.Array,  # (1, MB) int32
    seeds: jax.Array,
    temps: jax.Array,
    top_ks: jax.Array,
    top_ps: jax.Array,
) -> tuple[Any, jax.Array]:
    # `offsets` starts the row mid-sequence: 0 for a whole prompt, the
    # reused-prefix length under shared-prefix reuse, the chunk start
    # under chunked prefill. The suffix attends earlier positions through
    # the block table (cached K/V), exactly like a multi-token decode.
    logits, mutated = model.apply(
        {"params": params, "cache": cache},
        prompt,
        deterministic=True,
        positions=offsets,
        block_tables=block_tables,
        mutable=["cache"],
    )
    # Sample at the LAST REAL position; padded positions' K/V landed in
    # the null block and padded-row logits are garbage nobody reads.
    last = jnp.take_along_axis(
        logits.astype(jnp.float32), (true_len - 1)[:, None, None], axis=1
    )[:, 0]
    tok = _sample_rows(
        last, seeds, jnp.zeros_like(true_len), temps, top_ks, top_ps
    )
    return mutated["cache"], tok


def _decode_impl(
    model: Any,
    params: Any,
    cache: Any,
    tokens: jax.Array,  # (B,) int32 — each row's last emitted token
    positions: jax.Array,  # (B,) int32 — that token's absolute position
    block_tables: jax.Array,  # (B, MB) int32
    seeds: jax.Array,
    emit_idx: jax.Array,
    temps: jax.Array,
    top_ks: jax.Array,
    top_ps: jax.Array,
) -> tuple[Any, jax.Array]:
    logits, mutated = model.apply(
        {"params": params, "cache": cache},
        tokens[:, None],
        deterministic=True,
        positions=positions,
        block_tables=block_tables,
        mutable=["cache"],
    )
    tok = _sample_rows(
        logits[:, -1].astype(jnp.float32), seeds, emit_idx, temps, top_ks, top_ps
    )
    return mutated["cache"], tok


def _verify_impl(
    model: Any,
    params: Any,
    cache: Any,
    tokens: jax.Array,  # (B, t) int32 — context token + t-1 draft tokens
    positions: jax.Array,  # (B,) int32 — absolute position of tokens[:, 0]
    block_tables: jax.Array,  # (B, MB) int32
) -> tuple[Any, jax.Array]:
    """Score a (gamma+1)-token slab per row in ONE call: the batched twin
    of speculative.py's target forward. Returns the greedy (argmax) token
    at every slab position — position j's argmax is the target model's
    next token GIVEN drafts < j, which is all greedy acceptance needs
    (speculative.py: accept while draft == argmax, emit the first
    correction from the same logits)."""
    logits, mutated = model.apply(
        {"params": params, "cache": cache},
        tokens,
        deterministic=True,
        positions=positions,
        block_tables=block_tables,
        mutable=["cache"],
    )
    return mutated["cache"], jnp.argmax(
        logits.astype(jnp.float32), axis=-1
    ).astype(jnp.int32)


def _cow_impl(cache: Any, src: jax.Array, dst: jax.Array) -> Any:
    """Copy-on-write device copy: pool block ``src`` → ``dst`` across
    every paged cache leaf (leaves are (num_blocks, bt, kv, dh))."""
    return jax.tree.map(lambda leaf: leaf.at[dst].set(leaf[src]), cache)


class PagedDecodeEngine:
    """Bucketed paged-KV decode over one model + params.

    Owns the device cache (donated through every step), the host-side
    pool allocator, the bucket policy, and the compile accounting. The
    scheduler calls :meth:`prefill` / :meth:`decode`; nothing here
    decides admission.
    """

    def __init__(
        self,
        model: Any,
        params: Any,
        *,
        block_tokens: int = 16,
        num_blocks: int | None = None,
        max_batch_slots: int = 8,
        prompt_buckets: list[int] | None = None,
        batch_buckets: list[int] | None = None,
        prefix_cache: bool = False,
        prefill_chunk: int = 0,
    ) -> None:
        if not hasattr(model, "for_paged_decoding"):
            raise ValueError(
                "paged serving needs a model exposing for_paged_decoding(); "
                f"{type(model).__name__} does not"
            )
        self.model = model
        self.params = params
        self.block_size = int(model.block_size)
        self.block_tokens = int(block_tokens)
        self.max_blocks_per_seq = -(-self.block_size // self.block_tokens)
        if num_blocks is None:
            # Default: every slot can host a worst-case sequence, + null.
            num_blocks = 1 + max_batch_slots * self.max_blocks_per_seq
        self.max_batch_slots = int(max_batch_slots)
        self.prompt_buckets = sorted(
            prompt_buckets or _round_up_buckets(self.block_size, start=8)
        )
        self.batch_buckets = sorted(
            batch_buckets or _round_up_buckets(self.max_batch_slots)
        )
        if self.prompt_buckets[-1] > self.block_size:
            raise ValueError(
                f"largest prompt bucket ({self.prompt_buckets[-1]}) exceeds "
                f"the model block_size ({self.block_size})"
            )
        if self.batch_buckets[-1] != self.max_batch_slots:
            raise ValueError(
                f"largest batch bucket ({self.batch_buckets[-1]}) must equal "
                f"max_batch_slots ({self.max_batch_slots})"
            )
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0 (0 = off), got {prefill_chunk}"
            )
        if self.prefill_chunk > self.prompt_buckets[-1]:
            raise ValueError(
                f"prefill_chunk ({self.prefill_chunk}) exceeds the largest "
                f"prompt bucket ({self.prompt_buckets[-1]}) — chunks must "
                "pad into an existing bucket (the bounded-compile contract)"
            )
        self.decode_model = model.for_paged_decoding(
            num_blocks=num_blocks, block_tokens=self.block_tokens
        )
        self.pool = PagedKVPool(
            num_blocks, self.block_tokens, prefix_cache=prefix_cache
        )

        # Zero cache pytree from an eval_shape trace — no param init work
        # (the generation.py idiom). Cache shapes are batch-INDEPENDENT
        # (the pool is shared), so one cache serves every bucket.
        mb = self.max_blocks_per_seq
        var_shapes = jax.eval_shape(
            lambda: self.decode_model.init(
                jax.random.key(0),
                jnp.zeros((1, 1), jnp.int32),
                deterministic=True,
                positions=jnp.zeros((1,), jnp.int32),
                block_tables=jnp.zeros((1, mb), jnp.int32),
            )
        )
        self._cache_struct = var_shapes["cache"]
        self._cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), self._cache_struct
        )
        # Bumped whenever a failed step forces a cache rebuild: the
        # scheduler compares epochs to learn that in-flight KV was lost.
        self.cache_epoch = 0

        # Per-engine CLOSURES under the jits: jax keys the pjit program
        # cache on the underlying callable, so wrapping the module-level
        # impls directly would make every engine in the process share one
        # cache and `_cache_size()` count other engines' programs. A fresh
        # function object per engine keeps the compile accounting local
        # (and the closed-over model off the static-argument hash path).
        def _prefill_bound(params: Any, cache: Any, *rest: Any) -> Any:
            return _prefill_impl(self.decode_model, params, cache, *rest)

        def _decode_bound(params: Any, cache: Any, *rest: Any) -> Any:
            return _decode_impl(self.decode_model, params, cache, *rest)

        def _verify_bound(params: Any, cache: Any, *rest: Any) -> Any:
            return _verify_impl(self.decode_model, params, cache, *rest)

        def _cow_bound(cache: Any, src: Any, dst: Any) -> Any:
            return _cow_impl(cache, src, dst)

        self._prefill_jit = jax.jit(_prefill_bound, donate_argnums=(1,))
        self._decode_jit = jax.jit(_decode_bound, donate_argnums=(1,))
        self._verify_jit = jax.jit(_verify_bound, donate_argnums=(1,))
        self._cow_jit = jax.jit(_cow_bound, donate_argnums=(0,))
        self._prefill_shapes: set[int] = set()
        self._decode_shapes: set[int] = set()
        self._verify_shapes: set[tuple[int, int]] = set()
        self._cow_used = False
        # Optional ``(kind, bucket) -> None`` hook, fired the first time a
        # bucket shape is seen (= an XLA compile is about to happen). The
        # scheduler wires it to a timeline instant: a request whose
        # prefill span brackets a compile instant explains its own tail.
        self.on_compile: Any = None

    def _note_shape(self, shapes: set, key: Any, kind: str, bucket: int) -> None:
        if key in shapes:
            return
        shapes.add(key)
        if self.on_compile is not None:
            try:
                self.on_compile(kind, bucket)
            except Exception:  # noqa: BLE001 — telemetry must not fail a step
                pass

    # --------------------------------------------------------- validation

    def validate_request(self, prompt_len: int, max_new_tokens: int) -> str | None:
        """Why this engine can never serve the request, or None if it can.

        Checked at ADMISSION (scheduler) and at the HTTP boundary (400,
        not a late 500): the model's context bound, the largest prompt
        bucket (prefill cannot pad past it), and the pool's total
        capacity — a request whose worst-case block need exceeds the
        whole pool would otherwise sit at the FIFO head forever, starving
        everything behind it (try_reserve can only say "not yet").
        """
        prompt_len, total = int(prompt_len), int(prompt_len) + int(max_new_tokens)
        if total > self.block_size:
            return (
                f"prompt+max_new_tokens ({total}) exceeds the model "
                f"block_size ({self.block_size})"
            )
        if self.prefill_chunk == 0 and prompt_len > self.prompt_buckets[-1]:
            # Chunked prefill lifts this bound: chunks of <= prefill_chunk
            # tokens each pad into an existing bucket, so long prompts are
            # servable up to the block_size check above.
            return (
                f"prompt length ({prompt_len}) exceeds the largest "
                f"serving prompt bucket ({self.prompt_buckets[-1]})"
            )
        capacity = self.pool.num_blocks - 1
        need = self.pool.blocks_needed(total)
        if need > capacity:
            return (
                f"request needs {need} worst-case KV blocks but the pool "
                f"only holds {capacity} — raise serving.num_blocks or "
                f"lower max_new_tokens"
            )
        return None

    # ----------------------------------------------------------- stepping

    def prefill(
        self,
        prompt_ids: np.ndarray,  # (Tp,) int32 — the slab to run (suffix
        # of the prompt under prefix reuse / one chunk under chunking)
        table_padded: list[int],
        *,
        seed: int,
        temperature: float,
        top_k: int | None,
        top_p: float | None,
        offset: int = 0,  # absolute position of prompt_ids[0]
        params: Any | None = None,  # hot-swap: admitted-epoch params
    ) -> int:
        """Run one joining sequence's prompt slab; returns the token
        sampled at its last real position (the first output token when
        the slab ends the prompt; discarded by the caller for non-final
        chunks — one program either way, the bounded-compile contract)."""
        tp = int(prompt_ids.shape[0])
        tb = bucket_for(tp, self.prompt_buckets)
        self._note_shape(self._prefill_shapes, tb, "prefill", tb)
        prompt = np.zeros((1, tb), np.int32)
        prompt[0, :tp] = prompt_ids
        try:
            cache, tok = self._prefill_jit(
                self.params if params is None else params,
                self._cache,
                jnp.asarray(prompt),
                jnp.asarray([tp], jnp.int32),
                jnp.asarray([int(offset)], jnp.int32),
                jnp.asarray([table_padded], jnp.int32),
                jnp.asarray([seed & 0xFFFFFFFF], jnp.uint32),
                jnp.asarray([temperature], jnp.float32),
                jnp.asarray([0 if top_k is None else top_k], jnp.int32),
                jnp.asarray([0.0 if top_p is None else top_p], jnp.float32),
            )
        except Exception:
            self._recover_cache_after_error()
            raise
        self._cache = cache
        return int(tok[0])

    def decode(
        self, rows: list[dict[str, Any]], *, params: Any | None = None
    ) -> list[int]:
        """Advance every row one token; returns next tokens, row-aligned.

        Each row dict: ``token`` (last emitted), ``position`` (its
        absolute position), ``table`` (padded physical ids), ``seed``,
        ``emit_idx``, ``temperature``, ``top_k``, ``top_p``. The batch is
        padded to a batch bucket with null-table greedy rows whose output
        is discarded.
        """
        n = len(rows)
        if n == 0:
            return []
        bb = bucket_for(n, self.batch_buckets)
        self._note_shape(self._decode_shapes, bb, "decode", bb)
        mb = self.max_blocks_per_seq

        def col(key: str, fill: Any, dtype: Any) -> np.ndarray:
            out = np.full((bb,), fill, dtype=dtype)
            for i, r in enumerate(rows):
                out[i] = r[key]
            return out

        tables = np.zeros((bb, mb), np.int32)
        for i, r in enumerate(rows):
            tables[i] = r["table"]
        try:
            cache, tok = self._decode_jit(
                self.params if params is None else params,
                self._cache,
                jnp.asarray(col("token", 0, np.int32)),
                jnp.asarray(col("position", 0, np.int32)),
                jnp.asarray(tables),
                jnp.asarray(
                    np.array(
                        [r["seed"] & 0xFFFFFFFF for r in rows] + [0] * (bb - n),
                        dtype=np.uint32,
                    )
                ),
                jnp.asarray(col("emit_idx", 0, np.int32)),
                jnp.asarray(col("temperature", 0.0, np.float32)),
                jnp.asarray(col("top_k", 0, np.int32)),
                jnp.asarray(col("top_p", 0.0, np.float32)),
            )
        except Exception:
            self._recover_cache_after_error()
            raise
        self._cache = cache
        return [int(t) for t in np.asarray(jax.device_get(tok))[:n]]

    def verify(
        self,
        rows: list[dict[str, Any]],
        *,
        width: int,
        params: Any | None = None,
    ) -> list[list[int]]:
        """Score a ``width``-token slab for every row in ONE bucketed call
        (batched speculative verify). Each row dict: ``tokens`` (width
        ints — last accepted token + the draft tokens), ``position``
        (tokens[0]'s absolute position), ``table``. Returns each row's
        per-position argmax — the target model's greedy continuation
        given every draft prefix. Writes the slab's K/V; rejected
        positions are simply overwritten when the corrected tokens are
        fed (position p maps to a fixed (block, slot), and queries never
        see past their own position — cursorless rollback)."""
        n = len(rows)
        if n == 0:
            return []
        bb = bucket_for(n, self.batch_buckets)
        self._note_shape(self._verify_shapes, (bb, width), "verify", bb)
        mb = self.max_blocks_per_seq
        tokens = np.zeros((bb, width), np.int32)
        positions = np.zeros((bb,), np.int32)
        tables = np.zeros((bb, mb), np.int32)
        for i, r in enumerate(rows):
            if len(r["tokens"]) != width:
                raise ValueError(
                    f"verify row {i} holds {len(r['tokens'])} tokens, "
                    f"expected width {width}"
                )
            tokens[i] = r["tokens"]
            positions[i] = r["position"]
            tables[i] = r["table"]
        try:
            cache, out = self._verify_jit(
                self.params if params is None else params,
                self._cache,
                jnp.asarray(tokens),
                jnp.asarray(positions),
                jnp.asarray(tables),
            )
        except Exception:
            self._recover_cache_after_error()
            raise
        self._cache = cache
        host = np.asarray(jax.device_get(out))
        return [[int(t) for t in host[i]] for i in range(n)]

    def cow_copy(self, src: int, dst: int) -> None:
        """Device-side copy-on-write: pool block ``src`` → ``dst`` in every
        cache leaf. The pool's cow_last_shared() picks the pair; this is
        the write half of its contract (must run before the next pool
        mutation can recycle ``src``)."""
        self._cow_used = True
        try:
            self._cache = self._cow_jit(
                self._cache,
                jnp.asarray([src], jnp.int32),
                jnp.asarray([dst], jnp.int32),
            )
        except Exception:
            self._recover_cache_after_error()
            raise

    def _recover_cache_after_error(self) -> None:
        """Donation safety: a jitted call that fails at RUNTIME has already
        consumed (deleted) the donated cache buffers, so without recovery
        every later prefill/decode would die on "Array has been deleted" —
        one transient device error would wedge the server for good.
        Trace-time failures never donate: a still-live cache (and the
        in-flight KV it holds) is kept untouched; a deleted one is rebuilt
        zeroed and ``cache_epoch`` bumped so the scheduler fails the
        in-flight sequences whose KV went with it.
        """
        leaves = jax.tree.leaves(self._cache)
        if any(
            leaf.is_deleted()
            for leaf in leaves
            if isinstance(leaf, jax.Array)
        ):
            self._cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), self._cache_struct
            )
            self.cache_epoch += 1

    # --------------------------------------------------------- accounting

    def cost_profile(
        self,
        *,
        peaks: dict[str, float] | None = None,
        full: bool = True,
        top_k: int = 10,
    ) -> list[dict[str, Any]]:
        """AOT cost profiles of the prefill/decode programs at their
        LARGEST buckets (worst-case per-step cost; smaller buckets are
        strictly cheaper). Nothing executes and nothing is donated —
        profiling works against abstract shapes, so the live cache and
        in-flight KV stay untouched. ``full=False`` skips the XLA compile
        (cost totals only). Failed profiles are dropped, not raised.
        """
        from ..telemetry import profiling

        if peaks is None:
            peaks = profiling.resolve_peaks()
        sds = jax.ShapeDtypeStruct
        param_structs = jax.tree.map(
            lambda x: sds(jnp.shape(x), x.dtype), self.params
        )
        cache_structs = jax.tree.map(
            lambda s: sds(s.shape, s.dtype), self._cache_struct
        )
        mb = self.max_blocks_per_seq
        tb = self.prompt_buckets[-1]
        bb = self.batch_buckets[-1]
        prefill_args = (
            param_structs,
            cache_structs,
            sds((1, tb), jnp.int32),   # prompt
            sds((1,), jnp.int32),      # true_len
            sds((1,), jnp.int32),      # offsets
            sds((1, mb), jnp.int32),   # block_tables
            sds((1,), jnp.uint32),     # seeds
            sds((1,), jnp.float32),    # temps
            sds((1,), jnp.int32),      # top_ks
            sds((1,), jnp.float32),    # top_ps
        )
        decode_args = (
            param_structs,
            cache_structs,
            sds((bb,), jnp.int32),     # tokens
            sds((bb,), jnp.int32),     # positions
            sds((bb, mb), jnp.int32),  # block_tables
            sds((bb,), jnp.uint32),    # seeds
            sds((bb,), jnp.int32),     # emit_idx
            sds((bb,), jnp.float32),   # temps
            sds((bb,), jnp.int32),     # top_ks
            sds((bb,), jnp.float32),   # top_ps
        )
        profiles: list[dict[str, Any]] = []
        for name, jitted, args in (
            (f"prefill_T{tb}", self._prefill_jit, prefill_args),
            (f"decode_B{bb}", self._decode_jit, decode_args),
        ):
            if full:
                prof = profiling.aot_profile(
                    jitted, args, name=name, peaks=peaks, top_k=top_k
                )
            else:
                prof = profiling.lower_cost_profile(jitted, args, name=name)
            if prof is not None:
                profiles.append(prof)
        return profiles

    def compile_stats(self) -> dict[str, Any]:
        """Bucket usage + compiled-program counts (the bounded-compile
        contract: programs <= prompt_buckets + batch_buckets, asserted by
        tests and reported by the load harness). Optional programs widen
        the budget only when their feature is exercised: batched
        speculative verify adds at most one program per batch bucket per
        slab width used, and the COW copy is exactly one program — so
        chunked prefill adds NOTHING (chunks pad into existing prompt
        buckets) and the budget stays a static, assertable bound."""
        verify_widths = {w for _, w in self._verify_shapes}
        stats: dict[str, Any] = {
            "prompt_buckets": list(self.prompt_buckets),
            "batch_buckets": list(self.batch_buckets),
            "prefill_shapes_used": sorted(self._prefill_shapes),
            "decode_shapes_used": sorted(self._decode_shapes),
            "verify_shapes_used": sorted(self._verify_shapes),
            "budget": (
                len(self.prompt_buckets)
                + len(self.batch_buckets)
                + len(self.batch_buckets) * len(verify_widths)
                + (1 if self._cow_used else 0)
            ),
        }
        try:  # jax's own cache entry count, when the API exists (0.4.x)
            stats["prefill_programs"] = int(self._prefill_jit._cache_size())
            stats["decode_programs"] = int(self._decode_jit._cache_size())
            stats["verify_programs"] = int(self._verify_jit._cache_size())
            stats["cow_programs"] = int(self._cow_jit._cache_size())
        except Exception:  # noqa: BLE001 — accounting is best-effort
            stats["prefill_programs"] = len(self._prefill_shapes)
            stats["decode_programs"] = len(self._decode_shapes)
            stats["verify_programs"] = len(self._verify_shapes)
            stats["cow_programs"] = 1 if self._cow_used else 0
        stats["within_budget"] = (
            stats["prefill_programs"]
            + stats["decode_programs"]
            + stats["verify_programs"]
            + stats["cow_programs"]
            <= stats["budget"]
        )
        return stats

    # ---------------------------------------------------------- hot swap

    def set_params(self, params: Any) -> None:
        """Swap the default params between scheduler steps (checkpoint
        hot-swap). Callers that pin a request to its admitted params pass
        them explicitly to prefill/decode/verify instead — the jitted
        programs take params as a traced argument, so neither path
        recompiles. The prefix cache must be invalidated by the caller
        (scheduler) — cached K/V is a function of the OLD params."""
        self.params = params


__all__ = [
    "PagedDecodeEngine",
    "bucket_for",
]
