"""Paged KV-cache pool: host-side free-list allocator + block tables.

The device-side cache is a pool of ``num_blocks`` fixed-size blocks of
``block_tokens`` positions each, owned per layer by the paged decode
model (models/gpt.py ``_paged_decode_attention``). THIS module owns the
host-side accounting that makes the pool safe to share between N
in-flight sequences (the vLLM PagedAttention layout, PAPERS.md MinT —
multiplexing many requests onto one accelerator is where serving
throughput/$ is decided):

* a **free list** of physical block ids (block 0 is the reserved null
  block — padded block-table entries point at it and its contents are
  garbage by construction, never read by a live query);
* **admission-time budget reservation**: a sequence reserves its
  worst-case block count (``ceil((prompt+max_new)/block_tokens)``) before
  joining the batch, so mid-flight allocation can never fail — the
  continuous scheduler admits only what the pool can finish;
* **lazy physical allocation**: reserved blocks are bound to physical ids
  only when the sequence actually reaches them, so pool occupancy tracks
  REAL cache bytes, not worst cases (the utilization gauge the serving
  telemetry exports).

Pure host-side Python (no jax): allocation is scheduler-thread-only and
lock-free here — the scheduler serializes all calls.
"""

from __future__ import annotations

from dataclasses import dataclass, field

NULL_BLOCK = 0


@dataclass
class BlockTable:
    """One sequence's logical→physical block mapping."""

    reserved: int  # admission-time budget (blocks), upper bound
    block_tokens: int
    blocks: list[int] = field(default_factory=list)  # physical ids, in order

    @property
    def allocated(self) -> int:
        return len(self.blocks)

    def padded(self, max_blocks: int) -> list[int]:
        """Physical ids padded with the null block to ``max_blocks``
        (the static shape the jitted decode step consumes)."""
        if len(self.blocks) > max_blocks:
            raise ValueError(
                f"table holds {len(self.blocks)} blocks > max_blocks "
                f"({max_blocks})"
            )
        return self.blocks + [NULL_BLOCK] * (max_blocks - len(self.blocks))


class PagedKVPool:
    """Free-list allocator over the physical block pool.

    Invariant: ``available`` (unreserved budget) never exceeds the free
    list, so a reserved sequence's :meth:`grow` cannot fail — admission
    control (:meth:`try_reserve`) is the only place that says no.
    """

    def __init__(self, num_blocks: int, block_tokens: int) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the null block), "
                f"got {num_blocks}"
            )
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1, got {block_tokens}")
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        # LIFO free list, block 0 excluded (null block).
        self._free = list(range(num_blocks - 1, 0, -1))
        self._available = num_blocks - 1  # capacity minus live reservations
        self._tables: set[int] = set()  # live table object ids (double-free guard)
        self.peak_allocated = 0
        self.peak_reserved = 0

    # ------------------------------------------------------------- sizing

    def blocks_needed(self, total_tokens: int) -> int:
        """Worst-case blocks for a sequence of ``total_tokens`` positions."""
        return max(1, -(-int(total_tokens) // self.block_tokens))

    # --------------------------------------------------------- allocation

    @property
    def available_blocks(self) -> int:
        """Unreserved budget — what admission control may still promise."""
        return self._available

    @property
    def allocated_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def try_reserve(self, total_tokens: int) -> BlockTable | None:
        """Admit a sequence of ``total_tokens`` worst-case positions.

        Returns its table (budget reserved, nothing bound yet) or None
        when the pool cannot guarantee completion — the scheduler then
        leaves the request queued instead of admitting work it would
        have to evict mid-flight.
        """
        need = self.blocks_needed(total_tokens)
        if need > self._available:
            return None
        self._available -= need
        table = BlockTable(reserved=need, block_tokens=self.block_tokens)
        self._tables.add(id(table))
        self.peak_reserved = max(
            self.peak_reserved, (self.num_blocks - 1) - self._available
        )
        return table

    def grow(self, table: BlockTable, upto_tokens: int) -> None:
        """Bind physical blocks so positions < ``upto_tokens`` are backed.

        Cannot fail within the reservation (the invariant admission
        bought); exceeding it is a scheduler bug and raises.
        """
        if id(table) not in self._tables:
            raise ValueError("grow() on a released or foreign block table")
        need = self.blocks_needed(upto_tokens)
        if need > table.reserved:
            raise ValueError(
                f"sequence needs {need} blocks > its reservation "
                f"({table.reserved}) — admission sizing bug"
            )
        while table.allocated < need:
            table.blocks.append(self._free.pop())
        self.peak_allocated = max(self.peak_allocated, self.allocated_blocks)

    def release(self, table: BlockTable) -> None:
        """Retire a sequence: free its blocks and its unused budget."""
        if id(table) not in self._tables:
            raise ValueError("release() on a released or foreign block table")
        self._tables.remove(id(table))
        self._free.extend(reversed(table.blocks))
        self._available += table.reserved
        table.blocks = []
        table.reserved = 0

    # ------------------------------------------------------------ telemetry

    def stats(self) -> dict[str, float]:
        capacity = self.num_blocks - 1
        return {
            "capacity_blocks": capacity,
            "block_tokens": self.block_tokens,
            "allocated_blocks": self.allocated_blocks,
            "reserved_blocks": capacity - self._available,
            "utilization": round(self.allocated_blocks / capacity, 4),
            "peak_allocated_blocks": self.peak_allocated,
            "peak_reserved_blocks": self.peak_reserved,
            "active_sequences": len(self._tables),
        }


__all__ = ["NULL_BLOCK", "BlockTable", "PagedKVPool"]
