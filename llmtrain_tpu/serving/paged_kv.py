"""Paged KV-cache pool: host-side free-list allocator + block tables.

The device-side cache is a pool of ``num_blocks`` fixed-size blocks of
``block_tokens`` positions each, owned per layer by the paged decode
model (models/gpt.py ``_paged_decode_attention``). THIS module owns the
host-side accounting that makes the pool safe to share between N
in-flight sequences (the vLLM PagedAttention layout, PAPERS.md MinT —
multiplexing many requests onto one accelerator is where serving
throughput/$ is decided):

* a **free list** of physical block ids (block 0 is the reserved null
  block — padded block-table entries point at it and its contents are
  garbage by construction, never read by a live query);
* **admission-time budget reservation**: a sequence reserves its
  worst-case block count (``ceil((prompt+max_new)/block_tokens)``) before
  joining the batch, so mid-flight allocation can never fail — the
  continuous scheduler admits only what the pool can finish;
* **lazy physical allocation**: reserved blocks are bound to physical ids
  only when the sequence actually reaches them, so pool occupancy tracks
  REAL cache bytes, not worst cases (the utilization gauge the serving
  telemetry exports);
* **content-addressed shared prefixes** (opt-in): a FULL block whose
  positions hold a pure function of the token prefix is registered under
  the chain hash ``h_i = sha256(h_{i-1} || tokens[i*bt:(i+1)*bt])`` and
  later requests with the same prefix bind it read-only (refcounted)
  instead of re-prefilling. The common system-prompt case prefills once
  per replica. A block whose prefix only PARTIALLY matches is bound
  shared too, then copy-on-write'd the moment the divergent token needs
  to be written. Released shared blocks park in an LRU "evictable" set —
  still cached, reclaimed on demand — so reuse can only REDUCE physical
  block need and the reservation invariant survives: for every table,
  shared binds consume reservation slots without consuming free blocks,
  hence ``free + evictable >= outstanding unbound reservations`` always.

Pure host-side Python (no jax): allocation is scheduler-thread-only and
lock-free here — the scheduler serializes all calls.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Sequence

NULL_BLOCK = 0


def hash_token_block(parent: str, tokens: Sequence[int]) -> str:
    """Chain hash of one full token block: position-aware by construction
    (the parent hash encodes everything before this block), so equal
    hashes mean equal K/V content for a deterministic model."""
    h = hashlib.sha256()
    h.update(parent.encode("ascii"))
    h.update(b"|")
    h.update(",".join(str(int(t)) for t in tokens).encode("ascii"))
    return h.hexdigest()


def chain_hashes(prompt_ids: Sequence[int], block_tokens: int) -> list[str]:
    """Chain hashes of every FULL block of ``prompt_ids`` (the trailing
    partial block has no hash — only complete blocks are content-stable)."""
    out: list[str] = []
    parent = ""
    for i in range(len(prompt_ids) // block_tokens):
        parent = hash_token_block(
            parent, prompt_ids[i * block_tokens : (i + 1) * block_tokens]
        )
        out.append(parent)
    return out


@dataclass
class PrefixMatch:
    """Outcome of a prefix-cache lookup for one prompt."""

    full_blocks: list[int] = field(default_factory=list)  # physical ids
    partial_block: int | None = None  # physical id, partially matching
    partial_tokens: int = 0  # tokens matched inside partial_block
    matched_tokens: int = 0  # total prompt tokens covered

    @property
    def hit(self) -> bool:
        return self.matched_tokens > 0


@dataclass
class _CacheEntry:
    """Host-side record of one cached (shareable) physical block."""

    hash: str
    parent: str
    tokens: tuple[int, ...]
    refs: int = 0
    # Set on hot-swap: content was computed under superseded params; no
    # new binds, and the block frees (not parks) when its refs drain.
    stale: bool = False


@dataclass
class BlockTable:
    """One sequence's logical→physical block mapping."""

    reserved: int  # admission-time budget (blocks), upper bound
    block_tokens: int
    blocks: list[int] = field(default_factory=list)  # physical ids, in order
    # Leading run of `blocks` that is SHARED (refcounted, read-only).
    # Everything past it is exclusively owned. COW and registration
    # preserve the leading-run shape.
    shared: int = 0

    @property
    def allocated(self) -> int:
        return len(self.blocks)

    def padded(self, max_blocks: int) -> list[int]:
        """Physical ids padded with the null block to ``max_blocks``
        (the static shape the jitted decode step consumes)."""
        if len(self.blocks) > max_blocks:
            raise ValueError(
                f"table holds {len(self.blocks)} blocks > max_blocks "
                f"({max_blocks})"
            )
        return self.blocks + [NULL_BLOCK] * (max_blocks - len(self.blocks))


class PagedKVPool:
    """Free-list allocator over the physical block pool.

    Invariant: ``available`` (unreserved budget) never exceeds the
    reclaimable supply (free list + evictable cached blocks), so a
    reserved sequence's :meth:`grow` cannot fail — admission control
    (:meth:`try_reserve`) is the only place that says no.
    """

    def __init__(
        self,
        num_blocks: int,
        block_tokens: int,
        *,
        prefix_cache: bool = False,
    ) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the null block), "
                f"got {num_blocks}"
            )
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1, got {block_tokens}")
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        # LIFO free list, block 0 excluded (null block).
        self._free = list(range(num_blocks - 1, 0, -1))
        self._available = num_blocks - 1  # capacity minus live reservations
        self._tables: set[int] = set()  # live table object ids (double-free guard)
        self.peak_allocated = 0
        self.peak_reserved = 0
        # ---- content-addressed prefix cache (docstring: shared prefixes)
        self.prefix_cache_enabled = bool(prefix_cache)
        self._index: dict[str, int] = {}  # chain hash -> physical block
        self._entries: dict[int, _CacheEntry] = {}  # physical block -> entry
        self._children: dict[str, list[int]] = {}  # parent hash -> blocks
        self._evictable: OrderedDict[int, None] = OrderedDict()  # refs==0, LRU
        self.prefix_hits = 0  # blocks bound shared instead of re-prefilled
        self.prefix_queries = 0
        self.prefix_hit_queries = 0  # queries that bound >= 1 cached block
        self.prefix_tokens_reused = 0
        self.prefix_evictions = 0
        self.cow_copies = 0
        # Optional event observer ``(name, args) -> None`` wired by the
        # scheduler to its timeline: pool-level events (evictions, COW,
        # cache invalidation) that explain request latency but have no
        # request of their own. Scheduler-thread-only, like every other
        # pool mutation; observer failures never reach the pool.
        self.observer: Any = None

    def _observe(self, name: str, **args: Any) -> None:
        if self.observer is None:
            return
        try:
            self.observer(name, args)
        except Exception:  # noqa: BLE001 — telemetry must not break paging
            pass

    # ------------------------------------------------------------- sizing

    def blocks_needed(self, total_tokens: int) -> int:
        """Worst-case blocks for a sequence of ``total_tokens`` positions."""
        return max(1, -(-int(total_tokens) // self.block_tokens))

    # --------------------------------------------------------- allocation

    @property
    def available_blocks(self) -> int:
        """Unreserved budget — what admission control may still promise."""
        return self._available

    @property
    def allocated_blocks(self) -> int:
        """Blocks live RIGHT NOW (bound to a sequence); parked cached
        blocks are reclaimable supply, not live occupancy."""
        return (self.num_blocks - 1) - len(self._free) - len(self._evictable)

    @property
    def cached_blocks(self) -> int:
        return len(self._evictable)

    def try_reserve(self, total_tokens: int) -> BlockTable | None:
        """Admit a sequence of ``total_tokens`` worst-case positions.

        Returns its table (budget reserved, nothing bound yet) or None
        when the pool cannot guarantee completion — the scheduler then
        leaves the request queued instead of admitting work it would
        have to evict mid-flight.
        """
        need = self.blocks_needed(total_tokens)
        if need > self._available:
            return None
        self._available -= need
        table = BlockTable(reserved=need, block_tokens=self.block_tokens)
        self._tables.add(id(table))
        self.peak_reserved = max(
            self.peak_reserved, (self.num_blocks - 1) - self._available
        )
        return table

    def _take_block(self) -> int:
        """Pop a physical block: free list first, then evict the LRU
        cached block. Cannot fail inside a reservation (class invariant)."""
        if self._free:
            return self._free.pop()
        if self._evictable:
            blk, _ = self._evictable.popitem(last=False)
            self._forget_entry(blk)
            self.prefix_evictions += 1
            self._observe("evict", block=blk, cached_blocks=len(self._evictable))
            return blk
        raise RuntimeError(
            "paged KV pool exhausted inside a reservation — accounting bug"
        )

    def _forget_entry(self, blk: int) -> None:
        ent = self._entries.pop(blk)
        self._index.pop(ent.hash, None)
        siblings = self._children.get(ent.parent)
        if siblings is not None:
            try:
                siblings.remove(blk)
            except ValueError:
                pass
            if not siblings:
                del self._children[ent.parent]

    def grow(self, table: BlockTable, upto_tokens: int) -> None:
        """Bind physical blocks so positions < ``upto_tokens`` are backed.

        Cannot fail within the reservation (the invariant admission
        bought); exceeding it is a scheduler bug and raises.
        """
        if id(table) not in self._tables:
            raise ValueError("grow() on a released or foreign block table")
        need = self.blocks_needed(upto_tokens)
        if need > table.reserved:
            raise ValueError(
                f"sequence needs {need} blocks > its reservation "
                f"({table.reserved}) — admission sizing bug"
            )
        while table.allocated < need:
            table.blocks.append(self._take_block())
        self.peak_allocated = max(self.peak_allocated, self.allocated_blocks)

    def release(self, table: BlockTable) -> None:
        """Retire a sequence: free its owned blocks, unpin its shared
        ones (refs drain to the evictable LRU), return its budget."""
        if id(table) not in self._tables:
            raise ValueError("release() on a released or foreign block table")
        self._tables.remove(id(table))
        for i, blk in enumerate(table.blocks):
            if i < table.shared:
                ent = self._entries.get(blk)
                if ent is None or ent.refs <= 0:
                    raise ValueError(
                        f"refcount double-free on shared block {blk}"
                    )
                ent.refs -= 1
                if ent.refs == 0:
                    if ent.stale:
                        self._forget_entry(blk)
                        self._free.append(blk)
                    else:
                        self._evictable[blk] = None  # MRU end
            else:
                self._free.append(blk)
        self._available += table.reserved
        table.blocks = []
        table.reserved = 0
        table.shared = 0

    # ------------------------------------------------------ prefix sharing

    def match_prefix(self, prompt_ids: Sequence[int]) -> PrefixMatch:
        """Longest cached prefix of ``prompt_ids``: a chain of full
        blocks, optionally extended by one partially-matching block.

        Matching is capped at ``len(prompt_ids) - 1`` tokens — at least
        one prompt token must remain for prefill, because sampling the
        first output token needs a real forward pass.
        """
        match = PrefixMatch()
        if not self.prefix_cache_enabled or len(prompt_ids) < 2:
            return match
        self.prefix_queries += 1
        bt = self.block_tokens
        limit = len(prompt_ids) - 1
        parent = ""
        for i in range(limit // bt):
            h = hash_token_block(parent, prompt_ids[i * bt : (i + 1) * bt])
            blk = self._index.get(h)
            if blk is None:
                break
            parent = h
            match.full_blocks.append(blk)
        # One partially-matching continuation block: shares the first
        # j < bt tokens, COW'd before the divergent token is written.
        start = len(match.full_blocks) * bt
        rest = [int(t) for t in prompt_ids[start:limit]]
        if rest:
            best_j, best_blk = 0, None
            for blk in self._children.get(parent, ()):
                ent = self._entries[blk]
                j = 0
                for a, b in zip(ent.tokens, rest):
                    if a != b:
                        break
                    j += 1
                if j > best_j:
                    best_j, best_blk = j, blk
            if best_blk is not None:
                match.partial_block = best_blk
                match.partial_tokens = best_j
        match.matched_tokens = start + match.partial_tokens
        return match

    def bind_prefix(self, table: BlockTable, match: PrefixMatch) -> int:
        """Bind a match's blocks into a freshly-reserved table (shared,
        refcounted). Returns the number of prompt tokens now backed by
        cached K/V. Must run before any :meth:`grow` on the table."""
        if id(table) not in self._tables:
            raise ValueError("bind_prefix() on a released or foreign table")
        if table.blocks:
            raise ValueError("bind_prefix() must precede grow()")
        if not match.hit:
            return 0
        shared = list(match.full_blocks)
        if match.partial_block is not None:
            shared.append(match.partial_block)
        if len(shared) > table.reserved:
            raise ValueError(
                f"prefix match spans {len(shared)} blocks > reservation "
                f"({table.reserved}) — matching must be capped by the prompt"
            )
        for blk in shared:
            ent = self._entries[blk]
            if ent.stale:
                raise ValueError(f"bind_prefix() on stale block {blk}")
            if ent.refs == 0:
                self._evictable.pop(blk, None)  # pin: no longer reclaimable
            ent.refs += 1
            table.blocks.append(blk)
        table.shared = len(shared)
        self.prefix_hits += len(shared)
        self.prefix_hit_queries += 1
        self.prefix_tokens_reused += match.matched_tokens
        self.peak_allocated = max(self.peak_allocated, self.allocated_blocks)
        return match.matched_tokens

    def cow_last_shared(self, table: BlockTable) -> tuple[int, int]:
        """Copy-on-write the table's last shared block (the partially-
        matched one): allocate a private destination, unpin the source,
        and hand back ``(src, dst)`` for the device-side copy.

        CONTRACT: the caller must issue the device copy before the next
        pool mutation — once unpinned, the source is evictable.
        """
        if id(table) not in self._tables:
            raise ValueError("cow_last_shared() on a released or foreign table")
        if table.shared == 0:
            raise ValueError("cow_last_shared() on a table with no shared blocks")
        idx = table.shared - 1
        src = table.blocks[idx]
        # Take dst while src is still pinned so eviction cannot grab src.
        dst = self._take_block()
        ent = self._entries[src]
        if ent.refs <= 0:
            raise ValueError(f"refcount underflow on shared block {src}")
        ent.refs -= 1
        if ent.refs == 0:
            if ent.stale:
                self._forget_entry(src)
                self._free.append(src)
            else:
                self._evictable[src] = None
        table.blocks[idx] = dst
        table.shared -= 1
        self.cow_copies += 1
        self.peak_allocated = max(self.peak_allocated, self.allocated_blocks)
        self._observe("cow", src=src, dst=dst)
        return src, dst

    def register_prefix(
        self, table: BlockTable, prompt_ids: Sequence[int]
    ) -> int:
        """After a prompt is fully prefilled, publish its full blocks into
        the content index so later requests can share them. Registered
        blocks convert from owned to shared (this table holds one ref);
        registration stops at the first block already indexed (an
        identical twin serves future lookups) so the table's shared run
        stays a contiguous prefix. Returns blocks newly registered."""
        if not self.prefix_cache_enabled:
            return 0
        if id(table) not in self._tables:
            raise ValueError("register_prefix() on a released or foreign table")
        bt = self.block_tokens
        nfull = len(prompt_ids) // bt  # immutable from now on: decode
        # writes land at positions >= len(prompt_ids), never below nfull*bt
        hashes = chain_hashes(prompt_ids[: nfull * bt], bt)
        registered = 0
        for i in range(table.shared, nfull):
            h = hashes[i]
            if h in self._index:
                break  # identical content already published
            blk = table.blocks[i]
            parent = hashes[i - 1] if i > 0 else ""
            self._index[h] = blk
            self._entries[blk] = _CacheEntry(
                hash=h,
                parent=parent,
                tokens=tuple(int(t) for t in prompt_ids[i * bt : (i + 1) * bt]),
                refs=1,
            )
            self._children.setdefault(parent, []).append(blk)
            table.shared += 1
            registered += 1
        return registered

    def invalidate_prefix_cache(self) -> int:
        """Hot-swap barrier: cached K/V was computed under superseded
        params. Parked blocks free immediately; live shared blocks are
        marked stale (their in-flight readers finish on the old params)
        and free — not park — when their refs drain. Returns blocks
        invalidated."""
        flushed = len(self._evictable)
        while self._evictable:
            blk, _ = self._evictable.popitem(last=False)
            self._forget_entry(blk)
            self._free.append(blk)
        for blk in list(self._entries):
            ent = self._entries[blk]
            ent.stale = True
            self._index.pop(ent.hash, None)
            siblings = self._children.get(ent.parent)
            if siblings is not None:
                try:
                    siblings.remove(blk)
                except ValueError:
                    pass
                if not siblings:
                    del self._children[ent.parent]
            flushed += 1
        if flushed:
            self._observe("prefix_invalidated", blocks=flushed)
        return flushed

    # ------------------------------------------------------------ telemetry

    def stats(self) -> dict[str, float]:
        capacity = self.num_blocks - 1
        out = {
            "capacity_blocks": capacity,
            "block_tokens": self.block_tokens,
            "allocated_blocks": self.allocated_blocks,
            "reserved_blocks": capacity - self._available,
            "utilization": round(self.allocated_blocks / capacity, 4),
            "peak_allocated_blocks": self.peak_allocated,
            "peak_reserved_blocks": self.peak_reserved,
            "active_sequences": len(self._tables),
        }
        if self.prefix_cache_enabled:
            out["prefix_cached_blocks"] = self.cached_blocks
            out["prefix_hits"] = self.prefix_hits
            out["prefix_queries"] = self.prefix_queries
            out["prefix_hit_queries"] = self.prefix_hit_queries
            out["prefix_tokens_reused"] = self.prefix_tokens_reused
            out["prefix_evictions"] = self.prefix_evictions
            # Fraction of lookups that bound at least one cached block
            # (prefix_hits counts BLOCKS, so it is not the numerator here).
            out["prefix_hit_rate"] = round(
                self.prefix_hit_queries / max(1, self.prefix_queries), 4
            )
            out["cow_copies"] = self.cow_copies
        return out


__all__ = [
    "NULL_BLOCK",
    "BlockTable",
    "PagedKVPool",
    "PrefixMatch",
    "chain_hashes",
    "hash_token_block",
]
