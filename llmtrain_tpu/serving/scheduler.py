"""Continuous (in-flight) batching scheduler over the paged decode engine.

The control layer of the serving subsystem: requests enter an admission
queue from any thread (HTTP handlers, the load generator); a single
scheduler thread runs :meth:`ContinuousBatchingScheduler.step` in a loop —
each step **joins** queued arrivals whose worst-case KV blocks the pool
can guarantee (prefill, first token), advances every in-flight sequence
one token, and **retires** finishers (EOS / max tokens) without draining
the batch. That per-step join/evict is what turns one accelerator into a
multi-tenant device (MinT, PAPERS.md): a long generation no longer
blocks a short one behind it, and batch occupancy — not queue discipline
— sets throughput.

Policies:

* ``paged`` (default) — the continuous-batching path above.
* ``speculative`` — draft-and-verify decode (speculative.py) as a
  first-class scheduler policy: requests flow through the SAME queue,
  metrics, and SLO accounting, but each is served by
  ``speculative_generate`` (batch-1 by that algorithm's contract, so
  occupancy stays 1 — the latency-optimal regime, while ``paged`` is the
  throughput-optimal one).

SLO accounting is server-side and per-request: submit→first-token (TTFT)
and inter-token gaps, the numbers the load harness (loadgen.py)
aggregates into p50/p95/p99. Metrics publish into the PR-4
MetricsRegistry under ``serve/*`` (→ ``llmtrain_serve_*`` in Prometheus).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import Any

import jax
import numpy as np

from ..utils.logging import get_logger
from .engine import PagedDecodeEngine

logger = get_logger()

_REQ_IDS = itertools.count()


@dataclass
class ServeRequest:
    """One generation request + its server-side measurements."""

    prompt_ids: np.ndarray  # (Tp,) int32
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    seed: int = 0
    eos_token_id: int | None = None
    request_id: int = field(default_factory=lambda: next(_REQ_IDS))
    # Measurements (scheduler-thread writes, reader waits on `done`).
    submitted_t: float = 0.0
    # perf_counter twin of submitted_t: EventTimeline spans are
    # perf_counter-relative, so the queue-wait span needs this clock.
    submitted_pc: float = 0.0
    first_token_t: float | None = None
    finished_t: float | None = None
    token_times: list[float] = field(default_factory=list)
    tokens: list[int] = field(default_factory=list)
    finish_reason: str | None = None
    error: str | None = None
    done: threading.Event = field(default_factory=threading.Event)
    # Set by a waiter that gave up (HTTP timeout, loadgen deadline): the
    # scheduler sheds the request — queued or in flight — instead of
    # spending device time decoding for a departed client.
    abandoned: threading.Event = field(default_factory=threading.Event)

    def abandon(self) -> None:
        self.abandoned.set()

    @property
    def ttft_ms(self) -> float | None:
        if self.first_token_t is None:
            return None
        return (self.first_token_t - self.submitted_t) * 1e3

    @property
    def latency_ms(self) -> float | None:
        if self.finished_t is None:
            return None
        return (self.finished_t - self.submitted_t) * 1e3


@dataclass
class _Row:
    """One in-flight sequence's scheduler-side state."""

    req: ServeRequest
    table: Any  # BlockTable
    prompt_len: int


class ContinuousBatchingScheduler:
    """Admission queue + per-step join/evict over a PagedDecodeEngine."""

    def __init__(
        self,
        engine: PagedDecodeEngine | None,
        *,
        max_batch_slots: int | None = None,
        registry: Any | None = None,  # telemetry MetricsRegistry
        policy: str = "paged",
        model: Any | None = None,
        params: Any | None = None,
        draft_model: Any | None = None,
        draft_params: Any | None = None,
        gamma: int = 4,
        timeline: Any | None = None,  # telemetry EventTimeline
    ) -> None:
        if policy not in ("paged", "speculative"):
            raise ValueError(
                f"serving policy {policy!r} unknown; expected 'paged' or "
                "'speculative'"
            )
        if policy == "paged" and engine is None:
            raise ValueError("policy='paged' requires a PagedDecodeEngine")
        if policy == "speculative" and (
            draft_model is None or draft_params is None
            or model is None or params is None
        ):
            raise ValueError(
                "policy='speculative' requires model/params AND "
                "draft_model/draft_params"
            )
        self.engine = engine
        self.policy = policy
        self.registry = registry
        # Serving timeline: queue-wait/prefill/decode spans tagged with
        # request ids, so one request's life is followable in Perfetto
        # (docs/observability.md). None = no tracing overhead.
        self.timeline = timeline
        self.max_batch_slots = int(
            max_batch_slots
            or (engine.max_batch_slots if engine is not None else 1)
        )
        self._model, self._params = model, params
        self._draft_model, self._draft_params = draft_model, draft_params
        self._gamma = int(gamma)

        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._queue: deque[ServeRequest] = deque()
        self._active: list[_Row] = []
        self._closed = False
        self._thread: threading.Thread | None = None

        # Aggregate accounting (scheduler thread only).
        self.requests_finished = 0
        self.tokens_generated = 0
        self.prefill_tokens = 0
        self.peak_occupancy = 0
        self._occupancy_samples = 0
        self._occupancy_total = 0

    # ----------------------------------------------------------- frontend

    def submit(self, req: ServeRequest) -> ServeRequest:
        """Thread-safe enqueue; returns immediately (wait on ``req.done``)."""
        req.submitted_t = time.monotonic()
        req.submitted_pc = time.perf_counter()
        with self._wake:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            self._queue.append(req)
            self._wake.notify()
        return req

    # ------------------------------------------------------------- backend

    def _span(self, name: str, **args: Any):
        """Timeline span tagged for Perfetto, no-op without a timeline."""
        if self.timeline is None:
            return nullcontext()
        return self.timeline.span(name, cat="serve", **args)

    def _record_queue_wait(self, req: ServeRequest) -> None:
        """Queue-wait span from the submit stamp to now — with the
        request_id tag it abuts the same request's prefill span, so one
        request's queue-wait → prefill → decode path reads as a track."""
        if self.timeline is None or req.submitted_pc <= 0.0:
            return
        self.timeline.record(
            "serve/queue_wait",
            t0=req.submitted_pc,
            t1=time.perf_counter(),
            cat="serve",
            request_id=req.request_id,
        )

    def step(self) -> bool:
        """One scheduler iteration: join, advance, evict. Returns whether
        any work happened (False = idle)."""
        if self.policy == "speculative":
            return self._step_speculative()
        return self._step_paged()

    def _step_paged(self) -> bool:
        engine = self.engine
        assert engine is not None
        epoch = engine.cache_epoch
        # ---- join: admit while a slot AND a worst-case block budget exist.
        # Head-of-line order — admission is FIFO so a huge request cannot
        # be starved by a stream of small ones slipping past it.
        admitted = 0
        while len(self._active) < self.max_batch_slots:
            with self._lock:
                req = self._queue[0] if self._queue else None
            if req is None:
                break
            if req.abandoned.is_set():
                with self._lock:
                    self._queue.popleft()
                self._retire_abandoned(req)
                continue
            # The HTTP layer pre-validates, but the scheduler must survive
            # direct submitters too: a request this engine can NEVER serve
            # (context bound, prompt bucket, worst-case need > whole pool)
            # fails ALONE instead of wedging the FIFO head forever —
            # try_reserve only distinguishes "not yet", not "never".
            reason = engine.validate_request(
                int(req.prompt_ids.shape[0]), int(req.max_new_tokens)
            )
            if reason is not None:
                with self._lock:
                    self._queue.popleft()
                self._fail(req, ValueError(reason))
                continue
            total = int(req.prompt_ids.shape[0]) + int(req.max_new_tokens)
            table = engine.pool.try_reserve(total)
            if table is None:
                break  # pool full: stays queued, retried next step
            with self._lock:
                self._queue.popleft()
            tp = int(req.prompt_ids.shape[0])
            engine.pool.grow(table, tp)
            self._record_queue_wait(req)
            try:
                with self._span(
                    "serve/prefill", request_id=req.request_id, prompt_tokens=tp
                ):
                    tok = engine.prefill(
                        req.prompt_ids,
                        table.padded(engine.max_blocks_per_seq),
                        seed=req.seed,
                        temperature=req.temperature,
                        top_k=req.top_k,
                        top_p=req.top_p,
                    )
            except Exception as exc:  # noqa: BLE001 — fail THIS request only
                engine.pool.release(table)
                self._fail(req, exc)
                if engine.cache_epoch != epoch:
                    # The failed call had already consumed the donated
                    # cache: every in-flight sequence's KV went with it.
                    self._fail_all_active(exc)
                    epoch = engine.cache_epoch
                continue
            now = time.monotonic()
            req.first_token_t = now
            req.token_times.append(now)
            req.tokens.append(tok)
            self.prefill_tokens += tp
            self.tokens_generated += 1
            row = _Row(req=req, table=table, prompt_len=tp)
            if self._is_finished(row):
                self._retire(row)
            else:
                self._active.append(row)
            admitted += 1

        # ---- shed abandoned in-flight work (the waiter already got its
        # timeout response) so the device never decodes for a gone client.
        kept: list[_Row] = []
        for r in self._active:
            if r.req.abandoned.is_set():
                engine.pool.release(r.table)
                self._retire_abandoned(r.req)
            else:
                kept.append(r)
        self._active = kept

        # ---- advance every in-flight sequence one token.
        stepped = False
        if self._active:
            occupancy = len(self._active)
            self.peak_occupancy = max(self.peak_occupancy, occupancy)
            self._occupancy_samples += 1
            self._occupancy_total += occupancy
            rows = []
            for r in self._active:
                # The fed token's absolute position; grow() binds its
                # block within the admission-time reservation.
                pos = r.prompt_len + len(r.req.tokens) - 1
                engine.pool.grow(r.table, pos + 1)
                rows.append(
                    {
                        "token": r.req.tokens[-1],
                        "position": pos,
                        "table": r.table.padded(engine.max_blocks_per_seq),
                        "seed": r.req.seed,
                        "emit_idx": len(r.req.tokens),
                        "temperature": r.req.temperature,
                        "top_k": 0 if r.req.top_k is None else r.req.top_k,
                        "top_p": 0.0 if r.req.top_p is None else r.req.top_p,
                    }
                )
            try:
                with self._span(
                    "serve/decode",
                    request_ids=[r.req.request_id for r in self._active],
                    batch=len(rows),
                ):
                    toks = engine.decode(rows)
            except Exception as exc:  # noqa: BLE001 — contain: a decode
                # failure must not kill the scheduler thread (every later
                # waiter would time out against a dead loop). The batch's
                # step output is unusable either way, so each in-flight
                # request fails loudly — and if the donated cache was
                # consumed the engine has already rebuilt it zeroed.
                self._fail_all_active(exc)
                self._publish_metrics()
                return True
            now = time.monotonic()
            survivors: list[_Row] = []
            for r, tok in zip(self._active, toks):
                r.req.tokens.append(int(tok))
                r.req.token_times.append(now)
                self.tokens_generated += 1
                if self._is_finished(r):
                    self._retire(r)
                else:
                    survivors.append(r)
            self._active = survivors
            stepped = True

        self._publish_metrics()
        return stepped or admitted > 0

    def _step_speculative(self) -> bool:
        from ..speculative import speculative_generate

        with self._lock:
            req = self._queue.popleft() if self._queue else None
        if req is None:
            self._publish_metrics()
            return False
        if req.abandoned.is_set():
            self._retire_abandoned(req)
            self._publish_metrics()
            return True
        self.peak_occupancy = max(self.peak_occupancy, 1)
        self._occupancy_samples += 1
        self._occupancy_total += 1
        self._record_queue_wait(req)
        try:
            with self._span(
                "serve/speculative_decode", request_id=req.request_id
            ):
                out = speculative_generate(
                    self._model,
                    self._params,
                    self._draft_model,
                    self._draft_params,
                    req.prompt_ids[None, :],
                    max_new_tokens=req.max_new_tokens,
                    gamma=self._gamma,
                    temperature=req.temperature,
                    top_k=req.top_k,
                    top_p=req.top_p,
                    eos_token_id=req.eos_token_id,
                    rng=jax.random.key(req.seed),
                )
        except Exception as exc:  # noqa: BLE001 — fail THIS request only
            self._fail(req, exc)
            self._publish_metrics()
            return True
        now = time.monotonic()
        completion = [int(t) for t in out[0, req.prompt_ids.shape[0] :]]
        if req.eos_token_id is not None and req.eos_token_id in completion:
            completion = completion[: completion.index(req.eos_token_id) + 1]
            req.finish_reason = "eos"
        else:
            req.finish_reason = "length"
        # The whole-loop jit emits every token in one dispatch: TTFT and
        # completion coincide (documented in docs/serving.md).
        req.first_token_t = now
        req.token_times = [now] * len(completion)
        req.tokens = completion
        self.tokens_generated += len(completion)
        self.prefill_tokens += int(req.prompt_ids.shape[0])
        req.finished_t = now
        self.requests_finished += 1
        if self.registry is not None:
            self.registry.inc("serve/requests")
        req.done.set()
        self._publish_metrics()
        return True

    # ------------------------------------------------------------ plumbing

    def _is_finished(self, row: _Row) -> bool:
        req = row.req
        if req.eos_token_id is not None and req.tokens[-1] == req.eos_token_id:
            req.finish_reason = "eos"
            return True
        if len(req.tokens) >= req.max_new_tokens:
            req.finish_reason = "length"
            return True
        return False

    def _retire(self, row: _Row) -> None:
        assert self.engine is not None
        self.engine.pool.release(row.table)
        row.req.finished_t = time.monotonic()
        self.requests_finished += 1
        if self.registry is not None:
            self.registry.inc("serve/requests")
        row.req.done.set()

    def _retire_abandoned(self, req: ServeRequest) -> None:
        logger.warning(
            "serve request %d abandoned by its waiter; shed", req.request_id
        )
        req.finish_reason = "abandoned"
        req.finished_t = time.monotonic()
        if self.registry is not None:
            self.registry.inc("serve/requests_abandoned")
        req.done.set()

    def _fail_all_active(self, cause: Exception) -> None:
        assert self.engine is not None
        for r in self._active:
            self.engine.pool.release(r.table)
            self._fail(
                r.req,
                RuntimeError(
                    f"in-flight KV lost to a failed engine step: {cause}"
                ),
            )
        self._active = []

    def _fail(self, req: ServeRequest, exc: Exception) -> None:
        logger.warning("serve request %d failed: %s", req.request_id, exc)
        req.error = str(exc)
        req.finish_reason = "error"
        req.finished_t = time.monotonic()
        if self.registry is not None:
            self.registry.inc("serve/request_errors")
        req.done.set()

    def _publish_metrics(self) -> None:
        if self.registry is None:
            return
        with self._lock:
            depth = len(self._queue)
        metrics = {
            "serve/queue_depth": float(depth),
            "serve/batch_occupancy": float(len(self._active)),
            "serve/peak_batch_occupancy": float(self.peak_occupancy),
            "serve/tokens_generated": float(self.tokens_generated),
        }
        if self.engine is not None:
            pool = self.engine.pool.stats()
            metrics["serve/kv_pool_used_blocks"] = pool["allocated_blocks"]
            metrics["serve/kv_pool_utilization"] = pool["utilization"]
            metrics["serve/kv_pool_reserved_blocks"] = pool["reserved_blocks"]
        self.registry.publish(metrics)

    # ----------------------------------------------------------- lifecycle

    def stats(self) -> dict[str, Any]:
        with self._lock:
            depth = len(self._queue)
        mean_occ = (
            self._occupancy_total / self._occupancy_samples
            if self._occupancy_samples
            else 0.0
        )
        out: dict[str, Any] = {
            "policy": self.policy,
            "queue_depth": depth,
            "active_sequences": len(self._active),
            "max_batch_slots": self.max_batch_slots,
            "requests_finished": self.requests_finished,
            "tokens_generated": self.tokens_generated,
            "prefill_tokens": self.prefill_tokens,
            "peak_batch_occupancy": self.peak_occupancy,
            "mean_batch_occupancy": round(mean_occ, 4),
        }
        if self.engine is not None:
            out["kv_pool"] = self.engine.pool.stats()
            out["compile"] = self.engine.compile_stats()
        return out

    def run_forever(self, poll_sec: float = 0.005) -> None:
        """Scheduler loop body for the background thread."""
        while True:
            with self._wake:
                if self._closed and not self._queue and not self._active:
                    return
                if not self._queue and not self._active and not self._closed:
                    self._wake.wait(timeout=poll_sec * 20)
            if self._closed and not self._queue and not self._active:
                return
            if not self.step():
                time.sleep(poll_sec)

    def start(self) -> "ContinuousBatchingScheduler":
        self._thread = threading.Thread(
            target=self.run_forever, name="serve-scheduler", daemon=True
        )
        self._thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Drain in-flight work, then stop the loop (bounded)."""
        with self._wake:
            self._closed = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                logger.warning("serve scheduler did not drain in %.0fs", timeout)


__all__ = ["ContinuousBatchingScheduler", "ServeRequest"]
