"""Continuous (in-flight) batching scheduler over the paged decode engine.

The control layer of the serving subsystem: requests enter an admission
queue from any thread (HTTP handlers, the load generator); a single
scheduler thread runs :meth:`ContinuousBatchingScheduler.step` in a loop —
each step **joins** queued arrivals whose worst-case KV blocks the pool
can guarantee (prefill, first token), advances every in-flight sequence
one token, and **retires** finishers (EOS / max tokens) without draining
the batch. That per-step join/evict is what turns one accelerator into a
multi-tenant device (MinT, PAPERS.md): a long generation no longer
blocks a short one behind it, and batch occupancy — not queue discipline
— sets throughput.

Scheduler-level policies layered on the paged path:

* **Shared-prefix reuse** — at admission the prompt is looked up in the
  pool's content-addressed prefix cache (paged_kv.py); matched blocks
  bind read-only (COW on a partial match) and prefill runs only the
  unmatched SUFFIX at its true offset. After the prompt is fully
  written, its full blocks are registered for later requests.
* **Chunked prefill** — with ``engine.prefill_chunk > 0``, long prompts
  stream into the pool one chunk per step, interleaved with the decode
  batch, so a huge prompt cannot stall every in-flight sequence's next
  token. Chunks pad into the EXISTING prompt buckets (engine contract),
  so the compile budget does not grow.
* **Checkpoint hot-swap** — :meth:`hot_swap` queues new params; the
  scheduler thread applies them between steps. Every request is pinned
  at admission to its **param epoch**: in-flight sequences finish on the
  params they were admitted under (decode runs grouped by epoch — params
  is a traced argument, so no recompile), new admissions use the new
  ones, and the prefix cache is invalidated (cached K/V is a function of
  the old params). Zero requests fail or restart across a swap.

Policies:

* ``paged`` (default) — the continuous-batching path above.
* ``speculative`` — draft-and-verify decode as a first-class scheduler
  policy. With a ``draft_engine`` attached, greedy requests are drafted
  and verified IN BATCH: gamma draft tokens per row come from batched
  one-token decodes on the draft engine, and the target scores every
  row's (gamma+1)-token slab in ONE bucketed ``verify`` call — emitted
  tokens are bit-identical to ``generate()`` (greedy acceptance keeps a
  draft only when it equals the target argmax). Sampled requests fall
  back to the batch-1 ``speculative_generate`` path (its per-token rng
  schedule is not batch-replayable).

SLO accounting is server-side and per-request: submit→first-token (TTFT)
and inter-token gaps, the numbers the load harness (loadgen.py)
aggregates into p50/p95/p99. Metrics publish into the PR-4
MetricsRegistry under ``serve/*`` (→ ``llmtrain_serve_*`` in Prometheus).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from contextlib import contextmanager, nullcontext
from dataclasses import dataclass, field
from typing import Any

import jax
import numpy as np

from ..telemetry.tracing import Tracer
from ..utils.logging import get_logger
from .engine import PagedDecodeEngine
from .overload import (
    REASON_DEADLINE_EXCEEDED,
    OverloadController,
    rejected_counter,
)

logger = get_logger()

_REQ_IDS = itertools.count()
# Request ids used to be the bare process-local counter, so two replica
# pods emitted IDENTICAL ids into merged fleet telemetry. Every id is now
# namespaced by a per-process random token — unique fleet-wide, still
# ordered (and greppable) within one process.
_PROC_TOKEN = os.urandom(4).hex()


def new_request_id() -> str:
    """``{process_token}/{n}``: collision-free across replica processes."""
    return f"{_PROC_TOKEN}/{next(_REQ_IDS)}"


@dataclass
class ServeRequest:
    """One generation request + its server-side measurements."""

    prompt_ids: np.ndarray  # (Tp,) int32
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    seed: int = 0
    eos_token_id: int | None = None
    request_id: str = field(default_factory=new_request_id)
    # Measurements (scheduler-thread writes, reader waits on `done`).
    submitted_t: float = 0.0
    # perf_counter twin of submitted_t: EventTimeline spans are
    # perf_counter-relative, so the queue-wait span needs this clock.
    submitted_pc: float = 0.0
    first_token_t: float | None = None
    finished_t: float | None = None
    token_times: list[float] = field(default_factory=list)
    tokens: list[int] = field(default_factory=list)
    finish_reason: str | None = None
    error: str | None = None
    # Checkpoint step of the params this request was ADMITTED under
    # (hot-swap audit trail: parity must check against these params).
    params_step: int | None = None
    # Overload control (serving/overload.py): the client's latency budget
    # from submit (X-Deadline-Ms over HTTP), the priority class the
    # weighted admission queue dequeues by, and the client-supplied
    # correlation id (X-Request-Id) tagged on the timeline spans.
    deadline_ms: float | None = None
    priority: str = "interactive"
    rid: str | None = None
    # Distributed trace (telemetry/tracing.py): the per-request span
    # buffer + W3C-style context. Set by the ingress that minted the root
    # (router, HTTP handler) or lazily by the scheduler's own submit;
    # resolved exactly once by whichever component sets ``done``.
    trace: Any = None
    # Queue depth seen at submit — the EWMA wait estimator's x-axis.
    queue_depth_at_submit: int = 0
    # Set when the overload layer rejected/shed this request: the
    # {reason} label on llmtrain_serve_rejected_total, and the 429
    # Retry-After hint (seconds).
    reject_reason: str | None = None
    retry_after_sec: float | None = None
    done: threading.Event = field(default_factory=threading.Event)
    # Set by a waiter that gave up (HTTP timeout, loadgen deadline): the
    # scheduler sheds the request — queued or in flight — instead of
    # spending device time decoding for a departed client.
    abandoned: threading.Event = field(default_factory=threading.Event)

    def abandon(self) -> None:
        self.abandoned.set()

    @property
    def trace_id(self) -> str | None:
        return self.trace.trace_id if self.trace is not None else None

    @property
    def ttft_ms(self) -> float | None:
        if self.first_token_t is None:
            return None
        return (self.first_token_t - self.submitted_t) * 1e3

    @property
    def latency_ms(self) -> float | None:
        if self.finished_t is None:
            return None
        return (self.finished_t - self.submitted_t) * 1e3


@dataclass
class _Row:
    """One in-flight sequence's scheduler-side state."""

    req: ServeRequest
    table: Any  # BlockTable
    prompt_len: int
    # Prompt tokens whose K/V is already in the pool (cached prefix +
    # prefilled chunks); == prompt_len once the first token can sample.
    prefilled: int = 0
    # Param epoch pinned at admission: the row decodes on these params
    # until it retires, whatever hot_swap() does meanwhile.
    epoch: int = 0
    # Batched speculative only: the row's table on the DRAFT engine pool.
    draft_table: Any = None


class ContinuousBatchingScheduler:
    """Admission queue + per-step join/evict over a PagedDecodeEngine."""

    def __init__(
        self,
        engine: PagedDecodeEngine | None,
        *,
        max_batch_slots: int | None = None,
        registry: Any | None = None,  # telemetry MetricsRegistry
        policy: str = "paged",
        model: Any | None = None,
        params: Any | None = None,
        draft_model: Any | None = None,
        draft_params: Any | None = None,
        draft_engine: PagedDecodeEngine | None = None,
        gamma: int = 4,
        timeline: Any | None = None,  # telemetry EventTimeline
        overload: OverloadController | None = None,
        tracer: Tracer | None = None,
    ) -> None:
        if policy not in ("paged", "speculative"):
            raise ValueError(
                f"serving policy {policy!r} unknown; expected 'paged' or "
                "'speculative'"
            )
        if policy == "paged" and engine is None:
            raise ValueError("policy='paged' requires a PagedDecodeEngine")
        if policy == "speculative" and (
            draft_model is None or draft_params is None
            or model is None or params is None
        ):
            raise ValueError(
                "policy='speculative' requires model/params AND "
                "draft_model/draft_params"
            )
        if draft_engine is not None and policy != "speculative":
            raise ValueError("draft_engine only applies to policy='speculative'")
        if draft_engine is not None and engine is None:
            raise ValueError(
                "batched speculative serving needs the TARGET PagedDecodeEngine "
                "too (draft_engine alone cannot verify)"
            )
        if policy == "speculative" and engine is not None and engine.prefill_chunk:
            raise ValueError(
                "chunked prefill is a paged-policy feature; the speculative "
                "verify slab needs the whole prompt resident before drafting"
            )
        self.engine = engine
        self.policy = policy
        self.registry = registry
        # Serving timeline: queue-wait/prefill/decode spans tagged with
        # request ids, so one request's life is followable in Perfetto
        # (docs/observability.md). None = no tracing overhead.
        self.timeline = timeline
        # Distributed tracing (telemetry/tracing.py): defaults on whenever
        # a timeline exists — per-request cost is a small span buffer, and
        # only tail-sampled traces are flushed in full detail.
        self.tracer = tracer if tracer is not None else (
            Tracer(timeline) if timeline is not None else None
        )
        if timeline is not None and engine is not None:
            # Pool-level KV events (evictions, COW) land as timeline
            # instants: they explain latency the per-request spans can't.
            engine.pool.observer = self._kv_event
            engine.on_compile = self._compile_event
        self.max_batch_slots = int(
            max_batch_slots
            or (engine.max_batch_slots if engine is not None else 1)
        )
        self._model, self._params = model, params
        self._draft_model, self._draft_params = draft_model, draft_params
        self._draft_engine = draft_engine
        self._gamma = int(gamma)

        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        # Overload control (serving/overload.py): with a controller the
        # admission queue becomes its bounded weighted-class queue and
        # submit() can reject synchronously; without one the original
        # unbounded FIFO behavior is unchanged.
        self._overload = overload
        self._queue: Any = overload.queue if overload is not None else deque()
        self._active: list[_Row] = []
        # Rows still streaming their prompt in under chunked prefill —
        # they hold a batch slot (their KV is resident) but don't decode.
        self._prefilling: list[_Row] = []
        self._closed = False
        self._thread: threading.Thread | None = None
        # Liveness beacon: monotonic time of the loop thread's last
        # iteration. /healthz turns 503 when this goes stale — the same
        # stance the training watchdog takes on the heartbeat file.
        self._beacon = time.monotonic()

        # Param epochs (checkpoint hot-swap). Epoch 0 is the params the
        # scheduler was built with; hot_swap() appends. Old epochs stay
        # resident only while a row admitted under them is in flight.
        self._param_epoch = 0
        self._params_by_epoch: dict[int, Any] = {
            0: engine.params if engine is not None else params
        }
        self._param_meta: dict[int, dict[str, Any]] = {
            0: {"step": None, "checkpoint": None}
        }
        self._epoch_refs: dict[int, int] = {}
        self._pending_swap: tuple[Any, int | None, str | None] | None = None
        self.hot_swaps = 0

        # Aggregate accounting (scheduler thread only).
        self.requests_finished = 0
        self.tokens_generated = 0
        self.prefill_tokens = 0  # tokens actually COMPUTED (reuse excluded)
        self.peak_occupancy = 0
        self._occupancy_samples = 0
        self._occupancy_total = 0
        # Batched speculative accounting.
        self.spec_rounds = 0
        self.spec_drafted = 0
        self.spec_accepted = 0

    # ----------------------------------------------------------- frontend

    def submit(self, req: ServeRequest) -> ServeRequest:
        """Thread-safe enqueue; returns immediately (wait on ``req.done``).

        With an overload controller attached the admission verdict is
        SYNCHRONOUS: a rejected request comes back with ``done`` already
        set, ``finish_reason == "rejected"``, and a ``reject_reason`` /
        ``retry_after_sec`` the HTTP layer maps to 429 + Retry-After —
        the caller never waits on a request that was never admitted."""
        req.submitted_t = time.monotonic()
        req.submitted_pc = time.perf_counter()
        if self.tracer is not None and req.trace is None:
            # Direct submitters (loadgen, tests) get a root minted here;
            # router/HTTP ingress attach their own before submitting.
            req.trace = self.tracer.start()
        verdict: tuple[str, float] | None = None
        with self._wake:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            if self._overload is not None:
                if req.deadline_ms is None and self._overload.default_deadline_ms:
                    req.deadline_ms = self._overload.default_deadline_ms
                depth = len(self._queue)
                req.queue_depth_at_submit = depth
                verdict = self._overload.admission_check(req, depth)
            if verdict is None:
                self._queue.append(req)
                self._wake.notify()
        if verdict is not None:
            reason, retry_after = verdict
            self._reject(req, reason, retry_after=retry_after)
        return req

    def _reject(
        self,
        req: ServeRequest,
        reason: str,
        *,
        retry_after: float | None = None,
        shed: bool = False,
    ) -> None:
        """Finalize an overload rejection: ``rejected`` at submit time,
        ``shed`` for a queued request dropped past its deadline. Every
        rejection lands as a labeled counter + timeline instant."""
        req.reject_reason = reason
        if retry_after is not None:
            req.retry_after_sec = retry_after
        req.finish_reason = "shed" if shed else "rejected"
        req.finished_t = time.monotonic()
        if self._overload is not None:
            self._overload.note_rejection(reason, shed=shed)
        if self.registry is not None:
            self.registry.inc(rejected_counter(reason))
        if self.timeline is not None:
            extra = {"rid": req.rid} if req.rid else {}
            if req.trace is not None:
                extra["trace_id"] = req.trace.trace_id
            self.timeline.instant(
                "serve/rejected",
                cat="serve",
                reason=reason,
                request_id=req.request_id,
                **extra,
            )
        if req.trace is not None:
            note: dict[str, Any] = {"reject_reason": reason}
            predicted = getattr(req, "admission_predicted_wait_ms", None)
            if predicted is not None:
                note["predicted_wait_ms"] = predicted
            req.trace.note(**note)
        self._finish_trace(req)
        req.done.set()

    def hot_swap(
        self,
        params: Any,
        *,
        step: int | None = None,
        checkpoint: str | None = None,
    ) -> None:
        """Queue a zero-downtime params swap (thread-safe); the scheduler
        thread applies it BETWEEN steps. In-flight sequences finish on
        the params they were admitted under (per-row epoch pinning);
        admissions after the swap use the new ones; the prefix cache is
        invalidated. No request fails or restarts."""
        with self._wake:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            self._pending_swap = (params, step, checkpoint)
            self._wake.notify()

    # ------------------------------------------------------------- backend

    def _span(self, name: str, **args: Any):
        """Timeline span tagged for Perfetto, no-op without a timeline."""
        if self.timeline is None:
            return nullcontext()
        return self.timeline.span(name, cat="serve", **args)

    @contextmanager
    def _traced_span(self, req: ServeRequest, name: str, **args: Any):
        """Per-request span, recorded twice: live into the timeline (with
        a ``trace_id`` correlation arg, un-treed — the always-on view) and
        into the request's tail-sampling buffer with true perf_counter
        stamps, flushed as part of the span TREE only if the trace is
        kept (telemetry/tracing.py)."""
        trace = req.trace
        live = args if trace is None else {**args, "trace_id": trace.trace_id}
        t0 = time.perf_counter()
        try:
            with self._span(name, **live):
                yield
        finally:
            if trace is not None:
                trace.add_span(name, t0=t0, t1=time.perf_counter(), **args)

    def _kv_event(self, name: str, args: dict[str, Any]) -> None:
        """PagedKVPool observer: pool-level events (prefix evictions, COW
        copies) become serving timeline instants."""
        if self.timeline is not None:
            self.timeline.instant(f"serve/kv_{name}", cat="serve", **args)

    def _compile_event(self, kind: str, bucket: int) -> None:
        """Engine first-bucket hook: the XLA compile about to happen lands
        as an instant — a prefill span bracketing one explains its own
        tail latency in ``llmtrain trace show``."""
        if self.timeline is not None:
            self.timeline.instant(
                "serve/compile", cat="serve", kind=kind, bucket=bucket
            )

    def _finish_trace(self, req: ServeRequest) -> None:
        """Resolve the request's distributed trace: add the decode-phase
        span, then let the tail sampler decide whether the buffered tree
        is flushed. Called by every path that sets ``done``; idempotent
        (the router may also sit on a request's completion path).

        Best-effort: it runs BEFORE ``req.done.set()`` on the scheduler
        step thread, so a tracer/timeline failure (e.g. OSError flushing
        a file-backed timeline) must not hang the client waiter or kill
        the loop."""
        try:
            self._finish_trace_inner(req)
        except Exception:  # noqa: BLE001 — tracing must never fail a request
            logger.warning(
                "trace finish failed for request %s", req.request_id,
                exc_info=True,
            )

    def _finish_trace_inner(self, req: ServeRequest) -> None:
        if self.tracer is None or req.trace is None:
            return
        t1 = time.perf_counter()
        if req.submitted_pc > 0.0 and req.finished_t is not None:
            # Map the monotonic measurement stamps onto the perf_counter
            # timeline via the paired submit stamps (identical clocks on
            # Linux; the offset keeps it exact elsewhere).
            off = req.submitted_pc - req.submitted_t
            t1 = req.finished_t + off
            if (
                req.first_token_t is not None
                and req.finished_t > req.first_token_t
            ):
                req.trace.add_span(
                    "serve/decode_phase",
                    t0=req.first_token_t + off,
                    t1=t1,
                    request_id=req.request_id,
                    tokens=len(req.tokens),
                )
        root_args: dict[str, Any] = {
            "request_id": req.request_id,
            "finish_reason": req.finish_reason,
        }
        if req.rid:
            root_args["rid"] = req.rid
        if req.ttft_ms is not None:
            root_args["ttft_ms"] = round(req.ttft_ms, 3)
        self.tracer.finish(
            req.trace,
            t0=req.submitted_pc if req.submitted_pc > 0.0 else t1,
            t1=t1,
            errored=req.error is not None or req.finish_reason == "error",
            **root_args,
        )

    def _record_queue_wait(self, req: ServeRequest) -> None:
        """Queue-wait span from the submit stamp to now — with the
        request_id tag it abuts the same request's prefill span, so one
        request's queue-wait → prefill → decode path reads as a track.
        Also the overload estimator's learning signal: the OBSERVED wait
        at the depth the request saw is what calibrates predicted wait."""
        if self._overload is not None and req.submitted_t > 0.0:
            self._overload.observe_queue_wait(
                (time.monotonic() - req.submitted_t) * 1e3,
                req.queue_depth_at_submit,
            )
        if req.submitted_pc <= 0.0:
            return
        t1 = time.perf_counter()
        if req.trace is not None:
            req.trace.add_span(
                "serve/queue_wait",
                t0=req.submitted_pc,
                t1=t1,
                request_id=req.request_id,
            )
        if self.timeline is None:
            return
        extra = {"rid": req.rid} if req.rid else {}
        if req.trace is not None:
            extra["trace_id"] = req.trace.trace_id
        self.timeline.record(
            "serve/queue_wait",
            t0=req.submitted_pc,
            t1=t1,
            cat="serve",
            request_id=req.request_id,
            **extra,
        )

    # -------------------------------------------------------- param epochs

    def _apply_pending_swap(self) -> bool:
        with self._lock:
            pending, self._pending_swap = self._pending_swap, None
        if pending is None:
            return False
        params, step, checkpoint = pending
        self._param_epoch += 1
        self._params_by_epoch[self._param_epoch] = params
        self._param_meta[self._param_epoch] = {
            "step": step, "checkpoint": checkpoint
        }
        # Legacy (batch-1) speculative serves new admissions on the new
        # params too; its in-flight unit is one whole request, so the
        # epoch pin is trivially the pop.
        self._params = params
        if self.engine is not None:
            self.engine.set_params(params)
            flushed = self.engine.pool.invalidate_prefix_cache()
            if flushed:
                logger.info(
                    "serve: hot-swap invalidated %d cached prefix blocks",
                    flushed,
                )
        self.hot_swaps += 1
        self._gc_epochs()
        logger.info(
            "serve: hot-swapped params to step %s (epoch %d, %d in flight "
            "pinned to older epochs)",
            step,
            self._param_epoch,
            sum(self._epoch_refs.values()),
        )
        return True

    def _pin_epoch(self, epoch: int) -> None:
        self._epoch_refs[epoch] = self._epoch_refs.get(epoch, 0) + 1

    def _unpin_epoch(self, epoch: int) -> None:
        n = self._epoch_refs.get(epoch, 0) - 1
        if n <= 0:
            self._epoch_refs.pop(epoch, None)
        else:
            self._epoch_refs[epoch] = n
        self._gc_epochs()

    def _gc_epochs(self) -> None:
        """Drop superseded params once their last pinned row retires —
        a swap must not double resident param memory forever."""
        for ep in [
            e
            for e in self._params_by_epoch
            if e != self._param_epoch and self._epoch_refs.get(e, 0) == 0
        ]:
            del self._params_by_epoch[ep]
            self._param_meta.pop(ep, None)

    # ------------------------------------------------------------ stepping

    def step(self) -> bool:
        """One scheduler iteration: join, advance, evict. Returns whether
        any work happened (False = idle)."""
        swapped = self._apply_pending_swap()
        shed = self._overload_tick()
        if self.policy == "speculative":
            return self._step_speculative() or swapped or shed
        return self._step_paged() or swapped or shed

    def _overload_tick(self) -> bool:
        """Per-step overload bookkeeping: feed the brownout hysteresis
        one pressure sample, and under sustained overload eagerly shed
        queued requests already past their deadline (their waiters get a
        fast 429 instead of a slow timeout, and the queue drains toward
        requests that can still make their SLO)."""
        ov = self._overload
        if ov is None:
            return False
        with self._lock:
            depth = len(self._queue)
        transition = ov.tick(depth)
        if transition is not None:
            logger.warning(
                "serve: brownout %s (predicted queue wait %.1f ms, "
                "queue depth %d)",
                transition, ov.predicted_wait_ms(depth), depth,
            )
            if self.timeline is not None:
                self.timeline.instant(
                    f"serve/brownout_{transition}",
                    cat="serve",
                    predicted_wait_ms=round(ov.predicted_wait_ms(depth), 3),
                    queue_depth=depth,
                )
        if not ov.shedding_active:
            return False
        now = time.monotonic()
        with self._lock:
            expired = self._queue.sweep(lambda r: ov.past_deadline(r, now))
        for req in expired:
            self._reject(req, REASON_DEADLINE_EXCEEDED, shed=True)
        return bool(expired)

    def _admit_paged(self, req: ServeRequest, overshoot: int = 0) -> _Row | None:
        """Reserve + prefix-bind one popped request (paged path). Returns
        the row (epoch pinned, prefix bound, COW issued) or None when the
        pool is full — the caller re-queues. Raises nothing; COW device
        failures are handled by the caller's prefill error path because
        the copy is issued lazily with the first slab."""
        engine = self.engine
        assert engine is not None
        tp = int(req.prompt_ids.shape[0])
        total = tp + int(req.max_new_tokens) + int(overshoot)
        table = engine.pool.try_reserve(total)
        if table is None:
            return None
        row = _Row(req=req, table=table, prompt_len=tp, epoch=self._param_epoch)
        req.params_step = self._param_meta[row.epoch].get("step")
        self._pin_epoch(row.epoch)
        return row

    def _prefill_next(self, row: _Row, *, limit: int | None = None) -> bool:
        """Prefill the row's next prompt slab (everything remaining, or at
        most ``limit`` tokens under chunked prefill) at its true offset.
        The FINAL slab samples the first output token, registers the
        prompt's full blocks in the prefix cache, and stamps TTFT; the
        sampled token of a non-final chunk is discarded (same compiled
        program either way). On failure the row is failed and released —
        and if the donated cache was consumed, every in-flight row goes
        with it. Returns success."""
        engine = self.engine
        assert engine is not None
        before = engine.cache_epoch
        start = row.prefilled
        end = (
            row.prompt_len
            if limit is None
            else min(row.prompt_len, start + int(limit))
        )
        slab = row.req.prompt_ids[start:end]
        final = end == row.prompt_len
        engine.pool.grow(row.table, end)
        extra = {"rid": row.req.rid} if row.req.rid else {}
        try:
            with self._traced_span(
                row.req,
                "serve/prefill",
                request_id=row.req.request_id,
                prompt_tokens=end - start,
                offset=start,
                **extra,
            ):
                tok = engine.prefill(
                    slab,
                    row.table.padded(engine.max_blocks_per_seq),
                    seed=row.req.seed,
                    temperature=row.req.temperature,
                    top_k=row.req.top_k,
                    top_p=row.req.top_p,
                    offset=start,
                    params=self._params_by_epoch[row.epoch],
                )
        except Exception as exc:  # noqa: BLE001 — fail THIS request only
            self._drop_row(row)
            self._fail(row.req, exc)
            if engine.cache_epoch != before:
                # The failed call had already consumed the donated cache:
                # every in-flight sequence's KV went with it.
                self._fail_all_in_flight(exc)
            return False
        row.prefilled = end
        self.prefill_tokens += end - start
        if final:
            if row.epoch == self._param_epoch:
                # Publish only CURRENT-epoch K/V: a row that straddled a
                # hot swap finished prefilling under superseded params,
                # and registering its blocks would hand stale cache to
                # post-swap admissions (their parity would break).
                engine.pool.register_prefix(row.table, row.req.prompt_ids)
            now = time.monotonic()
            row.req.first_token_t = now
            row.req.token_times.append(now)
            row.req.tokens.append(tok)
            self.tokens_generated += 1
        return True

    def _finish_or_activate(self, row: _Row) -> None:
        if self._is_finished(row):
            self._retire(row)
        else:
            self._active.append(row)

    def _shed_abandoned_in_flight(self) -> None:
        """Shed abandoned in-flight work (the waiter already got its
        timeout response) so the device never decodes for a gone client."""
        for rows in (self._active, self._prefilling):
            kept: list[_Row] = []
            for r in rows:
                if r.req.abandoned.is_set():
                    self._drop_row(r)
                    self._retire_abandoned(r.req)
                else:
                    kept.append(r)
            rows[:] = kept

    def _step_paged(self) -> bool:
        engine = self.engine
        assert engine is not None
        epoch = engine.cache_epoch
        chunk = engine.prefill_chunk
        # ---- join: admit while a slot AND a worst-case block budget exist.
        # Head-of-line order — admission is FIFO so a huge request cannot
        # be starved by a stream of small ones slipping past it.
        admitted = 0
        while len(self._active) + len(self._prefilling) < self.max_batch_slots:
            # Pop-first (the weighted-class queue's head is only defined
            # by the pop itself); a pool-full admission pushes the
            # request back to the FRONT of its own class, so ordering
            # within a class stays head-of-line.
            with self._lock:
                req = self._queue.popleft() if self._queue else None
            if req is None:
                break
            if req.abandoned.is_set():
                self._retire_abandoned(req)
                continue
            if (
                self._overload is not None
                and self._overload.shedding_active
                and self._overload.past_deadline(req)
            ):
                self._reject(req, REASON_DEADLINE_EXCEEDED, shed=True)
                continue
            if self._overload is not None:
                # Brownout clamp BEFORE validation/reservation: the
                # clamped budget is what the request decodes (and what
                # parity re-checks) under.
                req.max_new_tokens = self._overload.clamp_new_tokens(
                    req.max_new_tokens
                )
            # The HTTP layer pre-validates, but the scheduler must survive
            # direct submitters too: a request this engine can NEVER serve
            # (context bound, prompt bucket, worst-case need > whole pool)
            # fails ALONE instead of wedging the FIFO head forever —
            # try_reserve only distinguishes "not yet", not "never".
            reason = engine.validate_request(
                int(req.prompt_ids.shape[0]), int(req.max_new_tokens)
            )
            if reason is not None:
                self._fail(req, ValueError(reason))
                continue
            row = self._admit_paged(req)
            if row is None:
                # Pool full: back to its class head, retried next step.
                with self._lock:
                    self._queue.appendleft(req)
                break
            # Shared-prefix reuse: bind cached blocks read-only BEFORE any
            # grow; prefill then runs only the unmatched suffix. A partial
            # block match needs a private copy (COW) before its divergent
            # tail is written.
            match = engine.pool.match_prefix(req.prompt_ids)
            if req.trace is not None:
                # Prefix-cache verdict inside the request's trace: a miss
                # that forces a full prefill is a classic p99 explanation.
                req.trace.add_event(
                    "serve/prefix_cache",
                    t=time.perf_counter(),
                    hit=match.hit,
                    matched_tokens=match.matched_tokens,
                    prompt_tokens=int(req.prompt_ids.shape[0]),
                )
            if match.hit:
                engine.pool.bind_prefix(row.table, match)
                row.prefilled = match.matched_tokens
                if match.partial_block is not None:
                    src, dst = engine.pool.cow_last_shared(row.table)
                    try:
                        engine.cow_copy(src, dst)
                    except Exception as exc:  # noqa: BLE001 — contain
                        self._drop_row(row)
                        self._fail(req, exc)
                        if engine.cache_epoch != epoch:
                            self._fail_all_in_flight(exc)
                            epoch = engine.cache_epoch
                        continue
            self._record_queue_wait(req)
            if chunk and (row.prompt_len - row.prefilled) > chunk:
                # Chunked prefill: the prompt streams in one chunk per
                # step (below), interleaved with decode.
                self._prefilling.append(row)
                admitted += 1
                continue
            if not self._prefill_next(row):
                epoch = engine.cache_epoch
                continue
            self._finish_or_activate(row)
            admitted += 1

        self._shed_abandoned_in_flight()

        # ---- advance chunked prefills: ONE chunk per step, head-of-line,
        # so prompt streaming shares the device fairly with decode.
        chunked = False
        if self._prefilling:
            row = self._prefilling.pop(0)
            if self._prefill_next(row, limit=chunk):
                if row.prefilled == row.prompt_len:
                    self._finish_or_activate(row)
                else:
                    self._prefilling.insert(0, row)
            else:
                epoch = engine.cache_epoch
            chunked = True

        # ---- advance every in-flight sequence one token, grouped by the
        # param epoch each row was ADMITTED under (hot-swap pinning).
        # Params is a traced argument, so the groups share one compiled
        # program per batch bucket.
        stepped = False
        if self._active:
            occupancy = len(self._active)
            self.peak_occupancy = max(self.peak_occupancy, occupancy)
            self._occupancy_samples += 1
            self._occupancy_total += occupancy
            by_epoch: dict[int, list[_Row]] = {}
            for r in self._active:
                by_epoch.setdefault(r.epoch, []).append(r)
            epochs = sorted(by_epoch)
            survivors: list[_Row] = []
            for gi, ep in enumerate(epochs):
                group = by_epoch[ep]
                rows = []
                for r in group:
                    # The fed token's absolute position; grow() binds its
                    # block within the admission-time reservation.
                    pos = r.prompt_len + len(r.req.tokens) - 1
                    engine.pool.grow(r.table, pos + 1)
                    rows.append(
                        {
                            "token": r.req.tokens[-1],
                            "position": pos,
                            "table": r.table.padded(engine.max_blocks_per_seq),
                            "seed": r.req.seed,
                            "emit_idx": len(r.req.tokens),
                            "temperature": r.req.temperature,
                            "top_k": 0 if r.req.top_k is None else r.req.top_k,
                            "top_p": 0.0 if r.req.top_p is None else r.req.top_p,
                        }
                    )
                rids = [r.req.rid for r in group if r.req.rid]
                extra = {"rids": rids} if rids else {}
                try:
                    with self._span(
                        "serve/decode",
                        request_ids=[r.req.request_id for r in group],
                        batch=len(rows),
                        param_epoch=ep,
                        **extra,
                    ):
                        toks = engine.decode(
                            rows, params=self._params_by_epoch[ep]
                        )
                except Exception as exc:  # noqa: BLE001 — contain: a decode
                    # failure must not kill the scheduler thread (every
                    # later waiter would time out against a dead loop). The
                    # step output is unusable either way, so each in-flight
                    # request fails loudly — and if the donated cache was
                    # consumed the engine has already rebuilt it zeroed.
                    self._active = survivors + [
                        r for e2 in epochs[gi:] for r in by_epoch[e2]
                    ]
                    self._fail_all_in_flight(exc)
                    self._publish_metrics()
                    return True
                now = time.monotonic()
                for r, tok in zip(group, toks):
                    r.req.tokens.append(int(tok))
                    r.req.token_times.append(now)
                    self.tokens_generated += 1
                    if self._is_finished(r):
                        self._retire(r)
                    else:
                        survivors.append(r)
            self._active = survivors
            stepped = True

        self._publish_metrics()
        return stepped or chunked or admitted > 0

    # -------------------------------------------------------- speculative

    def _step_speculative(self) -> bool:
        if self._draft_engine is not None:
            return self._step_speculative_batched()
        return self._step_speculative_one()

    def _serve_speculative_single(self, req: ServeRequest) -> None:
        """Serve one request end-to-end via ``speculative_generate``
        (batch-1 by that algorithm's contract)."""
        from ..speculative import speculative_generate

        req.params_step = self._param_meta[self._param_epoch].get("step")
        try:
            with self._traced_span(
                req, "serve/speculative_decode", request_id=req.request_id
            ):
                out = speculative_generate(
                    self._model,
                    self._params,
                    self._draft_model,
                    self._draft_params,
                    req.prompt_ids[None, :],
                    max_new_tokens=req.max_new_tokens,
                    gamma=self._gamma,
                    temperature=req.temperature,
                    top_k=req.top_k,
                    top_p=req.top_p,
                    eos_token_id=req.eos_token_id,
                    rng=jax.random.key(req.seed),
                )
        except Exception as exc:  # noqa: BLE001 — fail THIS request only
            self._fail(req, exc)
            return
        now = time.monotonic()
        completion = [int(t) for t in out[0, req.prompt_ids.shape[0] :]]
        if req.eos_token_id is not None and req.eos_token_id in completion:
            completion = completion[: completion.index(req.eos_token_id) + 1]
            req.finish_reason = "eos"
        else:
            req.finish_reason = "length"
        # The whole-loop jit emits every token in one dispatch: TTFT and
        # completion coincide (documented in docs/serving.md).
        req.first_token_t = now
        req.token_times = [now] * len(completion)
        req.tokens = completion
        self.tokens_generated += len(completion)
        self.prefill_tokens += int(req.prompt_ids.shape[0])
        req.finished_t = now
        self.requests_finished += 1
        if self.registry is not None:
            self.registry.inc("serve/requests")
        self._finish_trace(req)
        req.done.set()

    def _step_speculative_one(self) -> bool:
        with self._lock:
            req = self._queue.popleft() if self._queue else None
        if req is None:
            self._publish_metrics()
            return False
        if req.abandoned.is_set():
            self._retire_abandoned(req)
            self._publish_metrics()
            return True
        if (
            self._overload is not None
            and self._overload.shedding_active
            and self._overload.past_deadline(req)
        ):
            self._reject(req, REASON_DEADLINE_EXCEEDED, shed=True)
            self._publish_metrics()
            return True
        if self._overload is not None:
            req.max_new_tokens = self._overload.clamp_new_tokens(
                req.max_new_tokens
            )
        self.peak_occupancy = max(self.peak_occupancy, 1)
        self._occupancy_samples += 1
        self._occupancy_total += 1
        self._record_queue_wait(req)
        self._serve_speculative_single(req)
        self._publish_metrics()
        return True

    def _step_speculative_batched(self) -> bool:
        """Draft-and-verify for EVERY in-flight greedy sequence per step:
        gamma+1 batched one-token decodes on the draft engine (the +1
        re-feeds the last draft so its K/V lands before the next round),
        then ONE bucketed target ``verify`` per param epoch. Greedy
        acceptance — keep draft j only while it equals the target argmax
        given drafts < j — makes the emitted stream bit-identical to
        ``generate()`` on the admitted params."""
        engine, draft = self.engine, self._draft_engine
        assert engine is not None and draft is not None
        gamma = self._gamma
        epoch_guard = engine.cache_epoch
        admitted = 0
        while len(self._active) < self.max_batch_slots:
            # Pop-first, like the paged join: the weighted-class queue's
            # head is only defined by the pop; resource-full paths push
            # the request back to the front of its class.
            with self._lock:
                req = self._queue.popleft() if self._queue else None
            if req is None:
                break
            if req.abandoned.is_set():
                self._retire_abandoned(req)
                continue
            if (
                self._overload is not None
                and self._overload.shedding_active
                and self._overload.past_deadline(req)
            ):
                self._reject(req, REASON_DEADLINE_EXCEEDED, shed=True)
                continue
            if self._overload is not None:
                req.max_new_tokens = self._overload.clamp_new_tokens(
                    req.max_new_tokens
                )
            if req.temperature > 0.0:
                # Sampled: categorical draws aren't replayable across the
                # batched slab; serve batch-1 (same results as before).
                self._record_queue_wait(req)
                self._serve_speculative_single(req)
                admitted += 1
                continue
            tp = int(req.prompt_ids.shape[0])
            need = int(req.max_new_tokens) + gamma  # verify overshoots by γ
            reason = engine.validate_request(tp, need) or draft.validate_request(
                tp, need
            )
            if reason is not None:
                self._fail(req, ValueError(reason))
                continue
            row = self._admit_paged(req, overshoot=gamma)
            if row is None:
                with self._lock:
                    self._queue.appendleft(req)
                break
            row.draft_table = draft.pool.try_reserve(tp + need)
            if row.draft_table is None:
                engine.pool.release(row.table)
                self._unpin_epoch(row.epoch)
                with self._lock:
                    self._queue.appendleft(req)
                break
            engine.pool.grow(row.table, tp)
            draft.pool.grow(row.draft_table, tp)
            self._record_queue_wait(req)
            try:
                with self._traced_span(
                    req,
                    "serve/prefill",
                    request_id=req.request_id,
                    prompt_tokens=tp,
                ):
                    tok = engine.prefill(
                        req.prompt_ids,
                        row.table.padded(engine.max_blocks_per_seq),
                        seed=req.seed,
                        temperature=req.temperature,
                        top_k=req.top_k,
                        top_p=req.top_p,
                        params=self._params_by_epoch[row.epoch],
                    )
                    # Draft prefill: its sampled token is discarded; the
                    # call exists to write the prompt's DRAFT K/V.
                    draft.prefill(
                        req.prompt_ids,
                        row.draft_table.padded(draft.max_blocks_per_seq),
                        seed=req.seed,
                        temperature=0.0,
                        top_k=None,
                        top_p=None,
                    )
            except Exception as exc:  # noqa: BLE001 — fail THIS request only
                self._drop_row(row)
                self._fail(req, exc)
                if engine.cache_epoch != epoch_guard:
                    self._fail_all_in_flight(exc)
                    epoch_guard = engine.cache_epoch
                continue
            now = time.monotonic()
            req.first_token_t = now
            req.token_times.append(now)
            req.tokens.append(tok)
            self.prefill_tokens += tp
            self.tokens_generated += 1
            self._finish_or_activate(row)
            admitted += 1

        self._shed_abandoned_in_flight()

        stepped = False
        if self._active:
            occupancy = len(self._active)
            self.peak_occupancy = max(self.peak_occupancy, occupancy)
            self._occupancy_samples += 1
            self._occupancy_total += occupancy
            # Brownout disables speculation: zero drafts per round (the
            # one draft-feed decode still runs so the draft KV stays
            # position-synced for the exit), and a width-1 verify emits
            # exactly one guaranteed-correct token per row — no device
            # time is spent on lookahead the overloaded fleet would
            # mostly throw away. Reservations were taken at full γ, so
            # flipping per step is always within budget.
            live_gamma = (
                0
                if self._overload is not None and self._overload.in_brownout
                else gamma
            )
            # ---- draft γ tokens per row, batched across rows; round γ
            # re-feeds the final draft so its K/V is resident next step.
            rows_now = list(self._active)
            drafts: list[list[int]] = [[] for _ in rows_now]
            prev = [r.req.tokens[-1] for r in rows_now]
            base = [r.prompt_len + len(r.req.tokens) - 1 for r in rows_now]
            try:
                with self._span(
                    "serve/speculative_draft",
                    batch=len(rows_now),
                    gamma=live_gamma,
                ):
                    for j in range(live_gamma + 1):
                        drows = []
                        for i, r in enumerate(rows_now):
                            pos = base[i] + j
                            draft.pool.grow(r.draft_table, pos + 1)
                            drows.append(
                                {
                                    "token": prev[i],
                                    "position": pos,
                                    "table": r.draft_table.padded(
                                        draft.max_blocks_per_seq
                                    ),
                                    "seed": 0,
                                    "emit_idx": 0,
                                    "temperature": 0.0,
                                    "top_k": 0,
                                    "top_p": 0.0,
                                }
                            )
                        out = draft.decode(drows)
                        if j < live_gamma:
                            for i, t in enumerate(out):
                                drafts[i].append(int(t))
                            prev = [int(t) for t in out]
            except Exception as exc:  # noqa: BLE001 — drafts unusable
                self._fail_all_in_flight(exc)
                self._publish_metrics()
                return True
            # ---- one bucketed verify per param epoch.
            by_epoch: dict[int, list[int]] = {}
            for i, r in enumerate(rows_now):
                by_epoch.setdefault(r.epoch, []).append(i)
            epochs = sorted(by_epoch)
            survivors: list[_Row] = []
            for gi, ep in enumerate(epochs):
                idxs = by_epoch[ep]
                vrows = []
                for i in idxs:
                    r = rows_now[i]
                    engine.pool.grow(r.table, base[i] + live_gamma + 1)
                    vrows.append(
                        {
                            "tokens": [r.req.tokens[-1]] + drafts[i],
                            "position": base[i],
                            "table": r.table.padded(engine.max_blocks_per_seq),
                        }
                    )
                try:
                    with self._span(
                        "serve/speculative_verify",
                        batch=len(vrows),
                        width=live_gamma + 1,
                        param_epoch=ep,
                    ):
                        outs = engine.verify(
                            vrows,
                            width=live_gamma + 1,
                            params=self._params_by_epoch[ep],
                        )
                except Exception as exc:  # noqa: BLE001 — contain
                    self._active = survivors + [
                        rows_now[i] for e2 in epochs[gi:] for i in by_epoch[e2]
                    ]
                    self._fail_all_in_flight(exc)
                    self._publish_metrics()
                    return True
                now = time.monotonic()
                for i, a in zip(idxs, outs):
                    r, d = rows_now[i], drafts[i]
                    self.spec_rounds += 1
                    self.spec_drafted += live_gamma
                    # a[j] = target argmax given drafts < j: emit a[0],
                    # then keep extending while the draft guessed it.
                    emitted = [a[0]]
                    acc = 0
                    while acc < live_gamma and d[acc] == a[acc]:
                        emitted.append(a[acc + 1])
                        acc += 1
                    self.spec_accepted += acc
                    for t in emitted:
                        if len(r.req.tokens) >= r.req.max_new_tokens:
                            break
                        r.req.tokens.append(int(t))
                        r.req.token_times.append(now)
                        self.tokens_generated += 1
                        if (
                            r.req.eos_token_id is not None
                            and int(t) == r.req.eos_token_id
                        ):
                            break
                    if self._is_finished(r):
                        self._retire(r)
                    else:
                        survivors.append(r)
            self._active = survivors
            stepped = True

        self._publish_metrics()
        return stepped or admitted > 0

    # ------------------------------------------------------------ plumbing

    def _is_finished(self, row: _Row) -> bool:
        req = row.req
        if req.eos_token_id is not None and req.tokens[-1] == req.eos_token_id:
            req.finish_reason = "eos"
            return True
        if len(req.tokens) >= req.max_new_tokens:
            req.finish_reason = "length"
            return True
        return False

    def _drop_row(self, row: _Row) -> None:
        """Return a row's pool resources + epoch pin (no req bookkeeping)."""
        assert self.engine is not None
        self.engine.pool.release(row.table)
        if row.draft_table is not None and self._draft_engine is not None:
            self._draft_engine.pool.release(row.draft_table)
        self._unpin_epoch(row.epoch)

    def _retire(self, row: _Row) -> None:
        self._drop_row(row)
        row.req.finished_t = time.monotonic()
        self.requests_finished += 1
        if self.registry is not None:
            self.registry.inc("serve/requests")
        self._finish_trace(row.req)
        row.req.done.set()

    def _retire_abandoned(self, req: ServeRequest) -> None:
        logger.warning(
            "serve request %s abandoned by its waiter; shed", req.request_id
        )
        req.finish_reason = "abandoned"
        req.finished_t = time.monotonic()
        if self.registry is not None:
            self.registry.inc("serve/requests_abandoned")
        if req.trace is not None:
            # An abandonment IS a latency incident (the waiter timed out):
            # force-keep the trace so the post-mortem has the span tree.
            req.trace.note(abandoned=True, error="abandoned by waiter")
        self._finish_trace(req)
        req.done.set()

    def _fail_all_in_flight(self, cause: Exception) -> None:
        for r in self._active + self._prefilling:
            self._drop_row(r)
            self._fail(
                r.req,
                RuntimeError(
                    f"in-flight KV lost to a failed engine step: {cause}"
                ),
            )
        self._active = []
        self._prefilling = []

    def _fail(self, req: ServeRequest, exc: Exception) -> None:
        logger.warning("serve request %s failed: %s", req.request_id, exc)
        req.error = str(exc)
        req.finish_reason = "error"
        req.finished_t = time.monotonic()
        if self.registry is not None:
            self.registry.inc("serve/request_errors")
        self._finish_trace(req)
        req.done.set()

    def _publish_metrics(self) -> None:
        if self.registry is None:
            return
        with self._lock:
            depth = len(self._queue)
        metrics = {
            "serve/queue_depth": float(depth),
            "serve/batch_occupancy": float(len(self._active)),
            "serve/peak_batch_occupancy": float(self.peak_occupancy),
            "serve/tokens_generated": float(self.tokens_generated),
            "serve/hot_swaps": float(self.hot_swaps),
        }
        if self.engine is not None:
            pool = self.engine.pool.stats()
            metrics["serve/kv_pool_used_blocks"] = pool["allocated_blocks"]
            metrics["serve/kv_pool_utilization"] = pool["utilization"]
            metrics["serve/kv_pool_reserved_blocks"] = pool["reserved_blocks"]
            if "prefix_hit_rate" in pool:
                metrics["serve/prefix_hits"] = pool["prefix_hits"]
                metrics["serve/prefix_hit_rate"] = pool["prefix_hit_rate"]
                metrics["serve/prefix_tokens_reused"] = pool[
                    "prefix_tokens_reused"
                ]
        if self._draft_engine is not None and self.spec_drafted:
            metrics["serve/spec_acceptance_rate"] = round(
                self.spec_accepted / self.spec_drafted, 4
            )
        if self._overload is not None:
            # The SLO-facing overload gauges: predicted wait is what
            # admission decides on, brownout is the degraded-mode flag
            # operators alert on (llmtrain_serve_brownout).
            metrics["serve/predicted_wait_ms"] = round(
                self._overload.predicted_wait_ms(depth), 3
            )
            metrics["serve/brownout"] = (
                1.0 if self._overload.in_brownout else 0.0
            )
        self.registry.publish(metrics)

    # ----------------------------------------------------------- lifecycle

    def stats(self) -> dict[str, Any]:
        with self._lock:
            depth = len(self._queue)
        mean_occ = (
            self._occupancy_total / self._occupancy_samples
            if self._occupancy_samples
            else 0.0
        )
        out: dict[str, Any] = {
            "policy": self.policy,
            "queue_depth": depth,
            "active_sequences": len(self._active),
            "prefilling_sequences": len(self._prefilling),
            "max_batch_slots": self.max_batch_slots,
            "requests_finished": self.requests_finished,
            "tokens_generated": self.tokens_generated,
            "prefill_tokens": self.prefill_tokens,
            "peak_batch_occupancy": self.peak_occupancy,
            "mean_batch_occupancy": round(mean_occ, 4),
        }
        meta = self._param_meta.get(self._param_epoch, {})
        out["params"] = {
            "epoch": self._param_epoch,
            "step": meta.get("step"),
            "checkpoint": meta.get("checkpoint"),
            "hot_swaps": self.hot_swaps,
            "live_epochs": sorted(self._params_by_epoch),
        }
        out["liveness"] = {
            "thread_alive": (
                self._thread.is_alive() if self._thread is not None else None
            ),
            "beacon_age_sec": round(time.monotonic() - self._beacon, 3),
        }
        if self._overload is not None:
            # Backpressure surface: /healthz exposes this block, and the
            # router's placement penalizes replicas whose predicted wait
            # or brownout flag says "don't send more here".
            out["overload"] = self._overload.stats()
        if self.engine is not None:
            out["kv_pool"] = self.engine.pool.stats()
            out["compile"] = self.engine.compile_stats()
            if self.engine.prefill_chunk:
                out["prefill_chunk"] = self.engine.prefill_chunk
        if self.policy == "speculative":
            spec: dict[str, Any] = {
                "gamma": self._gamma,
                "mode": "batched" if self._draft_engine is not None else "batch-1",
            }
            if self._draft_engine is not None:
                spec.update(
                    {
                        "rounds": self.spec_rounds,
                        "drafted": self.spec_drafted,
                        "accepted": self.spec_accepted,
                        "acceptance_rate": round(
                            self.spec_accepted / max(1, self.spec_drafted), 4
                        ),
                        "draft_kv_pool": self._draft_engine.pool.stats(),
                        "draft_compile": self._draft_engine.compile_stats(),
                    }
                )
            out["speculative"] = spec
        return out

    def alive(self, stale_sec: float = 30.0) -> bool:
        """Liveness truth for ``/healthz``: the loop thread is running
        and iterated within ``stale_sec``. A scheduler that was never
        ``start()``-ed (tests drive ``step()`` directly) counts alive —
        there is no loop to be dead."""
        if self._thread is None:
            return True
        if not self._thread.is_alive():
            return False
        return time.monotonic() - self._beacon <= float(stale_sec)

    def run_forever(self, poll_sec: float = 0.005) -> None:
        """Scheduler loop body for the background thread."""
        while True:
            self._beacon = time.monotonic()
            with self._wake:
                idle = (
                    not self._queue
                    and not self._active
                    and not self._prefilling
                    and self._pending_swap is None
                )
                if self._closed and idle:
                    return
                if idle and not self._closed:
                    self._wake.wait(timeout=poll_sec * 20)
            with self._lock:
                idle = (
                    not self._queue
                    and not self._active
                    and not self._prefilling
                    and self._pending_swap is None
                )
            if self._closed and idle:
                return
            if not self.step():
                time.sleep(poll_sec)

    def start(self) -> "ContinuousBatchingScheduler":
        self._thread = threading.Thread(
            target=self.run_forever, name="serve-scheduler", daemon=True
        )
        self._thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Drain in-flight work, then stop the loop (bounded)."""
        with self._wake:
            self._closed = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                logger.warning("serve scheduler did not drain in %.0fs", timeout)
        if self.timeline is not None:
            try:
                self.timeline.flush()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass


__all__ = ["ContinuousBatchingScheduler", "ServeRequest"]
