"""HTTP surface of the inference server (stdlib, no new dependencies).

Promoted from the original single-module ``serving.py``; the routes keep
their contracts and gain the batched backend:

* ``GET /healthz`` — liveness + model/checkpoint metadata, now including
  scheduler/KV-pool/compile stats when the continuous-batching engine is
  attached.
* ``GET /metrics`` — Prometheus exposition of the serving registry
  (``llmtrain_serve_*``), same text format the training Jobs export.
* ``POST /v1/generate`` — validation unchanged; with a scheduler attached
  the request is SUBMITTED to the continuous batch and the handler thread
  waits on its completion event (N handler threads → N in-flight
  sequences sharing one jitted program), otherwise the legacy
  one-decode-at-a-time lock path runs.

Thread discipline: ``ThreadingHTTPServer`` runs one handler thread per
connection, so every cross-request mutable — request counters, latency
accumulators — lives in :class:`ServerStats` behind its own lock (the
bare ``requests_served += 1`` this replaces was a read-modify-write race
between handler threads).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import jax
import numpy as np

from ..telemetry.stats import percentile as _nearest_rank_percentile

# Histogram buckets (ms) for the exemplar-carrying /metrics histograms —
# roughly log-spaced across interactive serving SLOs. Each observation
# may attach its trace_id as an OpenMetrics exemplar, so a scrape of a
# slow bucket hands you a trace id to feed ``llmtrain trace show``.
_TTFT_BUCKETS_MS = (
    5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
)
_LATENCY_BUCKETS_MS = (
    10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
    10000.0, 30000.0,
)
_PER_TOKEN_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0)


class ServerStats:
    """Lock-protected cross-request counters/accumulators.

    ``ThreadingHTTPServer`` handler threads all mutate this; int += is a
    read-modify-write, so every mutation happens under one lock
    (regression-tested by hammering :meth:`record` from many threads).
    """

    _RESERVOIR = 512  # newest samples kept for percentile estimation

    # (metric stem, reservoir attr) pairs exported as p50/p95/p99 gauges.
    _QUANTILES = ((0.50, "p50"), (0.95, "p95"), (0.99, "p99"))

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._requests = 0
        self._errors = 0
        self._tokens_out = 0
        self._latency_sum_ms = 0.0
        self._latencies_ms: list[float] = []
        self._ttft_ms: list[float] = []
        self._per_token_ms: list[float] = []

    @staticmethod
    def _push(reservoir: list[float], value: float) -> None:
        reservoir.append(value)
        if len(reservoir) > ServerStats._RESERVOIR:
            del reservoir[: -ServerStats._RESERVOIR]

    def record(
        self, *, latency_ms: float, tokens: int, ttft_ms: float | None = None
    ) -> None:
        with self._lock:
            self._requests += 1
            self._tokens_out += tokens
            self._latency_sum_ms += latency_ms
            self._push(self._latencies_ms, latency_ms)
            if ttft_ms is not None:
                self._push(self._ttft_ms, ttft_ms)
                # Per-token decode latency: time AFTER the first token over
                # the remaining tokens — the steady-state decode rate an SLO
                # cares about, not diluted by prefill.
                if tokens > 1:
                    self._push(
                        self._per_token_ms, (latency_ms - ttft_ms) / (tokens - 1)
                    )
            elif tokens > 0:
                self._push(self._per_token_ms, latency_ms / tokens)

    def record_error(self) -> None:
        with self._lock:
            self._errors += 1

    @property
    def requests_served(self) -> int:
        with self._lock:
            return self._requests

    @staticmethod
    def _percentile(sorted_vals: list[float], q: float) -> float | None:
        # Shared nearest-rank helper (telemetry/stats.py) so /metrics,
        # loadgen, and the trace summary all agree on what "p95" means.
        if not sorted_vals:
            return None
        return _nearest_rank_percentile(sorted_vals, q)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            n = self._requests
            lat = sorted(self._latencies_ms)
            ttft = sorted(self._ttft_ms)
            return {
                "requests_served": n,
                "errors": self._errors,
                "tokens_out": self._tokens_out,
                "mean_latency_ms": round(self._latency_sum_ms / n, 3) if n else None,
                "p50_latency_ms": round(lat[len(lat) // 2], 3) if lat else None,
                "p95_latency_ms": (
                    round(self._percentile(lat, 0.95), 3) if lat else None
                ),
                "p50_ttft_ms": round(ttft[len(ttft) // 2], 3) if ttft else None,
            }

    def prometheus_gauges(self) -> dict[str, float]:
        """Percentile gauges merged into GET /metrics on every scrape
        (``llmtrain_serve_ttft_ms_p50`` etc.) — live SLO latency from the
        reservoir, not a post-run summary. Empty reservoirs export
        nothing: an absent series beats a misleading 0."""
        with self._lock:
            series = {
                "serve/latency_ms": sorted(self._latencies_ms),
                "serve/ttft_ms": sorted(self._ttft_ms),
                "serve/per_token_ms": sorted(self._per_token_ms),
            }
        gauges: dict[str, float] = {}
        for stem, vals in series.items():
            for q, tag in self._QUANTILES:
                value = self._percentile(vals, q)
                if value is not None:
                    gauges[f"{stem}_{tag}"] = value
        return gauges


@dataclass
class ServerState:
    """Everything a request needs; built once by the CLI before serving."""

    model: Any
    params: Any
    tokenizer: Any | None
    step: int
    checkpoint: str
    eos_token_id: int | None = None
    max_new_tokens_cap: int = 256
    default_max_new_tokens: int = 48
    # Legacy path only — one decode at a time behind the device lock. The
    # scheduler path replaces the lock with the admission queue.
    lock: threading.Lock = field(default_factory=threading.Lock)
    stats: ServerStats = field(default_factory=ServerStats)
    # Continuous-batching backend (serving/scheduler.py) or a
    # ReplicaRouter (serving/router.py) — duck-typed; None = legacy.
    scheduler: Any | None = None
    # Telemetry registry served on GET /metrics (llmtrain_serve_*).
    registry: Any | None = None
    request_timeout_sec: float = 120.0
    # Zero-downtime checkpoint hot-swap: POST /reload calls this with the
    # request body; it loads the newest manifest-committed checkpoint,
    # applies scheduler.hot_swap()/router.rolling_reload(), and returns
    # the response dict (the CLI builds the closure). None = 404.
    reloader: Any | None = None
    # /healthz turns 503 when the scheduler loop's beacon is older than
    # this (serving.liveness_stale_sec) — the k8s livenessProbe contract.
    liveness_stale_sec: float = 30.0
    # Per-client token buckets at the HTTP boundary (overload.ClientRateGate,
    # keyed by X-Client-Id). None = no per-client rate limiting.
    client_gate: Any | None = None

    @property
    def requests_served(self) -> int:
        """Back-compat alias for the pre-ServerStats counter field."""
        return self.stats.requests_served


def _bad_request(msg: str) -> tuple[int, dict]:
    return 400, {"error": msg}


def _header(headers: Any, name: str) -> str | None:
    """Case-insensitive header lookup that works for both the stdlib
    ``email.message.Message`` (real requests) and plain dicts (tests)."""
    if headers is None:
        return None
    get = getattr(headers, "get", None)
    if get is None:
        return None
    value = get(name)
    if value is None and isinstance(headers, dict):
        lowered = {k.lower(): v for k, v in headers.items()}
        value = lowered.get(name.lower())
    return value


def _attach_trace(state: ServerState, req: Any, headers: Any) -> None:
    """Ingress tracing: adopt a propagated ``traceparent`` (the request
    is a hop of a router-minted trace — our spans parent under the
    router's dispatch span) or honor ``X-Trace: force``. With neither,
    nothing happens here: the scheduler/router mints its own root on
    submit. Trace failures never fail a request."""
    tracer = getattr(state.scheduler, "tracer", None)
    if tracer is None:
        return
    try:
        from ..telemetry.tracing import (
            FORCE_HEADER,
            TRACEPARENT_HEADER,
            TraceContext,
        )

        parent = TraceContext.from_traceparent(
            _header(headers, TRACEPARENT_HEADER)
        )
        forced = (_header(headers, FORCE_HEADER) or "").strip().lower() == "force"
        if parent is None and not forced:
            return
        root_name = (
            "router/request"
            if getattr(state.scheduler, "policy", "") == "router"
            else "serve/request"
        )
        req.trace = tracer.start(
            parent=parent, forced=forced, root_name=root_name
        )
    except Exception:  # noqa: BLE001 — tracing is best-effort
        pass


def _observe_histograms(
    state: ServerState,
    *,
    latency_ms: float,
    tokens: int,
    ttft_ms: float | None,
    trace_id: str | None,
) -> None:
    """Feed the /metrics histograms, tagging each observation with the
    request's trace id so slow buckets carry OpenMetrics exemplars."""
    if state.registry is None:
        return
    try:
        state.registry.observe(
            "serve/latency_ms", latency_ms,
            buckets=_LATENCY_BUCKETS_MS, trace_id=trace_id,
        )
        if ttft_ms is not None:
            state.registry.observe(
                "serve/ttft_ms", ttft_ms,
                buckets=_TTFT_BUCKETS_MS, trace_id=trace_id,
            )
            if tokens > 1:
                state.registry.observe(
                    "serve/per_token_ms",
                    (latency_ms - ttft_ms) / (tokens - 1),
                    buckets=_PER_TOKEN_BUCKETS_MS, trace_id=trace_id,
                )
    except Exception:  # noqa: BLE001 — metrics are best-effort
        pass


def _handle_generate_request(
    state: ServerState, body: dict, headers: Any = None
) -> tuple[int, dict]:
    """Pure request logic (no HTTP): validate -> decode -> respond.

    ``headers`` (optional, dict-like) carries the SLO envelope:
    ``X-Request-Id`` (echoed end-to-end and tagged on timeline spans),
    ``X-Deadline-Ms`` (remaining latency budget; admission rejects fast
    when it can't plausibly be met), ``X-Priority`` (class name for the
    weighted dequeue), and ``X-Client-Id`` (per-client token bucket).
    """
    code, payload = _generate_request_inner(state, body, headers)
    # X-Request-Id echoes on EVERY response — a client correlating a 400
    # needs it as much as one correlating a 200.
    rid = _header(headers, "X-Request-Id")
    if rid and isinstance(payload, dict) and "request_id" not in payload:
        payload["request_id"] = rid
    return code, payload


def _generate_request_inner(
    state: ServerState, body: dict, headers: Any = None
) -> tuple[int, dict]:
    from ..generation import generate

    if not isinstance(body, dict):
        return _bad_request("request body must be a JSON object")

    rid = _header(headers, "X-Request-Id")
    echo: dict[str, Any] = {"request_id": rid} if rid else {}
    deadline_ms: float | None = None
    raw_deadline = _header(headers, "X-Deadline-Ms")
    if raw_deadline is not None:
        try:
            deadline_ms = float(raw_deadline)
        except (TypeError, ValueError):
            deadline_ms = -1.0
        if deadline_ms <= 0:
            return 400, {
                "error": "X-Deadline-Ms must be a positive number", **echo
            }
    priority = _header(headers, "X-Priority") or "interactive"

    if state.client_gate is not None:
        client = _header(headers, "X-Client-Id") or "_anon"
        wait = state.client_gate.check(client)
        if wait is not None:
            if state.registry is not None:
                from .overload import REASON_RATE_LIMITED, rejected_counter

                state.registry.inc(rejected_counter(REASON_RATE_LIMITED))
            return 429, {
                "error": f"client {client!r} is over its request rate",
                "reason": "rate_limited",
                "retry_after": round(wait, 3),
                **echo,
            }
    unknown = set(body) - {
        "prompt", "prompt_ids", "max_new_tokens", "temperature",
        "top_k", "top_p", "seed", "eos_token_id",
    }
    if unknown:
        return _bad_request(f"unknown fields: {sorted(unknown)}")
    if ("prompt" in body) == ("prompt_ids" in body):
        return _bad_request("provide exactly one of 'prompt' or 'prompt_ids'")

    vocab = int(getattr(state.model, "vocab_size", 0) or 0)
    if "prompt" in body:
        if state.tokenizer is None:
            return _bad_request(
                "this server has no tokenizer; send 'prompt_ids' instead"
            )
        if not isinstance(body["prompt"], str) or not body["prompt"]:
            return _bad_request("'prompt' must be a non-empty string")
        ids = np.asarray(state.tokenizer.encode(body["prompt"]), dtype=np.int32)
    else:
        raw = body["prompt_ids"]
        if (
            not isinstance(raw, list)
            or not raw
            or not all(isinstance(t, int) for t in raw)
        ):
            return _bad_request("'prompt_ids' must be a non-empty list of ints")
        bound = vocab or 2**31 - 1  # int32 dtype bound when vocab unknown
        if not all(0 <= t < bound for t in raw):
            return _bad_request(f"prompt token ids must be in [0, {bound})")
        ids = np.asarray(raw, dtype=np.int32)
    if ids.size == 0:
        return _bad_request("prompt encodes to zero tokens")

    # A server started with a cap below the default must still accept
    # knob-less requests: the effective default is min(default, cap).
    max_new = body.get(
        "max_new_tokens",
        min(state.default_max_new_tokens, state.max_new_tokens_cap),
    )
    if not isinstance(max_new, int) or max_new < 1:
        return _bad_request("'max_new_tokens' must be a positive int")
    if max_new > state.max_new_tokens_cap:
        return _bad_request(
            f"'max_new_tokens' exceeds the server cap "
            f"({state.max_new_tokens_cap})"
        )
    block_size = int(getattr(state.model, "block_size", 10**9))
    if ids.size + max_new > block_size:
        return _bad_request(
            f"prompt ({ids.size}) + max_new_tokens ({max_new}) exceeds the "
            f"model block_size ({block_size})"
        )
    engine = getattr(state.scheduler, "engine", None)
    if engine is not None:
        # Paged-backend bounds (prompt bucket, pool capacity): reject at
        # the HTTP boundary as a 400, not a late 500 from inside prefill.
        reason = engine.validate_request(int(ids.size), int(max_new))
        if reason is not None:
            return _bad_request(reason)
    temperature = body.get("temperature", 1.0)
    if not isinstance(temperature, (int, float)) or isinstance(temperature, bool):
        return _bad_request("'temperature' must be a number")
    if temperature < 0:
        return _bad_request("'temperature' must be >= 0")
    top_k = body.get("top_k")
    if top_k is not None and (not isinstance(top_k, int) or isinstance(top_k, bool)):
        return _bad_request("'top_k' must be an int")
    top_p = body.get("top_p")
    if top_p is not None and (
        not isinstance(top_p, (int, float)) or isinstance(top_p, bool)
    ):
        return _bad_request("'top_p' must be a number")
    seed = body.get("seed", 0)
    if not isinstance(seed, int) or isinstance(seed, bool):
        return _bad_request("'seed' must be an int")
    eos = body.get("eos_token_id", state.eos_token_id)
    if eos is not None and (not isinstance(eos, int) or isinstance(eos, bool)):
        return _bad_request("'eos_token_id' must be an int")

    t0 = time.monotonic()
    extra: dict[str, Any] = {}
    trace_id: str | None = None
    if state.scheduler is not None:
        # Continuous batching: enqueue and wait; the scheduler thread
        # joins this sequence into the in-flight batch.
        from .scheduler import ServeRequest

        req = ServeRequest(
            prompt_ids=ids,
            max_new_tokens=max_new,
            temperature=float(temperature),
            top_k=top_k,
            top_p=top_p,
            seed=seed,
            eos_token_id=eos,
            deadline_ms=deadline_ms,
            priority=priority,
            rid=rid,
        )
        _attach_trace(state, req, headers)
        state.scheduler.submit(req)
        if not req.done.wait(timeout=state.request_timeout_sec):
            # Tell the scheduler this waiter is gone: under sustained
            # overload the queue would otherwise fill with requests that
            # still get fully decoded for nobody, and the server could
            # never catch up.
            req.abandon()
            state.stats.record_error()
            return 503, {
                "error": "request timed out in the serving queue", **echo
            }
        trace_id = req.trace_id
        if req.finish_reason in ("rejected", "shed"):
            # Overload control said no — fast 429 with the reason and a
            # Retry-After hint (do_POST lifts it into the header).
            payload: dict[str, Any] = {
                "error": f"request {req.finish_reason} by overload control",
                "reason": req.reject_reason,
                "finish_reason": req.finish_reason,
                **echo,
            }
            if trace_id is not None:
                payload["trace_id"] = trace_id
            if req.retry_after_sec is not None:
                payload["retry_after"] = round(req.retry_after_sec, 3)
            return 429, payload
        if req.error is not None:
            state.stats.record_error()
            payload = {"error": f"generation failed: {req.error}", **echo}
            if trace_id is not None:
                payload["trace_id"] = trace_id
            return 500, payload
        completion = list(req.tokens)
        if req.ttft_ms is not None:
            extra["ttft_ms"] = round(req.ttft_ms, 3)
        extra["finish_reason"] = req.finish_reason
        if trace_id is not None:
            extra["trace_id"] = trace_id
    else:
        with state.lock:
            out = generate(
                state.model,
                state.params,
                ids[None, :],
                max_new_tokens=max_new,
                temperature=temperature,
                top_k=top_k,
                top_p=top_p,
                eos_token_id=eos,
                rng=jax.random.key(seed),
            )
        completion = [int(t) for t in np.asarray(out)[0, ids.size :]]
        if eos is not None and eos in completion:
            completion = completion[: completion.index(eos) + 1]
    latency_ms = (time.monotonic() - t0) * 1000.0
    state.stats.record(
        latency_ms=latency_ms,
        tokens=len(completion),
        ttft_ms=extra.get("ttft_ms"),
    )
    _observe_histograms(
        state,
        latency_ms=latency_ms,
        tokens=len(completion),
        ttft_ms=extra.get("ttft_ms"),
        trace_id=trace_id,
    )
    if state.registry is not None and state.scheduler is None:
        # The scheduler publishes its own serve/* metrics; the legacy
        # path still counts requests for the /metrics endpoint.
        state.registry.inc("serve/requests")

    text = None
    if state.tokenizer is not None:
        try:
            text = state.tokenizer.decode(completion)
        except Exception:  # noqa: BLE001 — decode is best-effort for ids
            text = None
    return 200, {
        "completion_ids": completion,
        "text": text,
        "prompt_tokens": int(ids.size),
        "latency_ms": round(latency_ms, 3),
        **extra,
        **echo,
    }


def _handle_reload(state: ServerState, body: dict) -> tuple[int, dict]:
    """POST /reload — zero-downtime checkpoint hot-swap. The heavy work
    (manifest read, param load, scheduler.hot_swap) lives in the CLI's
    reloader closure; in-flight requests keep decoding on their admitted
    params throughout, so this endpoint is safe under live traffic."""
    if state.reloader is None:
        return 404, {"error": "this server has no reloader attached"}
    if body is None:
        body = {}
    if not isinstance(body, dict):
        return _bad_request("request body must be a JSON object (or empty)")
    try:
        out = state.reloader(body)
    except Exception as exc:  # noqa: BLE001 — a bad checkpoint must not 500
        # the serving loop: the old params keep serving.
        return 409, {"error": f"reload failed (still serving old params): {exc}"}
    return 200, {"status": "ok", **(out or {})}


def _handle_health(state: ServerState) -> tuple[int, dict]:
    """Liveness + stats. Parity with the training watchdog: a dead or
    wedged scheduler loop answers 503 (k8s livenessProbe restarts the
    pod) instead of serving stale-but-200 stats forever. A router in the
    scheduler seat is unhealthy when its whole fleet is evicted."""
    payload: dict[str, Any] = {
        "status": "ok",
        "model": type(state.model).__name__,
        "step": state.step,
        "checkpoint": state.checkpoint,
        "requests_served": state.stats.requests_served,
        "stats": state.stats.snapshot(),
    }
    if state.scheduler is not None:
        payload["scheduler"] = state.scheduler.stats()
        alive_fn = getattr(state.scheduler, "alive", None)
        if alive_fn is not None:
            alive = bool(alive_fn(state.liveness_stale_sec))
        else:
            healthy = (
                payload["scheduler"].get("router", {}).get("replicas_healthy")
            )
            alive = healthy is None or healthy > 0
        if not alive:
            payload["status"] = "unhealthy"
            return 503, payload
    return 200, payload


def _handle_metrics(state: ServerState) -> tuple[int, str]:
    """Prometheus text for GET /metrics (requires a registry)."""
    if state.registry is None:
        return 404, "no metrics registry attached\n"
    from ..telemetry.prometheus import render_prometheus

    gauges = dict(state.registry.latest())
    # Live SLO percentiles from the stats reservoir — computed at scrape
    # time so /metrics always reflects the newest requests.
    for name, value in state.stats.prometheus_gauges().items():
        gauges[name] = (value, None)
    return 200, render_prometheus(
        gauges,
        state.registry.counters(),
        {"component": "serve", "checkpoint": state.checkpoint},
        histograms=state.registry.histograms(),
    )


class _Handler(BaseHTTPRequestHandler):
    # Set by make_server().
    state: ServerState = None  # type: ignore[assignment]

    def _respond(
        self, code: int, payload: dict, headers: dict[str, str] | None = None
    ) -> None:
        data = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)

    @staticmethod
    def _slo_headers(code: int, payload: dict) -> dict[str, str]:
        """Lift the SLO envelope out of the payload into real headers:
        429/503 carry Retry-After (integer seconds, >= 1 per RFC 9110),
        and X-Request-Id echoes back whenever the request carried one."""
        out: dict[str, str] = {}
        retry_after = payload.get("retry_after") if isinstance(payload, dict) else None
        if code in (429, 503) and isinstance(retry_after, (int, float)):
            out["Retry-After"] = str(max(1, int(-(-float(retry_after) // 1))))
        rid = payload.get("request_id") if isinstance(payload, dict) else None
        if rid:
            out["X-Request-Id"] = str(rid)
        return out

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path == "/healthz":
            code, payload = _handle_health(self.state)
            headers = {}
            if code == 503:
                # An unhealthy replica tells the router/probe when to
                # come back, mirroring the 429 backpressure contract.
                headers["Retry-After"] = str(
                    max(1, int(self.state.liveness_stale_sec))
                )
            self._respond(code, payload, headers)
        elif self.path.split("?")[0] == "/metrics":
            code, text = _handle_metrics(self.state)
            body = text.encode("utf-8")
            self.send_response(code)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._respond(404, {"error": f"no route for GET {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path not in ("/v1/generate", "/reload"):
            self._respond(404, {"error": f"no route for POST {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"null")
        except (ValueError, json.JSONDecodeError):
            self._respond(400, {"error": "body is not valid JSON"})
            return
        if self.path == "/reload":
            self._respond(*_handle_reload(self.state, body))
            return
        try:
            code, payload = _handle_generate_request(
                self.state, body, self.headers
            )
            self._respond(code, payload, self._slo_headers(code, payload))
        except Exception as exc:  # noqa: BLE001 — server must not die
            self.state.stats.record_error()
            self._respond(500, {"error": f"generation failed: {exc}"})

    def log_message(self, fmt: str, *args: Any) -> None:
        from ..utils.logging import get_logger

        get_logger().info("serve: %s", fmt % args)


def make_server(
    state: ServerState, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    """Bind (port 0 = ephemeral; read ``server_address[1]``), don't serve."""
    handler = type("BoundHandler", (_Handler,), {"state": state})
    return ThreadingHTTPServer((host, port), handler)


__all__ = ["ServerState", "ServerStats", "make_server"]
