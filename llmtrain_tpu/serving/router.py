"""Fleet-scale serving tier: a replica router with prefix-cache-aware,
load-aware placement and rolling (zero-downtime) checkpoint reloads.

One engine serves one accelerator; a FLEET serves traffic. This module
is the front tier that turns N independent serving replicas — in-process
schedulers (same process, e.g. one per device) or remote HTTP servers
(k8s pods behind a headless Service) — into one endpoint:

* **Prefix-cache-aware placement.** The router hashes each prompt's
  full token blocks with the SAME chain hash the pool's prefix cache
  uses (paged_kv.chain_hashes) and remembers which replica last served
  each block. A request whose prefix lives on replica R scores toward R
  — landing it there turns the fleet's per-replica prefix caches into
  an (approximate) fleet-wide cache, the difference between "the system
  prompt prefills once per fleet" and "once per replica per eviction".
* **Load-aware scoring.** Affinity competes against load (queue depth +
  in-flight sequences + KV-pool utilization, the same numbers
  ``/healthz`` exposes): ``score = affinity_weight * matched_blocks −
  load``. A hot replica loses its affinity advantage instead of melting.
* **Health / eviction / failover.** ``fail_threshold`` consecutive
  submit failures evict a replica from rotation; it is re-probed after
  ``revive_sec``. A failed HTTP submit fails over to the next-best
  replica before the client sees an error.
* **Rolling hot-swap.** :meth:`rolling_reload` applies a checkpoint
  swap one replica at a time (scheduler.hot_swap per in-process
  replica, ``POST /reload`` per HTTP replica) — the rest of the fleet
  keeps serving, in-flight requests finish on their admitted params,
  zero requests fail.

The router duck-types the scheduler surface the HTTP layer and load
harness already consume (``submit`` / ``stats`` / ``registry`` /
``engine``), so ``make_server`` and ``run_loadgen`` work unchanged with
a router in the scheduler seat. Metrics publish under ``router/*`` (→
``llmtrain_router_*`` in Prometheus, scraped on the same federation
path as the training gauges).
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from typing import Any, Sequence

import numpy as np

from ..telemetry.tracing import TraceContext, Tracer, new_span_id
from ..utils.logging import get_logger
from .overload import REASON_RETRY_BUDGET, RetryBudget, rejected_counter
from .paged_kv import chain_hashes
from .scheduler import ContinuousBatchingScheduler, ServeRequest

logger = get_logger()

# Cap on hashed blocks per prompt: affinity only needs the head of the
# prompt (system prompt / template), not an unbounded hash walk.
_MAX_AFFINITY_BLOCKS = 64

# Overload-aware placement: predicted queue wait converts to load units
# at this rate, and an in-brownout replica carries a flat penalty — a
# browning-out replica should lose placement ties without being treated
# as dead.
_WAIT_MS_PER_LOAD_UNIT = 100.0
_BROWNOUT_LOAD_PENALTY = 5.0
# Load penalty while a replica's 429 backpressure window is open.
_BACKPRESSURE_LOAD_PENALTY = 10.0


class ReplicaBackpressure(Exception):
    """A replica answered 429: overloaded, not dead. The router fails
    the request over (budget permitting) without counting the replica
    toward eviction."""

    def __init__(
        self, name: str, reason: str | None, retry_after: float | None
    ) -> None:
        super().__init__(
            f"replica {name} backpressured"
            + (f" ({reason})" if reason else "")
        )
        self.replica_name = name
        self.reason = reason
        self.retry_after = retry_after


class InProcessReplica:
    """A serving replica living in this process: one scheduler + engine."""

    def __init__(self, scheduler: ContinuousBatchingScheduler, name: str) -> None:
        self.scheduler = scheduler
        self.name = name

    @property
    def engine(self):
        return self.scheduler.engine

    def submit(self, req: ServeRequest) -> None:
        self.scheduler.submit(req)

    def load(self) -> float:
        """Scalar load for placement: queued + in-flight sequences plus
        the KV pool's utilization (a nearly-full pool should lose ties
        even at equal occupancy — its next admission may have to wait)."""
        s = self.scheduler
        with s._lock:
            depth = len(s._queue)
        load = float(depth + len(s._active) + len(s._prefilling))
        if s.engine is not None:
            load += s.engine.pool.stats()["utilization"]
        ov = getattr(s, "_overload", None)
        if ov is not None:
            # Backpressure-aware placement: predicted queue wait and the
            # brownout flag push traffic toward calmer replicas.
            load += ov.predicted_wait_ms(depth) / _WAIT_MS_PER_LOAD_UNIT
            if ov.in_brownout:
                load += _BROWNOUT_LOAD_PENALTY
        return load

    def stats(self) -> dict[str, Any]:
        return self.scheduler.stats()

    def reload(
        self,
        *,
        params: Any | None = None,
        step: int | None = None,
        checkpoint: str | None = None,
    ) -> dict[str, Any]:
        if params is None:
            raise ValueError("in-process reload needs the loaded params")
        self.scheduler.hot_swap(params, step=step, checkpoint=checkpoint)
        return {"replica": self.name, "step": step, "checkpoint": checkpoint}

    def healthcheck(self) -> bool:
        thread = self.scheduler._thread
        return thread is None or thread.is_alive()

    def close(self) -> None:
        self.scheduler.close()


class HTTPReplica:
    """A remote serving replica behind ``POST /v1/generate`` (a k8s pod).

    ``submit`` is asynchronous like the scheduler's: the blocking POST
    runs on a short-lived thread that fills the request's result fields
    and sets ``done`` — the waiting handler/loadgen code is identical
    for both replica kinds. Load comes from the replica's ``/healthz``
    scheduler block, cached for ``poll_sec`` so placement doesn't pay a
    network round-trip per request.
    """

    def __init__(
        self, base_url: str, name: str | None = None, *,
        timeout_sec: float = 120.0, poll_sec: float = 2.0,
        probe_timeout_sec: float = 10.0,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.name = name or self.base_url
        self.timeout_sec = float(timeout_sec)
        self.poll_sec = float(poll_sec)
        # Health/stats probes get their own (short) timeout so a wedged
        # replica cannot stall the router's health sweep for the full
        # request timeout (router.probe_timeout_sec).
        self.probe_timeout_sec = float(probe_timeout_sec)
        self._inflight = 0
        self._lock = threading.Lock()
        self._cached_load = 0.0
        self._cached_at = 0.0
        # monotonic deadline of the replica's open 429 window; placement
        # penalizes it until then.
        self._backpressure_until = 0.0

    engine = None  # remote: the router cannot pre-validate against it

    def _get(self, path: str) -> dict[str, Any]:
        with urllib.request.urlopen(
            self.base_url + path,
            timeout=min(self.probe_timeout_sec, self.timeout_sec),
        ) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def _post(
        self,
        path: str,
        body: dict[str, Any],
        headers: dict[str, str] | None = None,
    ) -> dict[str, Any]:
        data = json.dumps(body).encode("utf-8")
        request = urllib.request.Request(
            self.base_url + path,
            data=data,
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        with urllib.request.urlopen(request, timeout=self.timeout_sec) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def perform(
        self, req: ServeRequest, *, traceparent: str | None = None
    ) -> None:
        """Blocking POST, called on the router's submit thread; raises on
        transport errors so the router can fail over. A 429 raises
        :class:`ReplicaBackpressure` (request fields untouched, so a
        failover re-perform is clean) and opens the replica's
        backpressure window. ``traceparent`` carries the router's hop
        span across the wire so the replica's spans parent under it."""
        body: dict[str, Any] = {
            "prompt_ids": [int(t) for t in req.prompt_ids],
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "seed": int(req.seed),
        }
        if req.top_k is not None:
            body["top_k"] = int(req.top_k)
        if req.top_p is not None:
            body["top_p"] = float(req.top_p)
        if req.eos_token_id is not None:
            body["eos_token_id"] = int(req.eos_token_id)
        headers: dict[str, str] = {}
        if traceparent:
            headers["traceparent"] = traceparent
        if req.rid:
            headers["X-Request-Id"] = str(req.rid)
        if req.priority:
            headers["X-Priority"] = str(req.priority)
        if req.deadline_ms is not None and req.deadline_ms > 0:
            # Propagate the REMAINING budget: time already spent in the
            # router must not be granted again by the replica.
            elapsed_ms = (
                (time.monotonic() - req.submitted_t) * 1e3
                if req.submitted_t > 0
                else 0.0
            )
            remaining = max(1.0, req.deadline_ms - elapsed_ms)
            headers["X-Deadline-Ms"] = f"{remaining:.1f}"
        try:
            out = self._post("/v1/generate", body, headers)
        except urllib.error.HTTPError as exc:
            if exc.code == 429:
                reason, retry_after = self._parse_backpressure(exc)
                with self._lock:
                    self._backpressure_until = time.monotonic() + retry_after
                raise ReplicaBackpressure(
                    self.name, reason, retry_after
                ) from exc
            raise
        finally:
            with self._lock:
                self._inflight -= 1
        now = time.monotonic()
        req.tokens = [int(t) for t in out.get("completion_ids", [])]
        req.first_token_t = now
        req.token_times = [now] * len(req.tokens)
        req.finish_reason = out.get("finish_reason", "length")
        req.finished_t = now
        req.done.set()

    @staticmethod
    def _parse_backpressure(
        exc: urllib.error.HTTPError,
    ) -> tuple[str | None, float]:
        """Reason + retry-after seconds from a 429 (header first, JSON
        body as fallback, 1s when neither parses)."""
        retry_after = 1.0
        header = exc.headers.get("Retry-After") if exc.headers else None
        if header is not None:
            try:
                retry_after = max(0.0, float(header))
            except (TypeError, ValueError):
                pass
        reason = None
        try:
            payload = json.loads(exc.read().decode("utf-8"))
            reason = payload.get("reason")
            if header is None and isinstance(
                payload.get("retry_after"), (int, float)
            ):
                retry_after = max(0.0, float(payload["retry_after"]))
        except Exception:  # noqa: BLE001 — body parse is best-effort
            pass
        return reason, retry_after

    def submit(self, req: ServeRequest) -> None:
        req.submitted_t = time.monotonic()
        req.submitted_pc = time.perf_counter()
        with self._lock:
            self._inflight += 1
        # The router calls perform() itself (failover needs the error);
        # this direct path exists for scheduler-compatible callers.
        threading.Thread(
            target=self._perform_logged, args=(req,), daemon=True
        ).start()

    def _perform_logged(self, req: ServeRequest) -> None:
        try:
            self.perform(req)
        except Exception as exc:  # noqa: BLE001 — surface on the request
            logger.warning("replica %s failed: %s", self.name, exc)
            req.error = str(exc)
            req.finish_reason = "error"
            req.finished_t = time.monotonic()
            req.done.set()

    def load(self) -> float:
        with self._lock:
            inflight = self._inflight
            backpressure_until = self._backpressure_until
        now = time.monotonic()
        if now - self._cached_at > self.poll_sec:
            try:
                sched = self._get("/healthz").get("scheduler", {})
                load = float(
                    sched.get("queue_depth", 0)
                    + sched.get("active_sequences", 0)
                    + sched.get("prefilling_sequences", 0)
                    + sched.get("kv_pool", {}).get("utilization", 0.0)
                )
                ov = sched.get("overload")
                if isinstance(ov, dict):
                    # The replica's own backpressure signal: predicted
                    # queue wait + brownout flag from /healthz.
                    load += (
                        float(ov.get("predicted_wait_ms", 0.0))
                        / _WAIT_MS_PER_LOAD_UNIT
                    )
                    if ov.get("in_brownout"):
                        load += _BROWNOUT_LOAD_PENALTY
                self._cached_load = load
                self._cached_at = now
            except Exception:  # noqa: BLE001 — health probe is best-effort
                pass
        total = self._cached_load + inflight
        if now < backpressure_until:
            # The replica 429'd recently: keep traffic off it until its
            # Retry-After window closes.
            total += _BACKPRESSURE_LOAD_PENALTY
        # In-flight submits routed here but not yet visible in the remote
        # queue stats keep bursts from all landing on one replica.
        return total

    def stats(self) -> dict[str, Any]:
        try:
            return self._get("/healthz").get("scheduler", {})
        except Exception as exc:  # noqa: BLE001
            return {"error": str(exc)}

    def reload(
        self,
        *,
        params: Any | None = None,
        step: int | None = None,
        checkpoint: str | None = None,
    ) -> dict[str, Any]:
        body: dict[str, Any] = {}
        if checkpoint is not None:
            body["checkpoint"] = checkpoint
        out = self._post("/reload", body)
        out.setdefault("replica", self.name)
        return out

    def healthcheck(self) -> bool:
        try:
            return self._get("/healthz").get("status") == "ok"
        except Exception:  # noqa: BLE001
            return False

    def close(self) -> None:
        pass


class _ReplicaState:
    """Router-side health bookkeeping for one replica."""

    def __init__(self, replica: Any) -> None:
        self.replica = replica
        self.healthy = True
        self.consecutive_failures = 0
        self.evicted_at = 0.0
        self.routed = 0
        self.failures = 0
        # Revival-probe bookkeeping: each FAILED probe doubles the wait
        # before the next one (capped), so a dead replica is not
        # re-probed on every placement call.
        self.revive_backoff = 1.0
        self.revive_probes = 0


class ReplicaRouter:
    """Load- and prefix-aware dispatch across serving replicas.

    Duck-types the scheduler surface (``submit``/``stats``/``registry``/
    ``engine``) so the HTTP server and load harness run unchanged with a
    router in the scheduler seat.
    """

    def __init__(
        self,
        replicas: Sequence[Any],
        *,
        registry: Any | None = None,
        affinity_weight: float = 4.0,
        max_affinity_entries: int = 4096,
        fail_threshold: int = 3,
        revive_sec: float = 10.0,
        block_tokens: int | None = None,
        retry_budget: int = 0,
        retry_window_sec: float = 10.0,
        timeline: Any | None = None,
        tracer: Tracer | None = None,
    ) -> None:
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.registry = registry
        # Distributed tracing: the router mints each request's root span
        # (``router/request``) and flushes kept traces to its own
        # timeline; replica hops parent under it via traceparent headers.
        self.timeline = timeline
        self.tracer = tracer if tracer is not None else (
            Tracer(timeline) if timeline is not None else None
        )
        self.affinity_weight = float(affinity_weight)
        self.max_affinity_entries = int(max_affinity_entries)
        self.fail_threshold = int(fail_threshold)
        self.revive_sec = float(revive_sec)
        # Fleet-wide failover retry budget: an overloaded fleet must not
        # be DDoS'd by its own router re-sending every 429. 0 = unlimited
        # (the pre-overload-control behavior).
        self._retry_budget = (
            RetryBudget(int(retry_budget), float(retry_window_sec))
            if retry_budget > 0
            else None
        )
        self.retry_window_sec = float(retry_window_sec)
        self.retries_rejected = 0
        self._states = [_ReplicaState(r) for r in replicas]
        if block_tokens is None:
            block_tokens = 16
            for r in replicas:
                engine = getattr(r, "engine", None)
                if engine is not None:
                    block_tokens = engine.pool.block_tokens
                    break
        self.block_tokens = int(block_tokens)
        self._lock = threading.Lock()
        # chain hash -> replica index, LRU-capped: the router's model of
        # WHERE each prefix block's K/V most recently landed.
        self._affinity: OrderedDict[str, int] = OrderedDict()
        self.requests_routed = 0
        self.affinity_routed = 0  # placements decided by a prefix match
        self.failovers = 0
        # Canary A/B split (lifecycle/controller.py): while set, a seeded
        # fraction of live traffic is steered to the canary replica and
        # the rest of the fleet never sees it in placement.
        self._canary_idx: int | None = None
        self._canary_frac = 0.0
        self._canary_rng = random.Random(0)
        self.canary_routed = 0

    # ------------------------------------------------------------ plumbing

    @property
    def replicas(self) -> list[Any]:
        return [s.replica for s in self._states]

    @property
    def policy(self) -> str:
        """Scheduler-surface compat: what the serve ready line reports."""
        return "router"

    @property
    def engine(self):
        """First healthy in-process engine — the HTTP layer's admission
        validator; None when the fleet is remote (each pod validates)."""
        for s in self._states:
            engine = getattr(s.replica, "engine", None)
            if s.healthy and engine is not None:
                return engine
        return None

    # Failed revival probes back off exponentially up to this multiple
    # of revive_sec — a permanently dead replica costs one probe per
    # _REVIVE_BACKOFF_CAP * revive_sec, not one per placement call.
    _REVIVE_BACKOFF_CAP = 16.0

    def _healthy_indices(self) -> list[int]:
        now = time.monotonic()
        out = []
        for i, s in enumerate(self._states):
            if (
                not s.healthy
                and now - s.evicted_at >= self.revive_sec * s.revive_backoff
            ):
                # Revival probe: one cheap REAL health check (HTTP
                # /healthz for remote replicas, scheduler-thread-alive
                # for in-process ones), not a request. Elapsed time
                # alone never reinstates a replica.
                s.revive_probes += 1
                if s.replica.healthcheck():
                    logger.info("router: replica %s revived", s.replica.name)
                    s.healthy = True
                    s.consecutive_failures = 0
                    s.revive_backoff = 1.0
                else:
                    # Still dead: stay evicted, restart the clock and
                    # widen the probe interval.
                    s.evicted_at = now
                    s.revive_backoff = min(
                        s.revive_backoff * 2.0, self._REVIVE_BACKOFF_CAP
                    )
                    logger.warning(
                        "router: replica %s failed revival probe %d; next "
                        "probe in %.1fs",
                        s.replica.name, s.revive_probes,
                        self.revive_sec * s.revive_backoff,
                    )
            if s.healthy:
                out.append(i)
        return out

    def _note_failure(self, idx: int, exc: Exception) -> None:
        s = self._states[idx]
        s.failures += 1
        s.consecutive_failures += 1
        logger.warning(
            "router: replica %s failure %d/%d: %s",
            s.replica.name, s.consecutive_failures, self.fail_threshold, exc,
        )
        if s.consecutive_failures >= self.fail_threshold and s.healthy:
            s.healthy = False
            s.evicted_at = time.monotonic()
            logger.warning("router: replica %s evicted", s.replica.name)

    def _note_success(self, idx: int) -> None:
        self._states[idx].consecutive_failures = 0

    # ----------------------------------------------------------- placement

    def _matched_blocks(self, hashes: list[str], idx: int) -> int:
        run = 0
        for h in hashes:
            if self._affinity.get(h) != idx:
                break
            run += 1
        return run

    def _record_affinity(self, hashes: list[str], idx: int) -> None:
        for h in hashes:
            self._affinity[h] = idx
            self._affinity.move_to_end(h)
        while len(self._affinity) > self.max_affinity_entries:
            self._affinity.popitem(last=False)

    # -------------------------------------------------------------- canary

    def set_canary(
        self, idx: int, *, traffic_frac: float = 0.0, seed: int = 0
    ) -> None:
        """Mark replica ``idx`` as the canary: a seeded ``traffic_frac``
        of live requests is steered to it; the rest of the fleet serves
        everything else (the A/B split of the promote soak window).
        With ``traffic_frac=0`` the canary is simply excluded from
        placement — only the controller's synthetic probes reach it."""
        if not 0 <= idx < len(self._states):
            raise ValueError(f"router: no replica index {idx}")
        if not 0.0 <= traffic_frac <= 1.0:
            raise ValueError("traffic_frac must be in [0, 1]")
        with self._lock:
            self._canary_idx = idx
            self._canary_frac = float(traffic_frac)
            self._canary_rng = random.Random(seed)

    def clear_canary(self) -> None:
        with self._lock:
            self._canary_idx = None
            self._canary_frac = 0.0

    @property
    def canary_index(self) -> int | None:
        return self._canary_idx

    def select(self, prompt_ids: np.ndarray) -> int:
        """Pick the replica index for a prompt (placement only, no
        dispatch — exposed for tests and dry-runs). Raises RuntimeError
        when every replica is evicted."""
        healthy = self._healthy_indices()
        canary = self._canary_idx
        if canary is not None and canary in healthy and len(healthy) > 1:
            if self._canary_frac > 0 and (
                self._canary_rng.random() < self._canary_frac
            ):
                # A/B split: this request is the canary's.
                with self._lock:
                    self.requests_routed += 1
                    self.canary_routed += 1
                    self._states[canary].routed += 1
                return canary
            healthy = [i for i in healthy if i != canary]
        if not healthy:
            raise RuntimeError("router: no healthy replicas")
        hashes = chain_hashes(
            [int(t) for t in prompt_ids[: _MAX_AFFINITY_BLOCKS * self.block_tokens]],
            self.block_tokens,
        )
        with self._lock:
            scored = []
            for i in healthy:
                matched = self._matched_blocks(hashes, i) if hashes else 0
                load = self._states[i].replica.load()
                # Affinity wins until the preferred replica is
                # ~affinity_weight*matched requests busier than a peer.
                scored.append((self.affinity_weight * matched - load, matched, i))
            score, matched, best = max(scored, key=lambda t: (t[0], -t[2]))
            self._record_affinity(hashes, best)
            self.requests_routed += 1
            if matched > 0:
                self.affinity_routed += 1
            self._states[best].routed += 1
        return best

    # ------------------------------------------------------------ dispatch

    def submit(self, req: ServeRequest) -> ServeRequest:
        t_mono = time.monotonic()
        t_pc = time.perf_counter()
        if self.tracer is not None and req.trace is None:
            req.trace = self.tracer.start(root_name="router/request")
        idx = self.select(req.prompt_ids)
        replica = self._states[idx].replica
        if req.trace is not None:
            req.trace.add_span(
                "router/place",
                t0=t_pc,
                t1=time.perf_counter(),
                replica=replica.name,
                request_id=req.request_id,
            )
        if isinstance(replica, HTTPReplica):
            # Stamp at router entry so the root span (and latency) cover
            # placement, not just the HTTP hop.
            req.submitted_t = t_mono
            req.submitted_pc = t_pc
            with replica._lock:
                replica._inflight += 1
            threading.Thread(
                target=self._perform_http,
                args=(req, idx),
                daemon=True,
            ).start()
            return req
        try:
            replica.submit(req)
            self._note_success(idx)
        except Exception as exc:  # noqa: BLE001 — failover before erroring
            self._note_failure(idx, exc)
            return self._failover(req, exclude={idx}, cause=exc)
        return req

    def _perform_http(self, req: ServeRequest, idx: int) -> None:
        replica = self._states[idx].replica
        hop_t0 = time.perf_counter()
        traceparent: str | None = None
        hop_sid: str | None = None
        if req.trace is not None:
            # Pre-allocate the hop span id: the replica needs it in the
            # traceparent header BEFORE the hop completes so its own
            # spans can parent under this dispatch.
            hop = TraceContext(
                req.trace.trace_id,
                new_span_id(),
                req.trace.root_span_id,
                req.trace.ctx.forced,
            )
            traceparent = hop.to_traceparent()
            hop_sid = hop.span_id
        try:
            replica.perform(req, traceparent=traceparent)
            self._note_success(idx)
            self._hop_done(req, hop_t0, hop_sid, replica.name)
            self._finish_trace(req)
        except ReplicaBackpressure as exc:
            # 429 = overloaded, not dead: no eviction strike; the replica
            # already opened its backpressure window for placement.
            logger.warning(
                "router: replica %s backpressured request %s (%s)",
                replica.name, req.request_id, exc.reason,
            )
            self._hop_done(
                req, hop_t0, hop_sid, replica.name,
                error=f"backpressure:{exc.reason or 'overloaded'}",
            )
            try:
                self._failover(req, exclude={idx}, cause=exc)
            except Exception as exc2:  # noqa: BLE001 — out of replicas
                req.error = str(exc2)
                req.finish_reason = "error"
                req.finished_t = time.monotonic()
                self._finish_trace(req)
                req.done.set()
        except Exception as exc:  # noqa: BLE001 — transport error: failover
            self._note_failure(idx, exc)
            self._hop_done(
                req, hop_t0, hop_sid, replica.name, error=str(exc)
            )
            try:
                self._failover(req, exclude={idx}, cause=exc)
            except Exception as exc2:  # noqa: BLE001 — out of replicas
                req.error = str(exc2)
                req.finish_reason = "error"
                req.finished_t = time.monotonic()
                self._finish_trace(req)
                req.done.set()

    def _hop_done(
        self,
        req: ServeRequest,
        t0: float,
        span_id: str | None,
        replica_name: str,
        error: str | None = None,
    ) -> None:
        """Buffer the router→replica HTTP hop span (failed hops too — a
        trace that failed over shows every attempt, not just the winner)."""
        if req.trace is None:
            return
        args: dict[str, Any] = {"replica": replica_name}
        if error is not None:
            args["error"] = error
        req.trace.add_span(
            "router/http_dispatch",
            t0=t0,
            t1=time.perf_counter(),
            span_id=span_id,
            **args,
        )

    def _finish_trace(self, req: ServeRequest) -> None:
        """Resolve the request's trace on the router's completion path
        (HTTP hops only — in-process replicas finish via their
        scheduler; Tracer.finish is idempotent either way)."""
        if self.tracer is None or req.trace is None:
            return
        t1 = time.perf_counter()
        root_args: dict[str, Any] = {
            "request_id": req.request_id,
            "finish_reason": req.finish_reason,
        }
        if req.rid:
            root_args["rid"] = req.rid
        if req.ttft_ms is not None:
            root_args["ttft_ms"] = round(req.ttft_ms, 3)
        self.tracer.finish(
            req.trace,
            t0=req.submitted_pc if req.submitted_pc > 0.0 else t1,
            t1=t1,
            errored=req.error is not None or req.finish_reason == "error",
            **root_args,
        )

    def _reject_retry(self, req: ServeRequest, cause: Exception) -> None:
        """Retry budget exhausted: finish the request as rejected (fast,
        honest 429 to the client) instead of re-hammering the fleet."""
        with self._lock:
            self.retries_rejected += 1
        req.reject_reason = REASON_RETRY_BUDGET
        req.retry_after_sec = (
            getattr(cause, "retry_after", None) or self.retry_window_sec
        )
        req.finish_reason = "rejected"
        req.finished_t = time.monotonic()
        if self.registry is not None:
            self.registry.inc(rejected_counter(REASON_RETRY_BUDGET))
        logger.warning(
            "router: retry budget exhausted; rejecting request %s (%s)",
            req.request_id, cause,
        )
        if req.trace is not None:
            req.trace.note(reject_reason=REASON_RETRY_BUDGET)
        self._finish_trace(req)
        req.done.set()

    def _failover(
        self, req: ServeRequest, *, exclude: set[int], cause: Exception
    ) -> ServeRequest:
        if self._retry_budget is not None and not self._retry_budget.try_spend():
            self._reject_retry(req, cause)
            return req
        healthy = [i for i in self._healthy_indices() if i not in exclude]
        if self._canary_idx is not None and len(healthy) > 1:
            # Never fail live traffic over onto an unproven canary while
            # a proven replica remains.
            healthy = [i for i in healthy if i != self._canary_idx] or healthy
        if not healthy:
            raise RuntimeError(
                f"router: no healthy replica left for failover ({cause})"
            )
        idx = min(healthy, key=lambda i: self._states[i].replica.load())
        with self._lock:
            self.failovers += 1
            self._states[idx].routed += 1
        replica = self._states[idx].replica
        logger.warning(
            "router: failing request %s over to %s", req.request_id,
            replica.name,
        )
        if req.trace is not None:
            # A failed-over request is always trace-worthy; forcing also
            # propagates the keep decision to the retry hop's replica.
            req.trace.note(failover=True)
            req.trace.force()
            req.trace.add_event(
                "router/failover",
                t=time.perf_counter(),
                replica=replica.name,
                cause=str(cause),
            )
        if isinstance(replica, HTTPReplica):
            with replica._lock:
                replica._inflight += 1
            self._perform_http(req, idx)
            return req
        replica.submit(req)
        self._note_success(idx)
        return req

    # ------------------------------------------------------------ hot swap

    def rolling_reload(
        self,
        *,
        params: Any | None = None,
        step: int | None = None,
        checkpoint: str | None = None,
    ) -> list[dict[str, Any]]:
        """Apply a checkpoint swap ONE replica at a time. Each replica's
        own hot-swap contract (in-flight finishes on old params, new
        admissions on new) makes the roll zero-downtime: at every moment
        every replica is serving, some on the old checkpoint, some on
        the new — exactly a k8s rolling update, without restarting
        anything or dropping a request."""
        results = []
        for idx, s in enumerate(self._states):
            if not s.healthy:
                results.append(
                    {"replica": s.replica.name, "skipped": "evicted"}
                )
                continue
            try:
                results.append(
                    s.replica.reload(
                        params=params, step=step, checkpoint=checkpoint
                    )
                )
                self._note_success(idx)
            except Exception as exc:  # noqa: BLE001 — roll on; report
                self._note_failure(idx, exc)
                results.append({"replica": s.replica.name, "error": str(exc)})
        return results

    def reload_replica(
        self,
        idx: int,
        *,
        params: Any | None = None,
        step: int | None = None,
        checkpoint: str | None = None,
    ) -> dict[str, Any]:
        """Hot-swap ONE replica (the canary path: swap a candidate in,
        or roll it back to the promoted baseline). Raises on failure —
        the caller decides whether that aborts a canary or triggers a
        fleet rollback."""
        if not 0 <= idx < len(self._states):
            raise ValueError(f"router: no replica index {idx}")
        s = self._states[idx]
        result = s.replica.reload(params=params, step=step, checkpoint=checkpoint)
        self._note_success(idx)
        return result

    # ----------------------------------------------------------- telemetry

    def stats(self) -> dict[str, Any]:
        """Fleet stats in the scheduler's shape (the load harness reads
        occupancy/policy keys) + a ``router`` block with placement and
        per-replica detail."""
        per_replica = []
        agg = {
            "peak_batch_occupancy": 0,
            "mean_batch_occupancy": 0.0,
            "max_batch_slots": 0,
            "queue_depth": 0,
            "active_sequences": 0,
            "requests_finished": 0,
            "tokens_generated": 0,
        }
        policy = None
        prefix_hits = prefix_queries = prefix_hit_queries = prefix_tokens = 0
        ov_rejected = ov_shed = ov_brownout = 0
        fleet_steps: set[Any] = set()
        for i, s in enumerate(self._states):
            rs = s.replica.stats() if s.healthy else {"evicted": True}
            policy = policy or rs.get("policy")
            ov = rs.get("overload")
            if isinstance(ov, dict):
                ov_rejected += int(ov.get("rejected_total", 0))
                ov_shed += int(ov.get("shed", 0))
                ov_brownout += int(bool(ov.get("in_brownout")))
            for k in agg:
                v = rs.get(k)
                if isinstance(v, (int, float)):
                    agg[k] += v
            pool = rs.get("kv_pool", {})
            prefix_hits += pool.get("prefix_hits", 0)
            prefix_queries += pool.get("prefix_queries", 0)
            prefix_hit_queries += pool.get("prefix_hit_queries", 0)
            prefix_tokens += pool.get("prefix_tokens_reused", 0)
            # Param identity: which checkpoint this replica is ADMITTING
            # on right now. step is comparable fleet-wide; epoch is the
            # replica-local swap counter.
            params_blk = rs.get("params") or {}
            param_step = params_blk.get("step")
            param_epoch = params_blk.get("epoch")
            if s.healthy and (param_step is not None or param_epoch is not None):
                fleet_steps.add(
                    param_step if param_step is not None
                    else f"epoch:{i}:{param_epoch}"
                )
            per_replica.append(
                {
                    "name": s.replica.name,
                    "healthy": s.healthy,
                    "routed": s.routed,
                    "failures": s.failures,
                    "revive_probes": s.revive_probes,
                    "load": s.replica.load() if s.healthy else None,
                    "param_epoch": param_epoch,
                    "param_step": param_step,
                    "stats": rs,
                }
            )
        out: dict[str, Any] = dict(agg)
        out["policy"] = policy or "paged"
        out["mean_batch_occupancy"] = round(agg["mean_batch_occupancy"], 4)
        out["router"] = {
            "replicas": per_replica,
            "replicas_healthy": sum(1 for s in self._states if s.healthy),
            "requests_routed": self.requests_routed,
            "affinity_routed": self.affinity_routed,
            "affinity_entries": len(self._affinity),
            "failovers": self.failovers,
            "affinity_weight": self.affinity_weight,
            # Distinct param steps healthy replicas are serving, minus
            # one: 0 = a converged fleet, >0 = a mixed-epoch fleet (mid
            # rollout, or a partially failed one — the promote
            # controller's fleet-rollback trigger).
            "epoch_divergence": max(0, len(fleet_steps) - 1),
            "canary": {
                "index": self._canary_idx,
                "traffic_frac": self._canary_frac,
                "routed": self.canary_routed,
            },
            "overload": {
                # Fleet-wide overload picture: summed replica counters
                # plus the router's own retry-budget state.
                "rejected_total": ov_rejected,
                "shed": ov_shed,
                "replicas_in_brownout": ov_brownout,
                "retries_rejected": self.retries_rejected,
                "retry_budget_remaining": (
                    self._retry_budget.remaining()
                    if self._retry_budget is not None
                    else None
                ),
            },
            "tracing": (
                self.tracer.stats() if self.tracer is not None else None
            ),
            "fleet_prefix": {
                "hits": prefix_hits,
                "queries": prefix_queries,
                "hit_queries": prefix_hit_queries,
                "tokens_reused": prefix_tokens,
                # hits counts reused BLOCKS (can exceed queries); the rate
                # is the fraction of admissions that reused anything.
                "hit_rate": round(prefix_hit_queries / max(1, prefix_queries), 4),
            },
        }
        self._publish_metrics(out)
        return out

    def _publish_metrics(self, stats: dict[str, Any]) -> None:
        if self.registry is None:
            return
        r = stats["router"]
        gauges = {
            "router/replicas_healthy": float(r["replicas_healthy"]),
            "router/requests_routed": float(r["requests_routed"]),
            "router/affinity_routed": float(r["affinity_routed"]),
            "router/affinity_entries": float(r["affinity_entries"]),
            "router/failovers": float(r["failovers"]),
            "router/fleet_prefix_hit_rate": float(
                r["fleet_prefix"]["hit_rate"]
            ),
            "router/queue_depth": float(stats["queue_depth"]),
            "router/active_sequences": float(stats["active_sequences"]),
            "router/epoch_divergence": float(r["epoch_divergence"]),
            "router/canary_routed": float(r["canary"]["routed"]),
            "router/rejected_total": float(r["overload"]["rejected_total"]),
            "router/shed_total": float(r["overload"]["shed"]),
            "router/replicas_in_brownout": float(
                r["overload"]["replicas_in_brownout"]
            ),
            "router/retries_rejected": float(
                r["overload"]["retries_rejected"]
            ),
        }
        if r["overload"]["retry_budget_remaining"] is not None:
            gauges["router/retry_budget_remaining"] = float(
                r["overload"]["retry_budget_remaining"]
            )
        for i, rep in enumerate(r["replicas"]):
            gauges[f"router/replica{i}_healthy"] = float(bool(rep["healthy"]))
            gauges[f"router/replica{i}_routed"] = float(rep["routed"])
            if rep["load"] is not None:
                gauges[f"router/replica{i}_load"] = float(rep["load"])
            if rep["param_epoch"] is not None:
                gauges[f"router/replica{i}_param_epoch"] = float(
                    rep["param_epoch"]
                )
            if rep["param_step"] is not None:
                gauges[f"router/replica{i}_param_step"] = float(
                    rep["param_step"]
                )
            occ = rep["stats"].get("active_sequences")
            if isinstance(occ, (int, float)):
                gauges[f"router/replica{i}_active_sequences"] = float(occ)
        self.registry.publish(gauges)

    # ----------------------------------------------------------- lifecycle

    def close(self, timeout: float = 30.0) -> None:
        for s in self._states:
            try:
                s.replica.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        if self.timeline is not None:
            try:
                self.timeline.flush()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass

    def start(self) -> "ReplicaRouter":
        """Scheduler-API compat: in-process replicas are started by their
        builder; remote ones are already running."""
        return self


def resolve_backends(discover: str) -> list[str]:
    """DNS-resolve ``host:port`` into one base URL per A record — the
    k8s headless-Service discovery path (the Service name resolves to
    every ready pod IP). Falls back to the literal host on resolver
    failure, so a plain hostname keeps working."""
    import socket

    host, _, port = discover.partition(":")
    port = port or "8000"
    try:
        infos = socket.getaddrinfo(host, int(port), proto=socket.IPPROTO_TCP)
        addrs = sorted({info[4][0] for info in infos})
    except OSError:
        addrs = [host]
    return [f"http://{a}:{port}" for a in addrs]


__all__ = [
    "HTTPReplica",
    "InProcessReplica",
    "ReplicaBackpressure",
    "ReplicaRouter",
    "resolve_backends",
]
