"""Open-loop load generator + SLO aggregation for the serving stack.

Throughput claims must be measured, not asserted (ROADMAP item 1): this
module drives the continuous-batching scheduler with a SEEDED open-loop
arrival process — requests arrive on a Poisson clock that does NOT wait
for completions, the arrival model under which tail latency means
anything (a closed loop self-throttles and hides queueing collapse) —
and aggregates the scheduler's server-side measurements into the SLO
numbers operators page on:

* **TTFT** (submit → first token) p50/p95/p99,
* **per-token latency** (inter-token gaps) p50/p95/p99,
* tokens/s and requests/s over the run,
* batch-occupancy and KV-pool peaks, and the compile-budget accounting.

The result feeds three sinks: the ``serving`` block in
``report.json``/``report.md`` (telemetry/report.py), ``llmtrain_serve_*``
Prometheus gauges via the MetricsRegistry, and the ``serve-bench`` CLI's
stdout summary. Everything is deterministic per (seed, rate, request
count) except wall-clock timing itself.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from ..telemetry.stats import percentiles as _stats_percentiles
from .scheduler import ContinuousBatchingScheduler, ServeRequest


def percentiles(samples: list[float]) -> dict[str, float | None]:
    """p50/p95/p99/mean/max by nearest-rank on the sorted samples —
    thin back-compat wrapper over the shared ``telemetry.stats`` helper
    (same math as /metrics gauges and ``llmtrain trace summary``), keeping
    this module's explicit-None shape for empty sample sets."""
    if not samples:
        return {"p50": None, "p95": None, "p99": None, "mean": None, "max": None}
    return _stats_percentiles([float(v) for v in samples])


def build_requests(
    *,
    num_requests: int,
    seed: int,
    vocab_size: int,
    prompt_tokens_min: int,
    prompt_tokens_max: int,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_token_id: int | None = None,
    shared_prefix_tokens: int = 0,
    shared_prefix_count: int = 1,
    long_fraction: float = 0.0,
    long_prompt_tokens: int = 0,
    deadline_ms: float | None = None,
    batch_fraction: float = 0.0,
) -> list[ServeRequest]:
    """Seeded request population: prompt lengths/ids and per-request rng
    seeds all derive from one numpy Generator, so a run is replayable —
    the property the bitwise parity check against ``generate()`` needs.

    Two mix knobs shape the population for the fleet features:

    * ``shared_prefix_tokens`` > 0 prepends one of
      ``shared_prefix_count`` fixed "system prompts" (seeded, chosen per
      request) — the workload where shared-prefix KV reuse and the
      router's prefix-affinity placement pay off;
    * ``long_fraction`` > 0 makes that fraction of requests use
      ``long_prompt_tokens``-token prompts (the rest stay in the
      min..max band) — the bimodal long/short mix chunked prefill
      exists for.

    Overload knobs: ``deadline_ms`` stamps every request with a latency
    budget (the admission controller's rejection signal), and
    ``batch_fraction`` > 0 marks that seeded fraction of requests
    ``priority="batch"`` — the mixed-class workload the weighted dequeue
    exists for. Both draw no extra rng when unused, so pre-existing
    seeded populations replay identically.
    """
    rng = np.random.default_rng(seed)
    prefixes = [
        rng.integers(0, vocab_size, size=shared_prefix_tokens, dtype=np.int64)
        .astype(np.int32)
        for _ in range(shared_prefix_count if shared_prefix_tokens > 0 else 0)
    ]
    reqs: list[ServeRequest] = []
    for i in range(num_requests):
        if long_fraction > 0.0 and rng.random() < long_fraction:
            tp = int(long_prompt_tokens)
        else:
            tp = int(rng.integers(prompt_tokens_min, prompt_tokens_max + 1))
        prompt = rng.integers(0, vocab_size, size=tp, dtype=np.int64).astype(
            np.int32
        )
        if prefixes:
            prefix = prefixes[int(rng.integers(0, len(prefixes)))]
            prompt = np.concatenate([prefix, prompt]).astype(np.int32)
        priority = "interactive"
        if batch_fraction > 0.0 and rng.random() < batch_fraction:
            priority = "batch"
        reqs.append(
            ServeRequest(
                prompt_ids=prompt,
                max_new_tokens=max_new_tokens,
                temperature=temperature,
                top_k=top_k,
                top_p=top_p,
                seed=int(rng.integers(0, 2**31 - 1)),
                eos_token_id=eos_token_id,
                deadline_ms=deadline_ms,
                priority=priority,
                rid=f"lg-{seed}-{i}",
            )
        )
    return reqs


def run_loadgen(
    scheduler: ContinuousBatchingScheduler,
    requests: list[ServeRequest],
    *,
    rate_rps: float,
    seed: int,
    timeout_sec: float = 300.0,
    arrival: str = "poisson",
    burst_factor: float = 10.0,
) -> dict[str, Any]:
    """Submit ``requests`` on a seeded open-loop arrival clock and block
    until every one completes (or ``timeout_sec`` lapses); returns the
    ``serving`` report block. The scheduler must already be running
    (``scheduler.start()``).

    ``arrival="poisson"`` is the steady open-loop process;
    ``arrival="burst"`` keeps the head and tail 20% of requests at
    ``rate_rps`` but drives the middle 60% at ``rate_rps *
    burst_factor`` — the seeded overload drill (calm → 10× burst → calm)
    that exercises admission control, shedding, and brownout hysteresis
    entry AND exit."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if arrival not in ("poisson", "burst"):
        raise ValueError(f"unknown arrival process {arrival!r}")
    if burst_factor <= 0:
        raise ValueError(f"burst_factor must be > 0, got {burst_factor}")
    arrival_rng = np.random.default_rng(seed ^ 0x5EED)
    if arrival == "burst":
        n = len(requests)
        rates = np.full(n, rate_rps)
        lo, hi = int(n * 0.2), int(n * 0.8)
        rates[lo:hi] = rate_rps * burst_factor
        # Unit-rate exponential gaps scaled per request: the SAME seeded
        # gap stream as the poisson process, squeezed where the burst is.
        offsets = np.cumsum(arrival_rng.exponential(1.0, n) / rates)
    else:
        offsets = np.cumsum(
            arrival_rng.exponential(1.0 / rate_rps, len(requests))
        )

    t0 = time.monotonic()
    for req, offset in zip(requests, offsets):
        delay = (t0 + offset) - time.monotonic()
        if delay > 0:
            # Open loop: the sleep tracks the ARRIVAL clock, never the
            # completion of earlier requests.
            time.sleep(delay)
        scheduler.submit(req)

    deadline = time.monotonic() + timeout_sec
    for req in requests:
        if not req.done.wait(timeout=max(0.0, deadline - time.monotonic())):
            req.abandon()  # shed: don't keep decoding for a lapsed run
    wall_sec = time.monotonic() - t0

    # Classify from FINAL state, after the scheduler has either retired
    # or shed every abandoned request — a request finishing in the window
    # between its lapsed wait() and the next shed check is a completion,
    # not a timeout (it must not be double-counted as both and fail the
    # bench run).
    for req in requests:
        req.done.wait(timeout=30.0)
    completed = [r for r in requests if r.finish_reason in ("eos", "length")]
    failed = [r for r in requests if r.finish_reason == "error"]
    # Overload-control outcomes: rejected at submit (fast 429) vs shed
    # from the queue past-deadline. Neither is a failure — they are the
    # system degrading AS DESIGNED; serve-bench bounds their fraction
    # separately (--max-rejected-frac).
    rejected = [r for r in requests if r.finish_reason == "rejected"]
    shed = [r for r in requests if r.finish_reason == "shed"]
    incomplete = (
        len(requests)
        - len(completed)
        - len(failed)
        - len(rejected)
        - len(shed)
    )
    ttft = [r.ttft_ms for r in completed if r.ttft_ms is not None]
    per_token: list[float] = []
    for r in completed:
        for a, b in zip(r.token_times, r.token_times[1:]):
            per_token.append((b - a) * 1e3)
    new_tokens = sum(len(r.tokens) for r in completed)

    stats = scheduler.stats()
    arrival_block: dict[str, Any] = {
        "process": f"{arrival}-open-loop",
        "rate_rps": rate_rps,
        "seed": seed,
    }
    if arrival == "burst":
        arrival_block["burst_factor"] = burst_factor
    block: dict[str, Any] = {
        "arrival": arrival_block,
        "requests": {
            "submitted": len(requests),
            "completed": len(completed),
            "failed": len(failed),
            "rejected": len(rejected),
            "shed": len(shed),
            "timed_out": incomplete,
        },
        "slo": {
            "ttft_ms": percentiles(ttft),
            "per_token_ms": percentiles(per_token),
        },
        "throughput": {
            "wall_sec": round(wall_sec, 3),
            "new_tokens": new_tokens,
            "tokens_per_sec": round(new_tokens / wall_sec, 3) if wall_sec else None,
            "requests_per_sec": (
                round(len(completed) / wall_sec, 3) if wall_sec else None
            ),
        },
        "occupancy": {
            "peak": stats["peak_batch_occupancy"],
            "mean": stats["mean_batch_occupancy"],
            "max_batch_slots": stats["max_batch_slots"],
        },
        "policy": stats["policy"],
    }
    if "kv_pool" in stats:
        block["kv_pool"] = stats["kv_pool"]
        pool = stats["kv_pool"]
        if "prefix_hit_rate" in pool:
            # Shared-prefix reuse: blocks bound from cache instead of
            # re-prefilled — the serving-block gain the bench asserts on.
            block["prefix_cache"] = {
                "hits": pool["prefix_hits"],
                "queries": pool["prefix_queries"],
                "hit_rate": pool["prefix_hit_rate"],
                "tokens_reused": pool["prefix_tokens_reused"],
                "evictions": pool["prefix_evictions"],
                "cow_copies": pool["cow_copies"],
            }
    if "compile" in stats:
        block["compile"] = stats["compile"]
    if "params" in stats:
        block["params"] = stats["params"]
    if "router" in stats:
        # Fleet view: placement counters, per-replica occupancy/health,
        # and the fleet-wide prefix hit rate.
        r = stats["router"]
        block["router"] = {
            "replicas_healthy": r["replicas_healthy"],
            "requests_routed": r["requests_routed"],
            "affinity_routed": r["affinity_routed"],
            "failovers": r["failovers"],
            "fleet_prefix": r["fleet_prefix"],
            "replicas": [
                {
                    "name": rep["name"],
                    "healthy": rep["healthy"],
                    "routed": rep["routed"],
                    "peak_batch_occupancy": rep["stats"].get(
                        "peak_batch_occupancy"
                    ),
                    "requests_finished": rep["stats"].get("requests_finished"),
                    "prefix_hit_rate": rep["stats"]
                    .get("kv_pool", {})
                    .get("prefix_hit_rate"),
                }
                for rep in r["replicas"]
            ],
        }
        block["prefix_cache"] = r["fleet_prefix"]

    if rejected or shed or "overload" in stats:
        # Overload-control outcomes, gateable like parity: the reason
        # taxonomy, how FAST the rejections were (a slow rejection is a
        # failed fast-fail), and the controller's own counters.
        by_reason: dict[str, int] = {}
        for r in rejected + shed:
            key = r.reject_reason or "unknown"
            by_reason[key] = by_reason.get(key, 0) + 1
        rejection_latency = [
            (r.finished_t - r.submitted_t) * 1e3
            for r in rejected + shed
            if r.finished_t > 0 and r.submitted_t > 0
        ]
        overload_block: dict[str, Any] = {
            "rejected": len(rejected),
            "shed": len(shed),
            "rejected_by_reason": by_reason,
            "rejection_latency_ms": percentiles(rejection_latency),
        }
        controller = stats.get("overload") or stats.get("router", {}).get(
            "overload"
        )
        if controller is not None:
            overload_block["controller"] = controller
        block["overload"] = overload_block

    registry = scheduler.registry
    if registry is not None:
        for name, stat in (("ttft_ms", ttft), ("per_token_ms", per_token)):
            pct = percentiles(stat)
            for q in ("p50", "p95", "p99"):
                if pct[q] is not None:
                    registry.publish({f"serve/{name}_{q}": pct[q]})
        if block["throughput"]["tokens_per_sec"] is not None:
            registry.publish(
                {"serve/tokens_per_sec": block["throughput"]["tokens_per_sec"]}
            )
    return block


__all__ = ["build_requests", "percentiles", "run_loadgen"]
