"""Continuous-batching inference subsystem (docs/serving.md).

Package layout (promoted from the original single-module ``serving.py``,
whose public names — ``ServerState``, ``make_server``, and the tested
``_handle_generate_request`` — keep importing from here):

* :mod:`~.http` — the stdlib HTTP surface (healthz / metrics / generate),
  lock-protected cross-request stats;
* :mod:`~.paged_kv` — the paged KV-cache block pool: free-list
  allocator, admission-time budget reservation, per-sequence block
  tables;
* :mod:`~.engine` — bucketed jitted prefill/decode steps over the pool,
  per-row generate()-exact sampling, compile-budget accounting;
* :mod:`~.scheduler` — continuous (in-flight) batching: admission queue,
  per-step join/evict, speculative decoding as a first-class policy,
  ``serve/*`` metrics;
* :mod:`~.loadgen` — seeded open-loop arrival harness emitting the
  p50/p95/p99 TTFT + per-token SLO block for report.json / Prometheus;
* :mod:`~.router` — the fleet tier: prefix-cache-aware + load-aware
  dispatch across N in-process or HTTP replicas, health/eviction/
  failover, rolling zero-downtime checkpoint reloads;
* :mod:`~.overload` — SLO-aware overload control: bounded deadline-aware
  admission, priority classes with token buckets, load shedding,
  brownout with hysteresis, and the router's retry budget.
"""

from .engine import PagedDecodeEngine, bucket_for
from .http import ServerState, ServerStats, _handle_generate_request, make_server
from .loadgen import build_requests, percentiles, run_loadgen
from .overload import (
    REJECT_REASONS,
    Brownout,
    ClientRateGate,
    EwmaWaitEstimator,
    OverloadController,
    RetryBudget,
    TokenBucket,
    WeightedClassQueue,
    rejected_counter,
)
from .paged_kv import NULL_BLOCK, BlockTable, PagedKVPool, PrefixMatch, chain_hashes
from .router import (
    HTTPReplica,
    InProcessReplica,
    ReplicaBackpressure,
    ReplicaRouter,
    resolve_backends,
)
from .scheduler import ContinuousBatchingScheduler, ServeRequest

__all__ = [
    "NULL_BLOCK",
    "REJECT_REASONS",
    "BlockTable",
    "Brownout",
    "ClientRateGate",
    "ContinuousBatchingScheduler",
    "EwmaWaitEstimator",
    "HTTPReplica",
    "InProcessReplica",
    "OverloadController",
    "ReplicaBackpressure",
    "RetryBudget",
    "TokenBucket",
    "WeightedClassQueue",
    "PagedDecodeEngine",
    "PagedKVPool",
    "PrefixMatch",
    "ReplicaRouter",
    "ServeRequest",
    "ServerState",
    "ServerStats",
    "bucket_for",
    "build_requests",
    "chain_hashes",
    "make_server",
    "percentiles",
    "rejected_counter",
    "resolve_backends",
    "run_loadgen",
]
