"""Model-adapter registry.

Parity target: reference ``src/llmtrain/registry/models.py`` — name→class
dict, duplicate registration raises listing available names (:32-37), unknown
lookup raises listing available names (:46-48).
"""

from __future__ import annotations

from typing import Callable, TypeVar

from ..models.base import ModelAdapter


class RegistryError(Exception):
    """Raised on duplicate registration or unknown lookup."""


_MODEL_ADAPTERS: dict[str, type[ModelAdapter]] = {}

T = TypeVar("T", bound=type[ModelAdapter])


def register_model(name: str) -> Callable[[T], T]:
    def decorator(cls: T) -> T:
        if name in _MODEL_ADAPTERS:
            raise RegistryError(
                f"Model adapter {name!r} is already registered. "
                f"Available: {sorted(_MODEL_ADAPTERS)}"
            )
        _MODEL_ADAPTERS[name] = cls
        return cls

    return decorator


def get_model_adapter(name: str) -> type[ModelAdapter]:
    try:
        return _MODEL_ADAPTERS[name]
    except KeyError:
        raise RegistryError(
            f"Unknown model adapter {name!r}. Available: {sorted(_MODEL_ADAPTERS)}"
        ) from None


def available_model_adapters() -> list[str]:
    return sorted(_MODEL_ADAPTERS)
