"""Data-module registry (twin of the model registry).

Parity target: reference ``src/llmtrain/registry/data.py``.
"""

from __future__ import annotations

from typing import Callable, TypeVar

from ..data.base import DataModule
from .models import RegistryError

_DATA_MODULES: dict[str, type[DataModule]] = {}

T = TypeVar("T", bound=type[DataModule])


def register_data_module(name: str) -> Callable[[T], T]:
    def decorator(cls: T) -> T:
        if name in _DATA_MODULES:
            raise RegistryError(
                f"Data module {name!r} is already registered. Available: {sorted(_DATA_MODULES)}"
            )
        _DATA_MODULES[name] = cls
        return cls

    return decorator


def get_data_module(name: str) -> type[DataModule]:
    try:
        return _DATA_MODULES[name]
    except KeyError:
        raise RegistryError(
            f"Unknown data module {name!r}. Available: {sorted(_DATA_MODULES)}"
        ) from None


def available_data_modules() -> list[str]:
    return sorted(_DATA_MODULES)
