"""Plugin registries with deterministic initialization.

Parity target: reference ``src/llmtrain/registry/__init__.py`` — registries
are populated by a fixed import list (not entry-point discovery), each plugin
module self-registering via decorator at import time (:7-20).
"""

from __future__ import annotations

import importlib

from .data import available_data_modules, get_data_module, register_data_module
from .models import (
    RegistryError,
    available_model_adapters,
    get_model_adapter,
    register_model,
)

_PLUGIN_MODULES = (
    "llmtrain_tpu.models.dummy_gpt",
    "llmtrain_tpu.models.gpt",
    "llmtrain_tpu.models.gpt_moe",
    "llmtrain_tpu.models.gpt_pipeline",
    "llmtrain_tpu.models.llama",
    "llmtrain_tpu.models.qwen2",
    "llmtrain_tpu.models.gemma",
    "llmtrain_tpu.data.dummy_text",
    "llmtrain_tpu.data.hf_text",
    "llmtrain_tpu.data.local_text",
    "llmtrain_tpu.data.mixed_text",
)


def initialize_registries() -> None:
    """Import every built-in plugin module exactly once."""
    for module in _PLUGIN_MODULES:
        importlib.import_module(module)


__all__ = [
    "RegistryError",
    "available_data_modules",
    "available_model_adapters",
    "get_data_module",
    "get_model_adapter",
    "initialize_registries",
    "register_data_module",
    "register_model",
]
