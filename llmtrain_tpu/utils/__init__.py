"""Run plumbing: run ids, run dirs, metadata, summaries, logging."""

from .logging import JsonFormatter, configure_logging, get_logger
from .metadata import distributed_env_snapshot, generate_meta, write_meta_json
from .run_dir import create_run_directory, write_resolved_config
from .run_id import generate_run_id, slugify_run_name
from .summary import format_run_summary

__all__ = [
    "JsonFormatter",
    "configure_logging",
    "create_run_directory",
    "distributed_env_snapshot",
    "format_run_summary",
    "generate_meta",
    "generate_run_id",
    "get_logger",
    "slugify_run_name",
    "write_meta_json",
    "write_resolved_config",
]
