"""Dual-format (JSON dict / indented text) run summaries.

Parity target: reference ``src/llmtrain/utils/summary.py`` — echoes every
config section plus the distributed env snapshot (summary.py:13-15,34-91),
appends dry-run resolution or training results (summary.py:92-118), and
renders a ``Planned run:`` text block (summary.py:199-217).
"""

from __future__ import annotations

from typing import Any

from ..config.schemas import RunConfig
from .metadata import distributed_env_snapshot


def format_run_summary(
    cfg: RunConfig,
    *,
    run_id: str,
    run_dir: str | None,
    dry_run: bool = False,
    dry_run_result: Any | None = None,
    train_result: Any | None = None,
    as_json: bool = True,
) -> dict[str, Any] | str:
    """Build the run summary as a JSON-able dict or a human-readable string."""
    summary: dict[str, Any] = {
        "run_id": run_id,
        "run_dir": run_dir,
        "dry_run": dry_run,
        "run": cfg.run.model_dump(),
        "model": cfg.model.model_dump(),
        "data": cfg.data.model_dump(),
        "trainer": cfg.trainer.model_dump(),
        "distributed": cfg.distributed.model_dump(),
        "resilience": cfg.resilience.model_dump(),
        "telemetry": cfg.telemetry.model_dump(),
        "mlflow": cfg.mlflow.model_dump(),
        "logging": cfg.logging.model_dump(),
        "output": cfg.output.model_dump(),
        "distributed_env": distributed_env_snapshot(),
    }

    if dry_run_result is not None:
        summary["dry_run_resolution"] = {
            "model_adapter": dry_run_result.model_adapter,
            "data_module": dry_run_result.data_module,
            "steps_executed": dry_run_result.steps_executed,
        }

    if train_result is not None:
        summary["train_result"] = {
            "final_step": train_result.final_step,
            "final_loss": train_result.final_loss,
            "final_val_loss": train_result.final_val_loss,
            "first_step_loss": train_result.first_step_loss,
            "total_tokens": train_result.total_tokens,
            "total_time": train_result.total_time,
            "peak_memory": train_result.peak_memory,
            "parameter_count": train_result.parameter_count,
            "trainable_parameter_count": train_result.trainable_parameter_count,
            "val_metrics": dict(train_result.val_metrics or {}),
            "resumed_from_step": train_result.resumed_from_step,
            "preempted": getattr(train_result, "preempted", False),
            "rollbacks": getattr(train_result, "rollbacks", 0),
        }

    if as_json:
        return summary
    return _render_text(summary)


def _render_text(summary: dict[str, Any]) -> str:
    lines: list[str] = ["Planned run:" if summary["dry_run"] else "Run summary:"]
    lines.append(f"  run_id: {summary['run_id']}")
    lines.append(f"  run_dir: {summary['run_dir']}")
    for section in (
        "run",
        "model",
        "data",
        "trainer",
        "distributed",
        "resilience",
        "telemetry",
        "mlflow",
        "logging",
        "output",
    ):
        lines.append(f"  {section}:")
        _render_mapping(lines, summary[section], indent=2)
    env = summary.get("distributed_env") or {}
    if env:
        lines.append("  distributed_env:")
        _render_mapping(lines, env, indent=2)
    if "dry_run_resolution" in summary:
        lines.append("  dry_run_resolution:")
        _render_mapping(lines, summary["dry_run_resolution"], indent=2)
    if "train_result" in summary:
        lines.append("  train_result:")
        _render_mapping(lines, summary["train_result"], indent=2)
    return "\n".join(lines)


def _render_mapping(lines: list[str], mapping: dict[str, Any], indent: int) -> None:
    """Indented key/value rendering; nested dicts (e.g. ``distributed.mesh``)
    recurse instead of printing a one-line Python repr."""
    pad = "  " * indent
    for key, value in mapping.items():
        if isinstance(value, dict) and value:
            lines.append(f"{pad}{key}:")
            _render_mapping(lines, value, indent + 1)
        else:
            lines.append(f"{pad}{key}: {value}")
