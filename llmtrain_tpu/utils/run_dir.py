"""Run-directory creation and atomic resolved-config snapshots.

Parity target: reference ``src/llmtrain/utils/run_dir.py`` — creates
``{root}/{run_id}/`` with ``exist_ok=False`` plus ``logs/``, cleans up a
partially-created dir on failure (run_dir.py:22-28), atomic config write via
``.tmp`` + ``replace`` (run_dir.py:37-45).
"""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Any

import yaml


def create_run_directory(root_dir: str | Path, run_id: str) -> Path:
    """Create ``{root_dir}/{run_id}`` (must not exist) with a ``logs/`` subdir."""
    root = Path(root_dir)
    root.mkdir(parents=True, exist_ok=True)
    run_dir = root / run_id
    run_dir.mkdir(exist_ok=False)
    try:
        (run_dir / "logs").mkdir()
    except OSError:
        shutil.rmtree(run_dir, ignore_errors=True)
        raise
    return run_dir


def write_resolved_config(run_dir: str | Path, resolved: dict[str, Any]) -> Path:
    """Atomically write the fully-resolved config to ``config.yaml``."""
    target = Path(run_dir) / "config.yaml"
    tmp = target.with_suffix(".yaml.tmp")
    tmp.write_text(yaml.safe_dump(resolved, sort_keys=False), encoding="utf-8")
    tmp.replace(target)
    return target
