"""Shared git-introspection helper."""

from __future__ import annotations

import subprocess


def git_sha(*, short: bool) -> str | None:
    """Current HEAD sha of the cwd repo, or ``None`` outside a repo."""
    cmd = ["git", "rev-parse"] + (["--short"] if short else []) + ["HEAD"]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True, timeout=5, check=False)
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None
