"""Deterministic, collision-safe run identifiers.

Parity target: reference ``src/llmtrain/utils/run_id.py`` — format
``{UTC %Y%m%d_%H%M%S}_{short git sha|nogit}_{slug<=40}`` (run_id.py:52-57),
lowercase slug alphabet ``[a-z0-9-_]`` (run_id.py:29-37), collision suffixes
``__01..__99`` then error (run_id.py:40-49).
"""

from __future__ import annotations

import re
from datetime import datetime, timezone
from pathlib import Path

from .git import git_sha

_MAX_SLUG_LEN = 40
_MAX_COLLISION_SUFFIX = 99
_SLUG_RE = re.compile(r"[^a-z0-9\-_]+")


def slugify_run_name(name: str) -> str:
    """Lowercase ``name`` and squash anything outside ``[a-z0-9-_]`` to ``-``."""
    slug = _SLUG_RE.sub("-", name.strip().lower())
    slug = re.sub(r"-{2,}", "-", slug).strip("-")
    if not slug:
        slug = "run"
    return slug[:_MAX_SLUG_LEN]


def _git_short_sha() -> str:
    """Short git sha of the cwd repo, or ``nogit`` outside a repo."""
    return git_sha(short=True) or "nogit"


def generate_run_id(run_name: str, output_root: str | Path) -> str:
    """Build ``{timestamp}_{sha}_{slug}``, suffixing ``__NN`` on collision."""
    timestamp = datetime.now(timezone.utc).strftime("%Y%m%d_%H%M%S")
    base = f"{timestamp}_{_git_short_sha()}_{slugify_run_name(run_name)}"
    root = Path(output_root)
    candidate = base
    if not (root / candidate).exists():
        return candidate
    for i in range(1, _MAX_COLLISION_SUFFIX + 1):
        candidate = f"{base}__{i:02d}"
        if not (root / candidate).exists():
            return candidate
    raise RuntimeError(
        f"Could not find a free run id after {_MAX_COLLISION_SUFFIX} attempts for {base!r}"
    )
