"""Run metadata (``meta.json``) generation.

Parity target: reference ``src/llmtrain/utils/metadata.py`` — meta_version,
run identity, UTC timestamp, full git sha, python/platform info, argv, cwd,
config paths, distributed env snapshot, hostname, pid (metadata.py:52-67),
atomic write (metadata.py:70-81). The env snapshot captures the JAX
rendezvous variables instead of torch's.
"""

from __future__ import annotations

import json
import os
import platform
import socket
import sys
from datetime import datetime, timezone
from pathlib import Path
from typing import Any

from .git import git_sha

META_VERSION = 1

# Env vars that determine multi-process topology (torch names kept for the
# K8s bootstrap contract + JAX-native names).
DISTRIBUTED_ENV_VARS = (
    "RANK",
    "WORLD_SIZE",
    "LOCAL_RANK",
    "MASTER_ADDR",
    "MASTER_PORT",
    "JOB_COMPLETION_INDEX",
    "JAX_PROCESS_ID",
    "JAX_NUM_PROCESSES",
    "JAX_COORDINATOR_ADDRESS",
    "TPU_WORKER_ID",
)


def _git_full_sha() -> str | None:
    return git_sha(short=False)


def distributed_env_snapshot() -> dict[str, str]:
    """Subset of os.environ relevant to multi-process topology."""
    return {k: os.environ[k] for k in DISTRIBUTED_ENV_VARS if k in os.environ}


def generate_meta(
    *,
    run_id: str,
    run_name: str,
    config_path: str | Path,
    resolved_config_path: str | Path | None,
) -> dict[str, Any]:
    """Assemble the ``meta.json`` payload."""
    return {
        "meta_version": META_VERSION,
        "run_id": run_id,
        "run_name": run_name,
        "created_at": datetime.now(timezone.utc).isoformat(),
        "git_sha": _git_full_sha(),
        "python_version": sys.version,
        "platform": platform.platform(),
        "argv": list(sys.argv),
        "cwd": str(Path.cwd()),
        "config_path": str(config_path),
        "resolved_config_path": str(resolved_config_path) if resolved_config_path else None,
        "distributed_env": distributed_env_snapshot(),
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
    }


def write_meta_json(run_dir: str | Path, meta: dict[str, Any]) -> Path:
    """Atomically write ``meta.json`` into the run directory."""
    target = Path(run_dir) / "meta.json"
    tmp = target.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(meta, indent=2, sort_keys=False), encoding="utf-8")
    tmp.replace(target)
    return target
