"""Hardware peak-FLOPs lookup and MFU arithmetic.

New capability over the reference (SURVEY §5: profiling/MFU absent there —
``peak_memory`` is a hardcoded 0.0 at reference trainer.py:542). Peak numbers
are bf16 per-chip figures by TPU generation; the CPU figure is a nominal
placeholder so local smoke runs still produce a (meaningless in absolute
terms, but trend-comparable) MFU.
"""

from __future__ import annotations

# bf16 peak FLOP/s per chip by TPU generation.
TPU_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}

CPU_NOMINAL_FLOPS = 2e11  # placeholder for local smoke runs
_DEFAULT_TPU_FLOPS = 197e12


def peak_flops_per_chip() -> float:
    """Best-effort bf16 peak FLOP/s of one local device."""
    import jax

    if jax.default_backend() != "tpu":
        return CPU_NOMINAL_FLOPS
    kind = jax.devices()[0].device_kind.lower()
    for key, peak in TPU_PEAK_FLOPS.items():
        if key in kind:
            return peak
    return _DEFAULT_TPU_FLOPS


def transformer_flops_per_token(
    *,
    n_params: int,
    n_layers: int,
    seq_len: int,
    d_model: int,
    n_trainable_params: int | None = None,
) -> float:
    """Training FLOPs/token ~ 6N + 12*L*T*d (PaLM appendix B approximation).

    With frozen parameters (LoRA, models/lora.py) the dW backward pass
    only runs for the trainable subset: forward 2N + activation-gradient
    chain 2N + weight gradients 2n → ``4N + 2n``, which degrades to the
    classic 6N when everything trains. Keeping the FLOP model honest here
    keeps the reported MFU honest (a frozen-base step does less math, so
    equal throughput must not claim equal utilization).
    """
    n_t = n_params if n_trainable_params is None else n_trainable_params
    return 4.0 * n_params + 2.0 * n_t + 12.0 * n_layers * seq_len * d_model


def mfu(
    tokens_per_sec_per_chip: float,
    *,
    n_params: int,
    n_layers: int,
    seq_len: int,
    d_model: int,
    peak_flops: float | None = None,
    n_trainable_params: int | None = None,
) -> float:
    """Model FLOPs utilization of one chip at the given throughput."""
    peak = peak_flops if peak_flops is not None else peak_flops_per_chip()
    flops_per_token = transformer_flops_per_token(
        n_params=n_params,
        n_layers=n_layers,
        seq_len=seq_len,
        d_model=d_model,
        n_trainable_params=n_trainable_params,
    )
    return tokens_per_sec_per_chip * flops_per_token / peak


def peak_memory_bytes() -> float:
    """Best-effort peak device-memory bytes of the first local device.

    Single owner of the lookup (trainer metrics, bench.py, and
    tools/bench_longctx.py all report it). PJRT backends differ in which
    keys they populate — ``peak_bytes_in_use`` is the TPU allocator's
    high-water mark; ``bytes_in_use`` is a floor when the peak counter is
    absent. Returns 0.0 when the backend reports nothing (CPU PJRT, and
    some tunneled clients)."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return 0.0
    if not stats:
        return 0.0
    return float(stats.get("peak_bytes_in_use") or stats.get("bytes_in_use") or 0.0)


def memory_stats_keys() -> list[str]:
    """Diagnostic: the keys the first local device's memory_stats reports
    (empty list = no stats). Logged by the long-context sweep when the
    peak reads 0.0 so a failing tunnel window records WHY."""
    import jax

    try:
        return sorted((jax.local_devices()[0].memory_stats() or {}).keys())
    except Exception:
        return []


__all__ = [
    "TPU_PEAK_FLOPS",
    "CPU_NOMINAL_FLOPS",
    "peak_flops_per_chip",
    "transformer_flops_per_token",
    "mfu",
    "peak_memory_bytes",
    "memory_stats_keys",
]
