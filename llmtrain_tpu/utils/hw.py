"""Hardware peak-FLOPs lookup and MFU arithmetic.

New capability over the reference (SURVEY §5: profiling/MFU absent there —
``peak_memory`` is a hardcoded 0.0 at reference trainer.py:542). Peak numbers
are bf16 per-chip figures by TPU generation; the CPU figure is a nominal
placeholder so local smoke runs still produce a (meaningless in absolute
terms, but trend-comparable) MFU.
"""

from __future__ import annotations

# bf16 peak FLOP/s per chip by TPU generation.
TPU_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}

CPU_NOMINAL_FLOPS = 2e11  # placeholder for local smoke runs
_DEFAULT_TPU_FLOPS = 197e12


def peak_flops_per_chip() -> float:
    """Best-effort bf16 peak FLOP/s of one local device."""
    import jax

    if jax.default_backend() != "tpu":
        return CPU_NOMINAL_FLOPS
    kind = jax.devices()[0].device_kind.lower()
    for key, peak in TPU_PEAK_FLOPS.items():
        if key in kind:
            return peak
    return _DEFAULT_TPU_FLOPS


def transformer_flops_per_token(
    *, n_params: int, n_layers: int, seq_len: int, d_model: int
) -> float:
    """Training FLOPs/token ~ 6N + 12*L*T*d (PaLM appendix B approximation)."""
    return 6.0 * n_params + 12.0 * n_layers * seq_len * d_model


def mfu(
    tokens_per_sec_per_chip: float,
    *,
    n_params: int,
    n_layers: int,
    seq_len: int,
    d_model: int,
    peak_flops: float | None = None,
) -> float:
    """Model FLOPs utilization of one chip at the given throughput."""
    peak = peak_flops if peak_flops is not None else peak_flops_per_chip()
    flops_per_token = transformer_flops_per_token(
        n_params=n_params, n_layers=n_layers, seq_len=seq_len, d_model=d_model
    )
    return tokens_per_sec_per_chip * flops_per_token / peak


__all__ = [
    "TPU_PEAK_FLOPS",
    "CPU_NOMINAL_FLOPS",
    "peak_flops_per_chip",
    "transformer_flops_per_token",
    "mfu",
]
