"""Structured logging for the framework.

Parity target: reference ``src/llmtrain/utils/logging.py`` — named logger
``llmtrain`` with ``propagate=False`` (logging.py:89), single-line JSON
formatter with timestamp/level/logger/message/exc_info (logging.py:11-23),
idempotent handler management that reuses the stream handler and swaps file
handlers (logging.py:48-87).
"""

from __future__ import annotations

import json
import logging
import sys
from datetime import datetime, timezone
from pathlib import Path

LOGGER_NAME = "llmtrain"


class JsonFormatter(logging.Formatter):
    """Format each record as one line of JSON."""

    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "timestamp": datetime.fromtimestamp(record.created, tz=timezone.utc).isoformat(),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            payload["exc_info"] = self.formatException(record.exc_info)
        return json.dumps(payload)


def get_logger() -> logging.Logger:
    return logging.getLogger(LOGGER_NAME)


def configure_logging(
    *,
    level: str = "INFO",
    json_output: bool = True,
    log_file: str | Path | None = None,
    stream=None,
) -> logging.Logger:
    """Configure the framework logger idempotently.

    Repeated calls reuse the existing stream handler (re-targeting its stream
    and formatter) and replace any file handlers so tests and multi-call CLI
    paths never stack duplicate handlers.
    """
    logger = get_logger()
    logger.setLevel(level)
    logger.propagate = False

    formatter: logging.Formatter
    if json_output:
        formatter = JsonFormatter()
    else:
        formatter = logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")

    target_stream = stream if stream is not None else sys.stderr

    stream_handler: logging.StreamHandler | None = None
    for handler in list(logger.handlers):
        if isinstance(handler, logging.FileHandler):
            handler.close()
            logger.removeHandler(handler)
        elif isinstance(handler, logging.StreamHandler):
            stream_handler = handler

    if stream_handler is None:
        stream_handler = logging.StreamHandler(target_stream)
        logger.addHandler(stream_handler)
    else:
        stream_handler.setStream(target_stream)
    stream_handler.setFormatter(formatter)

    if log_file is not None:
        file_handler = logging.FileHandler(log_file, encoding="utf-8")
        file_handler.setFormatter(formatter)
        logger.addHandler(file_handler)

    return logger
