"""Fleet-wide trace collection: merge per-process timelines, build trees.

Every process in a serving fleet — router, HTTP replicas, the promote
controller, the fleet supervisor — writes its own isolated
``timeline.jsonl`` (telemetry/timeline.py). Sampled request traces land
in those files as ``cat="trace"`` events whose args carry the full
``trace_id``/``span_id``/``parent_span_id`` tree (telemetry/tracing.py).
This module is the read side:

* :func:`discover_sources` / :func:`load_source` — find and parse the
  JSONL files under one or more run dirs, mapping each event's
  process-relative ``ts_us`` to absolute unix time via the segment
  headers' ``start_unix_time`` anchor (tracked per header while
  scanning, so multi-segment files stay correct).
* :func:`collect_traces` — group trace events by ``trace_id`` into
  :class:`Trace` span trees; parentage works across sources because the
  router pre-allocates its HTTP-hop span id and ships it in the
  ``traceparent`` header, so a replica's root span names a span that
  lives in the *router's* file.
* :func:`critical_path` — exclusive-time tiling of one trace: every
  millisecond of the root span is attributed to exactly one span name
  (child windows to the children, gaps to the parent), so the breakdown
  sums to the end-to-end latency by construction.
* :func:`summarize` / :func:`slowest` — per-span-kind nearest-rank
  percentiles and the top-k slowest traces for the ``llmtrain trace``
  CLI.
* :func:`merge_perfetto` — one Chrome/Perfetto trace with a track group
  per process and flow arrows linking parent→child spans across
  processes (open in ``ui.perfetto.dev``).

Everything here is offline post-processing over files: no locks, no
serving-path imports, safe to run against a live fleet's directories.
"""

from __future__ import annotations

import json
import zlib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterable, Sequence

from .stats import percentiles

__all__ = [
    "Span",
    "Trace",
    "TraceSource",
    "collect_traces",
    "critical_path",
    "discover_sources",
    "format_tree",
    "load_source",
    "merge_perfetto",
    "slowest",
    "summarize",
]


@dataclass
class Span:
    """One node of a trace tree, in absolute unix seconds."""

    name: str
    span_id: str
    parent_span_id: str | None
    t0: float
    t1: float
    source: str  # which process/file recorded it
    args: dict[str, Any] = field(default_factory=dict)

    @property
    def duration_ms(self) -> float:
        return (self.t1 - self.t0) * 1000.0


@dataclass
class TraceSource:
    """One parsed timeline file: events with absolute timestamps."""

    path: Path
    label: str
    events: list[dict[str, Any]] = field(default_factory=list)
    # Wall-clock anchor of the file's FIRST segment (None when the file
    # has no segment header — then events keep their relative stamps).
    start_unix_time: float | None = None


class Trace:
    """All spans of one ``trace_id``, assembled into a forest.

    A fully-sampled request has one root (the router's or scheduler's
    ``*/request`` span); when only some processes kept the trace, the
    orphaned subtrees surface as extra roots rather than being dropped.
    """

    def __init__(self, trace_id: str) -> None:
        self.trace_id = trace_id
        self.spans: list[Span] = []
        self._by_id: dict[str, Span] = {}

    def add(self, span: Span) -> None:
        # A span id can legitimately appear twice (the router records its
        # hop span; a replica's root CLAIMS that id as parent, not as its
        # own) — but identical ids mean a re-flushed line; first wins.
        if span.span_id in self._by_id:
            return
        self._by_id[span.span_id] = span
        self.spans.append(span)

    def get(self, span_id: str) -> Span | None:
        return self._by_id.get(span_id)

    def children(self, span_id: str) -> list[Span]:
        out = [s for s in self.spans if s.parent_span_id == span_id]
        out.sort(key=lambda s: (s.t0, s.t1))
        return out

    @property
    def roots(self) -> list[Span]:
        out = [
            s
            for s in self.spans
            if not s.parent_span_id or s.parent_span_id not in self._by_id
        ]
        out.sort(key=lambda s: (s.t0, s.t1))
        return out

    @property
    def root(self) -> Span | None:
        """The primary root: earliest-starting, longest span among the
        forest roots (the router/ingress request span when present)."""
        roots = self.roots
        if not roots:
            return None
        return max(roots, key=lambda s: s.t1 - s.t0)

    @property
    def duration_ms(self) -> float:
        root = self.root
        return root.duration_ms if root is not None else 0.0

    @property
    def sources(self) -> list[str]:
        seen: list[str] = []
        for s in self.spans:
            if s.source not in seen:
                seen.append(s.source)
        return seen


# ---------------------------------------------------------------- loading


def _source_label(path: Path) -> str:
    """Human label for a timeline file: the owning run/replica dir name
    plus the file stem (``replica0/timeline``, ``run/promote_timeline``)."""
    parent = path.parent
    if parent.name == "telemetry" and parent.parent.name:
        return f"{parent.parent.name}/{path.stem}"
    return f"{parent.name}/{path.stem}" if parent.name else path.stem


def load_source(path: str | Path, label: str | None = None) -> TraceSource:
    """Parse one timeline JSONL. Events gain ``_abs_ts`` (absolute unix
    seconds) from the most recent segment header's ``start_unix_time``;
    files with no header keep relative seconds (still self-consistent).
    Malformed lines are skipped — a live fleet may be mid-write."""
    p = Path(path)
    src = TraceSource(path=p, label=label or _source_label(p))
    anchor: float | None = None
    try:
        text = p.read_text(encoding="utf-8")
    except OSError:
        return src
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(ev, dict):
            continue
        if ev.get("ph") == "seg":
            start = ev.get("start_unix_time")
            if isinstance(start, (int, float)):
                anchor = float(start)
                if src.start_unix_time is None:
                    src.start_unix_time = anchor
            continue
        ts_us = ev.get("ts_us")
        if not isinstance(ts_us, (int, float)):
            continue
        ev["_abs_ts"] = (anchor or 0.0) + float(ts_us) / 1e6
        ev["_source"] = src.label
        src.events.append(ev)
    return src


def discover_sources(
    run_dirs: Sequence[str | Path],
) -> list[TraceSource]:
    """Find every ``*timeline*.jsonl`` under the given dirs (a file path
    is accepted directly) and load them. Duplicate labels get a numeric
    suffix so Perfetto track groups stay distinct."""
    paths: list[Path] = []
    for d in run_dirs:
        p = Path(d)
        if p.is_file():
            paths.append(p)
        elif p.is_dir():
            paths.extend(sorted(p.rglob("*timeline*.jsonl")))
    sources: list[TraceSource] = []
    seen_labels: dict[str, int] = {}
    for path in paths:
        src = load_source(path)
        n = seen_labels.get(src.label, 0)
        seen_labels[src.label] = n + 1
        if n:
            src.label = f"{src.label}#{n}"
            for ev in src.events:
                ev["_source"] = src.label
        sources.append(src)
    return sources


# -------------------------------------------------------------- assembly


def collect_traces(
    sources: Iterable[TraceSource],
) -> dict[str, Trace]:
    """Group every ``cat="trace"`` event across all sources into
    :class:`Trace` trees keyed by trace id."""
    traces: dict[str, Trace] = {}
    for src in sources:
        for ev in src.events:
            if ev.get("cat") != "trace":
                continue
            args = ev.get("args") or {}
            trace_id = args.get("trace_id")
            span_id = args.get("span_id")
            if not trace_id or not span_id:
                continue
            t0 = float(ev["_abs_ts"])
            t1 = t0 + float(ev.get("dur_us", 0)) / 1e6
            extra = {
                k: v
                for k, v in args.items()
                if k not in ("trace_id", "span_id", "parent_span_id")
            }
            trace = traces.setdefault(trace_id, Trace(trace_id))
            trace.add(
                Span(
                    name=str(ev.get("name", "?")),
                    span_id=str(span_id),
                    parent_span_id=args.get("parent_span_id") or None,
                    t0=t0,
                    t1=t1,
                    source=str(ev.get("_source", "?")),
                    args=extra,
                )
            )
    return traces


def slowest(traces: dict[str, Trace], k: int = 10) -> list[Trace]:
    """Top-k traces by root duration (the tail the sampler kept)."""
    ranked = sorted(
        traces.values(), key=lambda t: t.duration_ms, reverse=True
    )
    return ranked[: max(0, int(k))]


# -------------------------------------------------------- critical path


def critical_path(trace: Trace) -> dict[str, Any]:
    """Exclusive-time breakdown of one trace.

    Tiling: walk the tree from the primary root; each child's window
    (clipped to its parent and to what earlier siblings already claimed)
    is handed to the child's subtree, and every gap stays with the
    parent. Every instant of the root interval is attributed exactly
    once, so ``sum(breakdown) == end-to-end`` by construction — the
    property that makes "queue_wait was 80% of this request" a statement
    about the actual latency, not about overlapping span sums.
    """
    root = trace.root
    if root is None:
        return {"trace_id": trace.trace_id, "total_ms": 0.0, "breakdown": {}}
    breakdown: dict[str, float] = {}

    def walk(span: Span, lo: float, hi: float) -> None:
        lo, hi = max(lo, span.t0), min(hi, span.t1)
        if hi <= lo:
            return
        cursor = lo
        for child in trace.children(span.span_id):
            if child.t1 <= child.t0:  # zero-duration marks don't tile
                continue
            c0, c1 = max(child.t0, cursor), min(child.t1, hi)
            if c1 <= c0:
                continue
            if c0 > cursor:
                breakdown[span.name] = (
                    breakdown.get(span.name, 0.0) + (c0 - cursor)
                )
            walk(child, c0, c1)
            cursor = c1
        if hi > cursor:
            breakdown[span.name] = breakdown.get(span.name, 0.0) + (hi - cursor)

    walk(root, root.t0, root.t1)
    total_ms = root.duration_ms
    out = {
        name: round(sec * 1000.0, 3)
        for name, sec in sorted(
            breakdown.items(), key=lambda kv: kv[1], reverse=True
        )
    }
    return {
        "trace_id": trace.trace_id,
        "root": root.name,
        "total_ms": round(total_ms, 3),
        "breakdown": out,
        "sources": trace.sources,
        "spans": len(trace.spans),
    }


def summarize(traces: dict[str, Trace]) -> dict[str, Any]:
    """Per-span-kind latency percentiles across every collected trace —
    the fleet-wide answer to "where does tail time go"."""
    by_name: dict[str, list[float]] = {}
    root_ms: list[float] = []
    for trace in traces.values():
        if trace.root is not None:
            root_ms.append(trace.duration_ms)
        for span in trace.spans:
            if span.t1 > span.t0:
                by_name.setdefault(span.name, []).append(span.duration_ms)
    spans = {
        name: {"count": len(vals), **percentiles(vals)}
        for name, vals in sorted(by_name.items())
    }
    return {
        "traces": len(traces),
        "end_to_end_ms": percentiles(root_ms),
        "spans": spans,
    }


# ------------------------------------------------------------- rendering


def format_tree(trace: Trace) -> list[str]:
    """ASCII span tree of one trace (``llmtrain trace show``): offsets
    are milliseconds from the root start, one line per span, children
    indented under their parents, cross-process hops labeled."""
    root = trace.root
    lines = [f"trace {trace.trace_id}  ({trace.duration_ms:.1f} ms, "
             f"{len(trace.spans)} spans, {len(trace.sources)} processes)"]
    if root is None:
        return lines
    base = root.t0
    seen: set[str] = set()

    def emit(span: Span, depth: int) -> None:
        if span.span_id in seen:  # defensive: a cycle must not hang the CLI
            return
        seen.add(span.span_id)
        off = (span.t0 - base) * 1000.0
        pad = "  " * depth
        note = ""
        if span.args.get("error"):
            note = f"  error={span.args['error']}"
        elif span.args.get("sampled"):
            note = f"  [{span.args['sampled']}]"
        lines.append(
            f"{pad}{span.name}  +{off:.1f}ms  {span.duration_ms:.1f}ms"
            f"  ({span.source}){note}"
        )
        for child in trace.children(span.span_id):
            emit(child, depth + 1)

    for r in trace.roots:
        emit(r, 0)
    return lines


# ---------------------------------------------------------------- merge


def _flow_id(trace_id: str, parent: str, child: str) -> int:
    """Stable positive int id for a parent→child flow arrow."""
    return zlib.crc32(f"{trace_id}:{parent}:{child}".encode()) & 0x7FFFFFFF


def merge_perfetto(
    sources: Sequence[TraceSource],
    out_path: str | Path,
    *,
    traces: dict[str, Trace] | None = None,
) -> Path:
    """Merge every source into one Perfetto trace-event file.

    Each source becomes a process (track group) with its label as the
    process name; every event keeps its recording thread as the track.
    Timestamps are absolute-unix rebased to the earliest source anchor,
    so cross-process ordering in the UI is real ordering. For sampled
    traces, parent→child span links that CROSS sources are drawn as flow
    arrows (``ph: s``/``f``), which is what makes the router→replica
    handoff visible as an arrow instead of a coincidence.
    """
    if traces is None:
        traces = collect_traces(sources)
    base = min(
        (s.start_unix_time for s in sources if s.start_unix_time is not None),
        default=0.0,
    )
    # A source with no segment header carries RELATIVE stamps (see
    # load_source) — subtracting the unix-time base would fling its
    # events ~1.7e9 s before everything else and scramble cross-process
    # ordering. Rebase such sources so their t=0 lands at the merge
    # base: internally self-consistent, but NOT time-aligned with the
    # anchored sources — they're listed in ``otherData.unaligned`` so
    # the CLI can warn.
    unaligned = [s.label for s in sources if s.start_unix_time is None]
    trace_events: list[dict[str, Any]] = []
    # Where each span was recorded: (pid, tid, ts, dur) for flow anchors.
    span_pos: dict[tuple[str, str], tuple[int, int, float, float]] = {}
    for pid, src in enumerate(sources):
        trace_events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": src.label},
            }
        )
        src_base = 0.0 if src.start_unix_time is None else base
        tids: dict[str, int] = {}
        for ev in src.events:
            thread = ev.get("thread", "MainThread")
            if thread not in tids:
                tids[thread] = len(tids) + 1
                trace_events.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": pid,
                        "tid": tids[thread],
                        "args": {"name": thread},
                    }
                )
            ts = (float(ev["_abs_ts"]) - src_base) * 1e6
            dur = float(ev.get("dur_us", 0))
            ph = ev.get("ph", "X")
            out: dict[str, Any] = {
                "name": ev.get("name", "?"),
                "cat": ev.get("cat", "train"),
                "ph": ph,
                "ts": ts,
                "pid": pid,
                "tid": tids[thread],
            }
            if ph == "X":
                out["dur"] = dur
            if ph == "i":
                out["s"] = "t"
            args = dict(ev.get("args") or {})
            if "step" in ev:
                args["step"] = ev["step"]
            if ev.get("rolled_back"):
                args["rolled_back"] = True
            if args:
                out["args"] = args
            trace_events.append(out)
            if ev.get("cat") == "trace":
                tid_ = args.get("trace_id")
                sid = args.get("span_id")
                if tid_ and sid:
                    span_pos[(str(tid_), str(sid))] = (
                        pid, tids[thread], ts, dur,
                    )
    # Flow arrows for cross-source parent→child links.
    for trace in traces.values():
        for span in trace.spans:
            if not span.parent_span_id:
                continue
            parent = trace.get(span.parent_span_id)
            if parent is None or parent.source == span.source:
                continue
            src_pos = span_pos.get((trace.trace_id, parent.span_id))
            dst_pos = span_pos.get((trace.trace_id, span.span_id))
            if src_pos is None or dst_pos is None:
                continue
            fid = _flow_id(trace.trace_id, parent.span_id, span.span_id)
            trace_events.append(
                {
                    "name": "trace_link",
                    "cat": "trace",
                    "ph": "s",
                    "id": fid,
                    "ts": src_pos[2],
                    "pid": src_pos[0],
                    "tid": src_pos[1],
                }
            )
            trace_events.append(
                {
                    "name": "trace_link",
                    "cat": "trace",
                    "ph": "f",
                    "bp": "e",
                    "id": fid,
                    "ts": dst_pos[2],
                    "pid": dst_pos[0],
                    "tid": dst_pos[1],
                }
            )
    payload = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "sources": [str(s.path) for s in sources],
            "base_unix_time": base,
            "traces": len(traces),
            "unaligned": unaligned,
        },
    }
    target = Path(out_path)
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(json.dumps(payload), encoding="utf-8")
    return target
