"""Distributed request tracing: W3C-style context, span buffers, tail sampling.

The serving fleet is a router, N replicas (in-process or HTTP), an
overload controller, and a promote lifecycle — each process writing its
own isolated ``timeline.jsonl``. This module adds the cross-process
thread: a W3C-traceparent-style context (128-bit ``trace_id``, 64-bit
``span_id``, ``parent_span_id``) minted at the ingress (router or HTTP
handler), propagated over the replica hop as a ``traceparent`` header,
and recorded alongside every timeline span a request touches, so
``llmtrain trace show`` (telemetry/trace_collect.py) can reconstruct one
request's router→replica span tree from a directory of fleet run dirs.

Overhead is bounded with **tail-based sampling**: every request carries a
small in-memory :class:`RequestTrace` span buffer, but the buffer is only
flushed to the timeline — as ``cat="trace"`` events carrying the full
``trace_id``/``span_id``/``parent_span_id`` tree — when the request turns
out to be interesting: slow (top percentile of a latency reservoir),
errored, failed-over, or explicitly forced (``X-Trace: force``, which
propagates across the HTTP hop via the traceparent flags byte). Everything
else degrades to the pre-existing un-treed timeline spans, which still
carry a ``trace_id`` arg for correlation but cost nothing extra.

Clocks: buffered spans are stamped with ``time.perf_counter()`` — the
same clock :class:`~.timeline.EventTimeline` uses — so flushed spans land
at their TRUE time, not the flush time. Cross-process alignment uses the
timeline segment headers' ``start_unix_time`` (see trace_collect).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from .stats import percentile

__all__ = [
    "FORCE_HEADER",
    "TRACEPARENT_HEADER",
    "RequestTrace",
    "TailSampler",
    "TraceContext",
    "Tracer",
    "new_span_id",
    "new_trace_id",
]

TRACEPARENT_HEADER = "traceparent"
FORCE_HEADER = "X-Trace"

_HEX = set("0123456789abcdef")


def new_trace_id() -> str:
    """Globally unique 128-bit trace id (32 lowercase hex chars)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit span id (16 lowercase hex chars)."""
    return os.urandom(8).hex()


def _is_hex(s: str, n: int) -> bool:
    return len(s) == n and all(c in _HEX for c in s)


@dataclass
class TraceContext:
    """One position in a distributed trace: ``span_id`` is *this* hop's
    span, ``parent_span_id`` the remote/enclosing one. ``forced`` mirrors
    the traceparent sampled flag — a forced trace is kept on every process
    it touches, which is how ``X-Trace: force`` and failover retries get
    full fleet-wide detail."""

    trace_id: str
    span_id: str
    parent_span_id: str | None = None
    forced: bool = False

    @classmethod
    def root(cls, *, forced: bool = False) -> "TraceContext":
        return cls(new_trace_id(), new_span_id(), None, forced)

    def child(self) -> "TraceContext":
        """A new span under this one (same trace, forced flag inherited)."""
        return TraceContext(self.trace_id, new_span_id(), self.span_id, self.forced)

    def to_traceparent(self) -> str:
        """``00-{trace_id}-{span_id}-{flags}`` — flags ``01`` propagates
        the forced/sampled decision to the receiving process."""
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.forced else '00'}"

    @classmethod
    def from_traceparent(cls, header: str | None) -> "TraceContext | None":
        """Parse a traceparent header; None on anything malformed (a bad
        header must never fail a request — it just loses its trace)."""
        if not header:
            return None
        parts = header.strip().lower().split("-")
        if len(parts) != 4:
            return None
        version, trace_id, span_id, flags = parts
        if version != "00" or not _is_hex(trace_id, 32) or not _is_hex(span_id, 16):
            return None
        if not _is_hex(flags, 2) or trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        return cls(trace_id, span_id, None, forced=bool(int(flags, 16) & 0x01))


@dataclass
class TraceSpan:
    name: str
    span_id: str
    parent_span_id: str | None
    t0: float  # perf_counter seconds
    t1: float
    args: dict[str, Any] = field(default_factory=dict)


class RequestTrace:
    """Per-request in-memory span buffer (the tail-sampling staging area).

    Threads append concurrently (router completion thread, scheduler step
    loop, HTTP handler); a small lock serializes. ``max_spans`` bounds a
    pathological request — overflow is counted, not grown.
    """

    __slots__ = (
        "ctx",
        "root_name",
        "spans",
        "events",
        "notes",
        "finished",
        "dropped",
        "_max_spans",
        "_lock",
    )

    def __init__(
        self, ctx: TraceContext, *, root_name: str = "serve/request", max_spans: int = 256
    ) -> None:
        self.ctx = ctx
        self.root_name = root_name
        self.spans: list[TraceSpan] = []
        self.events: list[TraceSpan] = []  # zero-duration (t0 == t1) marks
        self.notes: dict[str, Any] = {}
        self.finished = False
        self.dropped = 0
        self._max_spans = max_spans
        self._lock = threading.Lock()

    @property
    def trace_id(self) -> str:
        return self.ctx.trace_id

    @property
    def root_span_id(self) -> str:
        return self.ctx.span_id

    def force(self) -> None:
        self.ctx.forced = True

    def add_span(
        self,
        name: str,
        *,
        t0: float,
        t1: float,
        parent: str | None = None,
        span_id: str | None = None,
        **args: Any,
    ) -> str:
        """Buffer a finished span; returns its span id (pre-allocate via
        ``span_id=`` when the id must be sent over the wire BEFORE the
        span completes — the router's HTTP dispatch hop does this)."""
        sid = span_id or new_span_id()
        span = TraceSpan(name, sid, parent or self.ctx.span_id, t0, t1, args)
        with self._lock:
            if len(self.spans) < self._max_spans:
                self.spans.append(span)
            else:
                self.dropped += 1
        return sid

    def add_event(
        self, name: str, *, t: float, parent: str | None = None, **args: Any
    ) -> None:
        """Buffer an instantaneous mark (prefix-cache hit, compile, shed
        verdict) — flushed as a zero-duration span under ``parent``."""
        ev = TraceSpan(name, new_span_id(), parent or self.ctx.span_id, t, t, args)
        with self._lock:
            if len(self.events) < self._max_spans:
                self.events.append(ev)
            else:
                self.dropped += 1

    def note(self, **kv: Any) -> None:
        """Attach root-span metadata (``failover=True``, ``error=...``);
        the ``failover`` note also upgrades the sampler verdict."""
        with self._lock:
            self.notes.update(kv)


class TailSampler:
    """Decides which finished traces are worth full-detail flushing.

    Keeps: forced (``X-Trace: force`` / propagated flags), errored,
    failed-over, warmup (the first ``warmup`` traces, so a fresh fleet has
    something to show), and slow — latency at or above the top
    ``slow_frac`` of a sliding reservoir of recent latencies. Everything
    else returns None (drop). Thread-safe; one instance per process.
    """

    def __init__(
        self,
        *,
        slow_frac: float = 0.05,
        reservoir: int = 512,
        warmup: int = 16,
    ) -> None:
        if not 0.0 < slow_frac <= 1.0:
            raise ValueError("slow_frac must be in (0, 1]")
        self._slow_frac = slow_frac
        self._reservoir_len = max(16, reservoir)
        self._warmup = warmup
        self._reservoir: list[float] = []
        self._idx = 0
        self._seen = 0
        self._lock = threading.Lock()

    def decide(
        self,
        latency_ms: float,
        *,
        errored: bool = False,
        failover: bool = False,
        forced: bool = False,
    ) -> str | None:
        with self._lock:
            seen = self._seen
            self._seen += 1
            res = self._reservoir
            threshold: float | None = None
            if res and len(res) >= self._warmup:
                threshold = percentile(sorted(res), 1.0 - self._slow_frac)
            # Sliding reservoir: overwrite in ring order once full.
            if len(res) < self._reservoir_len:
                res.append(latency_ms)
            else:
                res[self._idx] = latency_ms
                self._idx = (self._idx + 1) % self._reservoir_len
        if forced:
            return "forced"
        if errored:
            return "error"
        if failover:
            return "failover"
        if seen < self._warmup:
            return "warmup"
        if threshold is not None and latency_ms >= threshold:
            return "slow"
        return None


class Tracer:
    """Binds an :class:`EventTimeline` to a :class:`TailSampler`.

    ``start`` mints a request's context; ``finish`` is called exactly once
    per request by whichever component resolves it (scheduler retire/fail/
    reject, router HTTP-completion) — it asks the sampler, and on keep
    flushes the buffered tree into the timeline as ``cat="trace"`` events
    that the collector (trace_collect.py) reassembles fleet-wide.
    """

    def __init__(
        self,
        timeline: "EventTimeline",
        *,
        sampler: TailSampler | None = None,
        max_spans: int = 256,
    ) -> None:
        self.timeline = timeline
        self.sampler = sampler or TailSampler()
        self._max_spans = max_spans
        self._lock = threading.Lock()
        self.kept: dict[str, int] = {}
        self.finished = 0

    def start(
        self,
        *,
        parent: TraceContext | None = None,
        root_name: str = "serve/request",
        forced: bool = False,
    ) -> RequestTrace:
        """New request trace: a fresh root, or a child hop of a remote
        ``parent`` parsed from a traceparent header."""
        if parent is not None:
            ctx = parent.child()
            if forced:
                ctx.forced = True
        else:
            ctx = TraceContext.root(forced=forced)
        return RequestTrace(ctx, root_name=root_name, max_spans=self._max_spans)

    def finish(
        self,
        trace: RequestTrace | None,
        *,
        t0: float,
        t1: float | None = None,
        errored: bool = False,
        failover: bool = False,
        **root_args: Any,
    ) -> str | None:
        """Resolve a request's trace; returns the keep-reason or None.

        Idempotent — the first caller wins (router and scheduler can both
        sit on a request's completion path). ``t0``/``t1`` are
        perf_counter stamps bounding the root span (submit → done).
        """
        if trace is None:
            return None
        with trace._lock:
            if trace.finished:
                return None
            trace.finished = True
            notes = dict(trace.notes)
            spans = list(trace.spans)
            events = list(trace.events)
        if t1 is None:
            t1 = time.perf_counter()
        reason = self.sampler.decide(
            (t1 - t0) * 1000.0,
            errored=errored or bool(notes.get("error")),
            failover=failover or bool(notes.get("failover")),
            forced=trace.ctx.forced,
        )
        with self._lock:
            self.finished += 1
            if reason is not None:
                self.kept[reason] = self.kept.get(reason, 0) + 1
        if reason is None:
            return None
        tl = self.timeline
        ctx = trace.ctx
        root: dict[str, Any] = {
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "sampled": reason,
        }
        if ctx.parent_span_id:
            root["parent_span_id"] = ctx.parent_span_id
        if trace.dropped:
            root["dropped_spans"] = trace.dropped
        root.update(notes)
        root.update(root_args)
        tl.record(trace.root_name, t0=t0, t1=t1, cat="trace", **root)
        for s in spans + events:
            # Span args may legitimately carry a correlation trace_id
            # already (the live-span copy does); the tree ids win.
            merged = dict(s.args)
            merged.update(
                trace_id=ctx.trace_id,
                span_id=s.span_id,
                parent_span_id=s.parent_span_id,
            )
            tl.record(s.name, t0=s.t0, t1=s.t1, cat="trace", **merged)
        # Duck-typed timelines (tests, adapters) only promise the
        # instant/record/span surface — flush is an EventTimeline extra.
        flush = getattr(tl, "flush", None)
        if flush is not None:
            flush()
        return reason

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {"finished": self.finished, "kept": dict(self.kept)}
