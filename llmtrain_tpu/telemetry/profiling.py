"""XLA cost attribution and roofline analysis.

PR 4's telemetry stack measures *wall-clock* (timeline spans, step times,
HBM highwater) but attributes nothing against the hardware's peak — so
"0.48 MFU" (RESULTS.md) cannot answer *why not 0.6*: is the step compute-,
memory-, or comms-bound?  This module closes that gap with the accounting
discipline Megatron-LM uses to make MFU claims defensible (Narayanan et
al., arXiv:2104.04473):

* **Cost extraction** — XLA's own ``cost_analysis()`` (flops, bytes
  accessed, transcendentals) and ``memory_analysis()`` pulled from the
  jitted executables the run *actually dispatches* (train step, serving
  prefill/decode buckets).  Two tiers, chosen by call site:

  - :func:`lower_cost_profile` only *lowers* (no XLA compile) — cheap
    enough for the end of every fit, gives program-level totals;
  - :func:`aot_profile` lowers **and** compiles — the ``llmtrain
    profile`` CLI's path, which additionally yields post-optimization
    HLO for the per-op table, compile wall-times, and the compiled
    memory footprint.

* **Roofline attribution** — against a per-device-kind peak table
  (:data:`DEVICE_PEAKS`, config-overridable), each executable and each
  top-k HLO op category is classified compute-/memory-/comms-bound by
  comparing ``flops/peak_flops`` vs ``bytes/hbm_bw`` vs
  ``collective_bytes/ici_bw`` (Williams et al. roofline model).

* **MFU reconciliation** — the analytical MFU (XLA-counted flops) is
  compared against the measured tokens/s MFU (PaLM ``6N`` approximation,
  utils/hw.py).  Their ratio is *deterministic* (step time cancels):
  ``xla_flops_per_step / (tokens_per_step * palm_flops_per_token)`` — a
  value far outside [0.5, 2.0] means one of the two flop models is wrong
  for this architecture, and the report says so.

Everything here is pure measurement: no function in this module executes
device code, mutates donated buffers, or raises into a step loop (cost
hooks degrade to ``None``/empty on any backend oddity).
"""

from __future__ import annotations

import re
import time
from typing import Any, Callable, Iterable, Mapping

from ..utils.logging import get_logger

logger = get_logger()

# --------------------------------------------------------------------------
# Device peak table
# --------------------------------------------------------------------------

# Per-chip peaks by TPU generation: bf16 FLOP/s, HBM bandwidth (bytes/s),
# and aggregate ICI bandwidth (bytes/s, all links).  Bandwidths are
# approximate public figures — they set roofline *ratios*, not absolute
# claims, and every entry is overridable via
# ``telemetry.device_peaks`` in the run config.  The cpu row is a nominal
# placeholder (same stance as utils/hw.py CPU_NOMINAL_FLOPS) so local
# smoke runs still produce trend-comparable classifications.
DEVICE_PEAKS: dict[str, dict[str, float]] = {
    "v4": {"peak_flops": 275e12, "hbm_bytes_per_sec": 1228e9, "ici_bytes_per_sec": 270e9},
    "v5e": {"peak_flops": 197e12, "hbm_bytes_per_sec": 819e9, "ici_bytes_per_sec": 186e9},
    "v5 lite": {"peak_flops": 197e12, "hbm_bytes_per_sec": 819e9, "ici_bytes_per_sec": 186e9},
    "v5p": {"peak_flops": 459e12, "hbm_bytes_per_sec": 2765e9, "ici_bytes_per_sec": 540e9},
    "v6e": {"peak_flops": 918e12, "hbm_bytes_per_sec": 1640e9, "ici_bytes_per_sec": 360e9},
    "v6 lite": {"peak_flops": 918e12, "hbm_bytes_per_sec": 1640e9, "ici_bytes_per_sec": 360e9},
    "cpu": {"peak_flops": 2e11, "hbm_bytes_per_sec": 50e9, "ici_bytes_per_sec": 10e9},
}

_PEAK_KEYS = ("peak_flops", "hbm_bytes_per_sec", "ici_bytes_per_sec")


def resolve_peaks(
    device_kind: str | None = None,
    overrides: Mapping[str, float] | None = None,
) -> dict[str, float]:
    """Peak figures for ``device_kind`` (substring match, like
    utils/hw.py), with config overrides merged on top.

    ``device_kind`` None reads the first local jax device; any lookup
    failure falls back to the cpu row — attribution must degrade, never
    raise.
    """
    kind = device_kind
    if kind is None:
        try:
            import jax

            kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — no backend is a degraded profile, not an error
            kind = "cpu"
    kind = (kind or "cpu").lower()
    peaks = dict(DEVICE_PEAKS["cpu"])
    # Longest matching key wins so "v5 lite" beats "v5" styles of kind.
    best = ""
    for key, row in DEVICE_PEAKS.items():
        if key in kind and len(key) > len(best):
            best = key
            peaks = dict(row)
    peaks["device_kind"] = kind  # type: ignore[assignment]
    for key in _PEAK_KEYS:
        if overrides and key in overrides and overrides[key]:
            peaks[key] = float(overrides[key])
    return peaks


# --------------------------------------------------------------------------
# cost_analysis normalization
# --------------------------------------------------------------------------


def normalize_cost(raw: Any) -> dict[str, float]:
    """Flatten XLA ``cost_analysis()`` output to ``{property: float}``.

    The API shape differs by object: ``Lowered.cost_analysis()`` returns a
    plain dict, ``Compiled.cost_analysis()`` a list of per-computation
    dicts (first entry = entry computation), and either may be ``None`` on
    exotic backends.  All shapes land in one flat dict here.
    """
    if raw is None:
        return {}
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else {}
    out: dict[str, float] = {}
    try:
        for key, value in dict(raw).items():
            if isinstance(value, (int, float)):
                out[str(key)] = float(value)
    except Exception:  # noqa: BLE001
        return {}
    return out


def cost_summary(raw: Any) -> dict[str, float]:
    """The three headline properties from a raw ``cost_analysis()``."""
    cost = normalize_cost(raw)
    return {
        "flops": cost.get("flops", 0.0),
        "bytes_accessed": cost.get("bytes accessed", cost.get("bytes_accessed", 0.0)),
        "transcendentals": cost.get("transcendentals", 0.0),
    }


def memory_summary(compiled: Any) -> dict[str, float]:
    """``Compiled.memory_analysis()`` as a JSON-friendly dict (or {})."""
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        return {}
    if mem is None:
        return {}
    out: dict[str, float] = {}
    for attr in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        value = getattr(mem, attr, None)
        if isinstance(value, (int, float)):
            out[attr] = float(value)
    if out:
        out["total_hbm_bytes"] = (
            out.get("argument_size_in_bytes", 0.0)
            + out.get("output_size_in_bytes", 0.0)
            + out.get("temp_size_in_bytes", 0.0)
        )
    return out


# --------------------------------------------------------------------------
# Post-optimization HLO parsing (per-op cost table)
# --------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{[^}]*\})?")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(?P<rtype>\([^=]*?\)|\S+)\s+"
    r"(?P<opcode>[a-z][\w\-]*)\((?P<rest>.*)$"
)

# Opcodes whose cost is pure data movement (bytes counted, flops 0).
_ZERO_FLOP_OPS = frozenset({
    "parameter", "constant", "iota", "tuple", "get-tuple-element",
    "bitcast", "bitcast-convert", "copy", "copy-start", "copy-done",
    "reshape", "transpose", "broadcast", "slice", "dynamic-slice",
    "dynamic-update-slice", "concatenate", "pad", "reverse", "gather",
    "scatter", "after-all", "custom-call", "fusion", "call",
    "rng-bit-generator", "rng", "while", "conditional", "convolution",
    "optimization-barrier", "domain", "partition-id", "replica-id",
    "infeed", "outfeed", "send", "recv", "send-done", "recv-done",
})

_TRANSCENDENTAL_OPS = frozenset({
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "logistic", "tanh", "sqrt", "rsqrt", "cbrt", "power", "sine",
    "cosine", "tan", "atan2", "erf",
})

_COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "all-reduce-start",
    "all-gather-start", "collective-permute-start",
})

_REDUCE_OPS = frozenset({"reduce", "reduce-window", "sort", "select-and-scatter"})

_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")


def _shapes_bytes(text: str) -> tuple[float, float]:
    """(total bytes, total elements) over every shape literal in ``text``."""
    total_bytes = 0.0
    total_elems = 0.0
    for dtype, dims in _SHAPE_RE.findall(text):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue
        elems = 1.0
        for d in dims.split(","):
            if d:
                elems *= float(d)
        total_elems += elems
        total_bytes += elems * size
    return total_bytes, total_elems


def _split_operands(rest: str) -> tuple[str, str]:
    """Split ``rest`` (text after the opening paren of ``opcode(``) into
    (operand text, attribute text) at the balanced closing paren."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
            if depth == 0:
                return rest[:i], rest[i + 1 :]
    return rest, ""


def _dot_flops(rest: str, out_elems: float) -> float:
    """``2 * prod(output dims) * prod(contracting dim sizes)`` — the
    contracting sizes come from the first (lhs) operand shape plus the
    ``lhs_contracting_dims={...}`` attribute XLA prints inline."""
    operands, attrs = _split_operands(rest)
    match = _CONTRACT_RE.search(attrs) or _CONTRACT_RE.search(rest)
    lhs = _SHAPE_RE.search(operands)
    if match is None or lhs is None:
        return 2.0 * out_elems  # degraded guess: at least count the outputs
    dims = [d for d in lhs.group(2).split(",") if d]
    contract = 1.0
    for idx_text in match.group(1).split(","):
        if not idx_text:
            continue
        idx = int(idx_text)
        if 0 <= idx < len(dims):
            contract *= float(dims[idx])
    return 2.0 * out_elems * contract


def parse_hlo_ops(hlo_text: str) -> dict[str, Any]:
    """Aggregate per-opcode costs out of post-optimization HLO text.

    Accounting stance (documented in docs/observability.md):

    * **flops** are summed over *every* computation — an op fused into a
      loop fusion still does its math;
    * **bytes** are summed over the ENTRY computation only — only
      materialized buffers move through HBM, and fusion instructions at
      entry level carry exactly their operand+output traffic.

    Returns ``{"ops": {opcode: {...}}, "totals": {...},
    "collective_bytes": float}`` — all plain floats, JSON-ready.
    """
    ops: dict[str, dict[str, float]] = {}
    totals = {"flops": 0.0, "bytes_accessed": 0.0, "transcendentals": 0.0}
    collective_bytes = 0.0
    in_entry = False
    depth = 0
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.startswith("ENTRY "):
            in_entry = True
            depth = stripped.count("{") - stripped.count("}")
            continue
        if in_entry:
            depth += stripped.count("{") - stripped.count("}")
            # Attribute braces ({1,0}, dims={...}) are balanced within a
            # line; only the computation's closing brace drops depth <= 0.
            if stripped == "}" or depth < 0:
                in_entry = False
        match = _INSTR_RE.match(line)
        if match is None:
            continue
        opcode = match.group("opcode")
        rtype = match.group("rtype")
        rest = match.group("rest")
        out_bytes, out_elems = _shapes_bytes(rtype)

        flops = 0.0
        transcendentals = 0.0
        if opcode == "dot":
            flops = _dot_flops(rest, out_elems)
        elif opcode in _REDUCE_OPS:
            operands, _ = _split_operands(rest)
            _, in_elems = _shapes_bytes(operands)
            flops = max(in_elems, out_elems)
        elif opcode in _COLLECTIVE_OPS:
            flops = out_elems if "reduce" in opcode else 0.0
        elif opcode in _ZERO_FLOP_OPS:
            flops = 0.0
        else:
            # Elementwise/default: one flop per output element.
            flops = out_elems
            if opcode in _TRANSCENDENTAL_OPS:
                transcendentals = out_elems

        entry = ops.setdefault(
            opcode,
            {"count": 0.0, "flops": 0.0, "bytes_accessed": 0.0, "transcendentals": 0.0},
        )
        entry["count"] += 1
        entry["flops"] += flops
        entry["transcendentals"] += transcendentals
        totals["flops"] += flops
        totals["transcendentals"] += transcendentals
        if in_entry and opcode != "parameter":
            operands, _ = _split_operands(rest)
            op_bytes, _ = _shapes_bytes(operands)
            entry["bytes_accessed"] += out_bytes + op_bytes
            totals["bytes_accessed"] += out_bytes + op_bytes
            if opcode in _COLLECTIVE_OPS:
                collective_bytes += op_bytes
    return {"ops": ops, "totals": totals, "collective_bytes": collective_bytes}


def top_ops(
    parsed: Mapping[str, Any],
    peaks: Mapping[str, float],
    *,
    k: int = 10,
) -> list[dict[str, Any]]:
    """The top-``k`` opcodes ranked by ``max(flops share, bytes share)``,
    each carrying its own roofline class — the human-readable "where does
    the cost go" table."""
    ops: Mapping[str, Mapping[str, float]] = parsed.get("ops", {})
    totals: Mapping[str, float] = parsed.get("totals", {})
    total_flops = max(totals.get("flops", 0.0), 1.0)
    total_bytes = max(totals.get("bytes_accessed", 0.0), 1.0)
    rows: list[dict[str, Any]] = []
    for opcode, entry in ops.items():
        flops_frac = entry["flops"] / total_flops
        bytes_frac = entry["bytes_accessed"] / total_bytes
        if opcode in _COLLECTIVE_OPS:
            op_class = "comms"
        elif entry["flops"] <= 0 and entry["bytes_accessed"] <= 0:
            continue  # parameters/tuples: no cost, no row
        else:
            compute_t = entry["flops"] / max(peaks.get("peak_flops", 1.0), 1.0)
            memory_t = entry["bytes_accessed"] / max(
                peaks.get("hbm_bytes_per_sec", 1.0), 1.0
            )
            op_class = "compute" if compute_t >= memory_t else "memory"
        rows.append(
            {
                "op": opcode,
                "count": int(entry["count"]),
                "flops": entry["flops"],
                "bytes_accessed": entry["bytes_accessed"],
                "flops_frac": round(flops_frac, 4),
                "bytes_frac": round(bytes_frac, 4),
                "class": op_class,
            }
        )
    rows.sort(key=lambda r: max(r["flops_frac"], r["bytes_frac"]), reverse=True)
    return rows[:k]


# --------------------------------------------------------------------------
# Roofline classification
# --------------------------------------------------------------------------


def classify_roofline(
    *,
    flops: float,
    bytes_accessed: float,
    peaks: Mapping[str, float],
    collective_bytes: float = 0.0,
) -> dict[str, Any]:
    """Classify one executable compute-/memory-/comms-bound.

    The class is the argmax of the three analytical times (flops/peak,
    bytes/hbm_bw, collective_bytes/ici_bw); ``arithmetic_intensity`` vs
    ``ridge_intensity`` (peak_flops/hbm_bw) restates the compute-vs-memory
    half on the classic roofline axes.
    """
    peak_flops = max(float(peaks.get("peak_flops", 1.0)), 1.0)
    hbm_bw = max(float(peaks.get("hbm_bytes_per_sec", 1.0)), 1.0)
    ici_bw = max(float(peaks.get("ici_bytes_per_sec", 1.0)), 1.0)
    compute_ms = flops / peak_flops * 1e3
    memory_ms = bytes_accessed / hbm_bw * 1e3
    comms_ms = collective_bytes / ici_bw * 1e3
    times = {"compute": compute_ms, "memory": memory_ms, "comms": comms_ms}
    bound = max(times, key=lambda key: times[key])
    return {
        "class": bound,
        "analytical_ms": {key: round(val, 6) for key, val in times.items()},
        "arithmetic_intensity": round(flops / max(bytes_accessed, 1.0), 4),
        "ridge_intensity": round(peak_flops / hbm_bw, 4),
    }


def gradient_collective_bytes(
    axis_sizes: Mapping[str, int], trainable_grad_bytes: float
) -> float:
    """Per-chip gradient-sync bytes per step: ring all-reduce moves
    ``2*(dp-1)/dp * grad_bytes`` over the combined data-parallel degree
    (the ``data``/``fsdp``/``expert`` axes — parallel/sharding.py
    ZERO_PARTITION_AXES).  0 when unsharded: no cross-chip sync."""
    dp = 1
    for axis in ("data", "fsdp", "expert"):
        dp *= max(int(axis_sizes.get(axis, 1)), 1)
    if dp <= 1:
        return 0.0
    return 2.0 * (dp - 1) / dp * float(trainable_grad_bytes)


# --------------------------------------------------------------------------
# Executable profiles (two tiers)
# --------------------------------------------------------------------------


def lower_cost_profile(
    jitted: Any, args: tuple, *, name: str, n_chips: int = 1
) -> dict[str, Any] | None:
    """Tier-1-budget-safe cost probe: trace+lower only, NO XLA compile.

    Returns cost totals — enough for roofline class and MFU
    reconciliation at the end of every fit.  ``Lowered.cost_analysis()``
    describes the GLOBAL (pre-SPMD-partitioning) program, unlike the
    per-shard ``Compiled`` figures :func:`aot_profile` mines, so callers
    running under a mesh pass ``n_chips`` and the totals normalize to the
    per-device frame both tiers report in.  Args may be live arrays or
    ShapeDtypeStructs; nothing executes, so donation annotations on
    ``jitted`` never consume a buffer.  Returns None on any failure
    (attribution is optional, the run is not).
    """
    try:
        t0 = time.perf_counter()
        lowered = jitted.lower(*args)
        lower_s = time.perf_counter() - t0
        summary = cost_summary(lowered.cost_analysis())
        for key in ("flops", "bytes_accessed", "transcendentals"):
            summary[key] /= max(int(n_chips), 1)
        summary["name"] = name
        summary["lower_time_s"] = round(lower_s, 4)
        return summary
    except Exception as exc:  # noqa: BLE001
        logger.debug("cost lowering for %s failed: %s", name, exc)
        return None


def aot_profile(
    jitted: Any,
    args: tuple,
    *,
    name: str,
    peaks: Mapping[str, float],
    collective_bytes: float = 0.0,
    top_k: int = 10,
    n_chips: int = 1,
) -> dict[str, Any] | None:
    """Full ahead-of-time profile: lower, compile, and mine the compiled
    executable — per-op table from post-optimization HLO, memory
    analysis, timed compile.  The ``llmtrain profile`` path; too slow for
    in-run hooks.  Never executes the program."""
    try:
        t0 = time.perf_counter()
        lowered = jitted.lower(*args)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
    except Exception as exc:  # noqa: BLE001
        logger.warning("AOT profile of %s failed at lower/compile: %s", name, exc)
        return None
    summary = cost_summary(compiled.cost_analysis())
    if summary["flops"] <= 0.0:  # some backends only report on the Lowered
        # The Lowered figures are global-program; normalize to the
        # per-device frame the Compiled figures are in.
        lowered_summary = cost_summary(lowered.cost_analysis())
        if lowered_summary["flops"] > 0.0:
            summary = {
                k: v / max(int(n_chips), 1) for k, v in lowered_summary.items()
            }
    profile: dict[str, Any] = dict(summary)
    profile["name"] = name
    profile["lower_time_s"] = round(t1 - t0, 4)
    profile["compile_time_s"] = round(t2 - t1, 4)
    profile["memory"] = memory_summary(compiled)
    try:
        parsed = parse_hlo_ops(compiled.as_text())
    except Exception as exc:  # noqa: BLE001
        logger.debug("HLO parse for %s failed: %s", name, exc)
        parsed = {"ops": {}, "totals": {}, "collective_bytes": 0.0}
    hlo_collective = parsed.get("collective_bytes", 0.0)
    profile["collective_bytes"] = max(float(collective_bytes), hlo_collective)
    profile["top_ops"] = top_ops(parsed, peaks, k=top_k)
    profile["roofline"] = classify_roofline(
        flops=profile["flops"],
        bytes_accessed=profile["bytes_accessed"],
        collective_bytes=profile["collective_bytes"],
        peaks=peaks,
    )
    return profile


# --------------------------------------------------------------------------
# perf_attribution block (report.json / gauges)
# --------------------------------------------------------------------------

# Documented reconciliation tolerance: analytical(XLA)/measured(PaLM-6N)
# MFU ratio outside this band flags a flop-model mismatch in the report.
MFU_RECONCILE_BAND = (0.5, 2.0)


def build_perf_attribution(
    *,
    executables: Iterable[Mapping[str, Any]],
    peaks: Mapping[str, float],
    n_chips: int = 1,
    step_time_ms: float | None = None,
    tokens_per_step: float | None = None,
    palm_flops_per_token: float | None = None,
    measured_mfu: float | None = None,
    collective_bytes: float = 0.0,
    span_totals: Mapping[str, Mapping[str, float]] | None = None,
    steps: int | None = None,
) -> dict[str, Any]:
    """Assemble the ``perf_attribution`` report block.

    ``executables`` are per-executable cost dicts from either profiling
    tier; the primary (first) one — the train step for a fit — drives the
    MFU reconciliation and the step-time split.  All cost figures are
    PER-DEVICE: under SPMD partitioning ``cost_analysis()`` describes the
    per-shard module that each chip actually dispatches.
    ``tokens_per_step`` is the global figure; it divides by ``n_chips``
    wherever it meets a cost figure.
    """
    n_chips = max(int(n_chips), 1)
    rows: list[dict[str, Any]] = []
    for exe in executables:
        if not exe:
            continue
        row = dict(exe)
        row.setdefault("collective_bytes", collective_bytes if not rows else 0.0)
        if "roofline" not in row:
            row["roofline"] = classify_roofline(
                flops=row.get("flops", 0.0),
                bytes_accessed=row.get("bytes_accessed", 0.0),
                collective_bytes=row["collective_bytes"],
                peaks=peaks,
            )
        rows.append(row)

    block: dict[str, Any] = {
        "device_kind": peaks.get("device_kind", "unknown"),
        "n_chips": n_chips,
        "peaks": {key: float(peaks.get(key, 0.0)) for key in _PEAK_KEYS},
        "executables": rows,
    }

    primary = rows[0] if rows else None
    if primary is not None and step_time_ms and step_time_ms > 0:
        step_s = step_time_ms / 1e3
        flops_per_chip = primary.get("flops", 0.0)
        analytical_mfu = flops_per_chip / step_s / max(peaks.get("peak_flops", 1.0), 1.0)
        mfu_block: dict[str, Any] = {"analytical": round(analytical_mfu, 6)}
        if measured_mfu is not None:
            mfu_block["measured"] = round(float(measured_mfu), 6)
        if tokens_per_step and palm_flops_per_token:
            # Deterministic form: step time cancels out of the ratio.
            # Per-device flops over per-device tokens — the same "one
            # chip" frame utils/hw.py mfu() measures in.
            ratio = flops_per_chip / (
                float(tokens_per_step) / n_chips * float(palm_flops_per_token)
            )
            mfu_block["ratio_analytical_over_measured"] = round(ratio, 4)
            lo, hi = MFU_RECONCILE_BAND
            mfu_block["reconciled"] = bool(lo <= ratio <= hi)
            mfu_block["tolerance_band"] = [lo, hi]
        block["mfu"] = mfu_block

        roof = primary.get("roofline") or classify_roofline(
            flops=primary.get("flops", 0.0),
            bytes_accessed=primary.get("bytes_accessed", 0.0),
            collective_bytes=primary.get("collective_bytes", 0.0),
            peaks=peaks,
        )
        analytical = roof.get("analytical_ms", {})
        compute_ms = analytical.get("compute", 0.0)
        comms_ms = analytical.get("comms", 0.0)
        host_ms = 0.0
        if span_totals and steps:
            for span in ("data_wait", "host_dispatch"):
                entry = span_totals.get(span)
                if entry:
                    host_ms += entry.get("total_ms", 0.0) / max(steps, 1)
        gap_ms = max(0.0, step_time_ms - compute_ms - comms_ms - host_ms)
        block["step_time_split_ms"] = {
            "step": round(step_time_ms, 3),
            "analytical_compute": round(compute_ms, 3),
            "analytical_collective": round(comms_ms, 3),
            "measured_host": round(host_ms, 3),
            "unattributed_gap": round(gap_ms, 3),
        }
    return block


def attribution_gauges(block: Mapping[str, Any]) -> dict[str, float]:
    """Flatten a perf_attribution block into ``perf/*`` registry gauges
    (rendered as ``llmtrain_perf_*`` on /metrics)."""
    gauges: dict[str, float] = {}
    rows = block.get("executables") or []
    if rows:
        primary = rows[0]
        gauges["perf/flops_per_step"] = float(primary.get("flops", 0.0))
        gauges["perf/bytes_per_step"] = float(primary.get("bytes_accessed", 0.0))
        gauges["perf/collective_bytes_per_step"] = float(
            primary.get("collective_bytes", 0.0)
        )
        roof = primary.get("roofline") or {}
        gauges["perf/arithmetic_intensity"] = float(
            roof.get("arithmetic_intensity", 0.0)
        )
        classes = {"compute": 0.0, "memory": 1.0, "comms": 2.0}
        gauges["perf/roofline_class"] = classes.get(roof.get("class", ""), -1.0)
    mfu_block = block.get("mfu") or {}
    if "analytical" in mfu_block:
        gauges["perf/mfu_analytical"] = float(mfu_block["analytical"])
    if "ratio_analytical_over_measured" in mfu_block:
        gauges["perf/mfu_reconcile_ratio"] = float(
            mfu_block["ratio_analytical_over_measured"]
        )
    split = block.get("step_time_split_ms") or {}
    for key, value in split.items():
        gauges[f"perf/step_{key}_ms"] = float(value)
    return gauges


def render_top_ops_markdown(rows: Iterable[Mapping[str, Any]]) -> list[str]:
    """Markdown table lines for a top-ops list (report.md / profile CLI)."""
    lines = [
        "| op | count | flops | bytes | flops% | bytes% | class |",
        "|---|---|---|---|---|---|---|",
    ]
    for row in rows:
        lines.append(
            "| {op} | {count} | {flops:.3g} | {bytes_accessed:.3g} "
            "| {fp:.1f}% | {bp:.1f}% | {cls} |".format(
                op=row.get("op", "?"),
                count=row.get("count", 0),
                flops=row.get("flops", 0.0),
                bytes_accessed=row.get("bytes_accessed", 0.0),
                fp=100.0 * row.get("flops_frac", 0.0),
                bp=100.0 * row.get("bytes_frac", 0.0),
                cls=row.get("class", "?"),
            )
        )
    return lines


__all__ = [
    "DEVICE_PEAKS",
    "MFU_RECONCILE_BAND",
    "resolve_peaks",
    "normalize_cost",
    "cost_summary",
    "memory_summary",
    "parse_hlo_ops",
    "top_ops",
    "classify_roofline",
    "gradient_collective_bytes",
    "lower_cost_profile",
    "aot_profile",
    "build_perf_attribution",
    "attribution_gauges",
    "render_top_ops_markdown",
]
