"""Device + host memory accounting for the telemetry subsystem.

HBM is the budget every scaling decision spends against (batch size,
remat, prefetch depth, checkpoint gathers), yet until this module the
framework only read the allocator's peak ONCE, at the end of the run
(utils/hw.peak_memory_bytes). The monitor samples at every log interval:

* ``mem/hbm_used`` / ``mem/hbm_peak`` / ``mem/hbm_limit`` from the PJRT
  ``Device.memory_stats()`` counters (the TPU allocator's live numbers);
* when the backend reports nothing (CPU PJRT, some tunneled clients) the
  used/peak figures FALL BACK to live-array introspection — the summed
  ``nbytes`` of every addressable ``jax.Array`` — so smoke runs still
  produce a trend-comparable memory series (``mem/source`` in the report
  records which estimator produced the numbers);
* ``mem/host_rss`` / ``mem/host_rss_peak`` from /proc/self (Linux) with a
  ``resource.getrusage`` fallback — host-side leaks (queued batches,
  checkpoint copies) show up here, not in HBM;
* a **headroom warning channel**: when used/limit crosses
  ``headroom_warn_frac`` the monitor logs a warning and records a
  ``hbm_headroom`` instant on the timeline — once per excursion, so a run
  sitting at 95% does not spam every interval.
"""

from __future__ import annotations

from typing import Any

from ..utils.logging import get_logger

logger = get_logger()


def _device_memory_stats() -> dict[str, float] | None:
    """First local device's memory_stats, or None when unavailable/empty."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — optional per backend
        return None
    if not stats:
        return None
    return {k: float(v) for k, v in stats.items()}


def _live_array_bytes() -> tuple[int, int]:
    """(count, summed nbytes) of live addressable jax.Arrays — the CPU
    fallback estimator for device memory, and a leak signal everywhere."""
    try:
        import jax

        count = 0
        total = 0
        for arr in jax.live_arrays():
            count += 1
            try:
                if arr.is_fully_addressable:
                    total += int(arr.nbytes)
            except Exception:  # noqa: BLE001 — deleted/donated arrays mid-walk
                continue
        return count, total
    except Exception:  # noqa: BLE001
        return 0, 0


def _host_rss_bytes() -> tuple[float, float]:
    """(current RSS, peak RSS) in bytes; 0.0 when unreadable."""
    current = 0.0
    peak = 0.0
    try:
        with open("/proc/self/status", encoding="ascii", errors="ignore") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    current = float(line.split()[1]) * 1024.0
                elif line.startswith("VmHWM:"):
                    peak = float(line.split()[1]) * 1024.0
    except OSError:
        pass
    if peak == 0.0:
        try:
            import resource

            # ru_maxrss is KiB on Linux.
            peak = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024.0
        except Exception:  # noqa: BLE001
            pass
    return current, max(peak, current)


class MemoryMonitor:
    """Interval-cadence sampler producing ``mem/*`` metrics + peaks."""

    def __init__(
        self,
        *,
        headroom_warn_frac: float = 0.92,
        timeline: Any | None = None,  # EventTimeline; Any avoids the cycle
    ) -> None:
        self._warn_frac = headroom_warn_frac
        self._timeline = timeline
        self._peak_hbm = 0.0
        self._peak_rss = 0.0
        self._peak_live_bytes = 0
        self._source = "unsampled"
        self._in_excursion = False
        self.headroom_warnings = 0
        self._opt_state: dict[str, float] = {}
        self._activations: dict[str, float] = {}

    @property
    def source(self) -> str:
        """Which estimator produced hbm numbers: memory_stats | live_arrays."""
        return self._source

    def sample(self, step: int | None = None) -> dict[str, float]:
        """One metrics sample. Never raises — memory accounting must not be
        able to kill the run it measures."""
        out: dict[str, float] = {}
        live_count, live_bytes = _live_array_bytes()
        self._peak_live_bytes = max(self._peak_live_bytes, live_bytes)
        out["mem/live_arrays"] = float(live_count)
        out["mem/live_array_bytes"] = float(live_bytes)

        rss, rss_peak = _host_rss_bytes()
        if rss:
            out["mem/host_rss"] = rss
        self._peak_rss = max(self._peak_rss, rss_peak, rss)
        if self._peak_rss:
            out["mem/host_rss_peak"] = self._peak_rss

        stats = _device_memory_stats()
        limit = 0.0
        if stats is not None:
            self._source = "memory_stats"
            used = float(stats.get("bytes_in_use") or 0.0)
            peak = float(stats.get("peak_bytes_in_use") or used)
            limit = float(stats.get("bytes_limit") or 0.0)
        else:
            # CPU/tunneled fallback: live addressable array bytes stand in
            # for allocator counters (docs/observability.md records the
            # difference; `mem/source` in the report names the estimator).
            self._source = "live_arrays"
            used = float(live_bytes)
            peak = float(self._peak_live_bytes)
        self._peak_hbm = max(self._peak_hbm, peak, used)
        out["mem/hbm_used"] = used
        out["mem/hbm_peak"] = self._peak_hbm
        if limit > 0:
            out["mem/hbm_limit"] = limit
            frac = used / limit
            out["mem/hbm_used_frac"] = frac
            self._check_headroom(frac, used, limit, step)
        return out

    def _check_headroom(
        self, frac: float, used: float, limit: float, step: int | None
    ) -> None:
        if frac >= self._warn_frac and not self._in_excursion:
            self._in_excursion = True
            self.headroom_warnings += 1
            logger.warning(
                "HBM headroom low: %.1f%% of the device limit in use "
                "(%.2f / %.2f GiB) — above the %.0f%% warning threshold; "
                "an OOM here kills the whole step, consider remat/chunked CE "
                "or a smaller micro batch (docs/perf.md)",
                100.0 * frac,
                used / 2**30,
                limit / 2**30,
                100.0 * self._warn_frac,
            )
            if self._timeline is not None:
                self._timeline.instant(
                    "hbm_headroom",
                    cat="memory",
                    step=step,
                    used_frac=round(frac, 4),
                    bytes_in_use=used,
                    bytes_limit=limit,
                )
        elif frac < self._warn_frac:
            self._in_excursion = False

    def record_opt_state(self, info: dict[str, float]) -> None:
        """Static optimizer-state footprint (trainer._opt_state_memory):
        ``opt_state_bytes`` (logical total), ``opt_state_bytes_per_device``
        (resident on one device — the ZeRO ~N_dp× reduction shows here),
        ``opt_state_bytes_host`` (held off-device by host offload).
        Merged into the report's memory block."""
        self._opt_state = {k: float(v) for k, v in info.items()}

    def record_activations(self, info: dict[str, float]) -> None:
        """Analytic activation footprint under the run's activation-tier
        ladder (trainer._activation_memory): ``activation_bytes``
        (device-resident), ``activation_bytes_offloaded`` (staged in host
        RAM by the offload tier). Merged into the report's memory block
        like the opt-state block."""
        self._activations = {k: float(v) for k, v in info.items()}

    def peaks(self) -> dict[str, float]:
        """End-of-run summary block for the report."""
        out = {
            "hbm_peak_bytes": self._peak_hbm,
            "host_rss_peak_bytes": self._peak_rss,
            "live_array_peak_bytes": float(self._peak_live_bytes),
            "headroom_warnings": float(self.headroom_warnings),
        }
        out.update(self._opt_state)
        out.update(self._activations)
        return out


__all__ = ["MemoryMonitor"]
