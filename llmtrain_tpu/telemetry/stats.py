"""Shared latency statistics: ONE nearest-rank percentile, one histogram.

Before this module the repo had two subtly different percentile
implementations — ``serving/loadgen.py`` used ceil nearest-rank
(``s[ceil(q*n)-1]``) while ``serving/http.py`` used round-index
(``s[round(q*(n-1))]``) — so "p99" in a loadgen report and "p99" on the
``/healthz`` scrape could disagree on the exact same sample set. Every
consumer (loadgen, HTTP stats, promote lifecycle gates, the trace
summary) now goes through :func:`percentile` / :func:`percentiles`, which
implement the classic **nearest-rank** definition: the smallest sample
such that at least ``q`` of the distribution is ≤ it. Nearest-rank never
interpolates, so a reported p99 is always a latency that actually
happened — the property SLO gates rely on.

:class:`Histogram` is the fixed-bucket counterpart used for Prometheus
exposition with exemplars (docs/observability.md): cumulative ``le``
buckets, a running sum/count, and per-bucket *exemplars* — the trace_id
of the most recent observation that landed in each bucket — so a
dashboard spike links straight to the distributed trace that caused it.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Iterable, Sequence

__all__ = [
    "DEFAULT_QUANTILES",
    "Exemplar",
    "Histogram",
    "percentile",
    "percentiles",
]

# The quantile set every serving surface reports (loadgen report,
# /healthz snapshot, trace summary): keep them identical so "p95" means
# the same sample rank everywhere.
DEFAULT_QUANTILES: tuple[tuple[float, str], ...] = (
    (0.50, "p50"),
    (0.95, "p95"),
    (0.99, "p99"),
)


def percentile(sorted_samples: Sequence[float], q: float) -> float:
    """Nearest-rank percentile of an ALREADY SORTED sequence.

    ``q`` in (0, 1]; rank = ceil(q * n) clamped to [1, n]. Raises on an
    empty sequence — callers decide what "no data" means (loadgen emits
    ``{}``, the sampler treats it as "keep").
    """
    n = len(sorted_samples)
    if n == 0:
        raise ValueError("percentile of empty sequence")
    rank = math.ceil(q * n)
    return float(sorted_samples[min(n - 1, max(0, rank - 1))])


def percentiles(
    samples: Iterable[float],
    quantiles: tuple[tuple[float, str], ...] = DEFAULT_QUANTILES,
    *,
    round_to: int | None = 3,
) -> dict[str, float]:
    """Nearest-rank summary (p50/p95/p99 + mean/max) of raw samples.

    Returns ``{}`` on no samples — report renderers print ``n/a`` rather
    than fabricate a zero.
    """
    s = sorted(float(x) for x in samples)
    if not s:
        return {}
    out = {label: percentile(s, q) for q, label in quantiles}
    out["mean"] = sum(s) / len(s)
    out["max"] = s[-1]
    if round_to is not None:
        out = {k: round(v, round_to) for k, v in out.items()}
    return out


@dataclass
class Exemplar:
    """The most recent observation that landed in a bucket, with the
    trace_id linking it to a distributed trace (OpenMetrics exemplars)."""

    trace_id: str
    value: float
    unix_time: float


class Histogram:
    """Fixed-bucket cumulative histogram with per-bucket exemplars.

    Thread-safe (serving records from HTTP handler threads while the
    scrape handler snapshots). Buckets are upper bounds; ``+Inf`` is
    implicit. ``observe`` is O(#buckets) with a short critical section —
    cheap enough for the per-request serving path.
    """

    def __init__(self, buckets: Sequence[float]) -> None:
        if not buckets or sorted(buckets) != list(buckets):
            raise ValueError("histogram buckets must be sorted and non-empty")
        self.buckets: tuple[float, ...] = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._exemplars: list[Exemplar | None] = [None] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(
        self,
        value: float,
        *,
        trace_id: str | None = None,
        unix_time: float | None = None,
    ) -> None:
        value = float(value)
        idx = len(self.buckets)
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if trace_id is not None:
                self._exemplars[idx] = Exemplar(
                    trace_id, value, unix_time if unix_time is not None else 0.0
                )

    def snapshot(
        self,
    ) -> tuple[list[tuple[float, int, Exemplar | None]], float, int]:
        """``([(le, cumulative_count, exemplar), ...], sum, count)`` with a
        trailing ``(inf, total, exemplar)`` row for the ``+Inf`` bucket."""
        with self._lock:
            counts = list(self._counts)
            exemplars = list(self._exemplars)
            total_sum, total_count = self._sum, self._count
        rows: list[tuple[float, int, Exemplar | None]] = []
        cum = 0
        for i, ub in enumerate(self.buckets):
            cum += counts[i]
            rows.append((ub, cum, exemplars[i]))
        rows.append((math.inf, cum + counts[-1], exemplars[-1]))
        return rows, total_sum, total_count
