"""End-of-run report: one JSON + one Markdown summary per training run.

The run dir already holds everything a post-mortem needs — tracker DB,
timeline JSONL, hang reports, checkpoints — but nothing READS like an
answer to "how did this run go?". The report is that answer, written at
the end of every fit:

* ``report.json`` — machine-readable aggregation (the perf-trajectory
  tooling and bench harness consume this);
* ``report.md`` — the same content rendered for humans (renders directly
  in any repo/artifact browser).

Contents: final/first/min loss and a bounded loss trajectory, throughput
(tokens/sec, MFU), memory peaks (HBM + host RSS + estimator source),
resilience event counts (rollbacks, non-finite skips, faults injected,
straggler warnings, headroom warnings, tracker errors), and the
wall-clock breakdown by timeline span — the fraction of the run spent in
data wait vs dispatch vs checkpoint vs eval, which is the first question
every perf investigation asks.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Any

from ..utils.logging import get_logger

logger = get_logger()

# Loss-trajectory samples kept in report.json: enough to plot the run's
# shape, bounded so a 1M-step run doesn't produce a 100 MB report.
_TRAJECTORY_CAP = 512


def _thin(rows: list[Any], cap: int = _TRAJECTORY_CAP) -> list[Any]:
    if len(rows) <= cap:
        return rows
    stride = -(-len(rows) // cap)
    thinned = rows[::stride]
    if rows and thinned[-1] != rows[-1]:
        thinned.append(rows[-1])
    return thinned


def build_report(
    *,
    run_id: str,
    run_name: str,
    registry: Any,  # MetricsRegistry
    timeline: Any,  # EventTimeline
    memory: Any | None,  # MemoryMonitor
    wall_time_sec: float,
    train_result: dict[str, Any] | None = None,
    serving: dict[str, Any] | None = None,
    perf_attribution: dict[str, Any] | None = None,
    precision: dict[str, Any] | None = None,
    goodput: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Aggregate the telemetry state into the report dict."""
    latest = registry.latest()
    counters = registry.counters()
    history = registry.history()

    loss_rows = [
        [step, row["train/loss"]]
        for step, row in history
        if "train/loss" in row and step is not None
    ]
    losses = [v for _, v in loss_rows]
    loss_block: dict[str, Any] = {
        "first_logged": losses[0] if losses else None,
        "final": losses[-1] if losses else None,
        "min": min(losses) if losses else None,
        "trajectory": _thin(loss_rows),
    }
    val_rows = [
        [step, row["val/loss"]]
        for step, row in history
        if "val/loss" in row and step is not None
    ]
    if val_rows:
        loss_block["val_final"] = val_rows[-1][1]
        loss_block["val_trajectory"] = _thin(val_rows)

    def latest_value(key: str) -> float | None:
        entry = latest.get(key)
        return entry[0] if entry is not None else None

    throughput = {
        "tokens_per_sec": latest_value("train/tokens_per_sec"),
        "mfu": latest_value("train/mfu"),
        "step_time_sec": latest_value("train/step_time_sec"),
        "data_wait_ms": latest_value("train/data_wait_ms"),
        "host_dispatch_ms": latest_value("train/host_dispatch_ms"),
        "tokens_total": latest_value("train/tokens_total"),
    }

    mem_block: dict[str, Any] = {}
    if memory is not None:
        mem_block = {k: v for k, v in memory.peaks().items()}
        mem_block["source"] = memory.source

    spans = timeline.span_totals()
    tracked_ms = sum(s["total_ms"] for s in spans.values())
    span_block = {
        name: {
            **stats,
            "frac_of_wall": (
                round(stats["total_ms"] / (wall_time_sec * 1e3), 4)
                if wall_time_sec > 0
                else 0.0
            ),
        }
        for name, stats in sorted(spans.items())
    }

    events = {
        "instants": timeline.event_counts(),
        "counters": counters,
        "tracker_errors": registry.tracker_errors,
        "timeline_events_dropped": timeline.dropped,
    }

    # Recovery totals, first-class (docs/robustness.md): "how many times
    # did this run die/rewind/re-shard" is the first question after any
    # incident, and burying it in the counters dict made it invisible.
    result = train_result or {}
    resilience_block = {
        "resumes": int(counters.get("resilience/resumes", 0)),
        "resume_count": (
            int(latest_value("resilience/resume_count") or 0)
            or int(counters.get("resilience/resumes", 0))
        ),
        "rollbacks": int(
            result.get("rollbacks", counters.get("resilience/rollbacks", 0)) or 0
        ),
        "elastic_reshards": int(counters.get("resilience/elastic_reshard", 0)),
        "checkpoint_commits": int(counters.get("checkpoint/commits", 0)),
        "nonfinite_skips": int(counters.get("resilience/nonfinite_skips", 0)),
        "preempted": bool(result.get("preempted", False)),
    }

    report = {
        "schema": "llmtrain-telemetry-report/1",
        "run": {"run_id": run_id, "name": run_name},
        "wall_clock": {
            "total_sec": round(wall_time_sec, 3),
            "tracked_span_sec": round(tracked_ms / 1e3, 3),
        },
        "loss": loss_block,
        "throughput": throughput,
        "memory": mem_block,
        "resilience": resilience_block,
        "spans": span_block,
        "events": events,
    }
    if serving is not None:
        # SLO block from the serving load harness (serving/loadgen.py):
        # TTFT/per-token percentiles, throughput, occupancy, KV-pool and
        # compile accounting — docs/serving.md documents the schema.
        report["serving"] = serving
    if perf_attribution is not None:
        # Cost-attribution block (telemetry/profiling.py): XLA-counted
        # flops/bytes per executable, roofline class, MFU reconciliation,
        # step-time split — docs/observability.md "Attribution and
        # rooflines" documents the schema.
        report["perf_attribution"] = perf_attribution
    if precision is not None:
        # Numerics provenance (docs/perf.md "Quantized matmul training"):
        # the EFFECTIVE dtypes/paths the run compiled with — compute and
        # param dtype, loss_impl (incl. the large-vocab auto-selection),
        # and the capability-resolved matmul_precision — so a throughput
        # number in this report can never be quoted without its numerics.
        report["precision"] = precision
    if goodput is not None:
        # Cross-segment wall-clock attribution (telemetry/goodput.py):
        # per-segment category table + run totals + goodput_frac, computed
        # from the durable timeline/manifest artifacts — docs/
        # observability.md "Goodput" documents the taxonomy.
        report["goodput"] = goodput
    if train_result is not None:
        report["train_result"] = train_result
    return report


def _fmt(value: Any) -> str:
    if value is None:
        return "—"
    if isinstance(value, float):
        # Diverged runs put nan/inf here, and this report is exactly the
        # artifact that must survive them (int(inf) raises OverflowError).
        if math.isnan(value):
            return "NaN"
        if math.isinf(value):
            return "inf" if value > 0 else "-inf"
        if value == 0:
            return "0"
        if abs(value) >= 1e15:
            return f"{value:.3e}"
        if abs(value) >= 1000:
            return f"{value:,.1f}"
        return f"{value:.4g}"
    return str(value)


def _fmt_bytes(value: Any) -> str:
    """Byte quantities only — _fmt cannot know units, and rendering a
    token count as GiB (or 'GiB bytes') would mislabel the report."""
    if value is None:
        return "—"
    value = float(value)
    if not math.isfinite(value):
        return _fmt(value)
    for unit, scale in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if abs(value) >= scale:
            return f"{value / scale:.2f} {unit}"
    return f"{value:.0f} B"


def render_markdown(report: dict[str, Any]) -> str:
    """Human rendering of :func:`build_report`'s dict."""
    run = report["run"]
    lines = [
        f"# Run report — {run['name']} ({run['run_id']})",
        "",
        f"Wall clock: {_fmt(report['wall_clock']['total_sec'])} s "
        f"(tracked in spans: {_fmt(report['wall_clock']['tracked_span_sec'])} s)",
        "",
        "## Loss",
        "",
    ]
    loss = report["loss"]
    lines.append(
        f"- train: first {_fmt(loss['first_logged'])} → final {_fmt(loss['final'])}"
        f" (min {_fmt(loss['min'])})"
    )
    if "val_final" in loss:
        lines.append(f"- val (final): {_fmt(loss['val_final'])}")
    lines += ["", "## Throughput", ""]
    tp = report["throughput"]
    lines.append(f"- tokens/sec: {_fmt(tp['tokens_per_sec'])}")
    lines.append(f"- MFU: {_fmt(tp['mfu'])}")
    lines.append(f"- step time: {_fmt(tp['step_time_sec'])} s")
    lines.append(
        f"- data wait: {_fmt(tp['data_wait_ms'])} ms/step, "
        f"host dispatch: {_fmt(tp['host_dispatch_ms'])} ms/step"
    )
    resil = report.get("resilience") or {}
    if resil:
        lines += ["", "## Recovery", ""]
        lines.append(
            f"- resumes: {resil.get('resume_count', 0)} "
            f"(this segment: {resil.get('resumes', 0)})"
        )
        lines.append(f"- rollbacks: {resil.get('rollbacks', 0)}")
        lines.append(f"- elastic reshards: {resil.get('elastic_reshards', 0)}")
        lines.append(
            f"- checkpoint commits: {resil.get('checkpoint_commits', 0)}"
        )
        if resil.get("preempted"):
            lines.append("- **preempted** (clean SIGTERM save)")
    mem = report.get("memory") or {}
    if mem:
        lines += ["", "## Memory", ""]
        lines.append(
            f"- HBM peak: {_fmt_bytes(mem.get('hbm_peak_bytes'))} "
            f"(source: {mem.get('source', 'unknown')})"
        )
        lines.append(f"- host RSS peak: {_fmt_bytes(mem.get('host_rss_peak_bytes'))}")
        if mem.get("opt_state_bytes") is not None:
            # ZeRO accounting (trainer.zero, docs/perf.md): per-device vs
            # total is the sharding win; host bytes appear under offload.
            line = (
                f"- optimizer state: {_fmt_bytes(mem['opt_state_bytes'])} total, "
                f"{_fmt_bytes(mem.get('opt_state_bytes_per_device'))} per device"
            )
            if mem.get("opt_state_bytes_host"):
                line += f", {_fmt_bytes(mem['opt_state_bytes_host'])} host-offloaded"
            lines.append(line)
        warns = int(mem.get("headroom_warnings") or 0)
        if warns:
            lines.append(f"- **headroom warnings: {warns}** (see timeline)")
    spans = report.get("spans") or {}
    if spans:
        lines += [
            "",
            "## Wall-clock by span",
            "",
            "| span | count | total ms | max ms | % of wall |",
            "|---|---:|---:|---:|---:|",
        ]
        for name, stats in spans.items():
            lines.append(
                f"| {name} | {int(stats['count'])} | {stats['total_ms']:.1f} "
                f"| {stats['max_ms']:.1f} | {100.0 * stats['frac_of_wall']:.1f}% |"
            )
    events = report.get("events") or {}
    instants = events.get("instants") or {}
    counters = events.get("counters") or {}
    if instants or counters or events.get("tracker_errors"):
        lines += ["", "## Events", ""]
        for name, count in sorted(instants.items()):
            lines.append(f"- {name}: {count}")
        for name, count in sorted(counters.items()):
            lines.append(f"- {name}: {_fmt(count)}")
        if events.get("tracker_errors"):
            lines.append(f"- tracker errors (degraded to warnings): {events['tracker_errors']}")
        if events.get("timeline_events_dropped"):
            lines.append(f"- timeline events dropped (cap): {events['timeline_events_dropped']}")
    goodput = report.get("goodput") or {}
    if goodput:
        from .goodput import render_goodput_md

        lines += ["", "## Goodput", ""]
        if events.get("timeline_events_dropped"):
            lines.append(
                "- **warning**: the timeline dropped "
                f"{events['timeline_events_dropped']} event(s) (retention "
                "cap) — attribution below may undercount span categories"
            )
            lines.append("")
        lines.append(render_goodput_md(goodput).rstrip("\n"))
    serving = report.get("serving") or {}
    if serving:
        lines += ["", "## Serving", ""]
        req = serving.get("requests") or {}
        lines.append(
            f"- requests: {_fmt(req.get('completed'))}/{_fmt(req.get('submitted'))}"
            f" completed, {_fmt(req.get('failed'))} failed, "
            f"{_fmt(req.get('timed_out'))} timed out"
        )
        slo = serving.get("slo") or {}
        for key, label in (("ttft_ms", "TTFT"), ("per_token_ms", "per-token")):
            pct = slo.get(key) or {}
            lines.append(
                f"- {label} p50/p95/p99: {_fmt(pct.get('p50'))} / "
                f"{_fmt(pct.get('p95'))} / {_fmt(pct.get('p99'))} ms"
            )
        tpt = serving.get("throughput") or {}
        lines.append(
            f"- tokens/sec: {_fmt(tpt.get('tokens_per_sec'))} "
            f"({_fmt(tpt.get('new_tokens'))} new tokens in "
            f"{_fmt(tpt.get('wall_sec'))} s)"
        )
        occ = serving.get("occupancy") or {}
        lines.append(
            f"- batch occupancy: peak {_fmt(occ.get('peak'))}, mean "
            f"{_fmt(occ.get('mean'))} of {_fmt(occ.get('max_batch_slots'))} slots"
        )
        kv = serving.get("kv_pool") or {}
        if kv:
            lines.append(
                f"- KV pool: peak {_fmt(kv.get('peak_allocated_blocks'))} of "
                f"{_fmt(kv.get('capacity_blocks'))} blocks "
                f"({_fmt(kv.get('block_tokens'))} tokens each)"
            )
        comp = serving.get("compile") or {}
        if comp:
            lines.append(
                f"- compiled programs: {_fmt(comp.get('prefill_programs'))} "
                f"prefill + {_fmt(comp.get('decode_programs'))} decode "
                f"(budget {_fmt(comp.get('budget'))}, within: "
                f"{comp.get('within_budget')})"
            )
        par = serving.get("parity") or {}
        if par:
            lines.append(
                f"- parity vs sequential generate(): "
                f"{_fmt(par.get('checked', 0) - par.get('mismatched', 0))}/"
                f"{_fmt(par.get('checked'))} bitwise-identical"
            )
    perf = report.get("perf_attribution") or {}
    if perf:
        lines += ["", "## Performance attribution", ""]
        peaks = perf.get("peaks") or {}
        lines.append(
            f"- device: {perf.get('device_kind', '?')} × {perf.get('n_chips', 1)} "
            f"(peak {_fmt(peaks.get('peak_flops'))} FLOP/s, "
            f"HBM {_fmt_bytes(peaks.get('hbm_bytes_per_sec'))}/s, "
            f"ICI {_fmt_bytes(peaks.get('ici_bytes_per_sec'))}/s)"
        )
        mfu_block = perf.get("mfu") or {}
        if mfu_block:
            line = (
                f"- MFU: analytical {_fmt(mfu_block.get('analytical'))} vs "
                f"measured {_fmt(mfu_block.get('measured'))}"
            )
            if "ratio_analytical_over_measured" in mfu_block:
                line += (
                    f" (flop-model ratio {_fmt(mfu_block['ratio_analytical_over_measured'])}"
                    f", reconciled: {mfu_block.get('reconciled')})"
                )
            lines.append(line)
        split = perf.get("step_time_split_ms") or {}
        if split:
            lines.append(
                f"- step time {_fmt(split.get('step'))} ms = compute "
                f"{_fmt(split.get('analytical_compute'))} + collective "
                f"{_fmt(split.get('analytical_collective'))} + host "
                f"{_fmt(split.get('measured_host'))} + unattributed "
                f"{_fmt(split.get('unattributed_gap'))}"
            )
        for exe in perf.get("executables") or []:
            roof = exe.get("roofline") or {}
            lines.append(
                f"- `{exe.get('name', '?')}`: {_fmt(exe.get('flops'))} flops, "
                f"{_fmt_bytes(exe.get('bytes_accessed'))} accessed, "
                f"intensity {_fmt(roof.get('arithmetic_intensity'))} "
                f"(ridge {_fmt(roof.get('ridge_intensity'))}) → "
                f"**{roof.get('class', '?')}-bound**"
            )
            rows = exe.get("top_ops") or []
            if rows:
                from .profiling import render_top_ops_markdown

                lines += [""] + render_top_ops_markdown(rows) + [""]
    result = report.get("train_result")
    if result:
        lines += ["", "## Result", ""]
        for key in (
            "final_step",
            "final_loss",
            "final_val_loss",
            "total_tokens",
            "parameter_count",
            "preempted",
            "rollbacks",
        ):
            if key in result:
                lines.append(f"- {key}: {_fmt(result[key])}")
    return "\n".join(lines) + "\n"


def write_reports(run_dir: str | Path, report: dict[str, Any]) -> tuple[Path | None, Path | None]:
    """Write ``report.json`` and ``report.md`` into the run dir. Never
    raises — the report describes the run, it must not fail it."""
    base = Path(run_dir)
    json_path = base / "report.json"
    md_path = base / "report.md"
    try:
        base.mkdir(parents=True, exist_ok=True)
        json_path.write_text(
            json.dumps(report, indent=2, sort_keys=False), encoding="utf-8"
        )
    except (OSError, TypeError, ValueError) as exc:
        logger.warning("report.json write failed (%s)", exc)
        json_path = None
    try:
        md_path.write_text(render_markdown(report), encoding="utf-8")
    except OSError as exc:
        logger.warning("report.md write failed (%s)", exc)
        md_path = None
    return json_path, md_path


__all__ = ["build_report", "render_markdown", "write_reports"]
