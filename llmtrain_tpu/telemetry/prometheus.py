"""Prometheus exposition: text rendering, stdlib HTTP endpoint, textfile.

Fleet-scale operation needs metrics a MACHINE can scrape without parsing
logs (MinT, PAPERS.md): every other signal this framework emits (tracker
DB, JSONL timeline, heartbeat file) requires either the run dir or a
backend client. The Prometheus text format is the lowest common
denominator — node-exporter, VictoriaMetrics, Grafana Agent, and a plain
``curl`` all consume it.

Two transports, both fed from the same render:

* :class:`PrometheusEndpoint` — a tiny stdlib ``ThreadingHTTPServer``
  (daemon threads, no dependencies) serving ``GET /metrics``; the k8s Job
  manifests annotate the pods with ``prometheus.io/scrape`` so a cluster
  scraper discovers it. Config-gated (``telemetry.prometheus``) and
  started on EVERY process — each pod has its own IP, and non-main ranks
  serve genuinely per-host data (mem/*); processes sharing one network
  namespace race for the bind and the loser degrades to a warning
  (see Telemetry.start).
* **textfile fallback** — ``{run_dir}/telemetry/metrics.prom`` rewritten
  atomically at every flush, for node-exporter's textfile collector and
  for environments where an extra listening port is unwelcome.

Naming convention (docs/observability.md): tracker metric names map
``train/loss`` → ``llmtrain_train_loss`` — one ``llmtrain_`` namespace,
non-alphanumerics folded to ``_``.
"""

from __future__ import annotations

import math
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Callable

from ..utils.logging import get_logger

logger = get_logger()

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
_PREFIX = "llmtrain_"


def prometheus_name(metric: str) -> str:
    """``train/loss`` → ``llmtrain_train_loss`` (idempotent on valid names)."""
    base = _NAME_RE.sub("_", metric.strip("/ "))
    base = re.sub(r"__+", "_", base).strip("_")
    if not base:
        base = "unnamed"
    if base[0].isdigit():
        base = "_" + base
    return base if base.startswith(_PREFIX) else _PREFIX + base


def _fmt_value(value: float) -> str:
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    return repr(float(value))


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


_LABELED_RE = re.compile(r"^(?P<base>[^{]+)(?P<labels>\{.*\})$")


def _split_labels(metric: str) -> tuple[str, str]:
    """Registry keys may embed a label set (``serve/rejected{reason="x"}``
    — how per-reason counters share one Prometheus metric family). Split
    into (base metric, labels-or-empty); only the base gets name-folded."""
    m = _LABELED_RE.match(metric)
    if m is None:
        return metric, ""
    return m.group("base"), m.group("labels")


def render_histogram(name: str, histogram: Any) -> list[str]:
    """Exposition lines for one fixed-bucket histogram with exemplars.

    ``histogram`` is a :class:`~.stats.Histogram` (anything with its
    ``snapshot()`` shape). Exemplars use the OpenMetrics suffix syntax —
    ``..._bucket{le="250"} 17 # {trace_id="..."} 212.4`` — which links a
    dashboard's TTFT spike straight to the distributed trace that caused
    it (docs/observability.md); classic-format scrapers that reject the
    suffix can strip everything after `` # ``.
    """
    rows, total_sum, total_count = histogram.snapshot()
    pname = prometheus_name(name)
    lines = [f"# TYPE {pname} histogram"]
    for le, cum, exemplar in rows:
        le_str = "+Inf" if math.isinf(le) else _fmt_value(le)
        sample = f'{pname}_bucket{{le="{le_str}"}} {cum}'
        if exemplar is not None:
            sample += (
                f' # {{trace_id="{_escape_label(exemplar.trace_id)}"}}'
                f" {_fmt_value(exemplar.value)}"
            )
        lines.append(sample)
    lines.append(f"{pname}_sum {_fmt_value(total_sum)}")
    lines.append(f"{pname}_count {total_count}")
    return lines


def render_prometheus(
    gauges: dict[str, tuple[float, int | None]],
    counters: dict[str, float] | None = None,
    info: dict[str, str] | None = None,
    histograms: dict[str, Any] | None = None,
) -> str:
    """Render the registry's state as Prometheus exposition text.

    ``gauges`` is ``{tracker metric name: (value, step)}`` (the registry's
    :meth:`~.registry.MetricsRegistry.latest`); ``counters`` become
    ``counter``-typed series; ``info`` renders as the conventional
    ``llmtrain_run_info{...} 1`` labels-only metric; ``histograms`` maps
    metric name → :class:`~.stats.Histogram` (see :func:`render_histogram`).
    """
    lines: list[str] = []
    if info:
        labels = ",".join(
            f'{_NAME_RE.sub("_", k)}="{_escape_label(str(v))}"'
            for k, v in sorted(info.items())
        )
        lines.append("# TYPE llmtrain_run_info gauge")
        lines.append(f"llmtrain_run_info{{{labels}}} 1")
    # Labeled series (serve/rejected{reason="..."}) share one family:
    # emit one TYPE line per family, however many labeled samples.
    typed: set[str] = set()
    for metric in sorted(gauges):
        value, _step = gauges[metric]
        base, labels = _split_labels(metric)
        name = prometheus_name(base)
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{labels} {_fmt_value(value)}")
    typed.clear()
    for metric in sorted(counters or {}):
        base, labels = _split_labels(metric)
        name = prometheus_name(base) + "_total"
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} counter")
        lines.append(f"{name}{labels} {_fmt_value((counters or {})[metric])}")
    for metric in sorted(histograms or {}):
        lines.extend(render_histogram(metric, (histograms or {})[metric]))
    return "\n".join(lines) + "\n"


# Quote-aware label block: a `}` or `#` inside a quoted label value
# (escapes included) doesn't terminate it, so a value that happens to
# contain ` # {` still parses as one label set.
_LABELS_PAT = r"\{(?:[^\"{}]|\"(?:[^\"\\]|\\.)*\")*\}"
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    rf"(?P<labels>{_LABELS_PAT})?"
    r"\s+(?P<value>\S+)"
    # Optional OpenMetrics exemplar suffix — ` # {trace_id="..."} 212.4`
    # (see render_histogram) — anchored AFTER the sample value so it can
    # only ever match a real exemplar, never label-value content.
    rf"(?:\s+#\s+{_LABELS_PAT}\s+\S+(?:\s+\S+)?)?"
    r"\s*$"
)
_TYPE_RE = re.compile(r"^#\s*TYPE\s+(?P<name>\S+)\s+(?P<type>\S+)\s*$")


def federate_prometheus(sources: dict[str, str]) -> str:
    """Merge per-tenant exposition texts into ONE scrape payload.

    The fleet supervisor runs tenants as subprocesses whose own
    ``/metrics`` ports are ephemeral (or textfile-only); a cluster
    scraper should not have to discover N moving targets. This re-emits
    every tenant series with a ``tenant="<name>"`` label injected (merged
    in front of any existing labels) and additionally rolls counters up
    into an unlabeled fleet-wide sum, so ``llmtrain_*_total`` without a
    selector reads as "the whole fleet".

    ``sources`` maps tenant name → that tenant's exposition text (e.g.
    the content of its ``telemetry/metrics.prom`` textfile). Unparseable
    lines are dropped, not propagated — one corrupt tenant file must not
    poison the fleet scrape.
    """
    types: dict[str, str] = {}
    series: dict[str, list[str]] = {}
    counter_sums: dict[str, float] = {}
    for tenant in sorted(sources):
        tenant_label = f'tenant="{_escape_label(tenant)}"'
        for line in sources[tenant].splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("#"):
                m = _TYPE_RE.match(line)
                if m:
                    types.setdefault(m.group("name"), m.group("type"))
                continue
            # Exemplar suffixes (`... # {trace_id="..."} 1.2`) are valid
            # OpenMetrics but not part of the sample proper — _SAMPLE_RE
            # accepts-and-ignores them so histogram buckets federate
            # (without the exemplar) instead of being dropped.
            m = _SAMPLE_RE.match(line)
            if m is None:
                continue
            name, labels, value = m.group("name"), m.group("labels"), m.group("value")
            inner = (labels or "{}")[1:-1].strip()
            merged = tenant_label + ("," + inner if inner else "")
            series.setdefault(name, []).append(f"{name}{{{merged}}} {value}")
            if types.get(name) == "counter":
                try:
                    counter_sums[name] = counter_sums.get(name, 0.0) + float(value)
                except ValueError:
                    pass
    lines: list[str] = []
    for name in sorted(series):
        lines.append(f"# TYPE {name} {types.get(name, 'gauge')}")
        lines.extend(series[name])
        if name in counter_sums:
            lines.append(f"{name} {_fmt_value(counter_sums[name])}")
    return "\n".join(lines) + "\n" if lines else ""


def write_textfile(path: str | Path, text: str) -> bool:
    """Atomic write (tmp + rename) of the textfile-collector snapshot; a
    scraper must never read a half-written file. Never raises."""
    target = Path(path)
    tmp = target.with_name(target.name + ".tmp")
    try:
        target.parent.mkdir(parents=True, exist_ok=True)
        tmp.write_text(text, encoding="utf-8")
        tmp.replace(target)
        return True
    except OSError as exc:
        logger.warning("prometheus textfile write to %s failed (%s)", target, exc)
        return False


class _Handler(BaseHTTPRequestHandler):
    # the provider closure is injected per-server via the factory below
    provider: Callable[[], str]

    def do_GET(self) -> None:  # noqa: N802 - stdlib naming
        if self.path.split("?")[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        try:
            body = self.provider().encode("utf-8")
        except Exception as exc:  # noqa: BLE001 — a scrape must not crash training
            self.send_error(500, explain=str(exc)[:200])
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args: Any) -> None:
        # Scrapes arrive every few seconds; stdout noise helps nobody.
        pass


class PrometheusEndpoint:
    """Config-gated ``/metrics`` HTTP server on a daemon thread.

    ``port=0`` binds an ephemeral port (tests); the bound port is readable
    via :attr:`port`. Construction failures (port taken, no permission)
    raise — the caller (Telemetry facade) degrades them to a warning so a
    busy port never kills a training run.
    """

    def __init__(
        self,
        provider: Callable[[], str],
        *,
        host: str = "0.0.0.0",
        port: int = 9200,
    ) -> None:
        handler = type("BoundHandler", (_Handler,), {"provider": staticmethod(provider)})
        self._server = ThreadingHTTPServer((host, port), handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="prometheus-metrics",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return int(self._server.server_address[1])

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass
        self._thread.join(timeout=5.0)


__all__ = [
    "PrometheusEndpoint",
    "federate_prometheus",
    "prometheus_name",
    "render_histogram",
    "render_prometheus",
    "write_textfile",
]
