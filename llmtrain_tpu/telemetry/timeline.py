"""Structured per-step event timeline: spans + instants, JSONL + Perfetto.

The framework now has fast paths (async prefetch) and failure paths
(watchdog, rollback, fault injection) but, before this module, no single
record of *when* each of them happened relative to the step loop. The
timeline is that record: every span (data_wait, host_dispatch, checkpoint
save/wait, eval, rollback restore, prefetch assembly) and every instant
event (rollback, fault injection, straggler warning, HBM headroom,
hang detection) lands in one ordered stream that is

* appended to ``{run_dir}/telemetry/timeline.jsonl`` at each flush point
  (one JSON object per line — greppable mid-run, tail-able on a pod), and
* exported at end of run as ``{run_dir}/telemetry/trace.json`` in the
  Chrome/Perfetto trace-event format, so ``ui.perfetto.dev`` renders the
  whole run as a track-per-thread timeline.

Alignment with XLA profiles: ``span`` optionally enters a
``jax.profiler.TraceAnnotation`` of the same name, and the trainer wraps
each step in :func:`step_annotation` — so when a ``jax.profiler`` window
is active, the framework spans appear as named regions inside the XPlane
trace and line up 1:1 with the device timeline.

Rollback semantics (docs/robustness.md): events recorded during a window
that is later rolled back are NOT dropped — :meth:`EventTimeline.tag_rollback`
marks them ``rolled_back: true`` so a post-mortem can still see what the
poisoned window did. Tagging happens before the boundary flush, so the
JSONL on disk carries the tags too.

Thread safety: the prefetch producer and the step loop record
concurrently; all mutation is under one lock (the hot-path cost is a
dict append, far below the numpy work inside any span).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager, nullcontext
from pathlib import Path
from typing import Any, Iterator

from ..utils.logging import get_logger

logger = get_logger()


def step_annotation(step: int, *, enabled: bool = True):
    """``jax.profiler.StepTraceAnnotation`` for optimizer step ``step``.

    Best-effort: profiling alignment must never be able to kill a step, so
    any failure (old jax, no profiler backend) degrades to a nullcontext.
    """
    if not enabled:
        return nullcontext()
    try:
        import jax

        return jax.profiler.StepTraceAnnotation("train", step_num=step)
    except Exception:  # noqa: BLE001 — alignment is optional, training is not
        return nullcontext()


def _trace_annotation(name: str):
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001
        return nullcontext()


class EventTimeline:
    """Append-only event stream with bounded memory and JSONL persistence.

    ``jsonl_path`` None keeps the timeline memory-only (non-main ranks,
    eval-only runs). ``max_events`` bounds the retained list; overflow
    drops the OLDEST flushed events (the JSONL already has them) and
    counts the drop so the Perfetto export can say it is partial.
    """

    def __init__(
        self,
        jsonl_path: str | Path | None = None,
        *,
        process_index: int = 0,
        max_events: int = 200_000,
        xprof_annotations: bool = True,
        enabled: bool = True,
    ) -> None:
        # enabled=False makes every recording call a true no-op (no lock,
        # no retained dicts, no TraceAnnotation) so the master telemetry
        # switch removes the subsystem from the hot path entirely.
        self._enabled = enabled
        self._jsonl_path = Path(jsonl_path) if jsonl_path is not None else None
        self._process_index = process_index
        self._max_events = max(1000, int(max_events))
        self._xprof = xprof_annotations
        self._lock = threading.Lock()
        self._events: list[dict[str, Any]] = []
        self._flushed = 0  # events [0, _flushed) are already on disk
        self._dropped = 0
        # Event timestamps are perf_counter-relative microseconds; the
        # wall-clock anchor lets post-processing map them to real time.
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        # Segment identity (telemetry/goodput.py): the JSONL is opened in
        # append mode, so successive resume segments of one run share ONE
        # file — an EAGERLY written header line per process delimits them,
        # and segment_id = number of headers already on disk gives the
        # ledger a monotonic ordering with no reliance on file mtimes.
        # Written at construction (not first flush) so even a segment
        # SIGKILLed before its first flush leaves its start time behind.
        self._segment_id = 0
        self._segment_ended = False
        if self._enabled and self._jsonl_path is not None:
            self._segment_id = self._write_segment_header()

    # ------------------------------------------------------------- recording

    @property
    def origin_unix_time(self) -> float:
        return self._wall0

    @property
    def segment_id(self) -> int:
        """This process's 0-based position in the run's segment sequence."""
        return self._segment_id

    def _write_segment_header(self) -> int:
        """Append this process's segment-start record; returns its id.

        Best-effort like every other persistence path: an unwritable disk
        degrades to a memory-only segment (id from whatever was readable),
        never an exception in the constructor."""
        marker = '"name": "segment_start"'
        segment_id = 0
        try:
            if self._jsonl_path.is_file():
                segment_id = self._jsonl_path.read_text(
                    encoding="utf-8"
                ).count(marker)
        except OSError:
            pass
        header = {
            "name": "segment_start",
            "ph": "seg",
            "segment_id": segment_id,
            "start_unix_time": self._wall0,
            "process_index": self._process_index,
            "pid": os.getpid(),
        }
        try:
            self._jsonl_path.parent.mkdir(parents=True, exist_ok=True)
            with self._jsonl_path.open("a", encoding="utf-8") as fh:
                fh.write(json.dumps(header, sort_keys=True) + "\n")
        except OSError as exc:
            logger.warning(
                "timeline segment header to %s failed (%s); continuing",
                self._jsonl_path,
                exc,
            )
        return segment_id

    def end_segment(self) -> None:
        """Append the clean-exit footer (idempotent). Crashed segments
        never reach this; the goodput ledger then infers the end from the
        newest event timestamp and the heartbeat mtime instead."""
        if not self._enabled or self._jsonl_path is None or self._segment_ended:
            return
        self._segment_ended = True
        footer = {
            "name": "segment_end",
            "ph": "seg",
            "segment_id": self._segment_id,
            "end_unix_time": time.time(),
        }
        try:
            with self._jsonl_path.open("a", encoding="utf-8") as fh:
                fh.write(json.dumps(footer, sort_keys=True) + "\n")
        except OSError as exc:
            logger.warning(
                "timeline segment footer to %s failed (%s); continuing",
                self._jsonl_path,
                exc,
            )

    def _now_us(self) -> int:
        return int((time.perf_counter() - self._t0) * 1e6)

    def _append(self, event: dict[str, Any]) -> None:
        with self._lock:
            self._events.append(event)
            if len(self._events) > self._max_events:
                # Drop the oldest FLUSHED prefix first: those lines are
                # already durable in the JSONL. Unflushed events are only
                # dropped when flushing has no sink at all (memory-only).
                drop = len(self._events) - self._max_events
                drop = min(drop, self._flushed) if self._jsonl_path else drop
                if drop > 0:
                    del self._events[:drop]
                    self._flushed = max(0, self._flushed - drop)
                    self._dropped += drop

    @contextmanager
    def span(
        self, name: str, *, cat: str = "train", step: int | None = None, **args: Any
    ) -> Iterator[None]:
        """Record a duration event around the body; never raises from the
        recording itself (the body's exceptions propagate untouched)."""
        if not self._enabled:
            yield
            return
        start = self._now_us()
        cm = _trace_annotation(name) if self._xprof else nullcontext()
        try:
            with cm:
                yield
        finally:
            end = self._now_us()
            event: dict[str, Any] = {
                "name": name,
                "cat": cat,
                "ph": "X",
                "ts_us": start,
                "dur_us": max(0, end - start),
                "thread": threading.current_thread().name,
            }
            if step is not None:
                event["step"] = int(step)
            if args:
                event["args"] = args
            self._append(event)

    def record(
        self,
        name: str,
        *,
        t0: float,
        t1: float,
        cat: str = "train",
        step: int | None = None,
        **args: Any,
    ) -> None:
        """Record a duration event from perf_counter stamps the caller
        already took — the hot loop's path: its interval accumulators and
        the timeline share ONE set of clock reads, so the span record and
        the `train/data_wait_ms` family can never drift apart."""
        if not self._enabled:
            return
        event: dict[str, Any] = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts_us": int((t0 - self._t0) * 1e6),
            "dur_us": max(0, int((t1 - t0) * 1e6)),
            "thread": threading.current_thread().name,
        }
        if step is not None:
            event["step"] = int(step)
        if args:
            event["args"] = args
        self._append(event)

    def instant(
        self,
        name: str,
        *,
        cat: str = "event",
        step: int | None = None,
        t: float | None = None,
        **args: Any,
    ) -> None:
        """Point event; ``t`` (a perf_counter stamp the caller already
        took) backdates it — the trace flush path records marks at their
        TRUE time, not the flush time."""
        if not self._enabled:
            return
        event: dict[str, Any] = {
            "name": name,
            "cat": cat,
            "ph": "i",
            "ts_us": self._now_us() if t is None else int((t - self._t0) * 1e6),
            "dur_us": 0,
            "thread": threading.current_thread().name,
        }
        if step is not None:
            event["step"] = int(step)
        if args:
            event["args"] = args
        self._append(event)

    def tag_rollback(self, first_step: int, last_step: int) -> None:
        """Mark every retained event of steps [first_step, last_step] as
        belonging to a rolled-back window. Runs BEFORE the boundary flush,
        so unflushed events carry the tag into the JSONL; events of the
        window flushed in earlier intervals keep their lines but the
        paired ``rollback`` instant (recorded by the trainer) gives
        post-processing the window to re-tag them."""
        with self._lock:
            for event in self._events:
                step = event.get("step")
                if step is not None and first_step <= step <= last_step:
                    event["rolled_back"] = True

    # ----------------------------------------------------------- persistence

    def flush(self) -> None:
        """Append every not-yet-persisted event to the JSONL (no-op when
        memory-only). Never raises: a full disk must not kill the step loop."""
        if self._jsonl_path is None:
            return
        with self._lock:
            pending = self._events[self._flushed :]
            self._flushed = len(self._events)
        if not pending:
            return
        try:
            self._jsonl_path.parent.mkdir(parents=True, exist_ok=True)
            with self._jsonl_path.open("a", encoding="utf-8") as fh:
                for event in pending:
                    fh.write(json.dumps(event, sort_keys=True) + "\n")
        except OSError as exc:
            logger.warning("timeline flush to %s failed (%s); continuing", self._jsonl_path, exc)

    def events(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        return self._dropped

    # -------------------------------------------------------------- analysis

    def span_totals(self) -> dict[str, dict[str, float]]:
        """Wall-clock breakdown: {span name: {count, total_ms, max_ms}} over
        retained duration events — the report's and bench's summary input."""
        totals: dict[str, dict[str, float]] = {}
        for event in self.events():
            if event.get("ph") != "X":
                continue
            entry = totals.setdefault(
                event["name"], {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
            )
            ms = event["dur_us"] / 1e3
            entry["count"] += 1
            entry["total_ms"] += ms
            entry["max_ms"] = max(entry["max_ms"], ms)
        for entry in totals.values():
            entry["total_ms"] = round(entry["total_ms"], 3)
            entry["max_ms"] = round(entry["max_ms"], 3)
        return totals

    def event_counts(self) -> dict[str, int]:
        """{instant-event name: occurrences} — rollbacks, faults, warnings."""
        counts: dict[str, int] = {}
        for event in self.events():
            if event.get("ph") == "i":
                counts[event["name"]] = counts.get(event["name"], 0) + 1
        return counts

    # ------------------------------------------------------------- exporters

    def export_perfetto(self, path: str | Path) -> Path | None:
        """Write the retained events as a Chrome/Perfetto trace-event JSON.

        ``pid`` is the JAX process index, ``tid`` a stable small int per
        recording thread (with ``thread_name`` metadata so Perfetto shows
        real names). Returns the path, or None when the write failed
        (logged — exporting must not fail the run it describes)."""
        target = Path(path)
        events = self.events()
        tids: dict[str, int] = {}
        trace_events: list[dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self._process_index,
                "tid": 0,
                "args": {"name": f"llmtrain host {self._process_index}"},
            }
        ]
        for event in events:
            thread = event.get("thread", "MainThread")
            if thread not in tids:
                tids[thread] = len(tids) + 1
                trace_events.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": self._process_index,
                        "tid": tids[thread],
                        "args": {"name": thread},
                    }
                )
            out: dict[str, Any] = {
                "name": event["name"],
                "cat": event.get("cat", "train"),
                "ph": event.get("ph", "X"),
                "ts": event["ts_us"],
                "pid": self._process_index,
                "tid": tids[thread],
            }
            if out["ph"] == "X":
                out["dur"] = event.get("dur_us", 0)
            if out["ph"] == "i":
                out["s"] = "t"  # thread-scoped instant marker
            args = dict(event.get("args") or {})
            if "step" in event:
                args["step"] = event["step"]
            if event.get("rolled_back"):
                args["rolled_back"] = True
            if args:
                out["args"] = args
            trace_events.append(out)
        payload = {
            "traceEvents": trace_events,
            "displayTimeUnit": "ms",
            "otherData": {
                "origin_unix_time": self._wall0,
                "dropped_events": self._dropped,
            },
        }
        try:
            target.parent.mkdir(parents=True, exist_ok=True)
            target.write_text(json.dumps(payload), encoding="utf-8")
            return target
        except OSError as exc:
            logger.warning("perfetto export to %s failed (%s)", target, exc)
            return None


__all__ = ["EventTimeline", "step_annotation"]
