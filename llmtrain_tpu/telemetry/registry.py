"""MetricsRegistry: one publish surface, one flush point, degraded failures.

Before this module, the trainer called ``tracker.log_metrics`` directly at
several places per interval (global metrics, per-rank metrics, eval) — and
a tracker backend exception (mlflow server down, sqlite volume full,
tensorboard file rotated away) propagated straight into the step loop and
killed the run. Production stance (TorchTitan's metrics processor, MinT's
fleet telemetry — PAPERS.md): losing a metrics sample must cost a warning,
never a training run.

The registry is the indirection that buys that:

* components (trainer, prefetcher, watchdog, checkpoint manager) call
  :meth:`publish` / :meth:`inc` freely — pure dict work, cannot fail;
* :meth:`flush` pushes the pending sample to the tracker ONCE per log
  interval inside a try/except that degrades to a rate-limited warning
  and an error counter (`telemetry/tracker_errors`);
* the last flushed value of every metric stays readable via :meth:`latest`
  — which is what the Prometheus exporter scrapes and the end-of-run
  report aggregates, so observability keeps working even while the
  tracker backend is down.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any

from ..tracking.base import Tracker
from ..utils.logging import get_logger

logger = get_logger()

# Re-warn cadence while a tracker stays broken: the first failure warns,
# then every Nth, so a dead mlflow server doesn't turn the log into noise.
_REWARN_EVERY = 50

# Metric keys whose per-flush history feeds the end-of-run report's
# trajectory section (bounded deque; everything else keeps latest only).
_HISTORY_KEYS = (
    "train/loss",
    "val/loss",
    "train/tokens_per_sec",
    "train/mfu",
    "mem/hbm_used",
)


class MetricsRegistry:
    """Buffered metric publication with a degrade-to-warning tracker flush."""

    def __init__(
        self, tracker: Tracker | None, *, history_len: int = 2048
    ) -> None:
        self._tracker = tracker
        self._lock = threading.Lock()
        self._pending: dict[str, float] = {}
        self._latest: dict[str, tuple[float, int | None]] = {}
        self._counters: dict[str, float] = {}
        self._history: deque[tuple[int | None, dict[str, float]]] = deque(
            maxlen=history_len
        )
        self._histograms: dict[str, Any] = {}
        self._error_streak = 0
        self._total_errors = 0

    # -------------------------------------------------------------- publish

    def publish(self, metrics: dict[str, float], step: int | None = None) -> None:
        """Buffer a metrics sample for the next flush (last write wins per
        key within an interval). Also updates the live values immediately
        so Prometheus scrapes between flushes see fresh data."""
        if not metrics:
            return
        with self._lock:
            for key, value in metrics.items():
                value = float(value)
                self._pending[key] = value
                self._latest[key] = (value, step)

    def inc(self, name: str, by: float = 1.0) -> None:
        """Monotonic event counter (rollbacks, faults, tracker errors...)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + by

    def observe(
        self,
        name: str,
        value: float,
        *,
        buckets: tuple[float, ...],
        trace_id: str | None = None,
        unix_time: float | None = None,
    ) -> None:
        """Record one sample into a named fixed-bucket histogram.

        First call per name creates the histogram with ``buckets``
        (subsequent calls reuse it; changing the bucket layout of a live
        metric mid-run is not a thing Prometheus can represent anyway).
        ``trace_id`` attaches an exemplar to the bucket the sample lands
        in — the dashboard→trace link (docs/observability.md).
        """
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                from .stats import Histogram

                hist = self._histograms[name] = Histogram(buckets)
        hist.observe(value, trace_id=trace_id, unix_time=unix_time)

    # ---------------------------------------------------------------- flush

    def flush(self, step: int | None = None) -> bool:
        """Push the pending sample to the tracker; True when it landed.

        Failures NEVER propagate: the step loop calling this must survive
        any tracker backend state (satellite fix — backend exceptions used
        to unwind into the training loop)."""
        with self._lock:
            sample = dict(self._pending)
            self._pending.clear()
            if sample:
                row = {k: sample[k] for k in _HISTORY_KEYS if k in sample}
                if row:
                    self._history.append((step, row))
        if not sample or self._tracker is None:
            return bool(sample)
        try:
            self._tracker.log_metrics(sample, step=step)
        except Exception as exc:  # noqa: BLE001 — degrade, never kill the run
            self._error_streak += 1
            self._total_errors += 1
            self.inc("telemetry/tracker_errors")
            if self._error_streak == 1 or self._error_streak % _REWARN_EVERY == 0:
                logger.warning(
                    "tracker log_metrics failed (%s failure%s in a row): %s — "
                    "continuing without it; metrics stay available via the "
                    "telemetry registry/Prometheus endpoint",
                    self._error_streak,
                    "" if self._error_streak == 1 else "s",
                    exc,
                )
            return False
        if self._error_streak:
            logger.info(
                "tracker recovered after %d failed flush(es)", self._error_streak
            )
        self._error_streak = 0
        return True

    # -------------------------------------------- safe non-metric passthrough

    def safe_log_params(self, params: dict[str, Any]) -> bool:
        return self._safe("log_params", params)

    def safe_log_artifact(self, local_path: str, artifact_path: str | None = None) -> bool:
        return self._safe("log_artifact", local_path, artifact_path)

    def _safe(self, method: str, *args: Any) -> bool:
        """Tracker call with the same degrade-to-warning stance as flush."""
        if self._tracker is None:
            return False
        try:
            getattr(self._tracker, method)(*args)
            return True
        except Exception as exc:  # noqa: BLE001
            self._total_errors += 1
            self.inc("telemetry/tracker_errors")
            logger.warning("tracker %s failed: %s — continuing", method, exc)
            return False

    # ---------------------------------------------------------------- reads

    def latest(self) -> dict[str, tuple[float, int | None]]:
        with self._lock:
            return dict(self._latest)

    def counters(self) -> dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def histograms(self) -> dict[str, Any]:
        """Live :class:`~.stats.Histogram` objects by metric name (the
        objects are thread-safe; renderers snapshot them)."""
        with self._lock:
            return dict(self._histograms)

    def history(self) -> list[tuple[int | None, dict[str, float]]]:
        with self._lock:
            return list(self._history)

    @property
    def tracker_errors(self) -> int:
        return self._total_errors


__all__ = ["MetricsRegistry"]
