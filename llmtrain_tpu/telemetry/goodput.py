"""Goodput ledger: cross-segment wall-clock attribution from durable artifacts.

PR 4's timeline and PR 10's cost attribution answer "is a *step* fast?";
this module answers the fleet-scheduling question underneath them: of the
total wall-clock a run (or a whole fleet) consumed, how much became
training progress? Every second between the first segment's process start
and the run's end is attributed to a fixed taxonomy:

* ``productive_train`` — step executions that survived into the final
  trajectory (the LAST execution of each optimizer step);
* ``recomputed``      — step executions later re-run, after an in-process
  spike rollback or a resume from an older commit (the replay cost the
  chaos/fleet drills pay for crash consistency);
* ``compile``         — segment 0's window from process start to the first
  dispatched step (init + data setup + first-step compile);
* ``data_wait``       — host blocked waiting on the input pipeline;
* ``checkpoint``      — save gather + commit wait + rollback restore;
* ``eval``            — interval evaluation;
* ``restart_overhead``— process death → the NEXT segment's first
  dispatched step (the cross-segment gap seen from segment boundaries
  plus the replacement process's warmup; on k8s this includes pod
  reschedule time, visible as a beacon gap);
* ``suspended``       — fleet allocation-0 windows carved out of
  restart_overhead (scheduler decisions, not failures);
* ``unattributed``    — the residual (untimed host work: logging, report
  writes, metric flushes).

Everything is computed POST-HOC from durable artifacts — the per-run
``telemetry/timeline.jsonl`` (whose per-process segment header/footer
lines order segments without file mtimes), checkpoint manifests, and the
watchdog heartbeat file — so the ledger survives SIGKILL and can be
rendered for any past run by ``llmtrain goodput --run-dir`` with every
process dead. The invariant the tests pin: the categories sum to the
total wall-clock exactly (residual is a category, not an error term).

See docs/observability.md "Goodput" for the taxonomy contract and
docs/robustness.md for the chaos/fleet goodput floors gating on it.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Any, Iterable

from ..utils.logging import get_logger

logger = get_logger()

CATEGORIES = (
    "productive_train",
    "recomputed",
    "compile",
    "data_wait",
    "checkpoint",
    "eval",
    "restart_overhead",
    "suspended",
    "unattributed",
)

# Span-name → category map for the step-loop spans the trainer records on
# the main thread. Restricting attribution to THIS whitelist keeps
# concurrent producer-thread spans (prefetch assembly overlaps the step)
# from being double-counted against wall-clock.
_DATA_SPANS = frozenset({"data_wait"})
_CKPT_SPANS = frozenset({"checkpoint_save", "checkpoint_wait", "rollback_restore"})
_EVAL_SPANS = frozenset({"eval"})

_MANIFEST_RE = re.compile(r"step_(\d+)\.manifest\.json$")


class _Segment:
    """One process lifetime of the run, delimited by timeline header lines."""

    def __init__(self, segment_id: int, start: float) -> None:
        self.segment_id = segment_id
        self.start = start
        self.end: float | None = None  # footer end_unix_time when clean
        self.clean_end = False
        self.events: list[dict[str, Any]] = []


def _parse_segments(timeline_path: Path) -> list[_Segment]:
    """Split the (append-mode, cross-process) JSONL into ordered segments.

    Tolerant by design: a SIGKILL can tear the final line mid-write, and
    pre-ledger runs have no header lines at all (→ empty result; the
    ledger is unavailable rather than wrong)."""
    segments: list[_Segment] = []
    try:
        text = timeline_path.read_text(encoding="utf-8")
    except OSError as exc:
        logger.warning("goodput: timeline %s unreadable (%s)", timeline_path, exc)
        return []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            event = json.loads(line)
        except ValueError:
            continue  # torn tail line from a mid-write kill
        if not isinstance(event, dict):
            continue
        name = event.get("name")
        if name == "segment_start" and "start_unix_time" in event:
            segments.append(
                _Segment(int(event.get("segment_id", len(segments))),
                         float(event["start_unix_time"]))
            )
        elif name == "segment_end" and segments and "end_unix_time" in event:
            segments[-1].end = float(event["end_unix_time"])
            segments[-1].clean_end = True
        elif segments:
            segments[-1].events.append(event)
    segments.sort(key=lambda s: (s.segment_id, s.start))
    return segments


def _span_seconds(events: Iterable[dict[str, Any]], names: frozenset[str]) -> float:
    return sum(
        e.get("dur_us", 0) / 1e6
        for e in events
        if e.get("ph") == "X" and e.get("name") in names
    )


def final_committed_step(ckpt_dir: Path) -> int | None:
    """Newest manifest-committed step — read-only, no payload hashing."""
    best: int | None = None
    if not ckpt_dir.is_dir():
        return None
    for path in ckpt_dir.iterdir():
        m = _MANIFEST_RE.match(path.name)
        if m:
            step = int(m.group(1))
            best = step if best is None else max(best, step)
    return best


def _carve_suspensions(
    gap_start: float,
    gap_end: float,
    windows: Iterable[tuple[float, float]],
) -> float:
    """Seconds of [gap_start, gap_end] covered by suspension windows."""
    covered = 0.0
    for w0, w1 in windows:
        lo, hi = max(gap_start, float(w0)), min(gap_end, float(w1))
        if hi > lo:
            covered += hi - lo
    return min(covered, max(0.0, gap_end - gap_start))


def compute_goodput(
    run_dir: str | Path,
    *,
    suspensions: Iterable[tuple[float, float]] | None = None,
    heartbeat_name: str = "heartbeat",
) -> dict[str, Any] | None:
    """Build the ledger for one run directory, or None when the run has no
    segment-delimited timeline (pre-ledger runs, telemetry disabled).

    ``suspensions`` are wall-clock (t0, t1) allocation-0 windows supplied
    by the fleet supervisor; the overlap with cross-segment gaps moves
    from ``restart_overhead`` to ``suspended``.
    """
    run_dir = Path(run_dir)
    timeline_path = run_dir / "telemetry" / "timeline.jsonl"
    if not timeline_path.is_file():
        return None
    segments = _parse_segments(timeline_path)
    if not segments:
        return None
    windows = [(float(a), float(b)) for a, b in (suspensions or [])]

    # Segment end: footer when the process exited cleanly; otherwise the
    # newest event timestamp, extended (last segment only) by the watchdog
    # heartbeat mtime — the beacon often outlives the last flushed event
    # on a SIGKILL, and that stranded progress is real wall-clock.
    hb = run_dir / heartbeat_name
    hb_mtime = hb.stat().st_mtime if hb.is_file() else None
    for idx, seg in enumerate(segments):
        event_end = max(
            ((e.get("ts_us", 0) + e.get("dur_us", 0)) / 1e6 for e in seg.events),
            default=0.0,
        )
        if seg.end is None:
            seg.end = seg.start + event_end
            if idx == len(segments) - 1 and hb_mtime is not None:
                seg.end = max(seg.end, hb_mtime)
        if idx + 1 < len(segments):
            # A crashed segment's inferred end can never run past the next
            # process's start (clock jitter / stale heartbeat guard).
            seg.end = min(seg.end, segments[idx + 1].start)
        seg.end = max(seg.end, seg.start)

    # Step executions in global order; the LAST execution of each step is
    # the one that survived into the final trajectory — every earlier
    # execution (rollback replay, resume-from-older-commit) is recomputed.
    executions: list[tuple[int, int, float]] = []  # (seg_idx, step, dur_sec)
    for idx, seg in enumerate(segments):
        for e in seg.events:
            if e.get("ph") == "X" and e.get("name") == "host_dispatch" and "step" in e:
                executions.append((idx, int(e["step"]), e.get("dur_us", 0) / 1e6))
    last_exec_index: dict[int, int] = {}
    for i, (_, step, _) in enumerate(executions):
        last_exec_index[step] = i
    productive_ids = set(last_exec_index.values())

    seg_rows: list[dict[str, Any]] = []
    totals = {c: 0.0 for c in CATEGORIES}
    exec_cursor = 0
    for idx, seg in enumerate(segments):
        cats = {c: 0.0 for c in CATEGORIES}
        seg_total = seg.end - seg.start
        seg_execs: list[tuple[int, int, float]] = []
        while exec_cursor < len(executions) and executions[exec_cursor][0] == idx:
            seg_execs.append(executions[exec_cursor])
            exec_cursor += 1
        # The pre-step window ends where the step loop's own accounting
        # begins: the FIRST data_wait/host_dispatch span (data_wait for
        # step 1 starts before its dispatch — ending at the dispatch would
        # double-count the first batch's assembly).
        first_step_ts = min(
            (
                e.get("ts_us", 0) / 1e6
                for e in seg.events
                if e.get("ph") == "X"
                and e.get("name") in ("data_wait", "host_dispatch")
                and "step" in e
            ),
            default=None,
        )
        pre_step = seg_total if first_step_ts is None else min(first_step_ts, seg_total)
        gap = 0.0
        if idx == 0:
            cats["compile"] = pre_step
        else:
            gap = max(0.0, seg.start - segments[idx - 1].end)
            suspended = _carve_suspensions(segments[idx - 1].end, seg.start, windows)
            cats["suspended"] = suspended
            cats["restart_overhead"] = gap - suspended + pre_step
        cats["data_wait"] = _span_seconds(seg.events, _DATA_SPANS)
        cats["checkpoint"] = _span_seconds(seg.events, _CKPT_SPANS)
        cats["eval"] = _span_seconds(seg.events, _EVAL_SPANS)
        sync_sec = _span_seconds(seg.events, frozenset({"interval_sync"}))
        n_total = len(seg_execs)
        offset = exec_cursor - n_total
        prod_exec = sum(
            d for j, (_, _, d) in enumerate(seg_execs) if (offset + j) in productive_ids
        )
        rec_exec = sum(d for _, _, d in seg_execs) - prod_exec
        n_prod = sum(1 for j in range(n_total) if (offset + j) in productive_ids)
        prod_frac = (n_prod / n_total) if n_total else 1.0
        cats["productive_train"] = prod_exec + sync_sec * prod_frac
        cats["recomputed"] = rec_exec + sync_sec * (1.0 - prod_frac)
        known = sum(v for k, v in cats.items() if k != "unattributed") - gap
        cats["unattributed"] = max(0.0, seg_total - known)
        if known > seg_total > 0:
            # Clock-jitter overshoot (sub-ms in practice): scale the
            # in-segment categories so the ledger balances exactly.
            scale = (seg_total + gap) / (known + gap)
            for k in cats:
                cats[k] *= scale
        for k, v in cats.items():
            totals[k] += v
        seg_rows.append(
            {
                "segment_id": seg.segment_id,
                "start_unix_time": round(seg.start, 3),
                "end_unix_time": round(seg.end, 3),
                "duration_sec": round(seg_total, 3),
                "clean_end": seg.clean_end,
                "steps_executed": n_total,
                "first_step": min((s for _, s, _ in seg_execs), default=None),
                "last_step": max((s for _, s, _ in seg_execs), default=None),
                "categories": {k: round(v, 3) for k, v in cats.items()},
            }
        )

    wall = segments[-1].end - segments[0].start
    productive = totals["productive_train"]
    ledger = {
        "wall_clock_sec": round(wall, 3),
        "goodput_frac": round(productive / wall, 4) if wall > 0 else 0.0,
        "categories": {k: round(v, 3) for k, v in totals.items()},
        "num_segments": len(segments),
        "segments": seg_rows,
        "final_step": final_committed_step(run_dir / "checkpoints"),
        "balance_error_sec": round(wall - sum(totals.values()), 3),
        "source": {
            "timeline": str(timeline_path),
            "heartbeat_used": hb_mtime is not None,
            "suspension_windows": len(windows),
        },
    }
    promotions = _promotions_block(run_dir)
    if promotions is not None:
        ledger["promotions"] = promotions
    return ledger


def _promotions_block(run_dir: Path) -> dict[str, Any] | None:
    """Promotion-lifecycle attribution: when ``llmtrain promote`` watched
    this run, its ``promotions.jsonl`` is one more durable artifact —
    the ledger reports which committed steps were canaried and what was
    decided, on the run's own wall-clock timeline."""
    path = run_dir / "promotions.jsonl"
    if not path.is_file():
        return None
    from ..lifecycle.ledger import PromotionLedger

    ledger = PromotionLedger(path)
    summary = ledger.summary()
    events = [
        {
            "ts_unix": round(float(e.get("ts_unix", 0.0)), 3),
            "decision": e["decision"],
            "step": e["step"],
            "reason": e.get("reason"),
        }
        for e in ledger.entries()
    ]
    summary["events"] = events
    return summary


def render_goodput_md(ledger: dict[str, Any]) -> str:
    """Human-readable ledger — the report.md section and the CLI output."""
    wall = ledger["wall_clock_sec"]
    lines = [
        f"- wall clock: {wall}s across {ledger['num_segments']} segment(s), "
        f"goodput_frac = {ledger['goodput_frac']}"
        + (
            f", final committed step {ledger['final_step']}"
            if ledger.get("final_step") is not None
            else ""
        ),
        "",
        "| category | seconds | frac |",
        "|---|---|---|",
    ]
    for cat in CATEGORIES:
        sec = ledger["categories"].get(cat, 0.0)
        frac = (sec / wall) if wall > 0 else 0.0
        lines.append(f"| {cat} | {sec} | {frac:.4f} |")
    lines += [
        "",
        "| segment | dur_s | steps | productive | recomputed | "
        "restart | clean_end |",
        "|---|---|---|---|---|---|---|",
    ]
    for seg in ledger["segments"]:
        c = seg["categories"]
        lines.append(
            f"| {seg['segment_id']} | {seg['duration_sec']} | "
            f"{seg['steps_executed']} | {c['productive_train']} | "
            f"{c['recomputed']} | {c['restart_overhead']} | "
            f"{seg['clean_end']} |"
        )
    promos = ledger.get("promotions")
    if promos is not None:
        d = promos["decisions"]
        lines += [
            "",
            f"- promotions: {d['promote']} promoted, {d['rollback']} rolled "
            f"back, {d['abort']} aborted of {d['canary_start']} canaried"
            + (
                f"; serving step {promos['last_promoted_step']}"
                if promos.get("last_promoted_step") is not None
                else ""
            ),
        ]
        for e in promos.get("events", []):
            reason = f" ({e['reason']})" if e.get("reason") else ""
            lines.append(f"  - step {e['step']}: {e['decision']}{reason}")
    return "\n".join(lines) + "\n"


def goodput_gauges(ledger: dict[str, Any]) -> dict[str, float]:
    """Flat ``goodput/*`` metric map (→ ``llmtrain_goodput_*`` in the
    Prometheus rendering) for one computed ledger."""
    out = {
        "goodput/frac": float(ledger["goodput_frac"]),
        "goodput/wall_clock_sec": float(ledger["wall_clock_sec"]),
        "goodput/segments": float(ledger["num_segments"]),
    }
    for cat in CATEGORIES:
        out[f"goodput/{cat}_sec"] = float(ledger["categories"].get(cat, 0.0))
    promos = ledger.get("promotions")
    if promos is not None:
        for decision, count in promos["decisions"].items():
            out[f"goodput/promotions_{decision}"] = float(count)
        if promos.get("last_promoted_step") is not None:
            out["goodput/promoted_step"] = float(promos["last_promoted_step"])
    return out


__all__ = [
    "CATEGORIES",
    "compute_goodput",
    "final_committed_step",
    "goodput_gauges",
    "render_goodput_md",
]
