"""Unified telemetry subsystem: timeline, metrics registry, memory,
Prometheus export, and end-of-run reports.

One facade (:class:`Telemetry`) owns the pieces and their lifecycle so the
trainer wires a single object instead of five:

* :class:`~.timeline.EventTimeline` — structured span/instant stream,
  JSONL + Perfetto export, xprof-aligned;
* :class:`~.registry.MetricsRegistry` — the one publish surface every
  component (trainer, prefetcher, watchdog, checkpoint manager) uses,
  flushed to the tracker once per log interval with failures degraded to
  warnings;
* :class:`~.memory.MemoryMonitor` — HBM/host memory accounting with a
  headroom warning channel;
* :class:`~.prometheus.PrometheusEndpoint` — config-gated ``/metrics``
  HTTP server + textfile fallback;
* :mod:`~.report` — ``report.json`` / ``report.md`` aggregation.

Rank discipline mirrors the rest of the framework: every rank records
in memory (spans are free context for a crash report on any host), but
FILE outputs (JSONL, trace, report, textfile) and the metrics endpoint
are main-process-only — non-main ranks share the run dir read-only.

See docs/observability.md for the schema, naming convention, and scrape
setup.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any

from ..tracking.base import Tracker
from ..utils.logging import get_logger
from .memory import MemoryMonitor
from .prometheus import (
    PrometheusEndpoint,
    prometheus_name,
    render_prometheus,
    write_textfile,
)
from .registry import MetricsRegistry
from .report import build_report, render_markdown, write_reports
from .stats import percentile, percentiles
from .timeline import EventTimeline, step_annotation
from .tracing import TailSampler, TraceContext, Tracer

logger = get_logger()

# Cap on individual files registered as tracker artifacts per pattern walk
# (a profiler window can emit hundreds of tool files).
_ARTIFACT_CAP = 64


class Telemetry:
    """Facade tying the telemetry pieces to one run's lifecycle.

    ``cfg`` is the full RunConfig (the facade reads ``cfg.telemetry`` and
    run identity). Pass ``run_dir=None`` (or ``is_main=False``) for a
    memory-only instance — every method stays callable.
    """

    def __init__(
        self,
        cfg: Any,
        run_dir: str | Path | None,
        tracker: Tracker | None,
        *,
        process_index: int = 0,
        is_main: bool = True,
    ) -> None:
        self._cfg = cfg.telemetry
        self._run_name = cfg.run.name
        self._run_dir = Path(run_dir) if run_dir is not None else None
        self._is_main = is_main
        self._process_index = process_index
        self._writes_files = (
            self._cfg.enabled and is_main and self._run_dir is not None
        )
        telemetry_dir = (
            self._run_dir / "telemetry" if self._run_dir is not None else None
        )
        self._dir = telemetry_dir

        record_timeline = self._cfg.enabled and self._cfg.timeline
        self.timeline = EventTimeline(
            (telemetry_dir / "timeline.jsonl")
            if self._writes_files and self._cfg.timeline
            else None,
            process_index=process_index,
            max_events=self._cfg.max_events,
            xprof_annotations=record_timeline and self._cfg.xprof_annotations,
            # enabled=False -> every span/instant is a true no-op: the
            # master switch must remove the subsystem from the hot path,
            # not just its file outputs.
            enabled=record_timeline,
        )
        # The registry keeps the tracker even with telemetry disabled:
        # the trainer routes ALL tracker traffic through it, so severing
        # it here would turn `telemetry.enabled: false` into "no mlflow
        # logging at all" — the registry is plumbing, not telemetry.
        self.metrics = MetricsRegistry(tracker)
        self.memory = (
            MemoryMonitor(
                headroom_warn_frac=self._cfg.hbm_headroom_warn_frac,
                timeline=self.timeline,
            )
            if self._cfg.enabled and self._cfg.memory
            else None
        )
        self._endpoint: PrometheusEndpoint | None = None
        self._started = time.perf_counter()
        self._finalized = False
        # Last timeline-overflow total surfaced to the registry: flush()
        # publishes deltas so telemetry/timeline_dropped renders as a
        # Prometheus counter (llmtrain_telemetry_timeline_dropped_total)
        # and the report can warn that the goodput ledger may be lossy.
        self._dropped_reported = 0

    # -------------------------------------------------------------- lifecycle

    def step_annotation(self, step: int):
        """xprof step annotation honoring the config gate."""
        return step_annotation(
            step, enabled=self._cfg.enabled and self._cfg.xprof_annotations
        )

    def start(self) -> None:
        """Arm the run-scoped transports (Prometheus endpoint). Failures
        degrade to warnings — a busy port must not kill a training run.

        The endpoint starts on EVERY process, not just main: on k8s each
        pod has its own IP and the scrape annotation covers all of them,
        and non-main ranks serve genuinely per-host data (mem/*, span
        counters). Two ranks sharing one network namespace (local
        multi-process testing) simply lose the second bind to the
        degrade-to-warning path."""
        self._started = time.perf_counter()
        if not (self._cfg.enabled and self._cfg.prometheus):
            return
        if self._endpoint is not None:
            return
        try:
            self._endpoint = PrometheusEndpoint(
                self._render_prometheus,
                host=self._cfg.prometheus_host,
                port=self._cfg.prometheus_port,
            )
            logger.info(
                "prometheus metrics endpoint listening on %s:%d (/metrics)",
                self._cfg.prometheus_host,
                self._endpoint.port,
            )
        except OSError as exc:
            logger.warning(
                "prometheus endpoint failed to bind %s:%d (%s); continuing "
                "with the textfile fallback only",
                self._cfg.prometheus_host,
                self._cfg.prometheus_port,
                exc,
            )

    @property
    def prometheus_port(self) -> int | None:
        """Bound /metrics port, or None when the endpoint is not serving."""
        return self._endpoint.port if self._endpoint is not None else None

    def _render_prometheus(self) -> str:
        return render_prometheus(
            self.metrics.latest(),
            self.metrics.counters(),
            info={
                "run_name": self._run_name,
                "process_index": str(self._process_index),
            },
        )

    def record_opt_state_bytes(self, info: dict[str, float]) -> None:
        """Static optimizer-state footprint (trainer.zero memory
        accounting): lands in the report's memory block AND as ``mem/*``
        gauges so Prometheus/trackers see the ZeRO reduction live.
        Gated with the memory monitor — the telemetry master switch
        removes ALL ``mem/*`` traffic, accounting included."""
        if self.memory is None:
            return
        self.memory.record_opt_state(info)
        self.metrics.publish({f"mem/{k}": float(v) for k, v in info.items()})

    def record_activation_bytes(self, info: dict[str, float]) -> None:
        """Analytic activation footprint under the activation-tier ladder
        (trainer._activation_memory): ``activation_bytes`` device-resident
        + ``activation_bytes_offloaded`` host-staged, into the report's
        memory block AND as ``mem/*`` gauges — same contract as the
        opt-state accounting above."""
        if self.memory is None:
            return
        self.memory.record_activations(info)
        self.metrics.publish({f"mem/{k}": float(v) for k, v in info.items()})

    def flush(self, step: int | None = None) -> None:
        """The per-log-interval flush point: sample memory, push the pending
        metrics sample to the tracker (degraded on failure), persist the
        timeline, refresh the textfile snapshot.

        The registry flush runs even with telemetry disabled — it is how
        ALL tracker traffic flows now, and the master switch disables the
        telemetry extras, not experiment tracking."""
        if self.memory is not None:
            self.metrics.publish(self.memory.sample(step), step)
        self.metrics.flush(step)
        if not self._cfg.enabled:
            return
        self.timeline.flush()
        dropped = self.timeline.dropped
        if dropped > self._dropped_reported:
            self.metrics.inc(
                "telemetry/timeline_dropped", dropped - self._dropped_reported
            )
            self._dropped_reported = dropped
        if self._writes_files and self._cfg.prometheus_textfile:
            write_textfile(self._dir / "metrics.prom", self._render_prometheus())

    def finalize(
        self,
        train_result: dict[str, Any] | None = None,
        *,
        run_id: str | None = None,
        perf_attribution: dict[str, Any] | None = None,
        precision: dict[str, Any] | None = None,
    ) -> dict[str, Any] | None:
        """End-of-run: final flush, Perfetto export, report.json/report.md.

        ``perf_attribution`` is the cost-attribution block built by the
        caller (trainer via telemetry/profiling.py) — passed through to
        the report untouched.

        Returns the report dict (None when telemetry/reporting is off).
        Idempotent — a second call (e.g. an unwind path after the normal
        one) is a no-op.
        """
        if not self._cfg.enabled or self._finalized:
            return None
        self._finalized = True
        wall = time.perf_counter() - self._started
        self.flush()
        # Goodput ledger (telemetry/goodput.py): flush first so the JSONL
        # carries every event, stamp the clean-exit footer, THEN compute
        # post-hoc from the durable artifacts — the same numbers a
        # post-mortem `llmtrain goodput --run-dir` reads with this process
        # dead. Gauges publish before the final flush below so the
        # llmtrain_goodput_* family lands in the textfile snapshot.
        goodput = None
        if self._writes_files and self._cfg.timeline:
            self.timeline.end_segment()
            try:
                from .goodput import compute_goodput, goodput_gauges

                goodput = compute_goodput(self._run_dir)
                if goodput is not None:
                    self.metrics.publish(goodput_gauges(goodput))
                    self.flush()
            except Exception as exc:  # noqa: BLE001 — reporting must not fail the run
                logger.warning("goodput ledger computation failed: %s", exc)
        if self._writes_files and self._cfg.timeline:
            self.timeline.export_perfetto(self._dir / "trace.json")
        report = None
        if self._cfg.report:
            report = build_report(
                run_id=run_id or self._run_name,
                run_name=self._run_name,
                registry=self.metrics,
                timeline=self.timeline,
                memory=self.memory,
                wall_time_sec=wall,
                train_result=train_result,
                perf_attribution=perf_attribution,
                precision=precision,
                goodput=goodput,
            )
            if self._writes_files:
                write_reports(self._run_dir, report)
        return report

    def register_artifacts(self) -> None:
        """Register the run's telemetry + diagnostic files with the tracker
        (degrade-to-warning): report, trace, metrics snapshot, profiler
        traces, and any hang reports. Main process only."""
        if not (self._writes_files and self._run_dir is not None):
            return
        candidates: list[tuple[Path, str | None]] = [
            (self._run_dir / "report.json", None),
            (self._run_dir / "report.md", None),
        ]
        if self._dir is not None:
            candidates += [
                (self._dir / "trace.json", "telemetry"),
                (self._dir / "timeline.jsonl", "telemetry"),
                (self._dir / "metrics.prom", "telemetry"),
            ]
        for report_path in sorted(self._run_dir.glob("hang_report_*.txt"))[
            :_ARTIFACT_CAP
        ]:
            candidates.append((report_path, "diagnostics"))
        profile_dir = self._run_dir / "logs" / "profile"
        if profile_dir.is_dir():
            profile_files = sorted(
                p for p in profile_dir.rglob("*") if p.is_file()
            )
            if len(profile_files) > _ARTIFACT_CAP:
                logger.info(
                    "registering %d of %d profiler files as artifacts (cap)",
                    _ARTIFACT_CAP,
                    len(profile_files),
                )
            for p in profile_files[:_ARTIFACT_CAP]:
                rel = p.parent.relative_to(profile_dir).as_posix()
                candidates.append(
                    (p, "profile" if rel == "." else f"profile/{rel}")
                )
        for path, artifact_path in candidates:
            if path.is_file():
                self.metrics.safe_log_artifact(str(path), artifact_path)

    def close(self) -> None:
        """Release transports; safe to call multiple times / without start."""
        if self._endpoint is not None:
            self._endpoint.close()
            self._endpoint = None
        self.timeline.flush()


__all__ = [
    "EventTimeline",
    "MemoryMonitor",
    "MetricsRegistry",
    "PrometheusEndpoint",
    "TailSampler",
    "Telemetry",
    "TraceContext",
    "Tracer",
    "build_report",
    "percentile",
    "percentiles",
    "prometheus_name",
    "render_markdown",
    "render_prometheus",
    "step_annotation",
    "write_reports",
    "write_textfile",
]
