"""Flash attention dispatch: Pallas kernels on TPU, blockwise everywhere.

New TPU capability beyond the reference (full-matrix attention only,
reference models/gpt.py:56-69). Training differentiates through a
``jax.custom_vjp``:

* on TPU both directions run the Pallas kernels (pallas_attention.py) —
  the forward saves its logsumexp residual and the backward computes
  dq/dk/dv in two fused kernels (FlashAttention-2 scheme);
* elsewhere the backward differentiates the checkpointed XLA blockwise
  implementation.

Both paths are O(T) memory — no (T, T) materialization. Set
``LLMTRAIN_FLASH_BWD=blockwise`` to force the recompute backward on TPU
(the A/B knob for benchmarking fused vs recompute).

Key-padding masks are applied INSIDE attention on every path — flash
here, ring/ulysses in their own modules — matching the reference
(models/gpt.py:60-64): masked keys get -inf logits before the softmax.
Packed pipelines (hf_text/dummy_text windows) emit all-ones masks, for
which the masked and unmasked kernels agree exactly;
``model.extra.assume_packed`` drops the mask operand from the hot path
when the data is provably packed.

Grouped-query attention is native end to end: ``k``/``v`` may carry
n_kv_heads < n_heads — the Pallas kernels index K/V by head group and
the blockwise fallback groups queries in its einsums; K/V are never
materialized at full width on any path here.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from .blockwise_attention import blockwise_attention


def _auto_block(t: int) -> int | None:
    """Largest legal tile for sequence length ``t``.

    512 measured fastest on v5e at GPT-2-small shapes (fwd 9.67 ms vs
    10.10 at 256, bwd 11.93 vs 13.19 — RESULTS.md); smaller tiles keep odd
    lengths like 384 or 768 on the Pallas path instead of falling back.
    """
    for block in (512, 256, 128):
        if t >= block and t % block == 0:
            return block
    return None


def _use_pallas(t: int) -> bool:
    return jax.default_backend() == "tpu" and _auto_block(t) is not None


def _pallas_bwd_enabled() -> bool:
    return os.environ.get("LLMTRAIN_FLASH_BWD", "pallas").lower() != "blockwise"


def _blockwise(q, k, v, key_mask=None, window=0):
    # blockwise consumes grouped-query narrow K/V natively. query_mask =
    # key_mask upgrades to segment semantics (q and k cover the same
    # sequence here), matching the Pallas kernels and dense_attention.
    return blockwise_attention(q, k, v, causal=True, key_mask=key_mask,
                               query_mask=key_mask, window=window)


# ``window`` is a static Python int (0 = off) and travels as the leading
# nondiff arg of both custom_vjps — Mistral-style sliding-window masking
# with dead K/V blocks skipped in the Pallas kernels.
@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(window, q, k, v):
    if _use_pallas(q.shape[1]):
        from .pallas_attention import pallas_flash_attention

        block = _auto_block(q.shape[1])
        return pallas_flash_attention(
            q, k, v, causal=True, block_q=block, block_k=block, window=window
        )
    return _blockwise(q, k, v, window=window)


def _flash_fwd(window, q, k, v):
    if _use_pallas(q.shape[1]) and _pallas_bwd_enabled():
        from .pallas_attention import pallas_flash_attention_fwd

        block = _auto_block(q.shape[1])
        out, lse = pallas_flash_attention_fwd(
            q, k, v, causal=True, block_q=block, block_k=block, window=window
        )
        return out, (q, k, v, out, lse)
    return _flash(window, q, k, v), (q, k, v, None, None)


def _flash_bwd(window, residuals, g):
    q, k, v, out, lse = residuals
    if out is not None:
        from .pallas_attention import pallas_flash_attention_bwd

        block = _auto_block(q.shape[1])
        return pallas_flash_attention_bwd(
            q, k, v, out, lse, g, causal=True, block_q=block, block_k=block,
            window=window,
        )
    _, vjp = jax.vjp(lambda q_, k_, v_: _blockwise(q_, k_, v_, window=window),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


# Masked variant: the (B, T) key-padding mask travels as float32 so the
# custom_vjp can return a well-typed zero cotangent for it.
@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_masked(window, q, k, v, maskf):
    if _use_pallas(q.shape[1]):
        from .pallas_attention import pallas_flash_attention

        block = _auto_block(q.shape[1])
        return pallas_flash_attention(
            q, k, v, maskf, causal=True, block_q=block, block_k=block,
            window=window,
        )
    return _blockwise(q, k, v, key_mask=maskf, window=window)


def _flash_masked_fwd(window, q, k, v, maskf):
    if _use_pallas(q.shape[1]) and _pallas_bwd_enabled():
        from .pallas_attention import pallas_flash_attention_fwd

        block = _auto_block(q.shape[1])
        out, lse = pallas_flash_attention_fwd(
            q, k, v, maskf, causal=True, block_q=block, block_k=block,
            window=window,
        )
        return out, (q, k, v, maskf, out, lse)
    return _flash_masked(window, q, k, v, maskf), (q, k, v, maskf, None, None)


def _flash_masked_bwd(window, residuals, g):
    q, k, v, maskf, out, lse = residuals
    if out is not None:
        from .pallas_attention import pallas_flash_attention_bwd

        block = _auto_block(q.shape[1])
        dq, dk, dv = pallas_flash_attention_bwd(
            q, k, v, out, lse, g, maskf, causal=True, block_q=block,
            block_k=block, window=window,
        )
        return dq, dk, dv, jnp.zeros_like(maskf)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _blockwise(q_, k_, v_, key_mask=maskf, window=window),
        q, k, v,
    )
    dq, dk, dv = vjp(g)
    return dq, dk, dv, jnp.zeros_like(maskf)


_flash_masked.defvjp(_flash_masked_fwd, _flash_masked_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    attention_mask: jax.Array | None = None,
    causal: bool = True,
    window: int = 0,
) -> jax.Array:
    """Causal attention over (B, T, H, Dh); O(T) memory, differentiable.

    ``k``/``v`` may be grouped-query narrow (B, T, Hkv, Dh).
    ``attention_mask`` is the reference's (B, T) padding mask semantics
    (nonzero = real token): masked keys are excluded inside attention.
    ``window`` > 0 restricts each query to its trailing ``window`` keys
    (Mistral sliding-window semantics; requires ``causal``); the Pallas
    kernels skip dead K/V blocks, so compute is O(T·window).
    """
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if not causal:
        if window:
            raise ValueError("sliding window requires causal attention")
        return blockwise_attention(q, k, v, causal=False, key_mask=attention_mask)
    if attention_mask is None:
        return _flash(int(window), q, k, v)
    return _flash_masked(int(window), q, k, v, attention_mask.astype(jnp.float32))
