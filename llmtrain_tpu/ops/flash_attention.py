"""Flash attention dispatch: Pallas kernels on TPU, blockwise everywhere.

New TPU capability beyond the reference (full-matrix attention only,
reference models/gpt.py:56-69). Training differentiates through a
``jax.custom_vjp``:

* on TPU both directions run the Pallas kernels (pallas_attention.py) —
  the forward saves its logsumexp residual and the backward computes
  dq/dk/dv in two fused kernels (FlashAttention-2 scheme);
* elsewhere the backward differentiates the checkpointed XLA blockwise
  implementation.

Both paths are O(T) memory — no (T, T) materialization. Set
``LLMTRAIN_FLASH_BWD=blockwise`` to force the recompute backward on TPU
(the A/B knob for benchmarking fused vs recompute).

Padding masks route to the model's dense path (``models/gpt.py``); flash is
the packed/causal fast path, which is also what the data pipeline produces
(all-ones masks from hf_text windows).
"""

from __future__ import annotations

import os

import jax

from .blockwise_attention import blockwise_attention


def _auto_block(t: int) -> int | None:
    """Largest legal tile for sequence length ``t``.

    512 measured fastest on v5e at GPT-2-small shapes (fwd 9.67 ms vs
    10.10 at 256, bwd 11.93 vs 13.19 — RESULTS.md); smaller tiles keep odd
    lengths like 384 or 768 on the Pallas path instead of falling back.
    """
    for block in (512, 256, 128):
        if t >= block and t % block == 0:
            return block
    return None


def _use_pallas(t: int) -> bool:
    return jax.default_backend() == "tpu" and _auto_block(t) is not None


def _pallas_bwd_enabled() -> bool:
    return os.environ.get("LLMTRAIN_FLASH_BWD", "pallas").lower() != "blockwise"


@jax.custom_vjp
def _flash(q, k, v):
    block = _auto_block(q.shape[1])
    if jax.default_backend() == "tpu" and block is not None:
        from .pallas_attention import pallas_flash_attention

        return pallas_flash_attention(
            q, k, v, causal=True, block_q=block, block_k=block
        )
    return blockwise_attention(q, k, v, causal=True)


def _flash_fwd(q, k, v):
    if _use_pallas(q.shape[1]) and _pallas_bwd_enabled():
        from .pallas_attention import pallas_flash_attention_fwd

        block = _auto_block(q.shape[1])
        out, lse = pallas_flash_attention_fwd(
            q, k, v, causal=True, block_q=block, block_k=block
        )
        return out, (q, k, v, out, lse)
    return _flash(q, k, v), (q, k, v, None, None)


def _flash_bwd(residuals, g):
    q, k, v, out, lse = residuals
    if out is not None:
        from .pallas_attention import pallas_flash_attention_bwd

        block = _auto_block(q.shape[1])
        return pallas_flash_attention_bwd(
            q, k, v, out, lse, g, causal=True, block_q=block, block_k=block
        )
    _, vjp = jax.vjp(lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=True), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    attention_mask: jax.Array | None = None,
    causal: bool = True,
) -> jax.Array:
    """Causal attention over (B, T, H, Dh); O(T) memory, differentiable."""
    if attention_mask is not None:
        raise ValueError(
            "flash attention does not support padding masks; use attention='dense' "
            "for padded batches (hf_text/dummy_text produce all-ones masks)"
        )
    if not causal:
        return blockwise_attention(q, k, v, causal=False)
    return _flash(q, k, v)
