"""Flash attention dispatch: Pallas forward on TPU, blockwise everywhere.

New TPU capability beyond the reference (full-matrix attention only,
reference models/gpt.py:56-69). Training differentiates through a
``jax.custom_vjp``: the forward runs the Pallas kernel on TPU (or blockwise
on CPU), the backward recomputes via the checkpointed blockwise
implementation — O(T) memory both directions, no (T, T) materialization.

Padding masks route to the model's dense path (``models/gpt.py``); flash is
the packed/causal fast path, which is also what the data pipeline produces
(all-ones masks from hf_text windows).
"""

from __future__ import annotations

import jax

from .blockwise_attention import blockwise_attention


def _forward_best(q, k, v, causal: bool):
    # The Pallas kernel tiles with block_q=block_k=256 (min'd with T), so T
    # must divide evenly by the actual block size or the kernel raises.
    t = q.shape[1]
    if jax.default_backend() == "tpu" and t >= 128 and t % min(256, t) == 0:
        from .pallas_attention import pallas_flash_attention

        return pallas_flash_attention(q, k, v, causal=causal)
    return blockwise_attention(q, k, v, causal=causal)


@jax.custom_vjp
def _flash(q, k, v):
    return _forward_best(q, k, v, causal=True)


def _flash_fwd(q, k, v):
    return _flash(q, k, v), (q, k, v)


def _flash_bwd(residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=True), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    attention_mask: jax.Array | None = None,
    causal: bool = True,
) -> jax.Array:
    """Causal attention over (B, T, H, Dh); O(T) memory, differentiable."""
    if attention_mask is not None:
        raise ValueError(
            "flash attention does not support padding masks; use attention='dense' "
            "for padded batches (hf_text/dummy_text produce all-ones masks)"
        )
    if not causal:
        return blockwise_attention(q, k, v, causal=False)
    return _flash(q, k, v)
