"""Ring attention: exact causal attention sharded over the ``sequence`` axis.

New TPU capability beyond the reference (whose attention is single-device
full-matrix, reference models/gpt.py:56-69; max context = block_size). Each
device holds a (B, T/n, H, D) shard of Q/K/V. K/V shards rotate around the
``sequence`` mesh axis via ``lax.ppermute`` (one ICI hop per step) while each
device accumulates online-softmax partials of its local queries against the
visiting K/V block — so the full (T, T) score matrix never exists anywhere
and context length scales linearly with the number of devices. Pattern
follows the Ring Attention paper (see PAPERS.md); the per-block math reuses
``ops/blockwise_attention._chunk_scan``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .blockwise_attention import _chunk_scan, blockwise_attention


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    key_mask: jax.Array | None = None,
    *,
    axis_name: str = "sequence",
    causal: bool = True,
    kv_chunk: int = 512,
) -> jax.Array:
    """Local-shard ring attention; must run inside shard_map over ``axis_name``.

    q/k/v: (B, T_local, H, D) shards, contiguous along the global sequence in
    axis order; ``key_mask`` is the matching (B, T_local) padding-mask shard
    (nonzero = attend) and rotates around the ring WITH its K/V shard.
    Returns the (B, T_local, H, D) output shard.
    """
    axis_size = jax.lax.psum(1, axis_name)
    axis_index = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    q_offset = axis_index * t_local
    chunk = min(kv_chunk, t_local)
    if t_local % chunk != 0:
        chunk = t_local

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    masked = key_mask is not None

    def body(i, carry):
        acc, row_max, row_sum, k_cur, v_cur, m_cur = carry
        # After i rotations this device holds the K/V shard that started on
        # device (axis_index - i); its global offset drives the causal mask.
        kv_offset = ((axis_index - i) % axis_size) * t_local
        acc2, max2, sum2 = _chunk_scan(
            q,
            k_cur,
            v_cur,
            q_offset=q_offset,
            kv_offset=kv_offset,
            causal=causal,
            kv_chunk=chunk,
            key_mask=m_cur if masked else None,
            # The UNROTATED local mask is this shard's queries' segment
            # ids: equal-nonzero-value semantics (packed cross-document
            # masking) ride the ring exactly like the key shards do.
            query_mask=key_mask if masked else None,
        )
        new_max = jnp.maximum(row_max, max2)
        c1 = jnp.exp(row_max - new_max)
        c2 = jnp.exp(max2 - new_max)
        acc = acc * c1[..., None] + acc2 * c2[..., None]
        row_sum = row_sum * c1 + sum2 * c2
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        if masked:
            m_cur = jax.lax.ppermute(m_cur, axis_name, perm)
        return acc, new_max, row_sum, k_cur, v_cur, m_cur

    b, _, h, d = q.shape
    init = (
        jnp.zeros((b, t_local, h, d), jnp.float32),
        jnp.full((b, t_local, h), -1e30, jnp.float32),
        jnp.zeros((b, t_local, h), jnp.float32),
        k,
        v,
        jnp.asarray(key_mask, jnp.int32) if masked else jnp.zeros((), jnp.int32),
    )
    acc, _, row_sum, _, _, _ = jax.lax.fori_loop(0, axis_size, body, init)
    return (acc / row_sum[..., None]).astype(q.dtype)


# Mesh axes each (B, T, H, D) dim shards over — single source of truth for
# both the shard_map spec and the divisibility guard in ring_or_blockwise.
# Matches the activation logical-axis rules in parallel/sharding.py.
RING_DIM_AXES: tuple = (("data", "fsdp"), ("sequence",), ("tensor",), ())


def _dim_shards(mesh: jax.sharding.Mesh, dim: int) -> int:
    # Externally built meshes may carry a sequence axis without data/fsdp/
    # tensor names; absent axes count as unsharded (size 1).
    import math

    return math.prod(mesh.shape.get(a, 1) for a in RING_DIM_AXES[dim])


def _mesh_dim_axes(mesh: jax.sharding.Mesh) -> tuple:
    """RING_DIM_AXES restricted to axes the mesh actually has."""
    return tuple(
        tuple(a for a in axes if a in mesh.shape) for axes in RING_DIM_AXES
    )


def attention_shard_map(
    mesh: jax.sharding.Mesh,
    local_fn,
    *,
    with_mask: bool = False,
    mask_replicated: bool = False,
):
    """Wrap a local-shard attention fn into a (q, k, v[, key_mask])
    shard_map over the standard activation layout (``RING_DIM_AXES``):
    batch over (data, fsdp), sequence over ``sequence``, heads over
    ``tensor``. The (B, T) mask shards like (batch, sequence) — or, with
    ``mask_replicated``, only over batch, handing every device the full
    sequence mask (ulysses wants that post-exchange; gathering it at
    runtime would be a wasted per-layer collective).
    Shared by ring and ulysses (ops/ulysses_attention.py)."""
    P = jax.sharding.PartitionSpec
    dim_axes = _mesh_dim_axes(mesh)

    def _ax(axes):
        return axes if len(axes) > 1 else (axes[0] if axes else None)

    spec = P(*(_ax(axes) for axes in dim_axes))
    specs = [spec, spec, spec]
    if with_mask:
        specs.append(
            P(_ax(dim_axes[0]), None if mask_replicated else _ax(dim_axes[1]))
        )
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            local_fn,
            mesh=mesh,
            in_specs=tuple(specs),
            out_specs=spec,
            check_vma=False,
        )
    # jax < 0.5: top-level alias and the check_vma spelling don't exist yet.
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        local_fn,
        mesh=mesh,
        in_specs=tuple(specs),
        out_specs=spec,
        check_rep=False,
    )


def min_widen_factor(group: int, kv_heads: int, divisor: int) -> int | None:
    """Smallest exact K/V replication factor (a divisor of ``group``)
    making ``kv_heads * w`` divide ``divisor``; None when nothing does.
    The single widening rule shared by every narrow-K/V path."""
    return next(
        (
            w for w in range(1, group + 1)
            if group % w == 0 and (kv_heads * w) % divisor == 0
        ),
        None,
    )


def widen_kv_for_shards(q: jax.Array, k: jax.Array, v: jax.Array, mesh):
    """Widen grouped-query K/V by the SMALLEST exact factor that makes its
    head count divide the mesh's head shards — keeping K/V as narrow as
    the sharding allows (exact math; replicated kv heads) instead of
    abandoning a sharded path. Shared by ring and ulysses wrappers."""
    hs = _dim_shards(mesh, 2)
    if k.shape[2] % hs != 0:
        g = q.shape[2] // k.shape[2]
        w = min_widen_factor(g, k.shape[2], hs)
        if w is None:
            # g-fold widening reaches full H, which the caller's q check
            # already validated — only reachable when q itself doesn't
            # divide; keep the message clear instead of a StopIteration.
            raise ValueError(
                f"K/V heads ({k.shape[2]}, query heads {q.shape[2]}) cannot "
                f"be widened to divide the mesh head shards ({hs})"
            )
        k = jnp.repeat(k, w, axis=2)
        v = jnp.repeat(v, w, axis=2)
    return k, v


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: jax.sharding.Mesh,
    *,
    causal: bool = True,
    key_mask: jax.Array | None = None,
) -> jax.Array:
    """shard_map wrapper: global (B, T, H, D) arrays over the named mesh."""
    k, v = widen_kv_for_shards(q, k, v, mesh)
    fn = attention_shard_map(
        mesh,
        functools.partial(ring_attention, axis_name="sequence", causal=causal),
        with_mask=key_mask is not None,
    )
    if key_mask is not None:
        return fn(q, k, v, key_mask)
    return fn(q, k, v)


def route_or_blockwise(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    scheme: str,
    sharded_fn,
    extra_predicate=None,
    key_mask: jax.Array | None = None,
):
    """Shared route-or-fallback policy for sequence-parallel schemes.

    Routes to ``sharded_fn(q, k, v, mesh, causal=..., key_mask=...)``
    when an ambient mesh has a sequence axis > 1, every sharded dim
    divides evenly, and the optional ``extra_predicate(mesh, q)`` holds;
    otherwise falls back to single-device blockwise. Batch-1 traces (the
    param-init probe, ModelAdapter.init_params' (1, block_size) batch)
    fall back silently by design; real batches losing sequence
    parallelism get a trace-time warning.
    """
    mesh = _ambient_mesh()
    if (
        mesh is not None
        and "sequence" in mesh.axis_names
        and mesh.shape["sequence"] > 1
    ):
        # Narrow grouped-query K/V is widened minimally inside the sharded
        # wrappers (widen_kv_for_shards) when its head count doesn't
        # divide the head shards — never a reason to fall back.
        dims_ok = all(q.shape[d] % _dim_shards(mesh, d) == 0 for d in range(3))
        if dims_ok and (extra_predicate is None or extra_predicate(mesh, q)):
            return sharded_fn(q, k, v, mesh, causal=causal, key_mask=key_mask)
        if q.shape[0] > 1:
            from ..utils.logging import get_logger

            get_logger().warning(
                "%s attention falling back to single-device blockwise: "
                "shape (B=%d, T=%d, H=%d, Hkv=%d) vs mesh shards (batch %d, "
                "sequence %d, heads %d) — sequence parallelism is DISABLED "
                "for this computation",
                scheme,
                q.shape[0],
                q.shape[1],
                q.shape[2],
                k.shape[2],
                _dim_shards(mesh, 0),
                _dim_shards(mesh, 1),
                _dim_shards(mesh, 2),
            )
    # query_mask = key_mask keeps SEGMENT semantics on the fallback: a
    # split_documents mask degrading to key-padding-only here would
    # silently re-open cross-document attention.
    return blockwise_attention(
        q, k, v, causal=causal, key_mask=key_mask, query_mask=key_mask
    )


def ring_or_blockwise(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    key_mask: jax.Array | None = None,
):
    """Ring attention when an ambient mesh shards the sequence; blockwise
    otherwise (same math, no ring). ``key_mask`` is the reference's (B, T)
    padding mask, applied inside attention on both paths."""
    return route_or_blockwise(
        q, k, v, causal=causal, scheme="ring",
        sharded_fn=ring_attention_sharded, key_mask=key_mask,
    )


def _ambient_mesh() -> jax.sharding.Mesh | None:
    """The mesh from an enclosing ``with mesh:`` block, if any."""
    from ..parallel.sharding import ambient_mesh

    return ambient_mesh()
