"""Blockwise (memory-efficient) causal attention in pure JAX.

New TPU capability beyond the reference (which only has full-matrix attention,
reference models/gpt.py:56-69): computes exact attention with online softmax
over key/value chunks, so peak memory is O(T * block) instead of O(T^2). The
chunk loop is a ``lax.scan`` whose body is ``jax.checkpoint``-ed, giving the
same O(T) memory through autodiff — this is the single-device core that ring
attention (``ops/ring_attention.py``) extends across the ``sequence`` mesh
axis. Pattern follows the Blockwise Parallel Transformers / Ring Attention
papers (see PAPERS.md).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _chunk_scan(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    q_offset: jax.Array | int,
    kv_offset: jax.Array | int,
    causal: bool,
    kv_chunk: int,
    key_mask: jax.Array | None = None,
    query_mask: jax.Array | None = None,
    window: int = 0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Online-softmax accumulation of one q-chunk over all kv-chunks.

    q: (B, Tq, H, D); k/v: (B, Tk, Hkv, D) with Hkv dividing H (narrow
    grouped-query K/V is consumed natively). Offsets give the absolute
    positions of the first query/key, so the causal mask works on chunks
    of a larger sequence (ring attention passes nonzero kv_offset).
    ``key_mask`` is an optional (B, Tk) padding mask (nonzero = attend).
    ``window`` > 0 adds sliding-window masking (Mistral semantics: query
    i attends keys in (i-window, i]); mask-only here — the fallback path
    keeps its simple full scan, the Pallas kernels skip dead blocks.
    Returns (acc, row_max, row_sum) with acc un-normalized: out = acc / row_sum.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    tq = q.shape[1]
    num_kv = k.shape[1] // kv_chunk

    k_chunks = k.reshape(k.shape[0], num_kv, kv_chunk, *k.shape[2:])
    v_chunks = v.reshape(v.shape[0], num_kv, kv_chunk, *v.shape[2:])
    mask_chunks = None
    seg_chunks = None
    q_seg = None
    if key_mask is not None:
        mask_chunks = (key_mask != 0).reshape(key_mask.shape[0], num_kv, kv_chunk)
        if query_mask is not None:
            seg_chunks = key_mask.reshape(key_mask.shape[0], num_kv, kv_chunk)
            q_seg = query_mask

    q_pos = q_offset + jnp.arange(tq)

    b, tq_, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv != 0:
        raise ValueError(f"n_heads ({h}) must be a multiple of kv heads ({hkv})")
    group = h // hkv
    # Grouped-query attention consumes narrow K/V natively: queries are
    # viewed as (B, Tq, Hkv, G, D) and contracted against the narrow
    # heads — same FLOPs as the widened form, but K/V are never
    # materialized at full width (and ring attention rotates G x fewer
    # bytes over ICI).
    qg = q.reshape(b, tq_, hkv, group, d) if group > 1 else None

    @functools.partial(jax.checkpoint, prevent_cse=False)
    def body(carry, inputs):
        acc, row_max, row_sum = carry
        k_c, v_c, m_c, mseg_c, chunk_idx = inputs
        if group > 1:
            s = jnp.einsum("bqkgd,bskd->bqkgs", qg, k_c) * scale
            s = s.reshape(b, tq_, h, k_c.shape[1])
        else:
            s = jnp.einsum("bqhd,bkhd->bqhk", q, k_c) * scale
        s = s.astype(jnp.float32)
        if causal:
            k_pos = kv_offset + chunk_idx * kv_chunk + jnp.arange(kv_chunk)
            mask = q_pos[:, None] >= k_pos[None, :]  # (Tq, kv_chunk)
            if window:
                mask = mask & (q_pos[:, None] - k_pos[None, :] < window)
            s = jnp.where(mask[None, :, None, :], s, _NEG_INF)
        if m_c is not None:
            live = m_c[:, None, None, :]  # (B,1,1,chunk) real-key mask
            if q_seg is not None:
                # Segment semantics: equal nonzero mask values = same
                # document; keys outside the query's segment are dead.
                live = live & (
                    q_seg[:, :, None, None] == mseg_c[:, None, None, :]
                )
            s = jnp.where(live, s, _NEG_INF)
        new_max = jnp.maximum(row_max, s.max(axis=-1))
        correction = jnp.exp(row_max - new_max)
        p = jnp.exp(s - new_max[..., None])
        if group > 1:
            pg = p.reshape(b, tq_, hkv, group, k_c.shape[1]).astype(v_c.dtype)
            upd = jnp.einsum("bqkgs,bskd->bqkgd", pg, v_c).reshape(b, tq_, h, d)
        else:
            upd = jnp.einsum("bqhk,bkhd->bqhd", p.astype(v_c.dtype), v_c)
        acc = acc * correction[..., None] + upd.astype(jnp.float32)
        row_sum = row_sum * correction + p.sum(axis=-1)
        return (acc, new_max, row_sum), None

    # Data-dependent zeros (not fresh constants): under an enclosing
    # shard_map with varying-axes checking (e.g. the pipeline executor,
    # parallel/pipeline.py), a constant init would type-mismatch the
    # varying carry the body produces. Deriving from q inherits its
    # varying axes; XLA folds the multiply.
    zrow = q[..., 0].astype(jnp.float32) * 0.0  # (B, Tq, H)
    init = (
        q.astype(jnp.float32) * 0.0,
        zrow + _NEG_INF,
        zrow,
    )
    k_scan = jnp.moveaxis(k_chunks, 1, 0)
    v_scan = jnp.moveaxis(v_chunks, 1, 0)
    m_scan = None if mask_chunks is None else jnp.moveaxis(mask_chunks, 1, 0)
    mseg_scan = None if seg_chunks is None else jnp.moveaxis(seg_chunks, 1, 0)
    (acc, row_max, row_sum), _ = jax.lax.scan(
        body, init, (k_scan, v_scan, m_scan, mseg_scan, jnp.arange(num_kv))
    )
    return acc, row_max, row_sum


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_chunk: int = 512,
    kv_chunk: int = 512,
    q_offset: jax.Array | int = 0,
    kv_offset: jax.Array | int = 0,
    key_mask: jax.Array | None = None,
    query_mask: jax.Array | None = None,
    window: int = 0,
) -> jax.Array:
    """Exact attention over (B, T, H, D) tensors with O(T * chunk) memory.

    ``k``/``v`` may be grouped-query narrow (B, Tk, Hkv, D). ``key_mask``
    is an optional (B, Tk) padding mask (nonzero = attend), the
    reference's in-attention padding semantics (gpt.py:60-64).
    ``query_mask`` (B, Tq) upgrades both masks to SEGMENT semantics
    (packed sequences): equal nonzero values = same document, and a key
    is live only for same-segment queries. ``window`` > 0 restricts each
    query to its trailing ``window`` keys (requires ``causal``).
    """
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("sliding window requires causal attention")
    if query_mask is not None and key_mask is None:
        raise ValueError(
            "query_mask (segment semantics) requires key_mask — passing it "
            "alone would silently apply NO masking"
        )
    b, tq, h, d = q.shape
    q_chunk = min(q_chunk, tq)
    kv_chunk = min(kv_chunk, k.shape[1])
    if tq % q_chunk != 0 or k.shape[1] % kv_chunk != 0:
        # Fall back to single-chunk (dense) for ragged sizes.
        q_chunk, kv_chunk = tq, k.shape[1]

    num_q = tq // q_chunk

    def one_q_chunk(qi):
        qc = jax.lax.dynamic_slice_in_dim(q, qi * q_chunk, q_chunk, axis=1)
        qm = (
            jax.lax.dynamic_slice_in_dim(query_mask, qi * q_chunk, q_chunk, axis=1)
            if query_mask is not None
            else None
        )
        acc, _, row_sum = _chunk_scan(
            qc,
            k,
            v,
            q_offset=q_offset + qi * q_chunk,
            kv_offset=kv_offset,
            causal=causal,
            kv_chunk=kv_chunk,
            key_mask=key_mask,
            query_mask=qm,
            window=window,
        )
        return (acc / row_sum[..., None]).astype(q.dtype)

    if num_q == 1:
        return one_q_chunk(0)
    outs = jax.lax.map(one_q_chunk, jnp.arange(num_q))  # (num_q, B, q_chunk, H, D)
    return jnp.moveaxis(outs, 0, 1).reshape(b, tq, h, d)
