"""Hot-path ops: Pallas TPU kernels with XLA fallbacks."""
