"""Chunked (vocab-blocked) cross-entropy: CE loss without [B,T,V] tensors.

The dense LM loss path (models/base.py:masked_ce_components) materializes
the full logits tensor and, in the backward, its softmax gradient — at
GPT-2's V=50257 and the bench shape (64x512) that is the single largest
HBM resident of the train step (reference behavior spec: gpt.py:256-269;
the reference materializes the same tensors via F.cross_entropy).

This op computes the identical per-token loss by streaming over vocab
chunks with a running logsumexp (`lax.scan`), and a `custom_vjp` whose
backward RECOMPUTES each chunk's logits to accumulate dhidden and dW —
so peak memory is O(B*T*chunk) instead of O(B*T*V), trading one extra
hidden@W pass for the saved bandwidth (the flash-attention trade, applied
to the lm_head).

Matmuls run in the model dtype with f32 accumulation
(``preferred_element_type``) — MXU-friendly on TPU; the streaming
statistics and gradients accumulate in f32.

Select per run with ``model.extra.loss_impl: chunked_ce`` (models/gpt.py);
chunk size via ``model.extra.ce_chunk`` (default 8192, a multiple of the
128-lane TPU tile).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

DEFAULT_CHUNK = 8192


def _pad_vocab(w: jax.Array, chunk: int) -> tuple[jax.Array, int]:
    """Pad [V, d] to a chunk multiple; returns (padded [n*chunk, d], n)."""
    v = w.shape[0]
    n_chunks = -(-v // chunk)
    pad = n_chunks * chunk - v
    if pad:
        w = jnp.pad(w, ((0, pad), (0, 0)))
    return w, n_chunks


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def chunked_ce_per_token(
    hidden: jax.Array,
    w_vocab: jax.Array,
    labels: jax.Array,
    chunk: int = DEFAULT_CHUNK,
    compute_dtype: jnp.dtype | None = None,
    z_loss: float = 0.0,
) -> jax.Array:
    """Per-token CE loss, f32, shape (B, T).

    hidden: (B, T, d) post-final-norm activations. w_vocab: (V, d) in
    embedding layout (tied ``token_embedding.embedding`` directly; untied
    ``lm_head.kernel`` transposed). labels: (B, T) int ids. ``z_loss``
    adds PaLM's ``z_loss * log(Z)^2`` per token — free here, the
    streaming logsumexp is already computed.
    """
    loss, _ = _forward(hidden, w_vocab, labels, chunk, compute_dtype, z_loss)
    return loss


def _forward(hidden, w_vocab, labels, chunk, compute_dtype, z_loss):
    v = w_vocab.shape[0]
    dt = compute_dtype or hidden.dtype
    w_pad, n_chunks = _pad_vocab(w_vocab, chunk)
    w_chunks = w_pad.reshape(n_chunks, chunk, w_pad.shape[-1])

    h = hidden.astype(dt)

    def scan_chunk(carry, xs):
        m, s = carry  # running max / scaled sum-exp, (B, T) f32
        w_c, base = xs
        logits = jnp.einsum(
            "btd,vd->btv", h, w_c.astype(dt), preferred_element_type=jnp.float32
        )
        # Padded vocab rows must not contribute to the partition function.
        col_ok = (base + jnp.arange(chunk)) < v
        logits = jnp.where(col_ok[None, None, :], logits, -jnp.inf)
        m_c = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_c)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[..., None]), axis=-1
        )
        return (m_new, s), None

    b, t = labels.shape
    init = (
        jnp.full((b, t), -jnp.inf, jnp.float32),
        jnp.zeros((b, t), jnp.float32),
    )
    bases = jnp.arange(n_chunks) * chunk
    (m, s), _ = jax.lax.scan(scan_chunk, init, (w_chunks, bases))
    lse = m + jnp.log(s)

    label_emb = jnp.take(w_vocab, labels, axis=0).astype(dt)  # (B, T, d)
    label_logit = jnp.einsum(
        "btd,btd->bt", h, label_emb, preferred_element_type=jnp.float32
    )
    per_token = lse - label_logit
    if z_loss > 0.0:
        per_token = per_token + z_loss * jnp.square(lse)
    return per_token, lse


def _fwd(hidden, w_vocab, labels, chunk, compute_dtype, z_loss):
    loss, lse = _forward(hidden, w_vocab, labels, chunk, compute_dtype, z_loss)
    return loss, (hidden, w_vocab, labels, lse)


def _bwd(chunk, compute_dtype, z_loss, res, g):
    hidden, w_vocab, labels, lse = res
    v, d = w_vocab.shape
    dt = compute_dtype or hidden.dtype
    w_pad, n_chunks = _pad_vocab(w_vocab, chunk)
    w_chunks = w_pad.reshape(n_chunks, chunk, d)

    h = hidden.astype(dt)
    gf = g.astype(jnp.float32)  # (B, T)
    # d(per_token)/d(lse) = 1 (CE) + 2*z*lse (z-loss); both flow through
    # the softmax. The -label_logit term keeps coefficient -1.
    g_lse = gf * (1.0 + 2.0 * z_loss * lse) if z_loss > 0.0 else gf

    def scan_chunk(dh, xs):
        w_c, base = xs
        logits = jnp.einsum(
            "btd,vd->btv", h, w_c.astype(dt), preferred_element_type=jnp.float32
        )
        col_ok = (base + jnp.arange(chunk)) < v
        logits = jnp.where(col_ok[None, None, :], logits, -jnp.inf)
        # d(lse)/d(logit) = softmax; weight by the incoming cotangent.
        gp = jnp.exp(logits - lse[..., None]) * g_lse[..., None]  # (B, T, chunk)
        dh = dh + jnp.einsum(
            "btv,vd->btd", gp, w_c.astype(dt), preferred_element_type=jnp.float32
        )
        dw_c = jnp.einsum(
            "btv,btd->vd", gp, h, preferred_element_type=jnp.float32
        )
        return dh, dw_c

    bases = jnp.arange(n_chunks) * chunk
    dh, dw_chunks = jax.lax.scan(
        scan_chunk, jnp.zeros(hidden.shape, jnp.float32), (w_chunks, bases)
    )
    dw = dw_chunks.reshape(n_chunks * chunk, d)[:v]

    # The -label_logit term: dhidden -= g * W[label]; dW[label] -= g * hidden.
    label_emb = jnp.take(w_vocab, labels, axis=0).astype(jnp.float32)
    dh = dh - gf[..., None] * label_emb
    scatter = (-gf[..., None] * hidden.astype(jnp.float32)).reshape(-1, d)
    dw = dw.at[labels.reshape(-1)].add(scatter)

    return dh.astype(hidden.dtype), dw.astype(w_vocab.dtype), None


chunked_ce_per_token.defvjp(_fwd, _bwd)


def chunked_ce_components(
    hidden: jax.Array,
    w_vocab: jax.Array,
    labels: jax.Array,
    attention_mask: jax.Array | None,
    *,
    chunk: int = DEFAULT_CHUNK,
    z_loss: float = 0.0,
) -> tuple[jax.Array, jax.Array]:
    """Per-example ``(loss_sum, token_count)`` of shape (B,) — the drop-in
    counterpart of models/base.py:masked_ce_components, same mask-aware
    semantics (reference gpt.py:256-269), computed without full logits."""
    per_token = chunked_ce_per_token(hidden, w_vocab, labels, chunk, None, z_loss)
    if attention_mask is None:
        mask = jnp.ones_like(per_token)
    else:
        # Boolean semantics: segment ids > 1 (packed cross-document
        # masking) must not become loss weights.
        mask = (attention_mask != 0).astype(jnp.float32)
    return jnp.sum(per_token * mask, axis=-1), jnp.sum(mask, axis=-1)


__all__ = ["chunked_ce_per_token", "chunked_ce_components", "DEFAULT_CHUNK"]
