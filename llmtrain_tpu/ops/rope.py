"""Rotary position embeddings (RoPE), half-split ("rotate_half") layout.

Llama-family models encode position by rotating query/key pairs instead
of adding learned position embeddings (GPT, models/gpt.py:497-515). The
layout here is the HF-transformers/Llama convention — feature dim split
into two halves, NOT interleaved even/odd pairs — so parameters ported
from (or parity-tested against) ``transformers`` Llama checkpoints match
bit-for-bit (tests/test_llama.py).

TPU notes: angles are computed in f32 (bf16 loses position resolution
past ~256 positions) and the rotation is two fused elementwise multiplies
— XLA folds it into the surrounding projection, so RoPE adds no HBM
round-trip. Everything is shape-static under jit; the ``positions``
operand may be a traced value (decode offsets the cache cursor).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_angles(
    positions: jax.Array, head_dim: int, *, theta: float = 10000.0
) -> tuple[jax.Array, jax.Array]:
    """(cos, sin) tables, each ``positions.shape + (head_dim,)`` in f32.

    ``positions``: integer array of absolute token positions (any shape;
    typically (T,) at train time, (t,) offset by the cache cursor at
    decode time).
    """
    if head_dim % 2 != 0:
        raise ValueError(f"RoPE needs an even head_dim, got {head_dim}")
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta**exponent)  # (head_dim/2,)
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # (..., d/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # (..., d)
    return jnp.cos(emb), jnp.sin(emb)


def _rotate_half(x: jax.Array) -> jax.Array:
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rope(
    q: jax.Array,
    k: jax.Array,
    positions: jax.Array,
    *,
    theta: float = 10000.0,
) -> tuple[jax.Array, jax.Array]:
    """Rotate q and k by their absolute positions.

    q: (B, T, H, Dh); k: (B, T, Hkv, Dh) — K may be narrower (GQA); the
    rotation is per-head-feature so both use the same tables.
    ``positions``: (T,) absolute positions shared across the batch
    (generation batches rectangular prompts, generation.py:111-120), or
    (B, T) PER-ROW positions — paged decode batches sequences at
    different depths, so each row rotates by its own offsets.
    Rotation runs in f32 and casts back to the input dtype.
    """
    cos, sin = rope_angles(positions, q.shape[-1], theta=theta)
    if positions.ndim == 1:
        cos = cos[None, :, None, :]  # (1, T, 1, Dh)
        sin = sin[None, :, None, :]
    elif positions.ndim == 2:
        cos = cos[:, :, None, :]  # (B, T, 1, Dh)
        sin = sin[:, :, None, :]
    else:
        raise ValueError(
            f"positions must be (T,) or (B, T), got shape {positions.shape}"
        )

    def rot(x: jax.Array) -> jax.Array:
        xf = x.astype(jnp.float32)
        return (xf * cos + _rotate_half(xf) * sin).astype(x.dtype)

    return rot(q), rot(k)


__all__ = ["apply_rope", "rope_angles"]
