"""Pallas TPU flash-attention kernels: forward and backward.

The MXU-resident hot path for causal attention: one grid program per
(batch*head, q-block), streaming K/V through VMEM with online softmax, so
nothing of shape (T, T) ever exists. Written per the Pallas TPU guide
(grid/BlockSpec tiling, f32 accumulation via preferred_element_type, 2-D
iota for masks).

Two capabilities beyond the plain causal kernel:

* **Key-padding masks** (reference src/llmtrain/models/gpt.py:60-64 applies
  the padding mask inside attention): an optional (B, T) mask streams
  through VMEM as (1, 1, block_k) tiles and masked keys get -inf logits
  before the online softmax. Fully-masked query rows self-correct: the
  running-max correction factor zeroes any transient garbage the moment a
  live block arrives, and rows that never see a live key are zeroed by the
  caller's output mask (models/gpt.py) with zero cotangents flowing back.
* **Native grouped-query attention**: K/V may have fewer heads than Q
  (n_kv_heads). The forward and dq kernels map each query head to its
  K/V group via the BlockSpec index map — no jnp.repeat materialization
  in HBM — and the dk/dv kernel grids over (batch*kv_head, k-block),
  streaming the whole query-head group and reducing in-kernel, so
  gradients are born at the narrow width.

Backward (FlashAttention-2 recompute scheme): the forward also emits the
per-row logsumexp L; the backward recomputes P = exp(S - L) block-by-block
— never materializing (T, T) — in two kernels:

* dq kernel, gridded like the forward (per q-block, streaming K/V):
  dS = P * (dO Vᵀ - D),  dQ = scale * dS K,  with D = rowsum(dO * O).
* dk/dv kernel, gridded per (kv-head, k-block), streaming Q/dO/L/D of the
  query group from the causal diagonal down:  dV = Pᵀ dO,  dK = scale * dSᵀ Q.

``ops/flash_attention.py`` wires these into a ``jax.custom_vjp``; on
non-TPU backends it falls back to differentiating the XLA blockwise
implementation.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, *rest, block_k: int, scale: float, causal: bool,
    masked: bool, window: int = 0,
):
    """One q-block vs the streamed K/V sequence.

    Ref shapes: q (1, BQ, D), k/v (1, T, D), o (1, BQ, D), l (1, 1, BQ),
    optional mask (1, 1, T) int32 + its q-block view (1, 1, BQ) ahead of
    the outputs when ``masked``. Mask values are SEGMENT ids: nonzero =
    real token, equal values = same document (plain 0/1 padding masks are
    the one-segment special case). ``l`` is the per-row logsumexp of the
    scaled/masked logits — the residual the backward kernels use to
    recompute P without a re-softmax. It is carried with a singleton
    middle dim so its block shape satisfies Mosaic's tiling rule
    (second-to-last block dim == array dim).
    """
    if masked:
        mask_ref, mask_q_ref, o_ref, l_ref = rest
    else:
        (o_ref, l_ref) = rest
        mask_ref = mask_q_ref = None
    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    seq_len = k_ref.shape[1]
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale  # (BQ, D)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    num_kv = seq_len // block_k
    start_kv = 0
    if causal:
        # Only blocks that intersect the causal triangle for this q block.
        num_kv_live = jax.lax.div(qi * block_q + block_q + block_k - 1, block_k)
        num_kv = jnp.minimum(num_kv, num_kv_live)
    if window:
        # Sliding window: the earliest key this q block can see is
        # qi*BQ - window + 1; blocks wholly before it are dead.
        start_kv = jax.lax.div(
            jnp.maximum(qi * block_q - window + 1, 0), block_k
        )

    def body(kb, carry):
        acc, row_max, row_sum = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]  # (BK, D)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q,
            k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            live = q_pos >= k_pos
            if window:
                live &= q_pos - k_pos < window
            s = jnp.where(live, s, _NEG_INF)
        if masked:
            m_blk = mask_ref[0, 0, pl.ds(kb * block_k, block_k)]  # (BK,) int32
            mq = mask_q_ref[0, 0]  # (BQ,) int32 — this q-block's segments
            s = jnp.where(
                (m_blk[None, :] != 0) & (mq[:, None] == m_blk[None, :]),
                s,
                _NEG_INF,
            )
        new_max = jnp.maximum(row_max, s.max(axis=1))
        p = jnp.exp(s - new_max[:, None])
        correction = jnp.exp(row_max - new_max)
        acc = acc * correction[:, None] + jax.lax.dot_general(
            p,
            v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        row_sum = row_sum * correction + p.sum(axis=1)
        return acc, new_max, row_sum

    init = (
        jnp.zeros((block_q, head_dim), jnp.float32),
        jnp.full((block_q,), _NEG_INF, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    acc, row_max, row_sum = jax.lax.fori_loop(start_kv, num_kv, body, init)
    o_ref[0] = (acc / row_sum[:, None]).astype(o_ref.dtype)
    l_ref[0] = (row_max + jnp.log(row_sum))[None, :]


def _fold(x: jax.Array) -> jax.Array:
    """(B, T, H, D) -> (B*H, T, D): heads join the grid batch dimension."""
    b, t, h, d = x.shape
    return jnp.moveaxis(x, 2, 1).reshape(b * h, t, d)


def _unfold(x: jax.Array, b: int, h: int) -> jax.Array:
    bh, t, d = x.shape
    return jnp.moveaxis(x.reshape(b, h, t, d), 1, 2)


def _check_blocks(t: int, block_q: int, block_k: int) -> tuple[int, int]:
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q != 0 or t % block_k != 0:
        raise ValueError(f"sequence length {t} must be divisible by block sizes")
    return block_q, block_k


def _head_groups(h: int, hkv: int) -> int:
    """Query heads per K/V head; validates the GQA head relationship."""
    if h % hkv != 0:
        raise ValueError(f"n_heads ({h}) must be a multiple of n_kv_heads ({hkv})")
    return h // hkv


def _kv_index(h: int, hkv: int):
    """Folded-q row (b*h + head) -> folded-kv row (b*hkv + head//group)."""
    group = h // hkv

    def kv_row(bh):
        return (bh // h) * hkv + (bh % h) // group

    return kv_row


def _mask3(mask: jax.Array | None) -> jax.Array | None:
    """(B, T) padding mask -> (B, 1, T) int32 for legal (1, 1, BK) tiling."""
    if mask is None:
        return None
    return mask.astype(jnp.int32)[:, None, :]


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret", "window")
)
def pallas_flash_attention_fwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
    window: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """Flash attention over (B, T, H, D) q returning ``(out, lse)``.

    ``k``/``v`` may carry fewer heads (B, T, Hkv, D) for grouped-query
    attention; ``mask`` is an optional (B, T) key-padding mask (nonzero =
    attend). ``window`` > 0 restricts each query to its trailing
    ``window`` keys (Mistral sliding-window semantics; requires
    ``causal``) — dead K/V blocks are skipped, so compute is O(T·W).
    ``lse`` has shape (B*H, T), float32 — the backward residual.
    Falls back to smaller blocks automatically when T < block size.
    """
    b, t, h, d = q.shape
    hkv = k.shape[2]
    _head_groups(h, hkv)
    block_q, block_k = _check_blocks(t, block_q, block_k)
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("sliding window requires causal attention")

    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    scale = 1.0 / math.sqrt(d)
    kv_row = _kv_index(h, hkv)
    masked = mask is not None

    kernel = functools.partial(
        _flash_kernel, block_k=block_k, scale=scale, causal=causal, masked=masked,
        window=window,
    )
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, t, d), lambda bh, qi: (kv_row(bh), 0, 0)),
        pl.BlockSpec((1, t, d), lambda bh, qi: (kv_row(bh), 0, 0)),
    ]
    operands = [qf, kf, vf]
    if masked:
        mask3 = _mask3(mask)
        in_specs.append(pl.BlockSpec((1, 1, t), lambda bh, qi: (bh // h, 0, 0)))
        operands.append(mask3)
        # The SAME mask array again, tiled per q-block (segment ids for
        # this block's queries).
        in_specs.append(pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh // h, 0, qi)))
        operands.append(mask3)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, t), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)

    return _unfold(out, b, h), lse.reshape(b * h, t)


def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
    window: int = 0,
) -> jax.Array:
    """Causal flash attention over (B, T, H, D); forward only."""
    out, _ = pallas_flash_attention_fwd(
        q, k, v, mask, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, window=window,
    )
    return out


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, l_ref, d_ref, *rest,
    block_k: int, scale: float, causal: bool, masked: bool, window: int = 0,
):
    """dQ for one q-block, streaming K/V (same schedule as the forward).

    Ref shapes: q/do/dq (1, BQ, D), k/v (1, T, D), l/d (1, 1, BQ),
    optional mask (1, 1, T) + its q-block view (1, 1, BQ) ahead of the
    output when ``masked`` (segment semantics — see ``_flash_kernel``).
    """
    if masked:
        mask_ref, mask_q_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
        mask_ref = mask_q_ref = None
    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    seq_len = k_ref.shape[1]
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale  # (BQ, D)
    do = do_ref[0].astype(jnp.float32)  # (BQ, D)
    lse = l_ref[0, 0]  # (BQ,)
    delta = d_ref[0, 0]  # (BQ,) rowsum(dO * O)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    num_kv = seq_len // block_k
    start_kv = 0
    if causal:
        num_kv_live = jax.lax.div(qi * block_q + block_q + block_k - 1, block_k)
        num_kv = jnp.minimum(num_kv, num_kv_live)
    if window:
        start_kv = jax.lax.div(
            jnp.maximum(qi * block_q - window + 1, 0), block_k
        )

    def body(kb, dq_acc):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK), already scaled via q
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            live = q_pos >= k_pos
            if window:
                live &= q_pos - k_pos < window
            s = jnp.where(live, s, _NEG_INF)
        if masked:
            m_blk = mask_ref[0, 0, pl.ds(kb * block_k, block_k)]
            mq = mask_q_ref[0, 0]  # (BQ,)
            s = jnp.where(
                (m_blk[None, :] != 0) & (mq[:, None] == m_blk[None, :]),
                s,
                _NEG_INF,
            )
        p = jnp.exp(s - lse[:, None])  # (BQ, BK)
        dp = jax.lax.dot_general(
            do, v_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        ds = p * (dp - delta[:, None])
        return dq_acc + jax.lax.dot_general(
            ds, k_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(
        start_kv, num_kv, body, jnp.zeros((block_q, head_dim), jnp.float32)
    )
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkdv_kernel(
    q_ref, k_ref, v_ref, do_ref, l_ref, d_ref, *rest,
    block_q: int, scale: float, causal: bool, masked: bool, window: int = 0,
):
    """dK/dV for one (kv-head, k-block, group-member) grid point, streaming
    that query head's Q/dO/L/D from the causal diagonal down.

    Ref shapes: k/v/dk/dv (1, BK, D), q/do (1, T, D), l/d (1, 1, T),
    optional mask (1, 1, BK) + the full-length mask (1, 1, T) for the
    streamed queries' segments, ahead of the outputs when ``masked``
    (segment semantics — see ``_flash_kernel``).
    The query group (G = n_heads // n_kv_heads, 1 for classic MHA) is the
    INNERMOST grid dimension: the dk/dv output block stays resident across
    the G consecutive revisits and accumulates in float32 — VMEM stays
    O(T·D) however large the group (MQA makes G = n_heads).
    """
    if masked:
        mask_ref, mask_q_ref, dk_ref, dv_ref = rest
    else:
        dk_ref, dv_ref = rest
        mask_ref = mask_q_ref = None
    block_k = k_ref.shape[1]
    head_dim = k_ref.shape[2]
    seq_len = q_ref.shape[1]
    ki = pl.program_id(1)
    g = pl.program_id(2)

    k_blk = k_ref[0].astype(jnp.float32)  # (BK, D)
    v_blk = v_ref[0].astype(jnp.float32)

    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    if masked:
        k_seg = mask_ref[0, 0]  # (BK,) segment ids
        key_live = k_seg != 0

    num_q = seq_len // block_q
    start_q = 0
    if causal:
        # Q blocks strictly above the diagonal see none of this k-block.
        start_q = jax.lax.div(ki * block_k, block_q)
    if window:
        # The last query that can see this k-block sits at
        # k_pos_max + window - 1; later q blocks are dead.
        last_q = ki * block_k + block_k - 1 + window - 1
        num_q = jnp.minimum(num_q, jax.lax.div(last_q, block_q) + 1)

    def body(qb, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32) * scale
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = l_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta = d_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = jax.lax.dot_general(
            q_blk, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            live = q_pos >= k_pos
            if window:
                live &= q_pos - k_pos < window
            s = jnp.where(live, s, _NEG_INF)
        if masked:
            q_seg = mask_q_ref[0, 0, pl.ds(qb * block_q, block_q)]  # (BQ,)
            s = jnp.where(
                key_live[None, :] & (q_seg[:, None] == k_seg[None, :]),
                s,
                _NEG_INF,
            )
        p = jnp.exp(s - lse[:, None])  # (BQ, BK)
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BK, D)
        dp = jax.lax.dot_general(
            do_blk, v_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        ds = p * (dp - delta[:, None])
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BK, D)
        return dk_acc, dv_acc

    zeros = jnp.zeros((block_k, head_dim), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, num_q, body, (zeros, zeros))

    @pl.when(g == 0)
    def _zero_init():
        dk_ref[0] = jnp.zeros((block_k, head_dim), dk_ref.dtype)
        dv_ref[0] = jnp.zeros((block_k, head_dim), dv_ref.dtype)

    # q was pre-scaled, so dk already carries one factor of scale. The
    # astype matters for group==1, where the output refs keep the narrow
    # K/V dtype (accumulation across revisits only happens at f32,
    # group>1 — see grad_dtypes at the pallas_call).
    dk_ref[0] += dk.astype(dk_ref.dtype)
    dv_ref[0] += dv.astype(dv_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret", "window")
)
def pallas_flash_attention_bwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,
    g: jax.Array,
    mask: jax.Array | None = None,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
    window: int = 0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused flash-attention backward: ``(dq, dk, dv)`` for (B, T, H, D) q.

    ``k``/``v`` may be grouped-query narrow (B, T, Hkv, D) — dk/dv come
    back at that width, reduced over the query group in-kernel. ``out``/
    ``lse`` are the forward results (``pallas_flash_attention_fwd``); ``g``
    is the output cotangent; ``mask`` the same (B, T) key-padding mask as
    the forward. O(T) memory — P is recomputed per block from ``lse``,
    mirroring FlashAttention-2's backward.
    """
    b, t, h, d = q.shape
    hkv = k.shape[2]
    group = _head_groups(h, hkv)
    block_q, block_k = _check_blocks(t, block_q, block_k)
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("sliding window requires causal attention")

    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    of, gf = _fold(out), _fold(g)
    scale = 1.0 / math.sqrt(d)
    kv_row = _kv_index(h, hkv)
    masked = mask is not None
    mask_arr = _mask3(mask)

    # D = rowsum(dO * O): one cheap fused elementwise+reduce in XLA. lse and
    # delta travel as (BH, 1, T) so their (1, 1, block) specs tile legally.
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    lse3 = lse.reshape(b * h, 1, t)
    delta3 = delta.reshape(b * h, 1, t)

    seq_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),  # q
        pl.BlockSpec((1, t, d), lambda bh, qi: (kv_row(bh), 0, 0)),  # k
        pl.BlockSpec((1, t, d), lambda bh, qi: (kv_row(bh), 0, 0)),  # v
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),  # do
        pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),  # lse
        pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),  # delta
    ]
    dq_operands = [qf, kf, vf, gf, lse3, delta3]
    if masked:
        seq_specs.append(pl.BlockSpec((1, 1, t), lambda bh, qi: (bh // h, 0, 0)))
        dq_operands.append(mask_arr)
        # Same mask, q-block tiled (the queries' segment ids).
        seq_specs.append(
            pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh // h, 0, qi))
        )
        dq_operands.append(mask_arr)
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=block_k, scale=scale, causal=causal,
            masked=masked, window=window,
        ),
        grid=(b * h, t // block_q),
        in_specs=seq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(*dq_operands)

    # dk/dv grid over (batch*kv_head, k-block, group-member). The group is
    # innermost so the (1, BK, D) output block stays resident across the G
    # revisits and accumulates in f32; head g of kv-head j in batch b_i is
    # folded-q row b_i*h + j*G + g.
    def _q_row(r, g):
        return (r // hkv) * h + (r % hkv) * group + g

    kv_specs = [
        pl.BlockSpec((1, t, d), lambda r, ki, g: (_q_row(r, g), 0, 0)),  # q
        pl.BlockSpec((1, block_k, d), lambda r, ki, g: (r, ki, 0)),  # k
        pl.BlockSpec((1, block_k, d), lambda r, ki, g: (r, ki, 0)),  # v
        pl.BlockSpec((1, t, d), lambda r, ki, g: (_q_row(r, g), 0, 0)),  # do
        pl.BlockSpec((1, 1, t), lambda r, ki, g: (_q_row(r, g), 0, 0)),  # lse
        pl.BlockSpec((1, 1, t), lambda r, ki, g: (_q_row(r, g), 0, 0)),  # delta
    ]
    dkdv_operands = [qf, kf, vf, gf, lse3, delta3]
    if masked:
        kv_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda r, ki, g: (r // hkv, 0, ki))
        )
        dkdv_operands.append(mask_arr)
        # Full-length mask for the streamed queries' segment ids.
        kv_specs.append(
            pl.BlockSpec((1, 1, t), lambda r, ki, g: (r // hkv, 0, 0))
        )
        dkdv_operands.append(mask_arr)
    # f32 block residency is only needed when the group accumulates across
    # revisits; classic MHA (group == 1) writes each block once, so it
    # keeps the narrow dtype and its HBM footprint.
    grad_dtypes = (jnp.float32, jnp.float32) if group > 1 else (k.dtype, v.dtype)
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkdv_kernel, block_q=block_q, scale=scale, causal=causal,
            masked=masked, window=window,
        ),
        grid=(b * hkv, t // block_k, group),
        in_specs=kv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda r, ki, g: (r, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda r, ki, g: (r, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hkv, t, d), grad_dtypes[0]),
            jax.ShapeDtypeStruct((b * hkv, t, d), grad_dtypes[1]),
        ],
        interpret=interpret,
    )(*dkdv_operands)

    return (
        _unfold(dq, b, h),
        _unfold(dk.astype(k.dtype), b, hkv),
        _unfold(dv.astype(v.dtype), b, hkv),
    )
