"""Pallas TPU flash-attention kernels: forward and backward.

The MXU-resident hot path for causal attention: one grid program per
(batch*head, q-block), streaming K/V through VMEM with online softmax, so
nothing of shape (T, T) ever exists. Written per the Pallas TPU guide
(grid/BlockSpec tiling, f32 accumulation via preferred_element_type, 2-D
iota for masks).

Backward (FlashAttention-2 recompute scheme): the forward also emits the
per-row logsumexp L; the backward recomputes P = exp(S - L) block-by-block
— never materializing (T, T) — in two kernels:

* dq kernel, gridded like the forward (per q-block, streaming K/V):
  dS = P * (dO Vᵀ - D),  dQ = scale * dS K,  with D = rowsum(dO * O).
* dk/dv kernel, gridded per k-block, streaming Q/dO/L/D from the causal
  diagonal down:  dV = Pᵀ dO,  dK = scale * dSᵀ Q.

``ops/flash_attention.py`` wires these into a ``jax.custom_vjp``; on
non-TPU backends it falls back to differentiating the XLA blockwise
implementation.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, l_ref, *, block_k: int, scale: float, causal: bool
):
    """One q-block vs the streamed K/V sequence.

    Ref shapes: q (1, BQ, D), k/v (1, T, D), o (1, BQ, D), l (1, 1, BQ).
    ``l`` is the per-row logsumexp of the scaled/masked logits — the
    residual the backward kernels use to recompute P without a re-softmax.
    It is carried with a singleton middle dim so its block shape satisfies
    Mosaic's tiling rule (second-to-last block dim == array dim).
    """
    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    seq_len = k_ref.shape[1]
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale  # (BQ, D)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    num_kv = seq_len // block_k
    if causal:
        # Only blocks that intersect the causal triangle for this q block.
        num_kv_live = jax.lax.div(qi * block_q + block_q + block_k - 1, block_k)
        num_kv = jnp.minimum(num_kv, num_kv_live)

    def body(kb, carry):
        acc, row_max, row_sum = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]  # (BK, D)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q,
            k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        new_max = jnp.maximum(row_max, s.max(axis=1))
        p = jnp.exp(s - new_max[:, None])
        correction = jnp.exp(row_max - new_max)
        acc = acc * correction[:, None] + jax.lax.dot_general(
            p,
            v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        row_sum = row_sum * correction + p.sum(axis=1)
        return acc, new_max, row_sum

    init = (
        jnp.zeros((block_q, head_dim), jnp.float32),
        jnp.full((block_q,), _NEG_INF, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    acc, row_max, row_sum = jax.lax.fori_loop(0, num_kv, body, init)
    o_ref[0] = (acc / row_sum[:, None]).astype(o_ref.dtype)
    l_ref[0] = (row_max + jnp.log(row_sum))[None, :]


def _fold(x: jax.Array) -> jax.Array:
    """(B, T, H, D) -> (B*H, T, D): heads join the grid batch dimension."""
    b, t, h, d = x.shape
    return jnp.moveaxis(x, 2, 1).reshape(b * h, t, d)


def _unfold(x: jax.Array, b: int, h: int) -> jax.Array:
    bh, t, d = x.shape
    return jnp.moveaxis(x.reshape(b, h, t, d), 1, 2)


def _check_blocks(t: int, block_q: int, block_k: int) -> tuple[int, int]:
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q != 0 or t % block_k != 0:
        raise ValueError(f"sequence length {t} must be divisible by block sizes")
    return block_q, block_k


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def pallas_flash_attention_fwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Flash attention over (B, T, H, D) returning ``(out, lse)``.

    ``lse`` has shape (B*H, T), float32 — the backward-pass residual.
    Falls back to smaller blocks automatically when T < block size.
    """
    b, t, h, d = q.shape
    block_q, block_k = _check_blocks(t, block_q, block_k)

    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    scale = 1.0 / math.sqrt(d)

    kernel = functools.partial(_flash_kernel, block_k=block_k, scale=scale, causal=causal)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, t), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)

    return _unfold(out, b, h), lse.reshape(b * h, t)


def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Causal flash attention over (B, T, H, D); forward only."""
    out, _ = pallas_flash_attention_fwd(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k, interpret=interpret
    )
    return out


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, l_ref, d_ref, dq_ref,
    *, block_k: int, scale: float, causal: bool,
):
    """dQ for one q-block, streaming K/V (same schedule as the forward).

    Ref shapes: q/do/dq (1, BQ, D), k/v (1, T, D), l/d (1, 1, BQ).
    """
    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    seq_len = k_ref.shape[1]
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale  # (BQ, D)
    do = do_ref[0].astype(jnp.float32)  # (BQ, D)
    lse = l_ref[0, 0]  # (BQ,)
    delta = d_ref[0, 0]  # (BQ,) rowsum(dO * O)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    num_kv = seq_len // block_k
    if causal:
        num_kv_live = jax.lax.div(qi * block_q + block_q + block_k - 1, block_k)
        num_kv = jnp.minimum(num_kv, num_kv_live)

    def body(kb, dq_acc):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK), already scaled via q
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])  # (BQ, BK)
        dp = jax.lax.dot_general(
            do, v_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        ds = p * (dp - delta[:, None])
        return dq_acc + jax.lax.dot_general(
            ds, k_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(
        0, num_kv, body, jnp.zeros((block_q, head_dim), jnp.float32)
    )
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkdv_kernel(
    q_ref, k_ref, v_ref, do_ref, l_ref, d_ref, dk_ref, dv_ref,
    *, block_q: int, scale: float, causal: bool,
):
    """dK/dV for one k-block, streaming Q/dO/L/D from the causal diagonal.

    Ref shapes: k/v/dk/dv (1, BK, D), q/do (1, T, D), l/d (1, 1, T).
    """
    block_k = k_ref.shape[1]
    head_dim = k_ref.shape[2]
    seq_len = q_ref.shape[1]
    ki = pl.program_id(1)

    k_blk = k_ref[0].astype(jnp.float32)  # (BK, D)
    v_blk = v_ref[0].astype(jnp.float32)

    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    num_q = seq_len // block_q
    start_q = 0
    if causal:
        # Q blocks strictly above the diagonal see none of this k-block.
        start_q = jax.lax.div(ki * block_k, block_q)

    def body(qb, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32) * scale
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = l_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta = d_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = jax.lax.dot_general(
            q_blk, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])  # (BQ, BK)
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BK, D)
        dp = jax.lax.dot_general(
            do_blk, v_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        ds = p * (dp - delta[:, None])
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BK, D)
        return dk_acc, dv_acc

    zeros = jnp.zeros((block_k, head_dim), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, num_q, body, (zeros, zeros))
    # q was pre-scaled, so dk already carries one factor of scale.
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def pallas_flash_attention_bwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,
    g: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused flash-attention backward: ``(dq, dk, dv)`` for (B, T, H, D) inputs.

    ``out``/``lse`` are the forward results (``pallas_flash_attention_fwd``);
    ``g`` is the output cotangent. O(T) memory — P is recomputed per block
    from ``lse``, mirroring FlashAttention-2's backward.
    """
    b, t, h, d = q.shape
    block_q, block_k = _check_blocks(t, block_q, block_k)

    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    of, gf = _fold(out), _fold(g)
    scale = 1.0 / math.sqrt(d)

    # D = rowsum(dO * O): one cheap fused elementwise+reduce in XLA. lse and
    # delta travel as (BH, 1, T) so their (1, 1, block) specs tile legally.
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    lse3 = lse.reshape(b * h, 1, t)
    delta3 = delta.reshape(b * h, 1, t)

    seq_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),  # q
        pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),  # k
        pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),  # v
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),  # do
        pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),  # lse
        pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),  # delta
    ]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, scale=scale, causal=causal),
        grid=(b * h, t // block_q),
        in_specs=seq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, gf, lse3, delta3)

    kv_specs = [
        pl.BlockSpec((1, t, d), lambda bh, ki: (bh, 0, 0)),  # q
        pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),  # k
        pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),  # v
        pl.BlockSpec((1, t, d), lambda bh, ki: (bh, 0, 0)),  # do
        pl.BlockSpec((1, 1, t), lambda bh, ki: (bh, 0, 0)),  # lse
        pl.BlockSpec((1, 1, t), lambda bh, ki: (bh, 0, 0)),  # delta
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, block_q=block_q, scale=scale, causal=causal),
        grid=(b * h, t // block_k),
        in_specs=kv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, lse3, delta3)

    return _unfold(dq, b, h), _unfold(dk, b, h), _unfold(dv, b, h)
