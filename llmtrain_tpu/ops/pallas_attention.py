"""Pallas TPU flash-attention forward kernel.

The MXU-resident hot path for causal attention: one grid program per
(batch*head, q-block), streaming K/V through VMEM with online softmax, so
nothing of shape (T, T) ever exists. Written per the Pallas TPU guide
(grid/BlockSpec tiling, f32 accumulation via preferred_element_type, 2-D
iota for masks). Differentiability is provided in ``ops/flash_attention.py``
via custom_vjp with a blockwise-recompute backward.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, scale: float, causal: bool):
    """One q-block vs the streamed K/V sequence.

    Ref shapes: q (1, BQ, D), k/v (1, T, D), o (1, BQ, D).
    """
    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    seq_len = k_ref.shape[1]
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale  # (BQ, D)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    num_kv = seq_len // block_k
    if causal:
        # Only blocks that intersect the causal triangle for this q block.
        num_kv_live = jax.lax.div(qi * block_q + block_q + block_k - 1, block_k)
        num_kv = jnp.minimum(num_kv, num_kv_live)

    def body(kb, carry):
        acc, row_max, row_sum = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]  # (BK, D)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q,
            k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        new_max = jnp.maximum(row_max, s.max(axis=1))
        p = jnp.exp(s - new_max[:, None])
        correction = jnp.exp(row_max - new_max)
        acc = acc * correction[:, None] + jax.lax.dot_general(
            p,
            v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        row_sum = row_sum * correction + p.sum(axis=1)
        return acc, new_max, row_sum

    init = (
        jnp.zeros((block_q, head_dim), jnp.float32),
        jnp.full((block_q,), _NEG_INF, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    acc, _, row_sum = jax.lax.fori_loop(0, num_kv, body, init)
    o_ref[0] = (acc / row_sum[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Causal flash attention over (B, T, H, D); forward only.

    Falls back to smaller blocks automatically when T < block size.
    """
    b, t, h, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q != 0 or t % block_k != 0:
        raise ValueError(f"sequence length {t} must be divisible by block sizes")

    # Fold heads into the grid's batch dimension: (B*H, T, D).
    def fold(x):
        return jnp.moveaxis(x, 2, 1).reshape(b * h, t, d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    scale = 1.0 / math.sqrt(d)

    kernel = functools.partial(_flash_kernel, block_k=block_k, scale=scale, causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)

    return jnp.moveaxis(out.reshape(b, h, t, d), 1, 2)
