"""Fused lm-head + cross-entropy Pallas kernel: logits never touch HBM.

`ops/chunked_ce.py` already shrinks the loss from O(B*T*V) to
O(B*T*chunk) by streaming vocab chunks through XLA — but each chunk's
logits block is still an XLA-materialized intermediate that round-trips
HBM. This module closes the remaining gap with a blockwise Pallas TPU
kernel that computes per-token CE (+ PaLM z-loss) directly from
``(hidden [B,T,d], w_vocab [V,d], labels)``:

* **forward** tiles over (token-block × vocab-block) with the online
  logsumexp/max recurrence held in VMEM — the flash-attention trick
  applied to the lm-head:  ``m' = max(m, max(logits));
  s' = s*exp(m-m') + sum(exp(logits-m'))``; ``lse = m + log(s)``.
  The label logit is picked up for free while the tile is resident
  (a one-hot column-hit mask — no gather).
* **backward** RECOMPUTES each vocab tile's logits in-kernel and
  accumulates ``dhidden`` (vocab-innermost grid) and ``dW``
  (token-innermost grid) into f32 revisited output blocks, using
  ``dlogit = softmax * g_lse - onehot(label) * g``.

Neither pass ever writes a logits tile to HBM: the only [*, V]-shaped
traffic left in the step is the weight matrix itself.

Selection: ``model.extra.loss_impl: fused_ce`` (models/gpt.py). On a
backend without Pallas TPU support the explicit knob degrades to
chunked_ce with a once-per-process warning (the ``fp8_supported()``
pattern from ops/quant.py); ``model.extra.pallas_interpret: true``
forces the ``interpret=True`` emulation path so CPU runs — including
tier-1 parity tests on this container — execute the real kernel logic.

Block sizes via ``model.extra.fused_ce_block_t`` / ``fused_ce_block_v``
(defaults 256 / 512: a (512, d) f32 weight tile plus the (256, 512)
logits tile stay well under the ~16 MB/core VMEM budget up to d≈4k).
"""

from __future__ import annotations

import functools
import logging
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

logger = logging.getLogger(__name__)

DEFAULT_BLOCK_T = 256
DEFAULT_BLOCK_V = 512

# Finite stand-in for -inf: masked lanes must stay orderable and
# exp()-able without spawning inf-inf = NaN in the recurrence (same
# constant as ops/pallas_attention.py).
_NEG_INF = -1e30

LOSS_IMPLS = ("dense", "chunked_ce", "fused_ce")

_FALLBACK_WARNED: set[str] = set()
_AUTO_LOGGED: set[str] = set()


def pallas_ce_supported() -> bool:
    """True when the compiled (non-interpret) Pallas kernels can run.

    Mosaic lowering is TPU-only in this tree — same backend gate as
    ops/flash_attention.py:_use_pallas. CPU/GPU callers get the kernels
    via ``interpret=True`` (tests, bench) or fall back to chunked_ce.
    """
    return jax.default_backend() == "tpu"


def resolve_loss_impl(
    requested: str | None,
    *,
    vocab_size: int,
    ce_auto_vocab: int,
    interpret: bool = False,
) -> str:
    """The single selection authority for ``model.extra.loss_impl``.

    Explicit knob always wins (unknown value raises); ``fused_ce`` on a
    backend without Pallas support degrades to chunked_ce with a
    once-per-process warning rather than failing the run (the
    fp8-fallback contract from ops/quant.py). Unset auto-selects at
    ``vocab_size >= ce_auto_vocab``: fused on TPU, chunked elsewhere.
    Used by the GPT adapter family at build time and by the autotune
    planner so `llmtrain plan` verdicts assume the same impl training
    will materialize.
    """
    if requested is not None:
        if requested not in LOSS_IMPLS:
            raise ValueError(
                f"model.extra.loss_impl {requested!r} unknown; "
                f"expected one of {', '.join(LOSS_IMPLS)}"
            )
        if requested == "fused_ce" and not (pallas_ce_supported() or interpret):
            if "fused_ce" not in _FALLBACK_WARNED:
                _FALLBACK_WARNED.add("fused_ce")
                logger.warning(
                    "loss_impl: fused_ce requested but backend %r has no "
                    "Pallas TPU support; falling back to chunked_ce "
                    "(set model.extra.pallas_interpret: true to force the "
                    "interpret-mode kernel)",
                    jax.default_backend(),
                )
            return "chunked_ce"
        return requested
    if vocab_size >= ce_auto_vocab:
        impl = "fused_ce" if (pallas_ce_supported() or interpret) else "chunked_ce"
        if impl not in _AUTO_LOGGED:
            _AUTO_LOGGED.add(impl)
            logger.info(
                "loss_impl auto-selected: %s (vocab_size %d >= "
                "model.extra.ce_auto_vocab %d and loss_impl unset; pass "
                "loss_impl: dense to override)",
                impl,
                vocab_size,
                ce_auto_vocab,
            )
        return impl
    return "dense"


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _pad_rows(x: jax.Array, rows: int) -> jax.Array:
    pad = rows - x.shape[0]
    if pad:
        cfg = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        x = jnp.pad(x, cfg)
    return x


# ---------------------------------------------------------------------------
# forward kernel: grid (token-blocks, vocab-blocks), vocab innermost.
# The three (1, BT) outputs live at a fixed index per token-block and are
# revisited across the vocab dimension — the repo's established
# accumulate-across-innermost-grid-dim idiom (ops/pallas_attention.py
# _bwd_dkdv_kernel): zero/init at j == 0, finalize at j == n_vb - 1.
# ---------------------------------------------------------------------------


def _fwd_kernel(h_ref, w_ref, lab_ref, lse_ref, s_ref, ll_ref, *, block_v, vocab, n_vb):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        lse_ref[0] = jnp.full_like(lse_ref[0], _NEG_INF)
        s_ref[0] = jnp.zeros_like(s_ref[0])
        ll_ref[0] = jnp.zeros_like(ll_ref[0])

    h = h_ref[...]  # (BT, d)
    w = w_ref[...]  # (BV, d)
    logits = lax.dot_general(
        h, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (BT, BV)
    col = j * block_v + lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < vocab, logits, _NEG_INF)

    m_old = lse_ref[0]  # running max until the last step rewrites it as lse
    s_old = s_ref[0]
    m_new = jnp.maximum(m_old, jnp.max(logits, axis=1))
    s_new = s_old * jnp.exp(m_old - m_new) + jnp.sum(
        jnp.exp(logits - m_new[:, None]), axis=1
    )
    # Label logit while the tile is resident: exactly one column hits.
    hit = col == lab_ref[0][:, None]
    ll_ref[0] += jnp.sum(jnp.where(hit, logits, 0.0), axis=1)
    lse_ref[0] = m_new
    s_ref[0] = s_new

    @pl.when(j == n_vb - 1)
    def _finalize():
        lse_ref[0] = m_new + jnp.log(s_new)


def _dlogit_tile(h, w, labels, lse, g_lse, g, col, vocab):
    """Recompute one logits tile and its cotangent dlogit (f32, BT x BV).

    dlogit = softmax(logits) * g_lse - onehot(label) * g; masked vocab
    columns produce exp(-1e30 - lse) == 0 and can never match a label.
    """
    logits = lax.dot_general(
        h, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    logits = jnp.where(col < vocab, logits, _NEG_INF)
    gp = jnp.exp(logits - lse[:, None]) * g_lse[:, None]
    return gp - jnp.where(col == labels[:, None], g[:, None], 0.0)


def _bwd_dh_kernel(
    h_ref, w_ref, lab_ref, lse_ref, gl_ref, g_ref, dh_ref, *, block_v, vocab
):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dh_ref[...] = jnp.zeros_like(dh_ref)

    h = h_ref[...]
    w = w_ref[...]
    col = j * block_v + lax.broadcasted_iota(jnp.int32, (h.shape[0], w.shape[0]), 1)
    gp = _dlogit_tile(h, w, lab_ref[0], lse_ref[0], gl_ref[0], g_ref[0], col, vocab)
    dh_ref[...] += lax.dot_general(
        gp, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _bwd_dw_kernel(
    h_ref, w_ref, lab_ref, lse_ref, gl_ref, g_ref, dw_ref, *, block_v, vocab
):
    # Grid (vocab-blocks, token-blocks): token dim innermost so the dW
    # tile is the revisited accumulator.
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    h = h_ref[...]
    w = w_ref[...]
    col = pl.program_id(0) * block_v + lax.broadcasted_iota(
        jnp.int32, (h.shape[0], w.shape[0]), 1
    )
    gp = _dlogit_tile(h, w, lab_ref[0], lse_ref[0], gl_ref[0], g_ref[0], col, vocab)
    dw_ref[...] += lax.dot_general(
        gp, h, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _prep(hidden, w_vocab, labels, block_t, block_v, compute_dtype):
    """Flatten + pad operands to block multiples; returns the kernel view."""
    b, t = labels.shape
    v, d = w_vocab.shape
    n = b * t
    dt = compute_dtype or hidden.dtype
    n_tb = _cdiv(n, block_t)
    n_vb = _cdiv(v, block_v)
    h = _pad_rows(hidden.reshape(n, d).astype(dt), n_tb * block_t)
    w = _pad_rows(w_vocab.astype(dt), n_vb * block_v)
    # Padded token rows get label -1: hits no column, so their label
    # accumulator stays 0 and no backward one-hot term fires.
    lab = _pad_rows(labels.reshape(n).astype(jnp.int32), n_tb * block_t)
    lab = jnp.where(
        jnp.arange(n_tb * block_t) < n, lab, jnp.int32(-1)
    ).reshape(1, n_tb * block_t)
    return h, w, lab, n, v, d, n_tb, n_vb


def _row_spec(block_t):
    # (1, BT) blocks over a (1, N) array: the singleton leading dim keeps
    # per-token vectors legal under Mosaic's 2-D tiling rules (same trick
    # as the (1, 1, BQ) carries in ops/pallas_attention.py).
    return pl.BlockSpec((1, block_t), lambda i, j: (0, i))


def _forward(hidden, w_vocab, labels, block_t, block_v, compute_dtype, z_loss, interpret):
    h, w, lab, n, v, d, n_tb, n_vb = _prep(
        hidden, w_vocab, labels, block_t, block_v, compute_dtype
    )
    row = jax.ShapeDtypeStruct((1, n_tb * block_t), jnp.float32)
    lse2, _, ll2 = pl.pallas_call(
        partial(_fwd_kernel, block_v=block_v, vocab=v, n_vb=n_vb),
        grid=(n_tb, n_vb),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            _row_spec(block_t),
        ],
        out_specs=[_row_spec(block_t)] * 3,
        out_shape=[row, row, row],
        interpret=interpret,
    )(h, w, lab)
    b, t = labels.shape
    lse = lse2[0, :n]
    per_token = lse - ll2[0, :n]
    if z_loss > 0.0:
        per_token = per_token + z_loss * jnp.square(lse)
    return per_token.reshape(b, t), lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def fused_ce_per_token(
    hidden: jax.Array,
    w_vocab: jax.Array,
    labels: jax.Array,
    block_t: int = DEFAULT_BLOCK_T,
    block_v: int = DEFAULT_BLOCK_V,
    compute_dtype: jnp.dtype | None = None,
    z_loss: float = 0.0,
    interpret: bool = False,
) -> jax.Array:
    """Per-token CE loss, f32, shape (B, T) — drop-in for
    ops/chunked_ce.py:chunked_ce_per_token, computed by the Pallas
    kernels above. Same operand layout: ``w_vocab`` is (V, d) embedding
    layout (tied ``token_embedding.embedding`` directly, untied
    ``lm_head.kernel`` transposed)."""
    loss, _ = _forward(
        hidden, w_vocab, labels, block_t, block_v, compute_dtype, z_loss, interpret
    )
    return loss


def _fwd(hidden, w_vocab, labels, block_t, block_v, compute_dtype, z_loss, interpret):
    loss, lse = _forward(
        hidden, w_vocab, labels, block_t, block_v, compute_dtype, z_loss, interpret
    )
    return loss, (hidden, w_vocab, labels, lse)


def _bwd(block_t, block_v, compute_dtype, z_loss, interpret, res, g):
    hidden, w_vocab, labels, lse = res
    h, w, lab, n, v, d, n_tb, n_vb = _prep(
        hidden, w_vocab, labels, block_t, block_v, compute_dtype
    )
    gf = g.reshape(n).astype(jnp.float32)
    # d(per_token)/d(lse) = 1 (CE) + 2*z*lse (z-loss); the -label_logit
    # term keeps coefficient -1 via the one-hot in _dlogit_tile.
    g_lse = gf * (1.0 + 2.0 * z_loss * lse) if z_loss > 0.0 else gf
    n_pad = n_tb * block_t
    # Pad cotangents with 0 so padded token rows contribute nothing.
    lse_p = _pad_rows(lse, n_pad).reshape(1, n_pad)
    gl_p = _pad_rows(g_lse, n_pad).reshape(1, n_pad)
    g_p = _pad_rows(gf, n_pad).reshape(1, n_pad)

    row_in = _row_spec(block_t)
    dh = pl.pallas_call(
        partial(_bwd_dh_kernel, block_v=block_v, vocab=v),
        grid=(n_tb, n_vb),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            row_in,
            row_in,
            row_in,
            row_in,
        ],
        out_specs=pl.BlockSpec((block_t, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, d), jnp.float32),
        interpret=interpret,
    )(h, w, lab, lse_p, gl_p, g_p)

    col_in = pl.BlockSpec((1, block_t), lambda j, i: (0, i))
    dw = pl.pallas_call(
        partial(_bwd_dw_kernel, block_v=block_v, vocab=v),
        grid=(n_vb, n_tb),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda j, i: (i, 0)),
            pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
            col_in,
            col_in,
            col_in,
            col_in,
        ],
        out_specs=pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((n_vb * block_v, d), jnp.float32),
        interpret=interpret,
    )(h, w, lab, lse_p, gl_p, g_p)

    b, t = labels.shape
    dh = dh[:n].reshape(b, t, -1).astype(hidden.dtype)
    return dh, dw[:v].astype(w_vocab.dtype), None


fused_ce_per_token.defvjp(_fwd, _bwd)


def fused_ce_components(
    hidden: jax.Array,
    w_vocab: jax.Array,
    labels: jax.Array,
    attention_mask: jax.Array | None,
    *,
    block_t: int = DEFAULT_BLOCK_T,
    block_v: int = DEFAULT_BLOCK_V,
    z_loss: float = 0.0,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Per-example ``(loss_sum, token_count)`` of shape (B,) — same
    mask-aware contract as chunked_ce_components / masked_ce_components
    (segment ids > 1 from packing are boolean-ized, not loss weights)."""
    per_token = fused_ce_per_token(
        hidden, w_vocab, labels, block_t, block_v, None, z_loss, interpret
    )
    if attention_mask is None:
        mask = jnp.ones_like(per_token)
    else:
        mask = (attention_mask != 0).astype(jnp.float32)
    return jnp.sum(per_token * mask, axis=-1), jnp.sum(mask, axis=-1)


__all__ = [
    "fused_ce_per_token",
    "fused_ce_components",
    "resolve_loss_impl",
    "pallas_ce_supported",
    "LOSS_IMPLS",
    "DEFAULT_BLOCK_T",
    "DEFAULT_BLOCK_V",
]
