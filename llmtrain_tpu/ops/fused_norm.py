"""Fused residual-add + LayerNorm Pallas kernel (fwd + bwd).

The r06 attribution tables show the per-block pre-norms as pure
elementwise HBM round-trips: XLA reads the residual stream, writes the
sum, reads it back for the norm, writes the normed copy — twice per
layer. This kernel fuses ``s = x + residual; y = LN(s)`` into one VMEM
pass per token block and returns both ``y`` (for the sublayer) and
``s`` (the new residual stream), so the stream is read and written once.

Backward is the standard per-token LayerNorm gradient, recomputed from
the saved sum + per-token (mean, rstd):

    xhat  = (s - mean) * rstd
    dxhat = dy * scale
    ds    = rstd * (dxhat - mean_d(dxhat) - xhat * mean_d(dxhat * xhat))

``dscale``/``dbias`` accumulate into a revisited (1, d) output block
across the token-block grid (the same accumulate-across-grid idiom as
ops/pallas_attention.py and ops/fused_ce.py). ``dx == dresidual == ds``
(+ the incoming gradient on the returned sum), so the residual branch
costs nothing extra.

Wired per-block in models/gpt.py behind ``model.extra.fused_norm``;
``model.extra.pallas_interpret: true`` runs the emulated kernel on CPU
(tier-1 parity tests). Parameter names/shapes match ``nn.LayerNorm``
(``scale``/``bias`` of shape (d,)) so checkpoints are interchangeable
with the unfused path.
"""

from __future__ import annotations

import logging
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

logger = logging.getLogger(__name__)

DEFAULT_BLOCK_T = 256
_FALLBACK_WARNED: set[str] = set()


def resolve_fused_norm(requested: bool, *, interpret: bool = False) -> bool:
    """fp8-style degrade: fused_norm on a backend without Pallas TPU
    support silently (warn-once) reverts to the unfused nn.LayerNorm
    path instead of failing the run."""
    from .fused_ce import pallas_ce_supported

    if requested and not (pallas_ce_supported() or interpret):
        if "fused_norm" not in _FALLBACK_WARNED:
            _FALLBACK_WARNED.add("fused_norm")
            logger.warning(
                "model.extra.fused_norm requested but backend %r has no "
                "Pallas TPU support; using the unfused LayerNorm path "
                "(set model.extra.pallas_interpret: true to force the "
                "interpret-mode kernel)",
                jax.default_backend(),
            )
        return False
    return bool(requested)


def _fwd_kernel(x_ref, res_ref, sc_ref, b_ref, y_ref, s_ref, m_ref, r_ref, *, eps):
    s = x_ref[...].astype(jnp.float32)
    if res_ref is not None:
        s = s + res_ref[...].astype(jnp.float32)
    mu = jnp.mean(s, axis=1)
    var = jnp.mean(jnp.square(s - mu[:, None]), axis=1)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (s - mu[:, None]) * rstd[:, None]
    y_ref[...] = (xhat * sc_ref[0][None, :] + b_ref[0][None, :]).astype(y_ref.dtype)
    if s_ref is not None:
        s_ref[...] = s.astype(s_ref.dtype)
    m_ref[0] = mu
    r_ref[0] = rstd


def _bwd_kernel(s_ref, sc_ref, m_ref, r_ref, gy_ref, dx_ref, dsc_ref, db_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dsc_ref[0] = jnp.zeros_like(dsc_ref[0])
        db_ref[0] = jnp.zeros_like(db_ref[0])

    s = s_ref[...].astype(jnp.float32)
    gy = gy_ref[...].astype(jnp.float32)
    mu = m_ref[0]
    rstd = r_ref[0]
    xhat = (s - mu[:, None]) * rstd[:, None]
    dxhat = gy * sc_ref[0][None, :].astype(jnp.float32)
    m1 = jnp.mean(dxhat, axis=1)
    m2 = jnp.mean(dxhat * xhat, axis=1)
    dx_ref[...] = (rstd[:, None] * (dxhat - m1[:, None] - xhat * m2[:, None])).astype(
        dx_ref.dtype
    )
    dsc_ref[0] += jnp.sum(gy * xhat, axis=0)
    db_ref[0] += jnp.sum(gy, axis=0)


def _pad_tokens(x, n_pad):
    pad = n_pad - x.shape[0]
    if pad:
        x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
    return x


def _run_forward(x, residual, scale, bias, eps, block_t, interpret):
    shape = x.shape
    d = shape[-1]
    n = 1
    for dim in shape[:-1]:
        n *= dim
    n_tb = -(-n // block_t)
    n_pad = n_tb * block_t
    x2 = _pad_tokens(x.reshape(n, d), n_pad)
    operands = [x2]
    with_res = residual is not None
    if with_res:
        operands.append(_pad_tokens(residual.reshape(n, d), n_pad))
    operands += [scale.reshape(1, d), bias.reshape(1, d)]

    def kernel(*refs):
        if with_res:
            x_r, res_r, sc_r, b_r, y_r, s_r, m_r, r_r = refs
        else:
            x_r, sc_r, b_r, y_r, m_r, r_r = refs
            res_r = s_r = None
        _fwd_kernel(x_r, res_r, sc_r, b_r, y_r, s_r, m_r, r_r, eps=eps)

    tok = pl.BlockSpec((block_t, d), lambda i: (i, 0))
    param = pl.BlockSpec((1, d), lambda i: (0, 0))
    row = pl.BlockSpec((1, block_t), lambda i: (0, i))
    row_shape = jax.ShapeDtypeStruct((1, n_pad), jnp.float32)
    out_specs = [tok] + ([tok] if with_res else []) + [row, row]
    out_shape = [jax.ShapeDtypeStruct((n_pad, d), x.dtype)]
    if with_res:
        out_shape.append(jax.ShapeDtypeStruct((n_pad, d), x.dtype))
    out_shape += [row_shape, row_shape]
    outs = pl.pallas_call(
        kernel,
        grid=(n_tb,),
        in_specs=[tok] + ([tok] if with_res else []) + [param, param],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*operands)
    if with_res:
        y, s, mu, rstd = outs
    else:
        y, mu, rstd = outs
        s = y  # unused slot; the saved sum is x itself below
    return shape, n, y[:n].reshape(shape), s[:n].reshape(shape), mu, rstd


def _run_backward(s2, scale, mu, rstd, gy, shape, n, eps, block_t, interpret):
    d = shape[-1]
    n_tb = -(-n // block_t)
    n_pad = n_tb * block_t
    # Padded gy rows are zero: they add nothing to dscale/dbias and their
    # dx rows are sliced away.
    gy2 = _pad_tokens(gy.reshape(n, d), n_pad)
    tok = pl.BlockSpec((block_t, d), lambda i: (i, 0))
    param = pl.BlockSpec((1, d), lambda i: (0, 0))
    row = pl.BlockSpec((1, block_t), lambda i: (0, i))
    dx, dsc, db = pl.pallas_call(
        _bwd_kernel,
        grid=(n_tb,),
        in_specs=[tok, param, row, row, tok],
        out_specs=[tok, param, param],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, d), gy.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(s2, scale.reshape(1, d), mu, rstd, gy2)
    return dx[:n].reshape(shape), dsc[0].astype(scale.dtype), db[0]


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_layer_norm(
    x: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    eps: float = 1e-6,
    block_t: int = DEFAULT_BLOCK_T,
    interpret: bool = False,
) -> jax.Array:
    """LayerNorm over the last axis — the no-residual flavor (block
    input norm ln_1 / final ln_f sites)."""
    _, _, y, _, _, _ = _run_forward(x, None, scale, bias, eps, block_t, interpret)
    return y


def _ln_fwd(x, scale, bias, eps, block_t, interpret):
    shape, n, y, _, mu, rstd = _run_forward(
        x, None, scale, bias, eps, block_t, interpret
    )
    n_pad = -(-n // block_t) * block_t
    s2 = _pad_tokens(x.reshape(n, shape[-1]), n_pad)
    return y, (s2, scale, mu, rstd, shape, n)


def _ln_bwd(eps, block_t, interpret, res, gy):
    s2, scale, mu, rstd, shape, n = res
    dx, dsc, db = _run_backward(
        s2, scale, mu, rstd, gy, shape, n, eps, block_t, interpret
    )
    return dx.astype(gy.dtype), dsc, db.astype(scale.dtype)


fused_layer_norm.defvjp(_ln_fwd, _ln_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_add_layer_norm(
    x: jax.Array,
    residual: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    eps: float = 1e-6,
    block_t: int = DEFAULT_BLOCK_T,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """``(LN(x + residual), x + residual)`` in one HBM pass — the
    post-attention pre-MLP site: the first output feeds the sublayer,
    the second is the updated residual stream."""
    _, _, y, s, _, _ = _run_forward(x, residual, scale, bias, eps, block_t, interpret)
    return y, s


def _aln_fwd(x, residual, scale, bias, eps, block_t, interpret):
    shape, n, y, s, mu, rstd = _run_forward(
        x, residual, scale, bias, eps, block_t, interpret
    )
    n_pad = -(-n // block_t) * block_t
    s2 = _pad_tokens(s.reshape(n, shape[-1]), n_pad)
    return (y, s), (s2, scale, mu, rstd, shape, n)


def _aln_bwd(eps, block_t, interpret, res, g):
    gy, gs = g
    s2, scale, mu, rstd, shape, n = res
    ds, dsc, db = _run_backward(
        s2, scale, mu, rstd, gy, shape, n, eps, block_t, interpret
    )
    # The returned sum feeds the residual stream: its cotangent flows
    # straight through the add to both inputs.
    dx = (ds + gs).astype(gy.dtype)
    return dx, dx, dsc, db.astype(scale.dtype)


fused_add_layer_norm.defvjp(_aln_fwd, _aln_bwd)


__all__ = [
    "fused_layer_norm",
    "fused_add_layer_norm",
    "resolve_fused_norm",
    "DEFAULT_BLOCK_T",
]
