"""int8/fp8 quantization: inference weight compression AND a training matmul path.

Beyond-reference capability (the reference has no quantization path; its
serving story is the f32 notebook forward). Two entry points share one
quantization recipe (:func:`quantize_array`):

**Inference (weight-only int8)** — :func:`quantize_tree` rewrites a param
tree's big leaves into :class:`QuantizedArray` containers; ``__jax_array__``
dequantizes in-graph so XLA keeps the int8 buffer in HBM and fuses the
``convert+multiply`` into the consuming matmul's operand read. Rationale:
single-stream decode is weight-bandwidth bound (tools/diag_decode.py
attribution), so halving weight bytes is worth ~1% logit error — and TPU
v5e reads int8 natively.

**Training (quantized matmuls, ``model.extra.matmul_precision``)** —
:func:`quant_dot_general` builds a ``lax.dot_general`` replacement that
flax ``Dense``/``DenseGeneral`` modules consume via their ``dot_general=``
hook, and :class:`QuantDense` is the standalone drop-in. Modes:

* ``"int8"`` — weights quantized to symmetric per-channel int8 at each
  step's current value (just-in-time amax scaling over the contracting
  axes, so the scales group by output unit) and dequantized in-graph;
  activations stay in the compute dtype.
* ``"int8_act"`` — additionally fake-quantizes the activations
  per-channel over their contracting axes (int8 x int8 numerics).
* ``"fp8"`` — both operands cast to ``float8_e4m3fn`` with per-tensor
  just-in-time scaling into the e4m3 dynamic range, matmul accumulated
  in f32 via ``preferred_element_type`` (TransformerEngine-style).
  Requires backend support: :func:`fp8_supported` probes it once and
  :func:`resolve_matmul_precision` falls back to ``"f32"`` with a
  one-time warning when absent.
* ``"f32"`` — the unmodified flax/lax path (returns ``None`` so the
  module uses its default ``dot_general``).

Gradients are straight-through (``jax.custom_vjp``): quantization is an
identity in the backward pass, so gradients are exact f32 with respect
to the quantized operands — master weights, grad accumulation, the
optimizer, ZeRO sharding, and checkpoint contracts are all untouched
(the param tree never stores codes during training). Loss parity with
the f32 trajectory is *gated*, not assumed: bench.py's scenario matrix
trains N probe steps quantized-vs-f32 and fails the scenario line as
``degraded`` when the trajectories diverge beyond the documented rtol
(docs/perf.md "Quantized matmul training").

Scales are symmetric per-channel (no zero-point): dequant stays a single
fused multiply and 0.0 is exact, which LayerNorm-heavy stacks care about.
For :func:`quantize_tree` the per-channel rule is: ``embedding`` tables
one scale per row; all other kernels max over the largest leading axis
(the contraction dim in every layout we ship).
"""

from __future__ import annotations

import functools
import logging
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax, tree_util

Params = Any  # PyTree of arrays

logger = logging.getLogger(__name__)

_INT8_MAX = 127.0


@tree_util.register_pytree_node_class
class QuantizedArray:
    """int8 codes + broadcastable f32 scales, posing as the original array.

    Registered as a pytree *container*: under ``jit``/``tree.map`` it
    flattens into its two array children, so jitted programs carry the
    int8 buffer (not a dequantized copy) across the host→device boundary
    and through donation. ``__jax_array__`` makes every consuming jnp op
    dequantize in-graph to ``dtype`` (the weight's original dtype).
    """

    def __init__(self, q: jax.Array, scale: jax.Array, dtype: Any):
        self.q = q
        self.scale = scale
        self._dtype = jnp.dtype(dtype)

    # --- array protocol -------------------------------------------------
    @property
    def shape(self) -> tuple[int, ...]:
        return self.q.shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self) -> int:
        return self.q.ndim

    @property
    def size(self) -> int:
        return self.q.size

    @property
    def nbytes(self) -> int:
        """Actual storage cost: int8 codes + scale floats."""
        return int(self.q.size * 1 + self.scale.size * self.scale.dtype.itemsize)

    def dequantize(self) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.scale).astype(self._dtype)

    def __jax_array__(self) -> jax.Array:
        return self.dequantize()

    def astype(self, dtype) -> "QuantizedArray":
        """Retarget the *dequantized* dtype; codes and scales are shared."""
        return QuantizedArray(self.q, self.scale, dtype)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"QuantizedArray(shape={self.shape}, dtype={self._dtype.name}, "
            f"scale_shape={self.scale.shape})"
        )

    # --- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), self._dtype

    @classmethod
    def tree_unflatten(cls, dtype, children):
        return cls(children[0], children[1], dtype)


def quantize_array(w: jax.Array, *, reduce_axes: tuple[int, ...]) -> QuantizedArray:
    """Symmetric per-channel int8: ``scale = amax/127`` over ``reduce_axes``."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes, keepdims=True)
    # All-zero channels (e.g. a fresh LoRA B factor) get scale 1.0: the
    # codes are all 0 and dequantize exactly to 0.0 either way, without
    # a 0/0 NaN in the division below.
    scale = jnp.where(amax == 0.0, 1.0, amax / _INT8_MAX)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -_INT8_MAX, _INT8_MAX)
    return QuantizedArray(q.astype(jnp.int8), scale, w.dtype)


def _is_embedding_path(path) -> bool:
    for k in path:
        name = getattr(k, "key", None) or getattr(k, "name", None)
        if name is not None and "embedding" in str(name):
            return True
    return False


def _is_bias_path(path) -> bool:
    """Multi-dim bias leaves (Qwen2's (3, H, dh) fused qkv bias) pass the
    ndim gate but are exactly the quality-sensitive additive params the
    'biases stay float' contract promises to preserve."""
    if not path:
        return False
    name = getattr(path[-1], "key", None) or getattr(path[-1], "name", None)
    return name is not None and str(name) == "bias"


def quantize_tree(params: Params, *, min_size: int = 4096) -> Params:
    """Quantize every weight matrix in a param tree to int8.

    A leaf is quantized iff it is floating, at least 2-D, and has
    ``size >= min_size`` — norms, biases and tiny projections stay in
    their original dtype (they are a rounding error of the byte budget
    and the quality-sensitive part of the stack). Embedding tables get
    per-row scales; all other kernels per-output-unit scales (max over
    every axis but the last).

    The result is a same-structure tree whose big leaves are
    :class:`QuantizedArray` containers — directly consumable by
    ``model.apply``, ``generation.generate``, ``speculative_generate``
    and the Trainer's eval ``params_override``.
    """

    def _leaf(path, a):
        if isinstance(a, QuantizedArray):
            raise ValueError("quantize_tree: tree is already quantized")
        if not hasattr(a, "ndim") or a.ndim < 2:
            return a
        if not jnp.issubdtype(a.dtype, jnp.floating):
            return a
        if a.size < min_size:
            return a
        if _is_bias_path(path):
            return a
        if _is_embedding_path(path):
            reduce_axes: tuple[int, ...] = (a.ndim - 1,)
        else:
            leading = a.shape[:-1]
            reduce_axes = (leading.index(max(leading)),)
        return quantize_array(a, reduce_axes=reduce_axes)

    return tree_util.tree_map_with_path(
        _leaf, params, is_leaf=lambda x: isinstance(x, QuantizedArray)
    )


def dequantize_tree(params: Params) -> Params:
    """Materialize a quantized tree back to plain arrays (testing/export)."""
    return jax.tree.map(
        lambda a: a.dequantize() if isinstance(a, QuantizedArray) else a,
        params,
        is_leaf=lambda x: isinstance(x, QuantizedArray),
    )


def quant_stats(params: Params) -> dict[str, int | float]:
    """Byte accounting for a (possibly) quantized tree.

    ``bytes_dense`` is what the same tree would occupy with every
    quantized leaf restored to its original dtype — the compression
    ratio decode cares about, since weight bytes streamed per token is
    the single-stream bottleneck.
    """
    leaves = jax.tree.leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedArray)
    )
    n_q = sum(1 for a in leaves if isinstance(a, QuantizedArray))
    bytes_actual = 0
    bytes_dense = 0
    params_q = 0
    params_total = 0
    for a in leaves:
        params_total += int(a.size)
        if isinstance(a, QuantizedArray):
            params_q += int(a.size)
            bytes_actual += a.nbytes
            bytes_dense += int(a.size * a.dtype.itemsize)
        else:
            nbytes = int(a.size * a.dtype.itemsize)
            bytes_actual += nbytes
            bytes_dense += nbytes
    return {
        "quantized_leaves": n_q,
        "quantized_params": params_q,
        "total_params": params_total,
        "bytes": bytes_actual,
        "bytes_dense": bytes_dense,
        "compression": (bytes_dense / bytes_actual) if bytes_actual else 1.0,
    }


# ==========================================================================
# Training path: quantized matmuls with straight-through gradients.
# ==========================================================================

#: Accepted ``model.extra.matmul_precision`` values. "int8_act" is the
#: activations-too variant of "int8" (the knob's documented surface is
#: f32|int8|fp8; int8_act is the opt-in extension).
MATMUL_PRECISIONS = ("f32", "int8", "int8_act", "fp8")

# float8_e4m3fn dynamic range: the per-tensor scale maps each operand's
# amax onto this so the cast saturates instead of overflowing to inf.
_E4M3_MAX = 448.0


@functools.lru_cache(maxsize=1)
def fp8_supported() -> bool:
    """True when the installed jax + backend can run a float8_e4m3fn matmul.

    Probed once per process with a tiny end-to-end dot (dtype existing is
    not enough — a backend can expose the dtype but reject the HLO).
    Lazy: no jax compute happens at import time.
    """
    if not hasattr(jnp, "float8_e4m3fn"):
        return False
    try:
        a = jnp.ones((4, 4), jnp.float8_e4m3fn)
        out = lax.dot_general(
            a, a, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return bool(jax.device_get(out)[0, 0] == 4.0)
    except Exception:  # noqa: BLE001 — any backend rejection means "no"
        return False


_FALLBACK_WARNED: set[str] = set()


def resolve_matmul_precision(mode: str) -> str:
    """Validate a ``matmul_precision`` knob value and resolve capability.

    Unknown values raise (config-time, like ``loss_impl``); ``"fp8"``
    degrades to ``"f32"`` with a one-time warning when the backend can't
    run float8 matmuls — the clean-fallback contract: the run proceeds,
    the precision claim does not.
    """
    if mode not in MATMUL_PRECISIONS:
        raise ValueError(
            f"matmul_precision {mode!r} unknown; expected one of "
            f"{list(MATMUL_PRECISIONS)}"
        )
    if mode == "fp8" and not fp8_supported():
        if "fp8" not in _FALLBACK_WARNED:
            _FALLBACK_WARNED.add("fp8")
            logger.warning(
                "matmul_precision=fp8 requested but this jax/backend cannot "
                "run float8_e4m3fn matmuls; falling back to f32"
            )
        return "f32"
    return mode


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def fake_quant(w: jax.Array, reduce_axes: tuple[int, ...]) -> jax.Array:
    """Quantize-dequantize ``w`` to symmetric per-channel int8 (STE).

    Forward is exactly :func:`quantize_array` followed by dequant — the
    value the matmul consumes has int8 numerics (just-in-time amax
    scaling over ``reduce_axes``, per-output-unit scales for a kernel
    whose contracting dims are reduced). Backward is the identity
    (straight-through): the gradient flows to the f32 master weight
    untouched, so optimizer/ZeRO/checkpoint contracts never see codes.
    """
    return quantize_array(w, reduce_axes=reduce_axes).dequantize()


def _fake_quant_fwd(w, reduce_axes):
    return fake_quant(w, reduce_axes), None


def _fake_quant_bwd(reduce_axes, _res, g):
    return (g,)


fake_quant.defvjp(_fake_quant_fwd, _fake_quant_bwd)


def _fp8_dot_impl(lhs: jax.Array, rhs: jax.Array, dimension_numbers) -> jax.Array:
    """f32-accumulated float8_e4m3fn dot with per-tensor JIT scaling."""
    out_dtype = jnp.promote_types(lhs.dtype, rhs.dtype)
    lhs32 = lhs.astype(jnp.float32)
    rhs32 = rhs.astype(jnp.float32)
    # amax -> e4m3 range; the floor keeps all-zero operands at scale ~1
    # territory instead of 0/0 (mirrors quantize_array's zero guard).
    ls = jnp.maximum(jnp.max(jnp.abs(lhs32)), 1e-30) / _E4M3_MAX
    rs = jnp.maximum(jnp.max(jnp.abs(rhs32)), 1e-30) / _E4M3_MAX
    l8 = (lhs32 / ls).astype(jnp.float8_e4m3fn)
    r8 = (rhs32 / rs).astype(jnp.float8_e4m3fn)
    out = lax.dot_general(
        l8, r8, dimension_numbers, preferred_element_type=jnp.float32
    )
    return (out * (ls * rs)).astype(out_dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fp8_dot(lhs: jax.Array, rhs: jax.Array, dimension_numbers) -> jax.Array:
    """fp8 forward, exact straight-through backward.

    The whole dot is wrapped (not just the casts) because differentiating
    a dot with float8 operands would hand XLA an fp8 transpose — the
    backward here is the plain f32 ``dot_general`` vjp on the saved
    full-precision operands, i.e. exact master-weight gradients.
    """
    return _fp8_dot_impl(lhs, rhs, dimension_numbers)


def _fp8_dot_fwd(lhs, rhs, dimension_numbers):
    return _fp8_dot_impl(lhs, rhs, dimension_numbers), (lhs, rhs)


def _fp8_dot_bwd(dimension_numbers, res, g):
    lhs, rhs = res
    _, vjp = jax.vjp(
        lambda l, r: lax.dot_general(l, r, dimension_numbers), lhs, rhs
    )
    return vjp(g)


_fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


def quant_dot_general(mode: str) -> Callable | None:
    """A ``lax.dot_general`` replacement implementing ``mode``.

    Returns ``None`` for ``"f32"`` so callers can pass the result
    directly to flax's ``Dense(dot_general=...)`` hook — ``None`` selects
    the module's stock path, keeping f32 bit-identical to a build without
    this feature. ``mode`` must already be capability-resolved
    (:func:`resolve_matmul_precision`); an fp8 dot on an unsupported
    backend raises at trace time rather than silently degrading.
    """
    if mode not in MATMUL_PRECISIONS:
        raise ValueError(
            f"matmul_precision {mode!r} unknown; expected one of "
            f"{list(MATMUL_PRECISIONS)}"
        )
    if mode == "f32":
        return None

    def dot_general(
        lhs: jax.Array,
        rhs: jax.Array,
        dimension_numbers,
        precision=None,
        preferred_element_type=None,
    ) -> jax.Array:
        if mode == "fp8":
            del precision, preferred_element_type
            return _fp8_dot(lhs, rhs, dimension_numbers)
        (lhs_contract, rhs_contract), _ = dimension_numbers
        rhs_q = fake_quant(rhs, tuple(rhs_contract))
        if mode == "int8_act":
            lhs = fake_quant(lhs, tuple(lhs_contract))
        return lax.dot_general(
            lhs,
            rhs_q,
            dimension_numbers,
            precision=precision,
            preferred_element_type=preferred_element_type,
        )

    return dot_general


class QuantDense:
    """Drop-in ``nn.Dense`` with the quantized training matmul.

    Same parameter tree as ``nn.Dense`` ({"kernel", "bias"}), f32 master
    params, straight-through gradients — a checkpoint trained through
    ``QuantDense`` loads into ``nn.Dense`` verbatim and vice versa. The
    model families thread ``matmul_precision`` into their existing
    Dense/DenseGeneral modules via ``dot_general=quant_dot_general(mode)``
    instead (no param-tree change at all); this class is the standalone
    building block for code outside those families.

    Implemented as a thin factory over ``nn.Dense`` (imported lazily so
    ops/ keeps its no-flax-at-import property for kernel-only consumers).
    """

    def __new__(cls, *args: Any, matmul_precision: str = "int8", **kwargs: Any):
        from flax import linen as nn

        return nn.Dense(
            *args, **kwargs, dot_general=quant_dot_general(matmul_precision)
        )
