"""Weight-only int8 quantization for inference.

Beyond-reference capability (the reference has no quantization path;
its serving story is the f32 notebook forward,
reference notebooks/trained_vs_random_completion.ipynb). TPU-first
rationale: single-stream decode is weight-bandwidth bound
(tools/diag_decode.py attribution), so halving the bytes each weight
read moves is worth ~1% logit error — and TPU v5e reads int8 natively.

Design: a :class:`QuantizedArray` pytree container holding the int8
codes plus per-channel f32 scales. It implements ``__jax_array__``, so
anywhere a weight flows into a jnp/flax op it dequantizes *inside the
traced graph* — XLA keeps the int8 buffer in HBM and fuses the
``convert+multiply`` into the consuming matmul's operand read. No model
changes, no custom modules: ``model.apply(quantize_tree(params), x)``
just works, eager or jit, for every registered family.

Scales are symmetric per-channel:

* ``embedding`` tables — one scale per row (the lookup/logit channel);
* everything else (Dense/DenseGeneral kernels, stacked MoE expert
  kernels) — max over the largest leading axis. In every kernel layout
  we ship that axis is the contraction/input dimension (e.g. ``d_model``
  in a ``(d, 3, heads, hd)`` fused qkv kernel), so the scales group by
  output unit; and because dequant is an exact broadcast multiply, any
  grouping is *correct* — the choice only affects quality and the
  scale-tensor overhead, both of which this rule keeps small.

Symmetric (no zero-point) keeps dequant a single fused multiply and
keeps 0.0 exact, which LayerNorm/RMSNorm-heavy stacks care about.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import tree_util

Params = Any  # PyTree of arrays

_INT8_MAX = 127.0


@tree_util.register_pytree_node_class
class QuantizedArray:
    """int8 codes + broadcastable f32 scales, posing as the original array.

    Registered as a pytree *container*: under ``jit``/``tree.map`` it
    flattens into its two array children, so jitted programs carry the
    int8 buffer (not a dequantized copy) across the host→device boundary
    and through donation. ``__jax_array__`` makes every consuming jnp op
    dequantize in-graph to ``dtype`` (the weight's original dtype).
    """

    def __init__(self, q: jax.Array, scale: jax.Array, dtype: Any):
        self.q = q
        self.scale = scale
        self._dtype = jnp.dtype(dtype)

    # --- array protocol -------------------------------------------------
    @property
    def shape(self) -> tuple[int, ...]:
        return self.q.shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self) -> int:
        return self.q.ndim

    @property
    def size(self) -> int:
        return self.q.size

    @property
    def nbytes(self) -> int:
        """Actual storage cost: int8 codes + scale floats."""
        return int(self.q.size * 1 + self.scale.size * self.scale.dtype.itemsize)

    def dequantize(self) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.scale).astype(self._dtype)

    def __jax_array__(self) -> jax.Array:
        return self.dequantize()

    def astype(self, dtype) -> "QuantizedArray":
        """Retarget the *dequantized* dtype; codes and scales are shared."""
        return QuantizedArray(self.q, self.scale, dtype)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"QuantizedArray(shape={self.shape}, dtype={self._dtype.name}, "
            f"scale_shape={self.scale.shape})"
        )

    # --- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), self._dtype

    @classmethod
    def tree_unflatten(cls, dtype, children):
        return cls(children[0], children[1], dtype)


def quantize_array(w: jax.Array, *, reduce_axes: tuple[int, ...]) -> QuantizedArray:
    """Symmetric per-channel int8: ``scale = amax/127`` over ``reduce_axes``."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes, keepdims=True)
    # All-zero channels (e.g. a fresh LoRA B factor) get scale 1.0: the
    # codes are all 0 and dequantize exactly to 0.0 either way, without
    # a 0/0 NaN in the division below.
    scale = jnp.where(amax == 0.0, 1.0, amax / _INT8_MAX)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -_INT8_MAX, _INT8_MAX)
    return QuantizedArray(q.astype(jnp.int8), scale, w.dtype)


def _is_embedding_path(path) -> bool:
    for k in path:
        name = getattr(k, "key", None) or getattr(k, "name", None)
        if name is not None and "embedding" in str(name):
            return True
    return False


def _is_bias_path(path) -> bool:
    """Multi-dim bias leaves (Qwen2's (3, H, dh) fused qkv bias) pass the
    ndim gate but are exactly the quality-sensitive additive params the
    'biases stay float' contract promises to preserve."""
    if not path:
        return False
    name = getattr(path[-1], "key", None) or getattr(path[-1], "name", None)
    return name is not None and str(name) == "bias"


def quantize_tree(params: Params, *, min_size: int = 4096) -> Params:
    """Quantize every weight matrix in a param tree to int8.

    A leaf is quantized iff it is floating, at least 2-D, and has
    ``size >= min_size`` — norms, biases and tiny projections stay in
    their original dtype (they are a rounding error of the byte budget
    and the quality-sensitive part of the stack). Embedding tables get
    per-row scales; all other kernels per-output-unit scales (max over
    every axis but the last).

    The result is a same-structure tree whose big leaves are
    :class:`QuantizedArray` containers — directly consumable by
    ``model.apply``, ``generation.generate``, ``speculative_generate``
    and the Trainer's eval ``params_override``.
    """

    def _leaf(path, a):
        if isinstance(a, QuantizedArray):
            raise ValueError("quantize_tree: tree is already quantized")
        if not hasattr(a, "ndim") or a.ndim < 2:
            return a
        if not jnp.issubdtype(a.dtype, jnp.floating):
            return a
        if a.size < min_size:
            return a
        if _is_bias_path(path):
            return a
        if _is_embedding_path(path):
            reduce_axes: tuple[int, ...] = (a.ndim - 1,)
        else:
            leading = a.shape[:-1]
            reduce_axes = (leading.index(max(leading)),)
        return quantize_array(a, reduce_axes=reduce_axes)

    return tree_util.tree_map_with_path(
        _leaf, params, is_leaf=lambda x: isinstance(x, QuantizedArray)
    )


def dequantize_tree(params: Params) -> Params:
    """Materialize a quantized tree back to plain arrays (testing/export)."""
    return jax.tree.map(
        lambda a: a.dequantize() if isinstance(a, QuantizedArray) else a,
        params,
        is_leaf=lambda x: isinstance(x, QuantizedArray),
    )


def quant_stats(params: Params) -> dict[str, int | float]:
    """Byte accounting for a (possibly) quantized tree.

    ``bytes_dense`` is what the same tree would occupy with every
    quantized leaf restored to its original dtype — the compression
    ratio decode cares about, since weight bytes streamed per token is
    the single-stream bottleneck.
    """
    leaves = jax.tree.leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedArray)
    )
    n_q = sum(1 for a in leaves if isinstance(a, QuantizedArray))
    bytes_actual = 0
    bytes_dense = 0
    params_q = 0
    params_total = 0
    for a in leaves:
        params_total += int(a.size)
        if isinstance(a, QuantizedArray):
            params_q += int(a.size)
            bytes_actual += a.nbytes
            bytes_dense += int(a.size * a.dtype.itemsize)
        else:
            nbytes = int(a.size * a.dtype.itemsize)
            bytes_actual += nbytes
            bytes_dense += nbytes
    return {
        "quantized_leaves": n_q,
        "quantized_params": params_q,
        "total_params": params_total,
        "bytes": bytes_actual,
        "bytes_dense": bytes_dense,
        "compression": (bytes_dense / bytes_actual) if bytes_actual else 1.0,
    }
